"""Shape / layout manipulation ops.

Parity: reference python/paddle/tensor/manipulation.py + phi kernels
(concat, split, gather, scatter, transpose, ...). All static-shape; ops whose
output shape is data-dependent in the reference (nonzero, masked_select,
unique) here follow XLA conventions: they either take a static `size` hint or
run un-jitted on host — documented per-op.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..core.dispatch import primitive
from ..core.tensor import Tensor

_A = jnp.asarray


def _shape_of(x):
    return jnp.shape(x)


@primitive
def reshape(x, shape):
    x = _A(x)
    shape = [int(s) for s in shape]
    return jnp.reshape(x, shape)


@primitive
def transpose(x, perm):
    return jnp.transpose(_A(x), axes=[int(p) for p in perm])


def t(x):
    nd = x.ndim if isinstance(x, Tensor) else jnp.ndim(x)
    if nd < 2:
        return x if isinstance(x, Tensor) else Tensor(_A(x))
    return transpose(x, list(range(nd))[::-1])


@primitive
def concat(xs, axis=0):
    return jnp.concatenate([_A(x) for x in xs], axis=int(axis))


@primitive
def stack(xs, axis=0):
    return jnp.stack([_A(x) for x in xs], axis=int(axis))


@primitive
def _split_impl(x, sections, axis):
    x = _A(x)
    if isinstance(sections, int):
        return tuple(jnp.split(x, sections, axis=axis))
    # sections is a list of sizes; -1 means "the rest"
    sizes = list(sections)
    total = x.shape[axis]
    if -1 in sizes:
        known = sum(s for s in sizes if s != -1)
        sizes[sizes.index(-1)] = total - known
    offsets = np.cumsum(sizes)[:-1].tolist()
    return tuple(jnp.split(x, offsets, axis=axis))


def split(x, num_or_sections, axis=0):
    out = _split_impl(x, sections=num_or_sections, axis=int(axis))
    return list(out) if isinstance(out, tuple) else [out]


def chunk(x, chunks, axis=0):
    return split(x, chunks, axis)


def unbind(x, axis=0):
    n = x.shape[axis] if isinstance(x, Tensor) else jnp.shape(x)[axis]
    parts = split(x, n, axis)
    return [squeeze(p, axis=axis) for p in parts]


@primitive
def squeeze(x, axis=None):
    x = _A(x)
    if axis is None:
        return jnp.squeeze(x)
    axes = axis if isinstance(axis, (list, tuple)) else [axis]
    axes = tuple(a % x.ndim for a in axes if x.shape[a % x.ndim] == 1)
    return jnp.squeeze(x, axis=axes) if axes else x


@primitive
def unsqueeze(x, axis):
    x = _A(x)
    axes = axis if isinstance(axis, (list, tuple)) else [axis]
    out = x
    for a in sorted(int(a) if a >= 0 else int(a) + out.ndim + 1 for a in axes):
        out = jnp.expand_dims(out, a)
    return out


@primitive
def flatten(x, start_axis=0, stop_axis=-1):
    x = _A(x)
    nd = x.ndim
    if nd == 0:
        return x.reshape(1)
    s, e = start_axis % nd, stop_axis % nd
    new_shape = x.shape[:s] + (-1,) + x.shape[e + 1:]
    return x.reshape(new_shape)


@primitive
def tile(x, repeat_times):
    return jnp.tile(_A(x), tuple(int(r) for r in repeat_times))


@primitive
def expand(x, shape):
    x = _A(x)
    shape = list(shape)
    # paddle allows -1 meaning "keep this dim"
    xs = (1,) * (len(shape) - x.ndim) + x.shape
    shape = [xs[i] if s == -1 else int(s) for i, s in enumerate(shape)]
    return jnp.broadcast_to(x, shape)


def expand_as(x, y):
    return expand(x, y.shape if isinstance(y, Tensor) else jnp.shape(y))


def broadcast_to(x, shape):
    return expand(x, shape)


def broadcast_tensors(inputs):
    shapes = [tuple(i.shape) for i in inputs]
    out_shape = np.broadcast_shapes(*shapes)
    return [expand(i, list(out_shape)) for i in inputs]


@primitive
def flip(x, axis):
    axes = axis if isinstance(axis, (list, tuple)) else [axis]
    return jnp.flip(_A(x), axis=tuple(int(a) for a in axes))


@primitive
def roll(x, shifts, axis=None):
    return jnp.roll(_A(x), shifts, axis=axis)


@primitive
def rot90(x, k=1, axes=(0, 1)):
    return jnp.rot90(_A(x), k=k, axes=tuple(axes))


@primitive
def gather(x, index, axis=0):
    return jnp.take(_A(x), _A(index).astype(jnp.int32), axis=int(axis))


@primitive
def index_select(x, index, axis=0):
    return jnp.take(_A(x), _A(index).astype(jnp.int32), axis=int(axis))


@primitive
def gather_nd(x, index):
    x, index = _A(x), _A(index).astype(jnp.int32)
    idx = tuple(jnp.moveaxis(index, -1, 0))
    return x[idx]


@primitive
def take_along_axis(x, indices, axis):
    return jnp.take_along_axis(_A(x), _A(indices).astype(jnp.int32), axis=int(axis))


@primitive
def put_along_axis(x, indices, values, axis, reduce="assign"):
    x = _A(x)
    indices = _A(indices).astype(jnp.int32)
    values = jnp.broadcast_to(_A(values), indices.shape).astype(x.dtype)
    dnums = [jnp.arange(s) for s in indices.shape]
    grids = jnp.meshgrid(*dnums, indexing="ij")
    idx = tuple(
        indices if d == axis % x.ndim else g for d, g in enumerate(grids)
    )
    if reduce == "assign":
        return x.at[idx].set(values)
    if reduce in ("add", "sum"):
        return x.at[idx].add(values)
    if reduce in ("mul", "multiply"):
        return x.at[idx].multiply(values)
    raise ValueError("unsupported reduce %r" % reduce)


@primitive
def scatter(x, index, updates, overwrite=True):
    x = _A(x)
    index = _A(index).astype(jnp.int32).reshape(-1)
    updates = _A(updates)
    if overwrite:
        return x.at[index].set(updates)
    # paddle overwrite=False: zero the rows then accumulate
    zeroed = x.at[index].set(jnp.zeros_like(updates))
    return zeroed.at[index].add(updates)


@primitive
def scatter_nd_add(x, index, updates):
    x = _A(x)
    index = _A(index).astype(jnp.int32)
    idx = tuple(jnp.moveaxis(index, -1, 0))
    return x.at[idx].add(_A(updates))


def scatter_nd(index, updates, shape):
    from .creation import zeros

    base = zeros(shape, dtype=updates.dtype)
    return scatter_nd_add(base, index, updates)


@primitive
def where(condition, x=None, y=None):
    return jnp.where(_A(condition), _A(x), _A(y))


@primitive
def masked_fill(x, mask, value):
    return jnp.where(_A(mask), value, _A(x))


def masked_select(x, mask):
    """Data-dependent output shape: executes on host (un-jitted), like the
    reference's masked_select (phi/kernels/masked_select_kernel.h)."""
    xv = np.asarray(x.numpy() if isinstance(x, Tensor) else x)
    mv = np.asarray(mask.numpy() if isinstance(mask, Tensor) else mask)
    return Tensor(jnp.asarray(xv[mv.astype(bool)]))


def nonzero(x, as_tuple=False):
    """Data-dependent output shape: host fallback (reference where_index)."""
    xv = np.asarray(x.numpy() if isinstance(x, Tensor) else x)
    nz = np.nonzero(xv)
    if as_tuple:
        return tuple(Tensor(jnp.asarray(n.astype(np.int64))) for n in nz)
    return Tensor(jnp.asarray(np.stack(nz, axis=1).astype(np.int64)))


def unique(x, return_index=False, return_inverse=False, return_counts=False, axis=None):
    xv = np.asarray(x.numpy() if isinstance(x, Tensor) else x)
    res = np.unique(
        xv,
        return_index=return_index,
        return_inverse=return_inverse,
        return_counts=return_counts,
        axis=axis,
    )
    if not isinstance(res, tuple):
        return Tensor(jnp.asarray(res))
    return tuple(Tensor(jnp.asarray(r)) for r in res)


@primitive
def sort(x, axis=-1, descending=False):
    out = jnp.sort(_A(x), axis=int(axis))
    if descending:
        out = jnp.flip(out, axis=int(axis))
    return out


@primitive(nondiff=True)
def argsort(x, axis=-1, descending=False):
    x = _A(x)
    out = jnp.argsort(x, axis=int(axis))
    if descending:
        out = jnp.flip(out, axis=int(axis))
    return out.astype(jnp.int64)


@primitive
def topk(x, k, axis=-1, largest=True, sorted=True):
    x = _A(x)
    axis = int(axis) % x.ndim
    xm = jnp.moveaxis(x, axis, -1)
    if largest:
        vals, idx = jax.lax.top_k(xm, int(k))
    else:
        vals, idx = jax.lax.top_k(-xm, int(k))
        vals = -vals
    return (
        jnp.moveaxis(vals, -1, axis),
        jnp.moveaxis(idx.astype(jnp.int64), -1, axis),
    )


def kthvalue(x, k, axis=-1, keepdim=False):
    vals = sort(x, axis=axis)
    idx = argsort(x, axis=axis)
    from . import manipulation as m

    sel_v = m.slice_(vals, axes=[axis], starts=[k - 1], ends=[k])
    sel_i = m.slice_(idx, axes=[axis], starts=[k - 1], ends=[k])
    if not keepdim:
        sel_v = squeeze(sel_v, axis=axis)
        sel_i = squeeze(sel_i, axis=axis)
    return sel_v, sel_i


@primitive(name="slice")
def slice_(x, axes, starts, ends):
    x = _A(x)
    idx = [slice(None)] * x.ndim
    for a, s, e in zip(axes, starts, ends):
        idx[a] = slice(int(s), int(e))
    return x[tuple(idx)]


@primitive
def strided_slice(x, axes, starts, ends, strides):
    x = _A(x)
    idx = [slice(None)] * x.ndim
    for a, s, e, st in zip(axes, starts, ends, strides):
        idx[a] = slice(int(s), int(e), int(st))
    return x[tuple(idx)]


@primitive
def pad(x, pad, mode="constant", value=0.0, data_format="NCHW"):
    x = _A(x)
    pad = [int(p) for p in pad]
    if len(pad) == 2 * x.ndim:
        widths = [(pad[2 * i], pad[2 * i + 1]) for i in range(x.ndim)]
    else:
        # paddle convention: pad applies to the *last* len(pad)//2 spatial dims
        # (reversed pairs), e.g. NCHW with pad=[l,r,t,b]
        n_spatial = len(pad) // 2
        pairs = [(pad[2 * i], pad[2 * i + 1]) for i in range(n_spatial)]
        if data_format in ("NHWC", "NLC", "NDHWC"):
            # channel-last: the padded dims are the MIDDLE spatial axes,
            # channels stay untouched
            widths = ([(0, 0)] * (x.ndim - n_spatial - 1)
                      + list(reversed(pairs)) + [(0, 0)])
        else:
            widths = ([(0, 0)] * (x.ndim - n_spatial)
                      + list(reversed(pairs)))
    jmode = {"constant": "constant", "reflect": "reflect", "replicate": "edge",
             "circular": "wrap"}[mode]
    if jmode == "constant":
        return jnp.pad(x, widths, mode=jmode, constant_values=value)
    return jnp.pad(x, widths, mode=jmode)


@primitive
def repeat_interleave(x, repeats, axis=None):
    x = _A(x)
    if axis is None:
        x = x.reshape(-1)
        axis = 0
    r = repeats if isinstance(repeats, int) else _A(repeats)
    total = None
    if not isinstance(repeats, int):
        total = int(np.sum(np.asarray(repeats)))
    return jnp.repeat(x, r, axis=int(axis), total_repeat_length=total)


@primitive
def moveaxis(x, source, destination):
    return jnp.moveaxis(_A(x), source, destination)


@primitive
def swapaxes(x, axis0, axis1):
    return jnp.swapaxes(_A(x), int(axis0), int(axis1))


@primitive(nondiff=True)
def searchsorted(sorted_sequence, values, out_int32=False, right=False):
    out = jnp.searchsorted(
        _A(sorted_sequence), _A(values), side="right" if right else "left"
    )
    return out.astype(jnp.int32 if out_int32 else jnp.int64)


@primitive(nondiff=True)
def bucketize(x, sorted_sequence, out_int32=False, right=False):
    out = jnp.searchsorted(
        _A(sorted_sequence), _A(x), side="right" if right else "left"
    )
    return out.astype(jnp.int32 if out_int32 else jnp.int64)


@primitive(nondiff=True)
def one_hot(x, num_classes):
    return jax.nn.one_hot(_A(x).astype(jnp.int32), int(num_classes), dtype=jnp.float32)


@primitive
def index_add(x, index, axis, value):
    x = _A(x)
    index = _A(index).astype(jnp.int32)
    value = _A(value)
    x_m = jnp.moveaxis(x, axis, 0)
    v_m = jnp.moveaxis(value, axis, 0)
    out = x_m.at[index].add(v_m)
    return jnp.moveaxis(out, 0, axis)


@primitive
def index_put(x, indices, value, accumulate=False):
    x = _A(x)
    idx = tuple(_A(i) for i in indices)
    if accumulate:
        return x.at[idx].add(_A(value))
    return x.at[idx].set(jnp.broadcast_to(_A(value), x[idx].shape).astype(x.dtype))


@primitive
def as_strided(x, shape, stride, offset=0):
    x = _A(x).reshape(-1)
    idx = jnp.arange(int(np.prod(shape))).reshape(shape)
    flat = offset
    coords = jnp.unravel_index(idx.reshape(-1), shape)
    lin = offset + sum(c * s for c, s in zip(coords, stride))
    return x[lin].reshape(shape)


@primitive
def diff(x, n=1, axis=-1):
    return jnp.diff(_A(x), n=n, axis=axis)


@primitive
def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1):
    """im2col (reference phi/kernels/unfold_kernel). x: [N,C,H,W]."""
    x = _A(x)
    ks = kernel_sizes if isinstance(kernel_sizes, (list, tuple)) else [kernel_sizes] * 2
    st = strides if isinstance(strides, (list, tuple)) else [strides] * 2
    pd = paddings if isinstance(paddings, (list, tuple)) else [paddings] * 2
    dl = dilations if isinstance(dilations, (list, tuple)) else [dilations] * 2
    if len(pd) == 2:
        pd = [pd[0], pd[0], pd[1], pd[1]]
    N, C, H, W = x.shape
    x = jnp.pad(x, ((0, 0), (0, 0), (pd[0], pd[1]), (pd[2], pd[3])))
    patches = jax.lax.conv_general_dilated_patches(
        x, filter_shape=tuple(ks), window_strides=tuple(st),
        padding="VALID", rhs_dilation=tuple(dl),
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
    )
    n, ckk, oh, ow = patches.shape
    return patches.reshape(n, ckk, oh * ow)


# -- long-tail manipulation ops (VERDICT r1 item 8) -------------------------

@primitive
def unstack(x, axis=0, num=None):
    """Split along axis into unit slices, squeezing the axis (reference
    unstack_kernel). Returns a tuple of num arrays."""
    x = _A(x)
    n = x.shape[axis] if num is None else num
    return tuple(jnp.squeeze(s, axis)
                 for s in jnp.split(x, n, axis=axis))


@primitive
def reverse(x, axis):
    """reference reverse_kernel (alias family of flip)."""
    axes = axis if isinstance(axis, (list, tuple)) else [axis]
    return jnp.flip(_A(x), axis=tuple(axes))


@primitive
def fill(x, value):
    """Full overwrite with a scalar (reference fill_kernel); functional
    result (assign to .set_value for in-place API compat)."""
    x = _A(x)
    return jnp.full(x.shape, value, x.dtype)


@primitive
def fill_diagonal(x, value, offset=0, wrap=False):
    """reference fill_diagonal_kernel: write `value` on the diagonal."""
    x = _A(x)
    if x.ndim == 2:
        rows, cols = x.shape
        i = jnp.arange(rows)[:, None]
        j = jnp.arange(cols)[None, :]
        mask = (j - i) == offset
        if wrap and rows > cols:
            # wrapped diagonals restart every (cols + 1) rows
            mask = ((i - j) % (cols + 1)) == (-offset % (cols + 1))
        return jnp.where(mask, jnp.asarray(value, x.dtype), x)
    # n-d: all dims equal; fill positions where all indices match
    # (the reference kernel only defines offset/wrap for 2-D inputs)
    if offset != 0 or wrap:
        raise ValueError(
            "fill_diagonal: offset/wrap are only supported for 2-D "
            "inputs (got ndim=%d)" % x.ndim)
    grids = jnp.indices(x.shape)
    mask = jnp.ones(x.shape, bool)
    for g in grids[1:]:
        mask &= grids[0] == g
    return jnp.where(mask, jnp.asarray(value, x.dtype), x)


@primitive
def diag_embed(x, offset=0, dim1=-2, dim2=-1):
    """Embed the last dim as a diagonal of a new 2D tail (reference
    diag_embed_kernel)."""
    x = _A(x)
    n = x.shape[-1] + abs(offset)
    out_shape = x.shape[:-1] + (n, n)
    out = jnp.zeros(out_shape, x.dtype)
    i = jnp.arange(x.shape[-1])
    rows = i + max(-offset, 0)
    cols = i + max(offset, 0)
    out = out.at[..., rows, cols].set(x)
    # place the new axes at dim1/dim2
    nd = out.ndim
    d1 = dim1 % nd
    d2 = dim2 % nd
    if (d1, d2) != (nd - 2, nd - 1):
        perm = [a for a in range(nd) if a not in (nd - 2, nd - 1)]
        order = sorted([(d1, nd - 2), (d2, nd - 1)])
        for pos, src in order:
            perm.insert(pos, src)
        out = jnp.transpose(out, perm)
    return out


@primitive
def multiplex(inputs, index):
    """Row-wise select among candidate tensors (reference
    multiplex_kernel): out[i] = inputs[index[i]][i]."""
    stack = jnp.stack([_A(t) for t in inputs], axis=0)  # [K, N, ...]
    idx = _A(index).reshape(-1).astype(jnp.int32)
    rows = jnp.arange(stack.shape[1])
    return stack[idx, rows]


@primitive
def index_sample(x, index):
    """Per-row gather (reference index_sample_kernel):
    out[i, j] = x[i, index[i, j]]."""
    return jnp.take_along_axis(_A(x), _A(index).astype(jnp.int32), axis=1)


@primitive(nondiff=True)
def unique_consecutive(x, return_inverse=False, return_counts=False,
                       axis=None):
    """Deduplicate consecutive runs (reference unique_consecutive_kernel).

    TPU note: output size is data-dependent; like the reference CPU
    kernel this is a host-side op (eager only, documented)."""
    import numpy as np

    xv = np.asarray(_A(x))
    if axis is None:
        flat = xv.reshape(-1)
        keep = np.ones(flat.shape[0], bool)
        keep[1:] = flat[1:] != flat[:-1]
        out = flat[keep]
        outs = [jnp.asarray(out)]
        if return_inverse:
            inv = np.cumsum(keep) - 1
            outs.append(jnp.asarray(inv))
        if return_counts:
            pos = np.flatnonzero(keep)
            counts = np.diff(np.append(pos, flat.shape[0]))
            outs.append(jnp.asarray(counts))
        return tuple(outs) if len(outs) > 1 else outs[0]
    moved = np.moveaxis(xv, axis, 0)
    keep = np.ones(moved.shape[0], bool)
    keep[1:] = np.any(
        moved[1:].reshape(moved.shape[0] - 1, -1)
        != moved[:-1].reshape(moved.shape[0] - 1, -1), axis=1)
    out = np.moveaxis(moved[keep], 0, axis)
    outs = [jnp.asarray(out)]
    if return_inverse:
        outs.append(jnp.asarray(np.cumsum(keep) - 1))
    if return_counts:
        pos = np.flatnonzero(keep)
        outs.append(jnp.asarray(np.diff(np.append(pos, moved.shape[0]))))
    return tuple(outs) if len(outs) > 1 else outs[0]


@primitive
def fill_diagonal_tensor(x, y, offset=0, dim1=0, dim2=1):
    """Write tensor `y` along the (dim1, dim2) diagonal of x (reference
    fill_diagonal_tensor_kernel)."""
    x = _A(x)
    y = _A(y)
    moved = jnp.moveaxis(x, (dim1, dim2), (-2, -1))
    rows, cols = moved.shape[-2], moved.shape[-1]
    n = min(rows - max(-offset, 0), cols - max(offset, 0))
    i = jnp.arange(n)
    r = i + max(-offset, 0)
    c = i + max(offset, 0)
    # y's diagonal entries land on the trailing axis
    yv = jnp.moveaxis(y, -1, -1).astype(x.dtype)
    out = moved.at[..., r, c].set(yv)
    return jnp.moveaxis(out, (-2, -1), (dim1, dim2))
