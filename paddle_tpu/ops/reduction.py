"""Reduction ops.

Parity: reference python/paddle/tensor/math.py (sum/mean/...) and
phi/kernels/reduce_*. XLA lowers these to MXU/VPU-friendly tree reductions;
the reference's KernelPrimitive reduce machinery is unnecessary.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.dispatch import primitive

_A = jnp.asarray


def _norm_axis(axis):
    if axis is None:
        return None
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


def _reduce(name, fn, nondiff=False):
    @primitive(name=name, nondiff=nondiff)
    def op(x, axis=None, keepdim=False):
        return fn(_A(x), axis=_norm_axis(axis), keepdims=keepdim)

    return op


sum_ = _reduce("sum", jnp.sum)
mean = _reduce("mean", jnp.mean)
prod = _reduce("prod", jnp.prod)
max_ = _reduce("max", jnp.max)
min_ = _reduce("min", jnp.min)
amax = _reduce("amax", jnp.max)
amin = _reduce("amin", jnp.min)
nansum = _reduce("nansum", jnp.nansum)
nanmean = _reduce("nanmean", jnp.nanmean)
all_ = _reduce("all", jnp.all, nondiff=True)
any_ = _reduce("any", jnp.any, nondiff=True)


def sum(x, axis=None, keepdim=False, dtype=None):  # noqa: A001
    out = sum_(x, axis=axis, keepdim=keepdim)
    if dtype is not None:
        from .math import cast

        out = cast(out, dtype=dtype)
    return out


def max(x, axis=None, keepdim=False):  # noqa: A001
    return max_(x, axis=axis, keepdim=keepdim)


def min(x, axis=None, keepdim=False):  # noqa: A001
    return min_(x, axis=axis, keepdim=keepdim)


def all(x, axis=None, keepdim=False):  # noqa: A001
    return all_(x, axis=axis, keepdim=keepdim)


def any(x, axis=None, keepdim=False):  # noqa: A001
    return any_(x, axis=axis, keepdim=keepdim)


@primitive
def std(x, axis=None, unbiased=True, keepdim=False):
    return jnp.std(_A(x), axis=_norm_axis(axis), ddof=1 if unbiased else 0, keepdims=keepdim)


@primitive
def var(x, axis=None, unbiased=True, keepdim=False):
    return jnp.var(_A(x), axis=_norm_axis(axis), ddof=1 if unbiased else 0, keepdims=keepdim)


@primitive
def logsumexp(x, axis=None, keepdim=False):
    return jax.scipy.special.logsumexp(_A(x), axis=_norm_axis(axis), keepdims=keepdim)


@primitive
def median(x, axis=None, keepdim=False):
    return jnp.median(_A(x), axis=_norm_axis(axis), keepdims=keepdim)


@primitive
def quantile(x, q, axis=None, keepdim=False):
    return jnp.quantile(_A(x), jnp.asarray(q), axis=_norm_axis(axis), keepdims=keepdim)


@primitive(nondiff=True)
def argmax(x, axis=None, keepdim=False, dtype="int64"):
    from ..core import dtype as _dt

    x = _A(x)
    if axis is None:
        out = jnp.argmax(x.reshape(-1), axis=0)
        if keepdim:
            out = out.reshape((1,) * x.ndim)
        return out.astype(_dt.to_jax(dtype))
    out = jnp.argmax(x, axis=int(axis), keepdims=keepdim)
    return out.astype(_dt.to_jax(dtype))


@primitive(nondiff=True)
def argmin(x, axis=None, keepdim=False, dtype="int64"):
    from ..core import dtype as _dt

    x = _A(x)
    if axis is None:
        out = jnp.argmin(x.reshape(-1), axis=0)
        if keepdim:
            out = out.reshape((1,) * x.ndim)
        return out.astype(_dt.to_jax(dtype))
    out = jnp.argmin(x, axis=int(axis), keepdims=keepdim)
    return out.astype(_dt.to_jax(dtype))


@primitive(nondiff=True)
def count_nonzero(x, axis=None, keepdim=False):
    return jnp.count_nonzero(_A(x), axis=_norm_axis(axis), keepdims=keepdim).astype(jnp.int64)
