"""Top-level tensor-API completions.

Parity: the remaining reference `paddle.*` __all__ names (python/paddle/
__init__.py) — complex views, integer math, index grids, sharding
helpers, and the in-place spellings. Each cites its reference module.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..core.dispatch import primitive
from ..core.tensor import Tensor

_A = jnp.asarray


def _v(x):
    return x._value if isinstance(x, Tensor) else jnp.asarray(x)


# add_n / angle / gcd / lcm / imag are long-registered primitives in
# ops/math.py — re-exported here so the extras module mirrors the
# reference tensor-API file layout without double-registering
from .math import add_n, angle, gcd, imag, lcm  # noqa: F401


@primitive
def as_complex(x):
    """[..., 2] float -> [...] complex (reference tensor/manipulation.py
    as_complex)."""
    v = _A(x)
    return jax.lax.complex(v[..., 0], v[..., 1])


@primitive
def as_real(x):
    """[...] complex -> [..., 2] float (reference as_real)."""
    v = _A(x)
    return jnp.stack([jnp.real(v), jnp.imag(v)], axis=-1)


@primitive
def complex(real, imag):  # noqa: A001
    """reference tensor/creation.py complex."""
    return jax.lax.complex(_A(real).astype(jnp.float32),
                           _A(imag).astype(jnp.float32))


@primitive
def sgn(x):
    """Complex-aware sign: x/|x| for complex, sign(x) for real
    (reference tensor/math.py sgn)."""
    v = _A(x)
    if jnp.issubdtype(v.dtype, jnp.complexfloating):
        mag = jnp.abs(v)
        return jnp.where(mag == 0, 0, v / jnp.where(mag == 0, 1, mag))
    return jnp.sign(v)


def broadcast_shape(x_shape, y_shape):
    """reference tensor/manipulation.py broadcast_shape."""
    return list(np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


@primitive
def floor_mod(x, y):
    """Alias of mod (reference exposes both spellings)."""
    return jnp.mod(_A(x), _A(y))


@primitive
def frexp(x):
    """Mantissa/exponent decomposition (reference tensor/math.py frexp):
    x = m * 2**e with 0.5 <= |m| < 1."""
    m, e = jnp.frexp(_A(x))
    return m, e.astype(jnp.int32)


@primitive
def nanquantile(x, q, axis=None, keepdim=False):
    """reference tensor/stat.py nanquantile."""
    return jnp.nanquantile(_A(x).astype(jnp.float32), q, axis=axis,
                           keepdims=keepdim)


@primitive(nondiff=True)
def poisson(x):
    """Per-element Poisson draws with rate x (reference tensor/random.py
    poisson)."""
    from ..framework import random as _random

    key = _random.next_key()
    return jax.random.poisson(key, _A(x)).astype(_A(x).dtype)


@primitive(nondiff=True)
def randint_like(x, low=0, high=None, dtype=None):
    """reference tensor/creation.py randint_like."""
    from ..framework import random as _random

    v = _A(x)
    if high is None:
        low, high = 0, low
    key = _random.next_key()
    # reference randint_like: result dtype follows x (float inputs get
    # float results) unless overridden
    out_dtype = jnp.dtype(dtype) if dtype is not None else v.dtype
    return jax.random.randint(key, v.shape, low, high).astype(out_dtype)


@primitive
def take(x, index, mode="raise"):
    """Flat-index gather (reference tensor/math.py take): mode 'raise'
    validates eagerly (concrete indices only), 'wrap'/'clip' follow
    numpy semantics."""
    v = _A(x).reshape(-1)
    idx = _A(index).astype(jnp.int32)
    n = v.shape[0]
    if mode == "wrap":
        idx = ((idx % n) + n) % n
    elif mode == "clip":
        idx = jnp.clip(idx, 0, n - 1)
    elif mode == "raise":
        try:
            bad = bool(((idx < -n) | (idx >= n)).any())
        except jax.errors.TracerBoolConversionError:
            bad = False  # traced: cannot validate; clamp like XLA gather
        if bad:
            raise IndexError(
                "take(mode='raise'): index out of range for %d elements"
                % n)
        idx = jnp.where(idx < 0, idx + n, idx)
    else:
        raise ValueError("take: unknown mode %r" % (mode,))
    return v[idx]


def tril_indices(row, col=None, offset=0, dtype="int64"):
    """reference tensor/creation.py tril_indices -> [2, n] tensor."""
    col = col if col is not None else row
    r, c = np.tril_indices(row, offset, col)
    return Tensor(jnp.asarray(np.stack([r, c]), dtype))


def triu_indices(row, col=None, offset=0, dtype="int64"):
    col = col if col is not None else row
    r, c = np.triu_indices(row, offset, col)
    return Tensor(jnp.asarray(np.stack([r, c]), dtype))


def vsplit(x, num_or_sections):
    """Split along dim 0 (reference tensor/manipulation.py vsplit);
    delegates to split, which already resolves -1 ('rest') sections."""
    from .manipulation import split

    return split(x, num_or_sections, axis=0)


@primitive
def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    """Relabel class ids to a shard-local range (reference
    tensor/manipulation.py:577): ids inside shard_id's range become
    id - shard_id*shard_size, others ignore_value."""
    if not 0 <= shard_id < nshards:
        raise ValueError(
            "shard_id (%d) must be in [0, %d)" % (shard_id, nshards))
    v = _A(input)
    shard_size = (index_num + nshards - 1) // nshards
    lo = shard_id * shard_size
    hi = lo + shard_size
    inside = (v >= lo) & (v < hi)
    return jnp.where(inside, v - lo, ignore_value)


def shape(x):
    """Shape as an int32 tensor (reference tensor/attribute.py shape —
    the op form, not the python list property)."""
    return Tensor(jnp.asarray(_v(x).shape, jnp.int32))


def rank(x):
    return Tensor(jnp.asarray(_v(x).ndim))


def is_complex(x):
    return bool(jnp.issubdtype(_v(x).dtype, jnp.complexfloating))


def is_floating_point(x):
    return bool(jnp.issubdtype(_v(x).dtype, jnp.floating))


def is_integer(x):
    return bool(jnp.issubdtype(_v(x).dtype, jnp.integer))


def tolist(x):
    """reference tensor/manipulation.py tolist."""
    return np.asarray(_v(x)).tolist()


def iinfo(dtype):
    """reference paddle.iinfo over the int dtypes."""
    return jnp.iinfo(jnp.dtype(dtype))


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    """reference paddle.set_printoptions: display knobs for printed
    tensors (host-side numpy printing here)."""
    kw = {}
    if precision is not None:
        kw["precision"] = precision
    if threshold is not None:
        kw["threshold"] = threshold
    if edgeitems is not None:
        kw["edgeitems"] = edgeitems
    if linewidth is not None:
        kw["linewidth"] = linewidth
    if sci_mode is not None:
        kw["suppress"] = not sci_mode
    np.set_printoptions(**kw)


def check_shape(shape):  # noqa: A002
    """Validate a shape spec (reference fluid/data_feeder.py:185):
    entries must be ints (or -1 for deferred dims)."""
    for s in shape:
        if not isinstance(s, (int, np.integer)):
            raise TypeError(
                "shape entries must be integers, got %r" % (s,))
        if s < -1 or s == 0:
            raise ValueError(
                "shape entries must be positive or -1, got %d" % s)
    return True


@primitive
def crop(x, shape=None, offsets=None, name=None):
    """Slice a sub-box (reference tensor/creation.py crop / phi
    crop_kernel): offsets default 0, shape entries -1 mean 'to the
    end'."""
    v = _A(x)
    shp = list(shape) if shape is not None else list(v.shape)
    offs = list(offsets) if offsets is not None else [0] * v.ndim
    sizes = [v.shape[i] - offs[i] if shp[i] == -1 else shp[i]
             for i in range(v.ndim)]
    for i in range(v.ndim):
        if offs[i] + sizes[i] > v.shape[i]:
            # dynamic_slice would silently clamp the start — fail loud
            # like the reference's offset+size <= dim check
            raise ValueError(
                "crop: offsets[%d] + shape[%d] (%d) exceeds input dim %d"
                % (i, i, offs[i] + sizes[i], v.shape[i]))
    return jax.lax.dynamic_slice(v, offs, sizes)


def disable_signal_handler():
    """reference paddle.disable_signal_handler: the TPU runtime installs
    no custom signal handlers, so this is a documented no-op."""


def _make_inplace(fn_name, fn):
    def op(x, *args, **kwargs):
        out = fn(x, *args, **kwargs)
        if isinstance(x, Tensor):
            x._value = out._value if isinstance(out, Tensor) else _A(out)
            return x
        return out

    op.__name__ = fn_name
    op.__doc__ = ("In-place spelling of %s (reference *_ ops mutate "
                  "the input Tensor)." % fn_name.rstrip("_"))
    return op
