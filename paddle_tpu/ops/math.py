"""Elementwise & general math ops.

Parity targets: reference python/paddle/tensor/math.py and the PHI kernels in
/root/reference/paddle/phi/kernels/ (elementwise_*, activation, scale, ...).
Every op is a pure jnp/lax expression — XLA fuses chains of these into single
HBM-bandwidth-bound kernels, which is the TPU answer to the reference's
hand-fused CUDA elementwise kernels (kernels/funcs/elementwise_base.h).
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..core.dispatch import primitive
from ..core.tensor import Tensor

_A = jnp.asarray


def _binop(name, fn):
    @primitive(name=name)
    def op(x, y):
        return fn(_A(x), _A(y))

    return op


add = _binop("add", jnp.add)
subtract = _binop("subtract", jnp.subtract)
multiply = _binop("multiply", jnp.multiply)
divide = _binop("divide", jnp.true_divide)
floor_divide = _binop("floor_divide", jnp.floor_divide)
remainder = _binop("remainder", jnp.remainder)
mod = remainder
floor_mod = remainder
maximum = _binop("maximum", jnp.maximum)
minimum = _binop("minimum", jnp.minimum)
fmax = _binop("fmax", jnp.fmax)
fmin = _binop("fmin", jnp.fmin)
pow_ = _binop("pow", jnp.power)
atan2 = _binop("atan2", jnp.arctan2)
heaviside = _binop("heaviside", jnp.heaviside)
nextafter = _binop("nextafter", jnp.nextafter)
hypot = _binop("hypot", jnp.hypot)
copysign = _binop("copysign", jnp.copysign)
gcd = _binop("gcd", jnp.gcd)
lcm = _binop("lcm", jnp.lcm)
logaddexp = _binop("logaddexp", jnp.logaddexp)


def pow(x, y):  # noqa: A001 — paddle.pow
    return pow_(x, y)


def _unop(name, fn):
    @primitive(name=name)
    def op(x):
        return fn(_A(x))

    return op


abs = _unop("abs", jnp.abs)  # noqa: A001
neg = _unop("neg", jnp.negative)
exp = _unop("exp", jnp.exp)
expm1 = _unop("expm1", jnp.expm1)
log = _unop("log", jnp.log)
log2 = _unop("log2", jnp.log2)
log10 = _unop("log10", jnp.log10)
log1p = _unop("log1p", jnp.log1p)
sqrt = _unop("sqrt", jnp.sqrt)
rsqrt = _unop("rsqrt", lambda x: jax.lax.rsqrt(x))
square = _unop("square", jnp.square)
sin = _unop("sin", jnp.sin)
cos = _unop("cos", jnp.cos)
tan = _unop("tan", jnp.tan)
asin = _unop("asin", jnp.arcsin)
acos = _unop("acos", jnp.arccos)
atan = _unop("atan", jnp.arctan)
sinh = _unop("sinh", jnp.sinh)
cosh = _unop("cosh", jnp.cosh)
tanh = _unop("tanh", jnp.tanh)
asinh = _unop("asinh", jnp.arcsinh)
acosh = _unop("acosh", jnp.arccosh)
atanh = _unop("atanh", jnp.arctanh)
floor = _unop("floor", jnp.floor)
ceil = _unop("ceil", jnp.ceil)
round_ = _unop("round", jnp.round)
trunc = _unop("trunc", jnp.trunc)
frac = _unop("frac", lambda x: x - jnp.trunc(x))
sign = _unop("sign", jnp.sign)
reciprocal = _unop("reciprocal", jnp.reciprocal)
erf = _unop("erf", jax.scipy.special.erf)
erfinv = _unop("erfinv", jax.scipy.special.erfinv)
lgamma = _unop("lgamma", jax.scipy.special.gammaln)
digamma = _unop("digamma", jax.scipy.special.digamma)
i0 = _unop("i0", jnp.i0)
sigmoid = _unop("sigmoid", jax.nn.sigmoid)
rad2deg = _unop("rad2deg", jnp.rad2deg)
deg2rad = _unop("deg2rad", jnp.deg2rad)
angle = _unop("angle", jnp.angle)
conj = _unop("conj", jnp.conj)
real = _unop("real", jnp.real)
imag = _unop("imag", jnp.imag)


def round(x):  # noqa: A001
    return round_(x)


@primitive
def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None):
    x = _A(x)
    out = x * scale + bias if bias_after_scale else (x + bias) * scale
    return out


@primitive
def clip(x, min=None, max=None):
    return jnp.clip(_A(x), min, max)


@primitive
def lerp(x, y, weight):
    x, y = _A(x), _A(y)
    return x + _A(weight) * (y - x)


@primitive
def stanh(x, scale_a=0.67, scale_b=1.7159):
    return scale_b * jnp.tanh(scale_a * _A(x))


@primitive
def logit(x, eps=None):
    x = _A(x)
    if eps is not None:
        x = jnp.clip(x, eps, 1.0 - eps)
    return jnp.log(x / (1.0 - x))


@primitive
def multiply_add(x, y, z):
    return _A(x) * _A(y) + _A(z)


@primitive
def addmm(input, x, y, beta=1.0, alpha=1.0):
    return beta * _A(input) + alpha * jnp.matmul(_A(x), _A(y))


@primitive
def matmul(x, y, transpose_x=False, transpose_y=False):
    x, y = _A(x), _A(y)
    if transpose_x:
        x = jnp.swapaxes(x, -1, -2) if x.ndim > 1 else x
    if transpose_y:
        y = jnp.swapaxes(y, -1, -2) if y.ndim > 1 else y
    return jnp.matmul(x, y)


@primitive
def dot(x, y):
    x, y = _A(x), _A(y)
    return jnp.sum(x * y, axis=-1)


@primitive
def mm(x, y):
    return jnp.matmul(_A(x), _A(y))


@primitive
def bmm(x, y):
    return jnp.matmul(_A(x), _A(y))


@primitive
def mv(x, vec):
    return jnp.matmul(_A(x), _A(vec))


@primitive
def inner(x, y):
    return jnp.inner(_A(x), _A(y))


@primitive
def outer(x, y):
    return jnp.outer(_A(x), _A(y))


@primitive
def kron(x, y):
    return jnp.kron(_A(x), _A(y))


@primitive
def cross(x, y, axis=9):
    ax = axis if axis != 9 else (next(
        (i for i, s in enumerate(jnp.shape(_A(x))) if s == 3), -1))
    return jnp.cross(_A(x), _A(y), axis=ax)


@primitive
def trace(x, offset=0, axis1=0, axis2=1):
    return jnp.trace(_A(x), offset=offset, axis1=axis1, axis2=axis2)


@primitive
def diagonal(x, offset=0, axis1=0, axis2=1):
    return jnp.diagonal(_A(x), offset=offset, axis1=axis1, axis2=axis2)


@primitive
def cumsum(x, axis=None, dtype=None):
    x = _A(x)
    if axis is None:
        x = x.reshape(-1)
        axis = 0
    return jnp.cumsum(x, axis=axis)


@primitive
def cumprod(x, dim=None, dtype=None):
    x = _A(x)
    if dim is None:
        x = x.reshape(-1)
        dim = 0
    return jnp.cumprod(x, axis=dim)


@primitive
def cummax_values(x, axis=-1):
    return jax.lax.cummax(_A(x), axis=axis)


@primitive
def cummin_values(x, axis=-1):
    return jax.lax.cummin(_A(x), axis=axis)


@primitive
def nan_to_num(x, nan=0.0, posinf=None, neginf=None):
    return jnp.nan_to_num(_A(x), nan=nan, posinf=posinf, neginf=neginf)


# non-differentiable predicates
@primitive(nondiff=True)
def isnan(x):
    return jnp.isnan(_A(x))


@primitive(nondiff=True)
def isinf(x):
    return jnp.isinf(_A(x))


@primitive(nondiff=True)
def isfinite(x):
    return jnp.isfinite(_A(x))


@primitive
def increment(x, value=1.0):
    return _A(x) + value


@primitive
def cast(x, dtype):
    from ..core import dtype as _dt

    return _A(x).astype(_dt.to_jax(dtype))


def astype(x, dtype):
    return cast(x, dtype=dtype)


# -- long-tail math ops (VERDICT r1 item 8; reference phi/kernels/) ---------

@primitive
def logcumsumexp(x, axis=-1):
    """reference phi/kernels/*logcumsumexp* — numerically stable running
    log-sum-exp along `axis` (fp32 statistics, input dtype result)."""
    x = _A(x)
    xf = x.astype(jnp.float32) if x.dtype == jnp.float16 else x
    return jax.lax.cumlogsumexp(xf, axis=axis).astype(x.dtype)


@primitive
def dist(x, y, p=2.0):
    """p-norm of (x - y) (reference phi/kernels/dist_kernel.h)."""
    d = jnp.abs(_A(x) - _A(y)).astype(jnp.float32)
    if p == float("inf"):
        return jnp.max(d).astype(_A(x).dtype)
    if p == 0:
        return jnp.sum((d != 0).astype(jnp.float32)).astype(_A(x).dtype)
    return (jnp.sum(d ** p) ** (1.0 / p)).astype(_A(x).dtype)


@primitive
def renorm(x, p, axis, max_norm):
    """Per-slice p-norm clamp along `axis` (reference renorm_kernel)."""
    x = _A(x)
    moved = jnp.moveaxis(x, axis, 0)
    flat = moved.reshape(moved.shape[0], -1).astype(jnp.float32)
    norms = jnp.sum(jnp.abs(flat) ** p, axis=1) ** (1.0 / p)
    scale = jnp.where(norms > max_norm,
                      max_norm / jnp.maximum(norms, 1e-12), 1.0)
    out = flat * scale[:, None]
    return jnp.moveaxis(out.reshape(moved.shape), 0, axis).astype(x.dtype)


@primitive(nondiff=True)
def mode(x, axis=-1, keepdim=False):
    """Most frequent value + its (last) index along axis (reference
    mode_kernel). Returns (values, indices)."""
    x = _A(x)
    sorted_x = jnp.sort(x, axis=axis)
    n = x.shape[axis]

    def counts_of(v):
        return jnp.sum(x == v, axis=axis, keepdims=True)

    # count occurrences of each sorted candidate, take the max-count value
    cand = jnp.moveaxis(sorted_x, axis, 0)  # [n, ...]
    xs = jnp.moveaxis(x, axis, 0)
    cnt = jnp.sum(cand[:, None] == xs[None], axis=1)  # [n, ...]
    # tie-break toward the LARGEST value (paddle mode_kernel scans sorted
    # order and keeps the last max-count run)
    best = (n - 1) - jnp.argmax(cnt[::-1], axis=0)
    values = jnp.take_along_axis(cand, best[None], axis=0)[0]
    # paddle returns the LAST index where the value occurs
    idx_grid = jnp.arange(n).reshape((n,) + (1,) * (x.ndim - 1))
    match = xs == values[None]
    indices = jnp.max(jnp.where(match, idx_grid, -1), axis=0)
    if keepdim:
        values = jnp.expand_dims(values, axis)
        indices = jnp.expand_dims(indices, axis)
    return values, indices.astype(jnp.int64)


@primitive
def nanmedian(x, axis=None, keepdim=False):
    """Median ignoring NaNs (reference nanmedian_kernel)."""
    x = _A(x)
    return jnp.nanmedian(x, axis=axis, keepdims=keepdim).astype(x.dtype)


@primitive
def squared_l2_norm(x):
    """sum(x^2) (reference squared_l2_norm_kernel — grad-clip hot path)."""
    xf = _A(x).astype(jnp.float32)
    return jnp.sum(xf * xf)


@primitive
def clip_by_norm(x, max_norm):
    """Scale x so ||x||_2 <= max_norm (reference clip_by_norm_kernel)."""
    x = _A(x)
    norm = jnp.sqrt(jnp.sum(x.astype(jnp.float32) ** 2))
    scale = jnp.where(norm > max_norm, max_norm / jnp.maximum(norm, 1e-12),
                      1.0)
    return (x * scale).astype(x.dtype)


@primitive
def add_n(inputs):
    """Sum a list of tensors (reference add_n_kernel — the grad
    accumulation op)."""
    if not isinstance(inputs, (list, tuple)):
        return _A(inputs)
    out = _A(inputs[0])
    for t in inputs[1:]:
        out = out + _A(t)
    return out


@primitive
def identity_loss(x, reduction="none"):
    """reference identity_loss_kernel (IPU-origin utility: reduce or pass
    through the input as a loss)."""
    x = _A(x)
    if reduction in ("mean", 0):
        return jnp.mean(x)
    if reduction in ("sum", 1):
        return jnp.sum(x)
    return x
