"""paddle.autograd namespace (reference python/paddle/autograd/)."""
from __future__ import annotations

from ..core.autograd import backward as _backward_impl
from ..core.autograd import grad  # noqa: F401
from ..core.dispatch import no_grad, enable_grad  # noqa: F401
from ..core.tensor import Tensor


def backward(tensors, grad_tensors=None, retain_graph=False):
    from ..core import autograd as eng

    tensors = tensors if isinstance(tensors, (list, tuple)) else [tensors]
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    import jax.numpy as jnp

    seeds = []
    for t, g in zip(tensors, grad_tensors):
        if g is None:
            seeds.append(jnp.ones(t._value.shape, t._value.dtype))
        else:
            seeds.append(g._value if isinstance(g, Tensor) else jnp.asarray(g))
    eng.run_backward(list(tensors), seeds, retain_graph=retain_graph)


class PyLayerContext:
    def __init__(self):
        self._saved = []
        self.attrs = {}

    def save_for_backward(self, *tensors):
        self._saved = list(tensors)

    def saved_tensor(self):
        return list(self._saved)


class PyLayer:
    """User-defined differentiable op (reference python/paddle/autograd/py_layer.py).

    Subclass with static `forward(ctx, *args)` and `backward(ctx, *grads)`.
    The backward is registered as a GradNode whose vjp calls the user code.
    """

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        from ..core import autograd as eng
        from ..core.dispatch import tape_enabled

        ctx = PyLayerContext()
        in_tensors = [a for a in args if isinstance(a, Tensor)]
        need_grad = tape_enabled() and any(
            not t.stop_gradient for t in in_tensors
        )
        with no_grad():
            outs = cls.forward(ctx, *args, **kwargs)
        single = not isinstance(outs, (tuple, list))
        outs_t = [outs] if single else list(outs)
        if need_grad:
            out_vals = [o._value for o in outs_t]

            def vjp_fn(cots):
                with no_grad():
                    gs = cls.backward(ctx, *[
                        Tensor(c) for c in cots
                    ])
                gs = [gs] if isinstance(gs, Tensor) else list(gs)
                out = []
                gi = iter(gs)
                for t in in_tensors:
                    g = next(gi, None)
                    out.append(None if g is None else g._value)
                return out

            node = eng.GradNode(
                cls.__name__, vjp_fn, in_tensors, out_vals
            )
            wrapped = eng.attach_node(out_vals, node)
            return wrapped[0] if single else list(wrapped)
        return outs
