"""paddle.autograd namespace (reference python/paddle/autograd/)."""
from __future__ import annotations

from ..core.autograd import backward as _backward_impl
from ..core.autograd import grad  # noqa: F401
from ..core.dispatch import no_grad, enable_grad  # noqa: F401
from ..core.tensor import Tensor


def backward(tensors, grad_tensors=None, retain_graph=False):
    from ..core import autograd as eng

    tensors = tensors if isinstance(tensors, (list, tuple)) else [tensors]
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    import jax.numpy as jnp

    seeds = []
    for t, g in zip(tensors, grad_tensors):
        if g is None:
            seeds.append(jnp.ones(t._value.shape, t._value.dtype))
        else:
            seeds.append(g._value if isinstance(g, Tensor) else jnp.asarray(g))
    eng.run_backward(list(tensors), seeds, retain_graph=retain_graph)


class PyLayerContext:
    def __init__(self):
        self._saved = []
        self.attrs = {}

    def save_for_backward(self, *tensors):
        from ..core.autograd import get_saved_tensor_hooks

        # the hooks ACTIVE AT SAVE TIME travel with the saved tensors
        # (reference semantics: backward may run after the hook scope)
        pack, self._unpack = get_saved_tensor_hooks()
        self._saved = [pack(t) if pack is not None else t
                       for t in tensors]

    def saved_tensor(self):
        unpack = getattr(self, "_unpack", None)
        return [unpack(t) if unpack is not None else t
                for t in self._saved]


class PyLayer:
    """User-defined differentiable op (reference python/paddle/autograd/py_layer.py).

    Subclass with static `forward(ctx, *args)` and `backward(ctx, *grads)`.
    The backward is registered as a GradNode whose vjp calls the user code.
    """

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        from ..core import autograd as eng
        from ..core.dispatch import tape_enabled

        ctx = PyLayerContext()
        in_tensors = [a for a in args if isinstance(a, Tensor)]
        need_grad = tape_enabled() and any(
            not t.stop_gradient for t in in_tensors
        )
        with no_grad():
            outs = cls.forward(ctx, *args, **kwargs)
        single = not isinstance(outs, (tuple, list))
        outs_t = [outs] if single else list(outs)
        if need_grad:
            out_vals = [o._value for o in outs_t]

            def vjp_fn(cots):
                with no_grad():
                    gs = cls.backward(ctx, *[
                        Tensor(c) for c in cots
                    ])
                gs = [gs] if isinstance(gs, Tensor) else list(gs)
                out = []
                gi = iter(gs)
                for t in in_tensors:
                    g = next(gi, None)
                    out.append(None if g is None else g._value)
                return out

            node = eng.GradNode(
                cls.__name__, vjp_fn, in_tensors, out_vals
            )
            wrapped = eng.attach_node(out_vals, node)
            return wrapped[0] if single else list(wrapped)
        return outs


class saved_tensors_hooks:
    """reference autograd.saved_tensors_hooks: intercept tensors saved
    for backward (pack on save, unpack on use) — the offload/compress
    hook point. The eager engine saves via vjp closures, so the hooks
    wrap Tensor residual registration in core.autograd."""

    def __init__(self, pack_hook, unpack_hook):
        self.pack_hook = pack_hook
        self.unpack_hook = unpack_hook

    def __enter__(self):
        from ..core import autograd as eng

        self._prev = eng.get_saved_tensor_hooks()
        eng.set_saved_tensor_hooks(self.pack_hook, self.unpack_hook)
        return self

    def __exit__(self, *exc):
        from ..core import autograd as eng

        eng.set_saved_tensor_hooks(*self._prev)  # nested scopes restore
        return False
