"""paddle.tensor.attribute (reference python/paddle/tensor/attribute.py):
tensor property queries."""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["rank", "shape", "is_complex", "is_floating_point",
           "is_integer", "real", "imag"]


def _v(x):
    return x._value if isinstance(x, Tensor) else jnp.asarray(x)


def rank(input):
    return Tensor(jnp.asarray(_v(input).ndim))


def shape(input):
    return list(_v(input).shape)


def is_complex(x):
    return jnp.issubdtype(_v(x).dtype, jnp.complexfloating)


def is_floating_point(x):
    return jnp.issubdtype(_v(x).dtype, jnp.floating)


def is_integer(x):
    return jnp.issubdtype(_v(x).dtype, jnp.integer)


def real(x):
    return Tensor(jnp.real(_v(x)))


def imag(x):
    return Tensor(jnp.imag(_v(x)))
