"""paddle.tensor namespace (reference python/paddle/tensor/): the op
modules grouped by kind. The TPU build defines ops in paddle_tpu.ops.*;
this namespace re-exports them under the reference's module names so
`paddle.tensor.creation.to_tensor`-style imports port unchanged."""
from ..ops import creation, linalg, manipulation, math, reduction  # noqa: F401
from ..ops import comparison as logic  # noqa: F401
from ..ops.creation import to_tensor  # noqa: F401
from ..ops.linalg import einsum  # noqa: F401
from ..ops.manipulation import (  # noqa: F401
    argsort,
    searchsorted,
    sort,
    topk,
    where,
)
from ..ops.reduction import argmax, argmin, mean, median, std, var  # noqa: F401

from . import attribute  # noqa: F401

# reference module aliases
search = manipulation
stat = reduction
random = creation
