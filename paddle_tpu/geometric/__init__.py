"""paddle.geometric — graph learning ops.

Parity: reference python/paddle/geometric/ (math.py segment_sum/mean/min/max
backed by phi segment_pool kernels; message_passing/ send_u_recv :24,
send_ue_recv, send_uv backed by graph_send_recv CUDA kernels; reindex.py;
sampling/neighbors.py sample_neighbors). TPU-native: segment reductions are
jax.ops.segment_* (XLA scatter-reduce, which TPU lowers onto the VPU);
device ops require an explicit/derivable segment count because XLA needs
static output shapes — `out_size` plays that role exactly as the reference's
optional out_size arg does. Host-side graph preprocessing (reindex,
neighbor sampling) runs in numpy like the reference's CPU kernels.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import primitive
from ..core.tensor import Tensor

__all__ = [
    "send_u_recv", "send_ue_recv", "send_uv",
    "segment_sum", "segment_mean", "segment_min", "segment_max",
    "reindex_graph", "reindex_heter_graph", "sample_neighbors",
]


def _num_segments(segment_ids, out_size):
    if out_size is not None:
        return int(out_size)
    ids = segment_ids.numpy() if isinstance(segment_ids, Tensor) \
        else np.asarray(segment_ids)
    return int(ids.max()) + 1 if ids.size else 0


def _reduce(msgs, ids, num_out, reduce_op):
    """Shared segment reduction. Empty segments yield 0 (the reference's
    convention) — detected by count, which also works for integer dtypes
    where the +/-inf sentinel check would not."""
    ids = jnp.asarray(ids).astype(jnp.int32)
    if reduce_op == "sum":
        return jax.ops.segment_sum(msgs, ids, num_out)
    cnt = jax.ops.segment_sum(jnp.ones(ids.shape, jnp.int32), ids, num_out)
    cnt = cnt.reshape((-1,) + (1,) * (msgs.ndim - 1))
    if reduce_op == "mean":
        s = jax.ops.segment_sum(msgs, ids, num_out)
        return s / jnp.maximum(cnt, 1).astype(s.dtype)
    fn = {"min": jax.ops.segment_min, "max": jax.ops.segment_max}[reduce_op]
    out = fn(msgs, ids, num_out)
    return jnp.where(cnt > 0, out, jnp.zeros_like(out))


def _segment_reduce(kind):
    @primitive(name="segment_" + kind)
    def op(data, segment_ids, num_segments):
        return _reduce(data, segment_ids, num_segments, kind)

    def api(data, segment_ids, name=None, out_size=None):
        n = _num_segments(segment_ids, out_size)
        return op(data, segment_ids, n)

    api.__name__ = "segment_" + kind
    api.__doc__ = ("reference python/paddle/geometric/math.py segment_%s"
                   % kind)
    return api


segment_sum = _segment_reduce("sum")
segment_mean = _segment_reduce("mean")
segment_min = _segment_reduce("min")
segment_max = _segment_reduce("max")


@primitive
def _gather_scatter(x, src_index, dst_index, num_out, reduce_op):
    msgs = jnp.take(x, jnp.asarray(src_index).astype(jnp.int32), axis=0)
    return _reduce(msgs, dst_index, num_out, reduce_op)


def send_u_recv(x, src_index, dst_index, reduce_op="sum", out_size=None,
                name=None):
    """Gather x[src_index], reduce into dst_index slots (reference
    message_passing/send_recv.py:24 send_u_recv; out_size=None infers
    max(dst_index)+1 as the reference does)."""
    return _gather_scatter(x, src_index, dst_index,
                           _num_segments(dst_index, out_size), reduce_op)


@primitive
def _gather_scatter_ue(x, e, src_index, dst_index, num_out, message_op,
                       reduce_op):
    msgs = jnp.take(x, jnp.asarray(src_index).astype(jnp.int32), axis=0)
    e = jnp.asarray(e)
    while e.ndim < msgs.ndim:
        e = e[..., None]
    msgs = msgs + e if message_op == "add" else msgs * e
    return _reduce(msgs, dst_index, num_out, reduce_op)


def send_ue_recv(x, y, src_index, dst_index, message_op="add",
                 reduce_op="sum", out_size=None, name=None):
    """Node+edge message then reduce (reference send_ue_recv)."""
    return _gather_scatter_ue(x, y, src_index, dst_index,
                              _num_segments(dst_index, out_size),
                              message_op, reduce_op)


@primitive
def _send_uv(x, y, src_index, dst_index, message_op):
    src = jnp.asarray(src_index).astype(jnp.int32)
    dst = jnp.asarray(dst_index).astype(jnp.int32)
    xs = jnp.take(x, src, axis=0)
    yd = jnp.take(y, dst, axis=0)
    return {"add": xs + yd, "sub": xs - yd, "mul": xs * yd,
            "div": xs / yd}[message_op]


def send_uv(x, y, src_index, dst_index, message_op="add", name=None):
    """Per-edge message from both endpoints (reference send_uv)."""
    return _send_uv(x, y, src_index, dst_index, message_op)


# ---- host-side graph preprocessing (reference CPU kernels) -----------------

def _to_np(x):
    return x.numpy() if isinstance(x, Tensor) else np.asarray(x)


def reindex_graph(x, neighbors, count, value_buffer=None, index_buffer=None,
                  name=None):
    """Compress global node ids to local contiguous ids (reference
    geometric/reindex.py reindex_graph; phi cpu/graph_reindex_kernel).

    Returns (reindex_src, reindex_dst, out_nodes): out_nodes = unique nodes
    in [x ++ neighbors] with x first, in first-seen order; reindex_src maps
    each neighbor to its local id; reindex_dst repeats each x-node's local
    id `count` times.
    """
    import paddle_tpu as paddle

    xs, nb, cnt = _to_np(x), _to_np(neighbors), _to_np(count)
    order = {}
    for v in xs.tolist():
        order.setdefault(int(v), len(order))
    for v in nb.tolist():
        order.setdefault(int(v), len(order))
    out_nodes = np.fromiter(order.keys(), dtype=xs.dtype, count=len(order))
    reindex_src = np.array([order[int(v)] for v in nb.tolist()],
                           dtype=xs.dtype)
    reindex_dst = np.repeat(np.arange(len(xs), dtype=xs.dtype), cnt)
    return (paddle.to_tensor(reindex_src), paddle.to_tensor(reindex_dst),
            paddle.to_tensor(out_nodes))


def reindex_heter_graph(x, neighbors, count, value_buffer=None,
                        index_buffer=None, name=None):
    """Heterogeneous variant: neighbors/count are lists per edge type."""
    import paddle_tpu as paddle

    xs = _to_np(x)
    nbs = [_to_np(n) for n in neighbors]
    cnts = [_to_np(c) for c in count]
    order = {}
    for v in xs.tolist():
        order.setdefault(int(v), len(order))
    for nb in nbs:
        for v in nb.tolist():
            order.setdefault(int(v), len(order))
    out_nodes = np.fromiter(order.keys(), dtype=xs.dtype, count=len(order))
    reindex_src = np.concatenate(
        [[order[int(v)] for v in nb.tolist()] for nb in nbs]).astype(xs.dtype)
    reindex_dst = np.concatenate(
        [np.repeat(np.arange(len(xs), dtype=xs.dtype), c) for c in cnts])
    return (paddle.to_tensor(reindex_src), paddle.to_tensor(reindex_dst),
            paddle.to_tensor(out_nodes))


def sample_neighbors(row, colptr, input_nodes, sample_size=-1, eids=None,
                     return_eids=False, perm_buffer=None, name=None):
    """Uniform neighbor sampling over a CSC graph (reference
    geometric/sampling/neighbors.py; phi cpu/graph_sample_neighbors_kernel).

    Returns (out_neighbors, out_count[, out_eids]).
    """
    import paddle_tpu as paddle

    rown, cp, nodes = _to_np(row), _to_np(colptr), _to_np(input_nodes)
    eid = _to_np(eids) if eids is not None else None
    rng = np.random.RandomState()
    outs, counts, out_eids = [], [], []
    for v in nodes.tolist():
        beg, end = int(cp[v]), int(cp[v + 1])
        deg = end - beg
        if sample_size < 0 or deg <= sample_size:
            idx = np.arange(beg, end)
        else:
            idx = beg + rng.choice(deg, size=sample_size, replace=False)
        outs.append(rown[idx])
        counts.append(len(idx))
        if return_eids and eid is not None:
            out_eids.append(eid[idx])
    neighbors = (np.concatenate(outs) if outs
                 else np.empty((0,), rown.dtype))
    count = np.asarray(counts, dtype=cp.dtype)
    if return_eids:
        e = (np.concatenate(out_eids) if out_eids
             else np.empty((0,), rown.dtype))
        return (paddle.to_tensor(neighbors), paddle.to_tensor(count),
                paddle.to_tensor(e))
    return paddle.to_tensor(neighbors), paddle.to_tensor(count)
