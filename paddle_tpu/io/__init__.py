"""paddle.io — datasets & DataLoader.

Parity: reference python/paddle/io/ (Dataset/IterableDataset/samplers/
DataLoader with multiprocess workers, fluid/dataloader/dataloader_iter.py).
TPU-native notes: the loader produces host numpy batches and transfers them
asynchronously; worker parallelism uses threads (numpy releases the GIL for
the decode/augment work that matters) with a bounded prefetch queue — the
role the reference's shared-memory worker processes play. The C++ slot-record
DataFeed for PS-style ingestion lives in csrc/datafeed.
"""
from __future__ import annotations

import itertools
import os
import queue
import threading
import time

import numpy as np

from ..core.tensor import Tensor
from ..framework import random as _random


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset has no __getitem__")

    def __len__(self):
        raise RuntimeError("IterableDataset has no __len__")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return self.tensors[0].shape[0]


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __len__(self):
        return min(len(d) for d in self.datasets)

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            item = d[idx]
            out.extend(item if isinstance(item, (tuple, list)) else [item])
        return tuple(out)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for d in self.datasets:
            yield from d


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths):
    total = len(dataset)
    if sum(lengths) != total:
        raise ValueError("sum of lengths != dataset size")
    perm = np.random.permutation(total).tolist()
    out = []
    off = 0
    for n in lengths:
        out.append(Subset(dataset, perm[off:off + n]))
        off += n
    return out


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))

    def __len__(self):
        return len(self.data_source)


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None,
                 generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples

    @property
    def num_samples(self):
        return self._num_samples or len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        if self.replacement:
            return iter(np.random.randint(0, n, self.num_samples).tolist())
        return iter(np.random.permutation(n)[:self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        self.weights = np.asarray(weights, np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        idx = np.random.choice(len(self.weights), self.num_samples,
                               replace=self.replacement, p=p)
        return iter(idx.tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False,
                 batch_size=1, drop_last=False):
        self.batch_size = batch_size
        self.drop_last = drop_last
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Shards indices across data-parallel ranks (reference
    python/paddle/io/dataloader/batch_sampler.py DistributedBatchSampler)."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        from ..distributed import env as dist_env

        self.dataset = dataset
        self.batch_size = batch_size
        self.nranks = num_replicas if num_replicas is not None else \
            dist_env.get_world_size()
        self.local_rank = rank if rank is not None else dist_env.get_rank()
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.epoch = 0
        import math

        self.num_samples = int(math.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def set_epoch(self, epoch):
        self.epoch = epoch

    def __iter__(self):
        n = len(self.dataset)
        if self.shuffle:
            rng = np.random.RandomState(self.epoch)
            indices = rng.permutation(n).tolist()
        else:
            indices = list(range(n))
        indices += indices[: (self.total_size - len(indices))]
        indices = indices[self.local_rank:self.total_size:self.nranks]
        batch = []
        for idx in indices:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size


def default_collate_fn(batch):
    sample = batch[0]
    if isinstance(sample, (tuple, list)):
        return [default_collate_fn([b[i] for b in batch])
                for i in range(len(sample))]
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in sample}
    if isinstance(sample, Tensor):
        import jax.numpy as jnp

        return Tensor(jnp.stack([b._value for b in batch]))
    if isinstance(sample, np.ndarray):
        return _to_tensor(np.stack(batch))
    if isinstance(sample, (int, float, np.integer, np.floating)):
        return _to_tensor(np.asarray(batch))
    return batch


def _to_tensor(arr):
    import jax.numpy as jnp

    if arr.dtype == np.float64:
        arr = arr.astype(np.float32)
    return Tensor(jnp.asarray(arr))


class DataLoader:
    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=True, timeout=0, worker_init_fn=None,
                 persistent_workers=False, pin_memory=False):
        self.dataset = dataset
        if pin_memory and collate_fn is None and num_workers <= 0:
            # batch assembly through the recycling host pool: steady-state
            # epochs do no host allocation for the stacked batch buffers
            # (the reference's pinned-memory DataLoader role). In-process
            # collation only: worker processes must never touch the
            # parent's jax runtime or drag the pool's ctypes handle
            # across fork/spawn, so num_workers>0 keeps the default
            # numpy collate (workers assemble, parent converts).
            from .host_pool import HostBufferPool

            self._pin_pool = HostBufferPool()
            self.collate_fn = self._pinned_collate
        else:
            self._pin_pool = None
            self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = prefetch_factor
        self.use_shared_memory = use_shared_memory
        self.worker_init_fn = worker_init_fn
        self.timeout = timeout
        self.persistent_workers = persistent_workers
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if self._iterable_mode:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(
                dataset, shuffle=shuffle, batch_size=batch_size,
                drop_last=drop_last)

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset DataLoader has no len()")
        return len(self.batch_sampler)

    def _pinned_collate(self, batch):
        sample = batch[0]
        if isinstance(sample, (tuple, list)):
            return [self._pinned_collate([b[i] for b in batch])
                    for i in range(len(sample))]
        if isinstance(sample, dict):
            return {k: self._pinned_collate([b[k] for b in batch])
                    for k in sample}
        if isinstance(sample, np.ndarray):
            import jax.numpy as jnp

            shape = (len(batch),) + sample.shape
            dt = sample.dtype if sample.dtype != np.float64 \
                else np.dtype(np.float32)
            buf = self._pin_pool.take(shape, dt)
            for i, b in enumerate(batch):
                buf[i] = b
            # copy=True is load-bearing: on the CPU backend jnp.asarray
            # zero-copy ALIASES page-aligned numpy memory, and the pool
            # is about to recycle this buffer. On TPU this copy is the
            # H2D transfer that happens anyway.
            out = Tensor(jnp.array(buf, copy=True))
            self._pin_pool.give(buf)
            return out
        return default_collate_fn(batch)

    def _batches(self):
        if self._iterable_mode:
            it = iter(self.dataset)
            while True:
                batch = list(itertools.islice(it, self.batch_size))
                if not batch:
                    return
                if len(batch) < self.batch_size and self.drop_last:
                    return
                yield self.collate_fn(batch)
        else:
            for idx_batch in self.batch_sampler:
                yield self.collate_fn([self.dataset[i] for i in idx_batch])

    def __iter__(self):
        if self.num_workers <= 0:
            yield from self._batches()
            return
        mp_iter = None
        if getattr(self, "use_shared_memory", True) is not False and \
                not self._iterable_mode:
            try:
                # only CONSTRUCTION failures (no mp/shm on this host)
                # select the fallback; mid-epoch errors must propagate,
                # never silently restart the epoch on another path
                mp_iter = _MPIterator(self)
            except (ImportError, OSError):
                mp_iter = None
        if mp_iter is not None:
            yield from mp_iter
            return
        # threaded prefetch pipeline (also the IterableDataset path: the
        # stream owns its state, so it stays in-process)
        q = queue.Queue(maxsize=self.num_workers * self.prefetch_factor)
        stop = object()

        def producer():
            try:
                for b in self._batches():
                    q.put(b)
            finally:
                q.put(stop)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is stop:
                break
            yield item


# -- multiprocess workers ----------------------------------------------------
#
# Parity: reference python/paddle/fluid/dataloader/worker.py (worker
# processes fed index batches over queues) and
# paddle/fluid/imperative/data_loader.cc (shared-memory result transport:
# the array PAYLOAD crosses processes through a SharedMemory segment;
# only (name, dtype, shape) goes through the pickled queue).

class WorkerInfo:
    def __init__(self, id, num_workers, dataset):
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset


_worker_info = None


def get_worker_info():
    """Inside a worker process: (id, num_workers, dataset); None in the
    main process (reference dataloader/worker.py get_worker_info)."""
    return _worker_info


def _shm_pack(batch):
    """numpy leaves -> (treedef-ish nested struct with shm descriptors)."""
    from multiprocessing import shared_memory

    blocks = []

    def pack(x):
        if isinstance(x, np.ndarray) and x.nbytes > 0:
            shm = shared_memory.SharedMemory(create=True, size=x.nbytes)
            dst = np.ndarray(x.shape, x.dtype, buffer=shm.buf)
            dst[...] = x
            blocks.append(shm)
            # ownership transfers to the CONSUMER (parent unlinks in
            # _shm_unpack); without unregistering, the worker's
            # resource_tracker unlinks the segment when the worker
            # exits, racing the parent's attach
            try:
                from multiprocessing import resource_tracker

                resource_tracker.unregister(shm._name, "shared_memory")
            # ptlint: silent-except-ok — private resource_tracker API
            # varies across py versions; worst case is a benign unlink
            # race warning at worker exit
            except Exception:
                pass
            return ("__shm__", shm.name, x.dtype.str, x.shape)
        return x

    def walk(obj):
        if isinstance(obj, (list, tuple)):
            return type(obj)(walk(o) for o in obj)
        if isinstance(obj, dict):
            return {k: walk(v) for k, v in obj.items()}
        return pack(obj)

    out = walk(batch)
    for shm in blocks:
        shm.close()  # worker's mapping; the segment lives until unlink
    return out


def _shm_unpack(obj):
    from multiprocessing import shared_memory

    if isinstance(obj, tuple) and len(obj) == 4 and obj[0] == "__shm__":
        _, name, dtype, shape = obj
        shm = shared_memory.SharedMemory(name=name)
        arr = np.ndarray(shape, np.dtype(dtype), buffer=shm.buf).copy()
        shm.close()
        shm.unlink()
        return arr
    if isinstance(obj, (list, tuple)):
        return type(obj)(_shm_unpack(o) for o in obj)
    if isinstance(obj, dict):
        return {k: _shm_unpack(v) for k, v in obj.items()}
    return obj


def _default_collate_numpy(batch):
    """default_collate_fn staged as numpy — workers must not touch the
    jax runtime of the forked parent; the parent wraps to Tensors."""
    sample = batch[0]
    if isinstance(sample, (tuple, list)):
        return [_default_collate_numpy([b[i] for b in batch])
                for i in range(len(sample))]
    if isinstance(sample, dict):
        return {k: _default_collate_numpy([b[k] for b in batch])
                for k in sample}
    if isinstance(sample, Tensor):
        return np.stack([np.asarray(b._value) for b in batch])
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, (int, float, np.integer, np.floating)):
        return np.asarray(batch)
    return batch


def _tree_to_tensor(obj):
    if isinstance(obj, np.ndarray):
        return _to_tensor(obj)
    if isinstance(obj, (list, tuple)):
        return type(obj)(_tree_to_tensor(o) for o in obj)
    if isinstance(obj, dict):
        return {k: _tree_to_tensor(v) for k, v in obj.items()}
    return obj


def _worker_loop(dataset, collate_fn, index_q, result_q, wid, nworkers,
                 use_shm, init_fn):
    global _worker_info
    _worker_info = WorkerInfo(wid, nworkers, dataset)
    if init_fn is not None:
        init_fn(wid)
    while True:
        item = index_q.get()
        if item is None:
            break
        bidx, indices = item
        try:
            batch = collate_fn([dataset[i] for i in indices])
            payload = _shm_pack(batch) if use_shm else batch
            result_q.put((bidx, payload, None))
        except Exception as e:  # surface worker errors in the parent
            result_q.put((bidx, None, "%s: %s" % (type(e).__name__, e)))


class _MPIterator:
    """Ordered multiprocess iteration (reference
    _DataLoaderIterMultiProcess): index batches fan out round-robin,
    results reassemble in order."""

    def __init__(self, loader):
        import multiprocessing as mp

        self.loader = loader
        ctx = mp.get_context("fork" if hasattr(os, "fork") else "spawn")
        n = loader.num_workers
        self._index_qs = [ctx.Queue() for _ in range(n)]
        self._result_q = ctx.Queue()
        use_shm = getattr(loader, "use_shared_memory", True)
        # workers stage numpy; the parent wraps to Tensors (forked
        # children must never touch the parent's jax runtime)
        self._numpy_mode = loader.collate_fn is default_collate_fn
        worker_collate = (_default_collate_numpy if self._numpy_mode
                          else loader.collate_fn)
        self._procs = [
            ctx.Process(
                target=_worker_loop,
                args=(loader.dataset, worker_collate,
                      self._index_qs[w], self._result_q, w, n, use_shm,
                      getattr(loader, "worker_init_fn", None)),
                daemon=True)
            for w in range(n)]
        for p in self._procs:
            p.start()

    def _recv(self, user_timeout):
        """One result with liveness checks: a dead worker must raise,
        not hang the parent forever."""
        deadline = (time.monotonic() + user_timeout) if user_timeout \
            else None
        while True:
            try:
                return self._result_q.get(timeout=1.0)
            except queue.Empty:
                dead = [p for p in self._procs
                        if not p.is_alive() and p.exitcode not in (0, None)]
                if dead:
                    raise RuntimeError(
                        "DataLoader worker(s) died unexpectedly "
                        "(exitcodes %s)" % [p.exitcode for p in dead])
                if deadline is not None and time.monotonic() > deadline:
                    raise RuntimeError(
                        "DataLoader timed out after %.1fs waiting for a "
                        "batch (timeout=%s)" % (user_timeout, user_timeout))

    def __iter__(self):
        loader = self.loader
        n = loader.num_workers
        user_timeout = getattr(loader, "timeout", 0) or None
        # bounded prefetch: at most num_workers * prefetch_factor index
        # batches outstanding (the reference's queue-capacity contract)
        limit = max(n * getattr(loader, "prefetch_factor", 2), n)
        try:
            batches = list(enumerate(loader.batch_sampler))
            sent = 0
            done_sent = False

            def dispatch():
                nonlocal sent, done_sent
                while sent < len(batches) and \
                        (sent - self._received) < limit:
                    bidx, idx_batch = batches[sent]
                    self._index_qs[bidx % n].put((bidx, list(idx_batch)))
                    sent += 1
                if sent == len(batches) and not done_sent:
                    for q in self._index_qs:
                        q.put(None)
                    done_sent = True

            self._received = 0
            pending = {}
            want = 0
            dispatch()
            while want < len(batches):
                if want in pending:
                    payload = pending.pop(want)
                else:
                    bidx, payload, err = self._recv(user_timeout)
                    self._received += 1
                    dispatch()
                    if err is not None:
                        raise RuntimeError(
                            "DataLoader worker failed: %s" % err)
                    payload = _shm_unpack(payload)
                    if self._numpy_mode:
                        payload = _tree_to_tensor(payload)
                    if bidx != want:
                        pending[bidx] = payload
                        continue
                yield payload
                want += 1
        finally:
            self._shutdown()

    def _shutdown(self):
        for p in self._procs:
            if p.is_alive():
                p.terminate()
        for p in self._procs:
            p.join(timeout=5)
        # drain undelivered results so their SharedMemory segments are
        # unlinked instead of leaking in /dev/shm past process exit
        while True:
            try:
                _, payload, _err = self._result_q.get_nowait()
            except Exception:
                break
            try:
                _shm_unpack(payload)
            # ptlint: silent-except-ok — draining orphaned shm results
            # at shutdown; the segment may already be unlinked
            except Exception:
                pass

from .host_pool import HostBufferPool  # noqa: F401,E402
