"""Native data feed — C++-threaded record ingestion for input pipelines.

Parity: the reference's DataFeed/Dataset stack
(paddle/fluid/framework/data_feed.h:1083 `DataFeed`, :1325
`InMemoryDataFeed`, data_set.cc) is a C++ multi-threaded reader with
in-memory shuffle feeding training workers. Ours is csrc/feed.cc: N reader
threads parse length-prefixed "ptrec" files through a shuffle buffer into a
bounded queue; Python consumes records and batches them into numpy arrays
for device_put. This is the high-throughput alternative to the pure-Python
paddle_tpu.io.DataLoader path, as in the reference where Dataset feeds
train_from_dataset while DataLoader serves the imperative path.
"""
from __future__ import annotations

import ctypes
import pickle

import numpy as np

from ..core import native


class RecordWriter:
    """Write a .ptrec record file (length-prefixed binary records)."""

    def __init__(self, path):
        self._lib = native.get_lib()
        self._f = self._lib.pt_feed_write_open(str(path).encode())
        if not self._f:
            raise IOError("cannot open %s" % path)

    def write(self, data):
        if not isinstance(data, (bytes, bytearray)):
            raise TypeError("RecordWriter.write expects bytes")
        rc = self._lib.pt_feed_write_record(self._f, bytes(data), len(data))
        if rc != 0:
            raise IOError("write_record failed")

    def write_example(self, example):
        """Serialize a dict of numpy arrays as one record."""
        self.write(pickle.dumps(example, protocol=4))

    def close(self):
        if self._f:
            self._lib.pt_feed_write_close(self._f)
            self._f = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class DataFeed:
    """Iterate records from .ptrec files via C++ reader threads.

    Args mirror the reference's Dataset config (data_set.cc): file list,
    reader thread count, shuffle buffer size, rng seed.
    """

    def __init__(self, filenames, num_threads=2, shuffle_buffer=0, seed=0,
                 queue_capacity=1024, deserialize=True):
        self._lib = native.get_lib()
        self._h = self._lib.pt_feed_create(queue_capacity, shuffle_buffer,
                                           seed)
        if isinstance(filenames, (str, bytes)):
            filenames = [filenames]
        for fn in filenames:
            self._lib.pt_feed_add_file(self._h, str(fn).encode())
        self._num_threads = num_threads
        self._deserialize = deserialize
        self._started = False

    def __iter__(self):
        if not self._started:
            self._lib.pt_feed_start(self._h, self._num_threads)
            self._started = True
        cap = 1 << 20
        buf = ctypes.create_string_buffer(cap)
        while True:
            n = self._lib.pt_feed_next(self._h, buf, cap)
            if n == -2:
                cap *= 16
                buf = ctypes.create_string_buffer(cap)
                continue
            if n <= 0:
                return
            rec = buf.raw[:n]
            yield pickle.loads(rec) if self._deserialize else rec

    def batched(self, batch_size, drop_last=True):
        """Yield dicts of stacked numpy arrays, ready for device_put."""
        batch = []
        for ex in self:
            batch.append(ex)
            if len(batch) == batch_size:
                yield _stack(batch)
                batch = []
        if batch and not drop_last:
            yield _stack(batch)

    def close(self):
        if self._h is not None:
            self._lib.pt_feed_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        # ptlint: silent-except-ok — __del__ at feed-GC time must
        # never raise (native lib may already be unloaded)
        except Exception:
            pass


def _stack(examples):
    first = examples[0]
    if isinstance(first, dict):
        return {k: np.stack([e[k] for e in examples]) for k in first}
    if isinstance(first, (tuple, list)):
        return tuple(np.stack([e[i] for e in examples])
                     for i in range(len(first)))
    return np.stack(examples)
