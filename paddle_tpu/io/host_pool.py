"""Host buffer pool — pinned-host-memory analog for the input pipeline.

Parity: reference memory/allocation CUDAPinnedAllocator +
AllocatorFacade stats (allocator_facade.h:44, memory/stats.cc). On TPU,
PJRT owns device memory entirely (XLA buffer assignment + donation);
what remains host-side is the batch-assembly buffer churn, which this
pool removes: page-aligned buffers recycled across steps, so
steady-state training performs no host allocation for input batches.

Usage:
    pool = HostBufferPool(max_pooled_bytes=256 << 20)
    arr = pool.take((batch, seq), np.int32)   # numpy view into a pool
    ... fill arr, device_put ...
    pool.give(arr)                            # recycle
"""
from __future__ import annotations

import ctypes

import numpy as np

from ..core import native


def _lib():
    lib = native.get_lib()
    if not getattr(lib, "_hostpool_ready", False):
        c = ctypes
        lib.pt_hostpool_create.restype = c.c_int
        lib.pt_hostpool_create.argtypes = [c.c_longlong]
        lib.pt_hostpool_alloc.restype = c.c_void_p
        lib.pt_hostpool_alloc.argtypes = [c.c_int, c.c_longlong]
        lib.pt_hostpool_free.restype = c.c_int
        lib.pt_hostpool_free.argtypes = [c.c_int, c.c_void_p]
        lib.pt_hostpool_stats.restype = c.c_int
        lib.pt_hostpool_stats.argtypes = [c.c_int,
                                          c.POINTER(c.c_longlong)]
        lib.pt_hostpool_trim.restype = c.c_int
        lib.pt_hostpool_trim.argtypes = [c.c_int]
        lib.pt_hostpool_destroy.argtypes = [c.c_int]
        lib._hostpool_ready = True
    return lib


class HostBufferPool:
    """Recycling page-aligned host buffers with numpy views."""

    def __init__(self, max_pooled_bytes=0):
        self._lib = _lib()
        self._h = self._lib.pt_hostpool_create(int(max_pooled_bytes))
        self._ptr_of = {}      # id(base buffer) -> raw pointer
        self._outstanding = {}  # ptr -> generation token
        self._gen = 0

    def _on_gc(self, ptr, token, base_id):
        """Finalizer: a taken buffer whose array was dropped without
        give() (exception paths) is reclaimed instead of leaking. The
        generation token keeps a stale finalizer from freeing the SAME
        pointer after the pool recycled it to a newer take()."""
        if self._ptr_of.get(base_id) == ptr:
            del self._ptr_of[base_id]  # stale id must not mis-resolve
        if self._outstanding.get(ptr) == token and self._h is not None \
                and self._h >= 0:
            del self._outstanding[ptr]
            self._lib.pt_hostpool_free(self._h, ptr)

    def take(self, shape, dtype):
        """-> writable numpy array backed by a pooled buffer."""
        import weakref

        dtype = np.dtype(dtype)
        nbytes = int(np.prod(shape)) * dtype.itemsize
        ptr = self._lib.pt_hostpool_alloc(self._h, max(nbytes, 1))
        if not ptr:
            raise MemoryError("HostBufferPool.alloc(%d) failed" % nbytes)
        buf = (ctypes.c_char * max(nbytes, 1)).from_address(ptr)
        arr = np.frombuffer(buf, dtype=dtype,
                            count=int(np.prod(shape))).reshape(shape)
        arr.flags.writeable = True
        self._ptr_of[id(arr.base)] = ptr
        self._gen += 1
        self._outstanding[ptr] = self._gen
        weakref.finalize(buf, self._on_gc, ptr, self._gen, id(arr.base))
        return arr

    def give(self, arr):
        """Return a `take`n array's buffer to the pool. The array (and
        any views) must not be used afterwards."""
        ptr = self._ptr_of.pop(id(arr.base), None)
        if ptr is None or self._outstanding.pop(ptr, None) is None:
            raise ValueError("array was not taken from this pool")
        rc = self._lib.pt_hostpool_free(self._h, ptr)
        if rc != 0:
            raise RuntimeError("hostpool free failed rc=%d" % rc)

    def stats(self):
        out = (ctypes.c_longlong * 5)()
        rc = self._lib.pt_hostpool_stats(self._h, out)
        if rc != 0:
            raise RuntimeError("hostpool stats failed rc=%d" % rc)
        return {"bytes_in_use": out[0], "bytes_pooled": out[1],
                "hits": out[2], "misses": out[3],
                "peak_bytes_in_use": out[4]}

    def trim(self):
        self._lib.pt_hostpool_trim(self._h)

    def close(self):
        if self._h is not None and self._h >= 0:
            # outstanding views become dangling — caller's contract
            self._lib.pt_hostpool_destroy(self._h)
            self._h = -1
            self._ptr_of.clear()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        # ptlint: silent-except-ok — __del__ at pool-GC time must
        # never raise (buffers may already be freed)
        except Exception:
            pass
