"""HybridParallelOptimizer (reference
fleet/meta_optimizers/dygraph_optimizer/hybrid_parallel_optimizer.py:186):
wraps the inner optimizer; syncs dp grads, reduces the global grad-norm clip
across mesh axes, then steps. DistributedStrategy knobs honored on the
eager path:

- ``gradient_merge`` (reference gradient_merge_optimizer.py + dygraph
  GradientMergeOptimizer): accumulate grads across k_steps micro-steps in
  buffers and apply the inner optimizer once per window (avg=True divides
  by k). The static-graph route applies the auto_parallel_gradient_merge
  pass instead (fleet/meta_optimizers.py).
- ``sharding_configs['offload']`` (reference sharding/offload_helper.py):
  park optimizer accumulators in host memory between steps — HBM holds
  only params+grads+activations, the ZeRO-offload trade. On step, the
  accumulators stream back through the update; outputs are re-pinned to
  host.
"""
from __future__ import annotations

from ..core.dispatch import no_grad
from ..optimizer.clip import ClipGradByGlobalNorm  # noqa: F401 (re-export)


def _host_device():
    import jax

    try:
        return jax.devices("cpu")[0]
    except RuntimeError:
        return None


def _eager_multiprocess(group):
    """True when the group has a real multi-process backend, i.e. each
    process holds its OWN gradient value and an eager reduction is
    meaningful. Under single-controller SPMD (one process, mesh axis
    possibly >1) the compiled step already produced the globally-reduced
    gradient — an extra eager allreduce would be wrong (and would try to
    shard small tensors over the axis)."""
    if group is None or group.nranks <= 1:
        return False
    pg = getattr(group, "pg", None)
    return pg is not None and getattr(pg, "world_size", 1) > 1


class HybridParallelOptimizer:
    def __init__(self, optimizer, hcg=None, strategy=None):
        self._inner_opt = optimizer
        self._hcg = hcg
        self._strategy = strategy
        gm = bool(getattr(strategy, "gradient_merge", False))
        cfg = getattr(strategy, "gradient_merge_configs", None) or {}
        self._gm_k = int(cfg.get("k_steps", 1)) if gm else 1
        self._gm_avg = bool(cfg.get("avg", True))
        self._gm_count = 0
        self._gm_buffers = {}
        # error-feedback residuals for the quantized eager grad sync
        # (per-param flat f32, persists across steps — see
        # distributed/compress.py)
        self._ef_residuals = {}
        sh_cfg = getattr(strategy, "sharding_configs", None) or {}
        self._offload = bool(getattr(strategy, "sharding", False)
                             and sh_cfg.get("offload", False))
        # local SGD (reference localsgd_optimizer.py): k local updates
        # without per-step grad sync, then average params across dp.
        # adaptive variant (AdaptiveLocalSGDOptimizer): k re-derived at
        # every sync as ceil(sqrt(lr_0*loss/(lr*loss_0) * init_k)),
        # clipped to [1, 16] — the reference's Adaptive Communication
        # Strategies schedule.
        self._ls_adaptive = bool(getattr(strategy, "adaptive_localsgd",
                                         False))
        if self._ls_adaptive:
            ls_cfg = getattr(strategy,
                             "adaptive_localsgd_configs", None) or {}
            self._localsgd = True
            self._ls_k = max(1, int(ls_cfg.get("init_k_steps", 1)))
        else:
            ls_cfg = getattr(strategy, "localsgd_configs", None) or {}
            self._localsgd = bool(getattr(strategy, "localsgd", False))
            self._ls_k = max(1, int(ls_cfg.get("k_steps", 1))) \
                if self._localsgd else 1
        self._ls_init_k = self._ls_k
        self._ls_begin = max(1, int(ls_cfg.get("begin_step", 1)))
        self._ls_count = 0
        # first window ends k-1 effective steps after activation
        self._ls_next_sync = self._ls_begin + self._ls_k - 1
        self._ls_loss0 = None
        self._ls_lr0 = None
        self._last_loss = None

    # -- gradient merge ----------------------------------------------------

    def _merge_grads(self):
        """Stash this micro-step's grads; True when the window closes.
        A param may have no grad on any given micro-step (unused branch):
        its buffer still applies — and is always cleared — when the
        window closes, never leaking into the next window."""
        import jax.numpy as jnp

        from ..core.tensor import Tensor

        self._gm_count += 1
        last = self._gm_count >= self._gm_k
        for p in self._inner_opt._get_params():
            buf = self._gm_buffers.pop(id(p), None)
            g = p.grad._value if p.grad is not None else None
            if g is None and buf is None:
                continue
            acc = g if buf is None else (buf if g is None else buf + g)
            if last:
                if self._gm_avg:
                    acc = acc / jnp.asarray(self._gm_k, acc.dtype)
                if p.grad is None:
                    p.grad = Tensor(acc)
                else:
                    p.grad._value = acc
            else:
                self._gm_buffers[id(p)] = acc
        if last:
            self._gm_count = 0
        return last

    # -- ZeRO offload ------------------------------------------------------

    def _offload_accumulators(self):
        """Park accumulators on the host, remembering each one's device
        placement/sharding so onload restores it exactly (a sharded
        ZeRO state must NOT come back committed to one chip)."""
        import jax

        host = _host_device()
        accs = getattr(self._inner_opt, "_accumulators", None)
        if not accs or host is None:
            return
        shardings = getattr(self, "_acc_shardings", None)
        if shardings is None:
            shardings = self._acc_shardings = {}
        for key, v in list(accs.items()):
            if hasattr(v, "sharding"):
                shardings[key] = v.sharding
            accs[key] = jax.device_put(v, host)

    def _onload_accumulators(self):
        """Bring host-parked state back to its original placement before
        the jitted update — committed-CPU state mixed with device params
        would otherwise fail device placement."""
        import jax

        accs = getattr(self._inner_opt, "_accumulators", None)
        if not accs:
            return
        shardings = getattr(self, "_acc_shardings", {})
        default = jax.devices()[0]
        for key, v in list(accs.items()):
            accs[key] = jax.device_put(v, shardings.get(key, default))

    # -- step --------------------------------------------------------------

    @no_grad()
    def step(self):
        if self._gm_k > 1:
            if not self._merge_grads():
                # window still open: drop this micro-step's grads, the
                # buffer holds the running sum (reference GradientMerge
                # zeroes the grad var after accumulation)
                self._inner_opt.clear_grad()
                return
        # dp grad sync (fused_allreduce_gradients analog); on the compiled
        # path XLA already inserted the reduction, eager path does it here.
        # Under local SGD (past begin_step) the per-step grad sync is
        # skipped; parameters are averaged every k_steps instead.
        self._ls_count += 1
        ls_active = (self._localsgd
                     and self._ls_count >= self._ls_begin)
        if self._hcg is not None and not ls_active:
            dp_group = self._hcg.get_data_parallel_group()
            if _eager_multiprocess(dp_group):
                from ..distributed import collective, compress

                if compress.quantized_sync_enabled():
                    # same bucketed compressed sync as DataParallel —
                    # with the per-param error-feedback residuals that
                    # make lossy grad reduction convergence-safe (a
                    # bare compressed all_reduce would drop sub-ulp
                    # gradient mass systematically, no residual)
                    compress.sync_gradients_compressed(
                        self._inner_opt._get_params(), dp_group,
                        residuals=self._ef_residuals)
                else:
                    for p in self._inner_opt._get_params():
                        if p.grad is not None:
                            collective.all_reduce(p.grad, group=dp_group)
                            p.grad._value = \
                                p.grad._value / dp_group.nranks
        if self._offload:
            self._onload_accumulators()
        self._inner_opt.step()
        if self._offload:
            self._offload_accumulators()
        # window counts from activation, so every local window is
        # exactly k_steps long regardless of begin_step; an explicit
        # next-sync pointer lets the adaptive variant vary k per window
        if ls_active and self._ls_count >= self._ls_next_sync \
                and self._hcg is not None:
            dp_group = self._hcg.get_data_parallel_group()
            if _eager_multiprocess(dp_group):
                from ..distributed import collective

                for p in self._inner_opt._get_params():
                    collective.all_reduce(p, group=dp_group)
                    p._value = p._value / dp_group.nranks
            if self._ls_adaptive:
                self._ls_k = self._adaptive_k(dp_group)
            self._ls_next_sync = self._ls_count + self._ls_k

    def _adaptive_k(self, dp_group):
        """Next window length from the reference formula
        ceil(sqrt(lr_0*loss / (lr*loss_0) * init_k)), clipped to 16
        (localsgd_optimizer.py communicate_avg_loss). Needs the loss —
        available on the minimize() flow; plain step() keeps current k."""
        import math

        loss_t = self._last_loss
        # consume it: a stale loss from an old minimize() call must not
        # keep driving the schedule once the user switches to plain
        # backward();step() loops
        self._last_loss = None
        if loss_t is None:
            return self._ls_k
        loss = float(loss_t) if not hasattr(loss_t, "_value") \
            else float(loss_t._value)
        if _eager_multiprocess(dp_group):
            from ..core.tensor import Tensor as _T
            from ..distributed import collective

            t = collective.all_reduce(_T(loss), group=dp_group)
            loss = float(t._value) / dp_group.nranks
        lr_t = max(float(self._inner_opt.get_lr()), 1e-12)
        if self._ls_loss0 is None:
            self._ls_loss0 = max(loss, 1e-12)
            self._ls_lr0 = lr_t
            return self._ls_k
        ratio = (self._ls_lr0 * loss) / (lr_t * self._ls_loss0)
        k = math.ceil(math.sqrt(max(ratio, 0.0) * self._ls_init_k))
        return int(min(16, max(1, k)))

    def clear_grad(self, *a, **k):
        self._inner_opt.clear_grad(*a, **k)

    clear_gradients = clear_grad

    def minimize(self, loss, *a, **k):
        self._last_loss = loss  # adaptive localsgd reads it at sync
        loss.backward()
        self.step()

    def get_lr(self):
        return self._inner_opt.get_lr()

    def set_lr(self, v):
        return self._inner_opt.set_lr(v)

    def state_dict(self):
        return self._inner_opt.state_dict()

    def set_state_dict(self, sd):
        return self._inner_opt.set_state_dict(sd)

    def __getattr__(self, name):
        return getattr(self._inner_opt, name)


class DygraphShardingOptimizer(HybridParallelOptimizer):
    """ZeRO-1 wrapper (reference dygraph_sharding_optimizer.py:29). Under the
    engine the opt state is already sharded over 'sharding'; eager path
    delegates."""
    pass
