"""HybridParallelOptimizer (reference
fleet/meta_optimizers/dygraph_optimizer/hybrid_parallel_optimizer.py:186):
wraps the inner optimizer; syncs dp grads, reduces the global grad-norm clip
across mesh axes, then steps."""
from __future__ import annotations

from ..core.dispatch import no_grad
from ..optimizer.clip import ClipGradByGlobalNorm


class HybridParallelOptimizer:
    def __init__(self, optimizer, hcg=None, strategy=None):
        self._inner_opt = optimizer
        self._hcg = hcg
        self._strategy = strategy

    @no_grad()
    def step(self):
        # dp grad sync (fused_allreduce_gradients analog); on the compiled
        # path XLA already inserted the reduction, eager path does it here.
        if self._hcg is not None:
            dp_group = self._hcg.get_data_parallel_group()
            if dp_group.nranks > 1:
                from ..distributed import collective

                for p in self._inner_opt._get_params():
                    if p.grad is not None:
                        collective.all_reduce(p.grad, group=dp_group)
                        p.grad._value = p.grad._value / dp_group.nranks
        self._inner_opt.step()

    def clear_grad(self, *a, **k):
        self._inner_opt.clear_grad(*a, **k)

    clear_gradients = clear_grad

    def minimize(self, loss, *a, **k):
        loss.backward()
        self.step()

    def get_lr(self):
        return self._inner_opt.get_lr()

    def set_lr(self, v):
        return self._inner_opt.set_lr(v)

    def state_dict(self):
        return self._inner_opt.state_dict()

    def set_state_dict(self, sd):
        return self._inner_opt.set_state_dict(sd)

    def __getattr__(self, name):
        return getattr(self._inner_opt, name)


class DygraphShardingOptimizer(HybridParallelOptimizer):
    """ZeRO-1 wrapper (reference dygraph_sharding_optimizer.py:29). Under the
    engine the opt state is already sharded over 'sharding'; eager path
    delegates."""
    pass
