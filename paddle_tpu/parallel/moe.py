"""Mixture-of-Experts with expert parallelism.

Parity: reference MoELayer
(/root/reference/python/paddle/incubate/distributed/models/moe/moe_layer.py:260)
with its gates (gate/gshard_gate.py, switch_gate.py, naive_gate.py) and the
global_scatter/global_gather all-to-all ops
(/root/reference/paddle/fluid/operators/collective/global_scatter_op.cc).

TPU-native design: instead of the reference's variable-size brpc/NCCL
all-to-all (token counts exchanged first, then payloads), dispatch is
capacity-based and dense — the GShard formulation. Tokens are routed into a
fixed [experts, capacity, d_model] buffer with einsum one-hots; the expert
dimension is sharded over a mesh axis (default the dp axis, matching the
reference's moe_group spanning data-parallel ranks) so GSPMD lowers the
dispatch/combine einsums into exactly one fused all-to-all pair over ICI.
Experts are evaluated as ONE batched matmul over the stacked expert weights
— MXU-friendly, no per-expert kernel launches. Over-capacity tokens are
dropped (contribute zero), as in GShard/Switch; the reference's
variable-length semantics cannot be expressed as a static XLA program.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..core.dispatch import primitive
from ..nn import initializer as I
from ..nn.layer import Layer

_A = jnp.asarray


def _constrain(x, *spec):
    try:
        return jax.lax.with_sharding_constraint(x, P(*spec))
    except Exception:
        return x


def _top1_dispatch(probs, capacity):
    """Switch-style top-1 routing. probs [T, E] -> combine [T, E, C], aux."""
    t, e = probs.shape
    idx1 = jnp.argmax(probs, axis=-1)
    mask1 = jax.nn.one_hot(idx1, e, dtype=probs.dtype)          # [T, E]
    gates1 = jnp.sum(probs * mask1, axis=-1)                    # [T]
    # load-balance loss: E * sum_e frac_tokens_e * mean_prob_e
    me = jnp.mean(probs, axis=0)
    ce = jnp.mean(mask1, axis=0)
    aux = e * jnp.sum(me * ce)
    pos1 = jnp.cumsum(mask1, axis=0) * mask1 - mask1            # [T, E] pos
    pos1 = jnp.sum(pos1, axis=-1)                               # [T]
    keep = (pos1 < capacity).astype(probs.dtype) * jnp.sum(mask1, -1)
    combine = (gates1 * keep)[:, None, None] * (
        mask1[:, :, None] *
        jax.nn.one_hot(pos1.astype(jnp.int32), capacity,
                       dtype=probs.dtype)[:, None, :])
    return combine, aux


def _top2_dispatch(probs, capacity):
    """GShard-style top-2 routing with renormalized combine weights."""
    t, e = probs.shape
    idx1 = jnp.argmax(probs, axis=-1)
    mask1 = jax.nn.one_hot(idx1, e, dtype=probs.dtype)
    probs_wo1 = probs * (1.0 - mask1)
    idx2 = jnp.argmax(probs_wo1, axis=-1)
    mask2 = jax.nn.one_hot(idx2, e, dtype=probs.dtype)
    g1 = jnp.sum(probs * mask1, axis=-1)
    g2 = jnp.sum(probs * mask2, axis=-1)
    denom = jnp.maximum(g1 + g2, 1e-9)
    g1, g2 = g1 / denom, g2 / denom
    # aux loss over first choice only (gshard_gate semantics)
    me = jnp.mean(probs, axis=0)
    ce = jnp.mean(mask1, axis=0)
    aux = e * jnp.sum(me * ce)
    pos1 = jnp.sum(jnp.cumsum(mask1, axis=0) * mask1 - mask1, axis=-1)
    # second choice queues behind all first choices of the same expert
    counts1 = jnp.sum(mask1, axis=0, keepdims=True)             # [1, E]
    pos2 = jnp.sum(
        (jnp.cumsum(mask2, axis=0) - 1 + counts1) * mask2, axis=-1)
    keep1 = (pos1 < capacity).astype(probs.dtype) * jnp.sum(mask1, -1)
    keep2 = (pos2 < capacity).astype(probs.dtype) * jnp.sum(mask2, -1)
    oh = lambda pos: jax.nn.one_hot(pos.astype(jnp.int32), capacity,
                                    dtype=probs.dtype)
    combine = (g1 * keep1)[:, None, None] * (
        mask1[:, :, None] * oh(pos1)[:, None, :])
    combine = combine + (g2 * keep2)[:, None, None] * (
        mask2[:, :, None] * oh(pos2)[:, None, :])
    return combine, aux


@primitive
def moe_mlp(x, gate_w, w1, b1, w2, b2, *, top_k, capacity, ep_axis,
            activation):
    """Full MoE feed-forward: gate -> dispatch -> batched experts -> combine.

    x [T, D]; gate_w [D, E]; w1 [E, D, H]; b1 [E, H]; w2 [E, H, D];
    b2 [E, D]. Returns (out [T, D], aux_loss scalar).
    """
    x = _A(x)
    xf = x.astype(jnp.float32)
    logits = xf @ _A(gate_w).astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)
    if top_k == 1:
        combine, aux = _top1_dispatch(probs, capacity)
    elif top_k == 2:
        combine, aux = _top2_dispatch(probs, capacity)
    else:
        raise NotImplementedError("top_k must be 1 or 2")
    combine = combine.astype(x.dtype)
    dispatch = (combine > 0).astype(x.dtype)                   # [T, E, C]
    # all-to-all boundary: expert dim sharded over ep_axis
    expert_in = jnp.einsum("tec,td->ecd", dispatch, x)
    expert_in = _constrain(expert_in, ep_axis, None, None)
    h = jnp.einsum("ecd,edh->ech", expert_in, _A(w1)) + _A(b1)[:, None, :]
    if activation == "gelu":
        h = jax.nn.gelu(h)
    elif activation == "relu":
        h = jax.nn.relu(h)
    elif activation == "silu":
        h = jax.nn.silu(h)
    y = jnp.einsum("ech,ehd->ecd", h, _A(w2)) + _A(b2)[:, None, :]
    y = _constrain(y, ep_axis, None, None)
    out = jnp.einsum("tec,ecd->td", combine, y)
    return out, aux.astype(x.dtype)


class MoELayer(Layer):
    """MoE feed-forward block (reference moe_layer.py:260 MoELayer).

    Experts are a single stacked parameter set evaluated as batched einsum
    (the reference keeps a python list of Expert sublayers and loops; on TPU
    that serializes the MXU, so we stack). Expert weights are sharded over
    `ep_axis` (a mesh axis name; defaults to "dp", mirroring the reference's
    moe_group over data-parallel ranks).

    After forward, `self.aux_loss` holds the load-balancing loss tensor —
    add `moe.aux_loss * coeff` to the training loss (the reference returns
    it through its gate object the same way).
    """

    def __init__(self, d_model, d_hidden, num_experts, top_k=2,
                 capacity_factor=1.25, gate="gshard", activation="gelu",
                 ep_axis="dp", name=None):
        super().__init__()
        if gate == "switch":
            top_k = 1
        elif gate == "naive":
            capacity_factor = float(num_experts)  # no drops
        self.d_model = d_model
        self.d_hidden = d_hidden
        self.num_experts = num_experts
        self.top_k = top_k
        self.capacity_factor = capacity_factor
        self.activation = activation
        self.ep_axis = ep_axis
        self.gate_weight = self.create_parameter(
            [d_model, num_experts], default_initializer=I.XavierNormal())
        self.w1 = self.create_parameter(
            [num_experts, d_model, d_hidden],
            default_initializer=I.XavierNormal())
        self.b1 = self.create_parameter([num_experts, d_hidden], is_bias=True)
        self.w2 = self.create_parameter(
            [num_experts, d_hidden, d_model],
            default_initializer=I.XavierNormal())
        self.b2 = self.create_parameter([num_experts, d_model], is_bias=True)
        self.w1._sharding_spec = P(ep_axis, None, None)
        self.b1._sharding_spec = P(ep_axis, None)
        self.w2._sharding_spec = P(ep_axis, None, None)
        self.b2._sharding_spec = P(ep_axis, None)
        self.aux_loss = None

    def capacity(self, num_tokens):
        return max(1, int(math.ceil(
            self.capacity_factor * num_tokens * self.top_k
            / self.num_experts)))

    def forward(self, x):
        shape = x.shape
        d = shape[-1]
        tokens = 1
        for s in shape[:-1]:
            tokens *= s
        x2 = x.reshape([tokens, d])
        out, aux = moe_mlp(
            x2, self.gate_weight, self.w1, self.b1, self.w2, self.b2,
            top_k=self.top_k, capacity=self.capacity(tokens),
            ep_axis=self.ep_axis, activation=self.activation)
        self.aux_loss = aux
        return out.reshape(shape)


# ---------------------------------------------------------------------------
# Eager all-to-all primitives for API parity with the reference's
# global_scatter/global_gather (operators/collective/global_scatter_op.cc).
# TPU deviation: XLA all-to-all moves equal-size splits; the reference's
# variable-count protocol (exchange counts, then ragged payloads) has no
# static-shape analog. Equal per-expert capacity is therefore required —
# which is how the dense MoE dispatch above lays tokens out anyway.
# ---------------------------------------------------------------------------

def global_scatter(x, group=None):
    """Exchange locally-grouped expert rows so each rank holds the rows of
    its own experts from every peer. x: [E * C, ...] with the leading dim
    grouped by (global) expert; requires E divisible by the group size."""
    from ..distributed import collective

    return collective.alltoall(x, group=group)


def global_gather(x, group=None):
    """Inverse of global_scatter (the same equal-split all_to_all with the
    send/receive roles swapped)."""
    from ..distributed import collective

    return collective.alltoall(x, group=group)
