"""paddle_tpu.parallel — hybrid-parallel execution (the reference's
fleet/meta_parallel + meta_optimizers rebuilt SPMD-first).

The central object is the compiled train step (engine.py): one pjit'd XLA
module per (model, mesh, shardings) in which dp/mp/sharding/sep parallelism
are sharding annotations and pp is a scan over stages. The wrapper Layers
(DataParallel, TensorParallel, ...) mark sharding metadata and keep the
reference's eager APIs working.
"""
from . import engine  # noqa: F401
from .data_parallel import DataParallel  # noqa: F401
from .moe import MoELayer, global_gather, global_scatter  # noqa: F401
from .mp_layers import (  # noqa: F401
    ColumnParallelLinear,
    ParallelCrossEntropy,
    RowParallelLinear,
    VocabParallelEmbedding,
)
from .pipeline_parallel import PipelineLayer, PipelineParallel  # noqa: F401
from .sharding_parallel import ShardingParallel, group_sharded_parallel  # noqa: F401
from .tensor_parallel import TensorParallel  # noqa: F401
