"""TensorParallel wrapper (reference fleet/meta_parallel/tensor_parallel.py:27:
broadcast params/inputs within the mp group). Single-controller SPMD already
has one global copy of every param, so the broadcasts are structurally
guaranteed; the wrapper's job is to carry the hcg and keep the API."""
from __future__ import annotations

from ..nn.layer import Layer


class TensorParallel(Layer):
    def __init__(self, layers, hcg=None, strategy=None):
        super().__init__()
        self._layers = layers
        self._hcg = hcg

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, prefix="", include_sublayers=True):
        return self._layers.named_parameters(prefix, include_sublayers)

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, sd, **kwargs):
        return self._layers.set_state_dict(sd, **kwargs)

    def train(self):
        self._layers.train()
        return self

    def eval(self):
        self._layers.eval()
        return self
