"""TensorParallel wrapper (reference fleet/meta_parallel/tensor_parallel.py:27).

The reference wrapper does two jobs at construction/step time:
1. broadcast non-sharded params within the mp group (ranks must agree
   bit-for-bit or TP activations diverge);
2. broadcast step inputs from the mp-group src rank.

Single-controller SPMD already has one global copy of every param, so
both are structurally guaranteed there; in a multi-process world
(init_parallel_env) the wrapper performs the real broadcasts over the
store-backed groups, and also seeds the mp-rank RNG tracker so dropout
masks differ across mp ranks (reference mpu/random.py).
"""
from __future__ import annotations

from ..nn.layer import Layer


def _world_pg():
    from ..distributed.process_group import get_world_group

    return get_world_group()


class TensorParallel(Layer):
    def __init__(self, layers, hcg=None, strategy=None):
        super().__init__()
        self._layers = layers
        self._hcg = hcg
        if hcg is not None and _world_pg() is not None:
            from ..distributed.fleet.utils.hybrid_parallel_util import (
                broadcast_mp_parameters,
            )

            if hcg.get_model_parallel_world_size() > 1:
                broadcast_mp_parameters(layers, hcg)
        if hcg is not None and hcg.get_model_parallel_world_size() > 1:
            # distinct dropout streams per mp rank (reference
            # meta_parallel/tensor_parallel.py + mpu/random.py). The rank
            # must be the PROCESS-level one: hcg.get_model_parallel_rank()
            # is 0 under single-controller SPMD (topology.py), so in a
            # multi-process world ask the mp Group, which derives the true
            # rank from the store-backed process group.
            from ..framework.random import get_rng_state_tracker

            mp_group = hcg.get_model_parallel_group()
            rank = mp_group.rank if _world_pg() is not None \
                else hcg.get_model_parallel_rank()
            get_rng_state_tracker().set_mp_rank(max(rank, 0))

    def forward(self, *inputs, **kwargs):
        if self._hcg is not None and _world_pg() is not None \
                and self._hcg.get_model_parallel_world_size() > 1:
            from ..distributed.fleet.utils.hybrid_parallel_util import (
                broadcast_input_data,
            )

            res = broadcast_input_data(self._hcg, *inputs, **kwargs)
            if kwargs:
                inputs, kwargs = res
            else:
                inputs = res
        return self._layers(*inputs, **kwargs)

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, prefix="", include_sublayers=True):
        return self._layers.named_parameters(prefix, include_sublayers)

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, sd, **kwargs):
        return self._layers.set_state_dict(sd, **kwargs)

    def train(self):
        self._layers.train()
        return self

    def eval(self):
        self._layers.eval()
        return self
