"""DataParallel.

Parity: reference paddle.DataParallel + EagerReducer
(distributed/collective/reducer.h:89 — bucketing, ready-counting hooks,
fused allreduce). TPU-native: under the compiled train step the batch is
sharded over 'dp' and XLA emits one fused gradient all-reduce schedule —
bucketing is unnecessary (documented deviation, SURVEY §7.6). Eagerly (one
process per host, single-controller), forward/backward just run; grads are
synchronized by `sync_gradients` when a real multi-rank dp group exists.
"""
from __future__ import annotations

from ..core.dispatch import no_grad
from ..distributed import collective
from ..nn.layer import Layer


class DataParallel(Layer):
    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None, hcg=None):
        super().__init__()
        self._layers = layers
        self._hcg = hcg
        self._group = group or (
            hcg.get_data_parallel_group() if hcg is not None
            else collective.Group("dp"))
        self.find_unused_parameters = find_unused_parameters
        # error-feedback residuals for the quantized eager sync path
        # (one flat f32 array per param, keyed by id; persists across
        # steps so dropped sub-ulp gradient mass re-enters next step)
        self._ef_residuals = {}

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    @no_grad()
    def sync_gradients(self):
        """Fused dp-group grad allreduce (reference
        fused_allreduce_gradients, fleet/utils/hybrid_parallel_util.py).

        With ``FLAGS_quantized_grad_sync`` on, grads coalesce into
        size-threshold buckets (``FLAGS_grad_sync_bucket_mb``) and each
        bucket rides ONE compressed store all-reduce — ~4x fewer wire
        bytes and far fewer round-trips than the per-param fp32 loop,
        with per-param error feedback preserving convergence
        (distributed/compress.py)."""
        from ..distributed import compress as _compress
        from .hybrid_optimizer import _eager_multiprocess

        if not _eager_multiprocess(self._group):
            # single-controller SPMD: the compiled step's psum already
            # reduced grads over the sharded batch — nothing to sync
            return
        if _compress.quantized_sync_enabled():
            _compress.sync_gradients_compressed(
                list(self._layers.parameters()), self._group,
                residuals=self._ef_residuals)
            return
        for p in self._layers.parameters():
            if p.grad is not None:
                collective.all_reduce(p.grad, op=collective.ReduceOp.SUM,
                                      group=self._group)
                p.grad._value = p.grad._value / self._group.nranks

    def scale_loss(self, loss):
        return loss

    # delegate the Layer surface to the wrapped model
    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, prefix="", include_sublayers=True):
        return self._layers.named_parameters(prefix, include_sublayers)

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, sd, **kwargs):
        return self._layers.set_state_dict(sd, **kwargs)

    def train(self):
        self._layers.train()
        return self

    def eval(self):
        self._layers.eval()
        return self
