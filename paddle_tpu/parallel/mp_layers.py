"""Tensor-parallel (Megatron-style) layers.

Parity: reference fleet/layers/mpu/mp_layers.py — VocabParallelEmbedding
(:35), ColumnParallelLinear (:173), RowParallelLinear (:332),
ParallelCrossEntropy (:498) and mp_ops.py's _c_identity/_mp_allreduce.

TPU-native: params carry PartitionSpecs over the 'mp' mesh axis; under pjit
the GSPMD partitioner inserts exactly the identity/all-reduce pairs the
reference codes by hand (c_identity forward + allreduce backward for column;
allreduce forward for row). Eager single-host execution still computes the
full math. with_sharding_constraint marks the activation boundaries.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..core.dispatch import primitive
from ..core.tensor import Tensor
from ..nn import functional as F
from ..nn import initializer as I
from ..nn.layer import Layer

_A = jnp.asarray


@primitive
def _sharded(x, spec_tuple):
    """Annotate an activation with a sharding constraint (no-op outside jit)."""
    x = _A(x)
    try:
        return jax.lax.with_sharding_constraint(x, P(*spec_tuple))
    except Exception:
        return x


def mark_sharding(t, *spec):
    if isinstance(t, Tensor):
        return _sharded(t, spec_tuple=tuple(spec))
    return t


class ColumnParallelLinear(Layer):
    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, gather_output=True, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.gather_output = gather_output
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=I.XavierNormal())
        self.weight._sharding_spec = P(None, "mp")  # split columns
        self.bias = None
        if has_bias:
            self.bias = self.create_parameter([out_features], is_bias=True)
            self.bias._sharding_spec = P("mp")

    def forward(self, x):
        out = F.linear(x, self.weight, self.bias)
        if not self.gather_output:
            # keep activation sharded over mp on the feature dim
            nd = out.ndim
            spec = [None] * nd
            spec[-1] = "mp"
            out = mark_sharding(out, *spec)
        return out


class RowParallelLinear(Layer):
    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=False,
                 fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.input_is_parallel = input_is_parallel
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=I.XavierNormal())
        self.weight._sharding_spec = P("mp", None)  # split rows
        self.bias = None
        if has_bias:
            self.bias = self.create_parameter([out_features], is_bias=True)
            self.bias._sharding_spec = P()

    def forward(self, x):
        if self.input_is_parallel:
            nd = x.ndim
            spec = [None] * nd
            spec[-1] = "mp"
            x = mark_sharding(x, *spec)
        out = F.linear(x, self.weight, self.bias)
        # partial sums are all-reduced by the partitioner; mark replicated
        out = mark_sharding(out, *([None] * out.ndim))
        return out


class VocabParallelEmbedding(Layer):
    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group=None, name=None):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=I.Normal(0.0, 0.02))
        self.weight._sharding_spec = P("mp", None)  # split vocab rows

    def forward(self, x):
        return F.embedding(x, self.weight)


def _axis_bound(axis):
    try:
        jax.lax.axis_index(axis)
        return True
    except Exception:
        return False


import functools as _functools


@_functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _pmax_stat(x, axis):
    """pmax as a pure statistic: zero gradient (pmax has no JAX
    differentiation rule; the softmax max-subtraction is gradient-free
    by the log-sum-exp shift identity anyway)."""
    return jax.lax.pmax(x, axis)


def _pmax_stat_fwd(x, axis):
    return jax.lax.pmax(x, axis), x


def _pmax_stat_bwd(axis, x, g):
    return (jnp.zeros_like(x),)


_pmax_stat.defvjp(_pmax_stat_fwd, _pmax_stat_bwd)


@primitive
def parallel_softmax_cross_entropy(logits, label, ignore_index=-100,
                                   mp_axis="mp"):
    """c_softmax_with_cross_entropy semantics (reference
    /root/reference/paddle/fluid/operators/collective/
    c_softmax_with_cross_entropy_op.cu and mp_layers.py:498): the vocab
    dim of `logits` is sharded over the mp axis and is NEVER gathered.

    Two execution forms, identical math:
    - per-shard (inside shard_map, mp axis bound): each rank holds
      [N, V/n]; global max/sum-exp/picked-logit come from pmax/psum over
      the axis, with the label's owning rank contributing the picked
      logit — exactly the reference kernel's 3 collectives.
    - GSPMD (pjit or eager): the reduction form is expressed with
      one_hot·x contractions so the partitioner lowers it to local
      reductions + all-reduce without materializing a gathered [N, V].
    """
    x = jnp.asarray(logits)
    li = jnp.asarray(label).astype(jnp.int32)
    if li.ndim == x.ndim and li.shape[-1] == 1:
        li = jnp.squeeze(li, -1)
    xf = x.astype(jnp.float32)
    if _axis_bound(mp_axis):
        n_shard = x.shape[-1]
        rank = jax.lax.axis_index(mp_axis)
        offset = rank * n_shard
        # global max over the sharded vocab dim (statistic only — the
        # softmax gradient identity makes its cotangent cancel)
        m = _pmax_stat(jnp.max(xf, axis=-1), mp_axis)  # [N...]
        e = jnp.exp(xf - m[..., None])
        s = jax.lax.psum(jnp.sum(e, axis=-1), mp_axis)
        # picked logit: only the owning shard contributes
        local = li - offset
        in_shard = (local >= 0) & (local < n_shard)
        safe = jnp.clip(local, 0, n_shard - 1)
        picked_local = jnp.take_along_axis(
            xf, safe[..., None], axis=-1)[..., 0]
        picked = jax.lax.psum(
            jnp.where(in_shard, picked_local, 0.0), mp_axis)
        loss = jnp.log(jnp.maximum(s, 1e-30)) + m - picked
    else:
        n_cls = x.shape[-1]
        m = jax.lax.stop_gradient(jnp.max(xf, axis=-1, keepdims=True))
        lse = jnp.log(jnp.sum(jnp.exp(xf - m), axis=-1)) + m[..., 0]
        # one_hot contraction instead of take_along_axis: partitions as
        # (local masked reduce + all-reduce) under a vocab sharding
        oh = jax.nn.one_hot(li, n_cls, dtype=xf.dtype)
        picked = jnp.sum(oh * xf, axis=-1)
        loss = lse - picked
    valid = li != ignore_index
    return jnp.where(valid, loss, 0.0)


class ParallelCrossEntropy(Layer):
    """Cross entropy over mp-sharded logits (reference mp_layers.py:498 —
    c_softmax_with_cross_entropy): no full-vocab gather in either the
    per-shard or the GSPMD execution form."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, input, label):
        return parallel_softmax_cross_entropy(
            input, label, ignore_index=self.ignore_index)


class ParallelEmbedding(VocabParallelEmbedding):
    pass


def get_rng_state_tracker():
    """reference mpu/random.py RNGStatesTracker. Real implementation in
    framework/random.py: named rng states; rank-local states fold in
    axis_index('mp') inside per-shard programs so dropout masks differ
    across mp ranks; under GSPMD the single logical mask is already
    per-position."""
    from ..framework.random import get_rng_state_tracker as _get

    return _get()


# -- paddle.distributed.split --------------------------------------------
# (reference python/paddle/distributed/collective.py split: create a
# model-parallel linear/embedding whose weight is partitioned over the
# mp ranks and apply it). Layers cache by name so repeated dygraph calls
# train ONE set of parallel weights, matching the reference's
# create-once static-graph semantics.

_split_layers = {}


def split(x, size, operation="linear", axis=0, num_partitions=1,
          gather_out=True, weight_attr=None, bias_attr=None, name=None):
    """NOTE on identity: unnamed calls are keyed by their CALL SITE, so
    the same source line re-executed each step (dygraph) reuses its one
    layer — including when the surrounding forward() is reached from
    different outer call sites (train loop vs eval), which MUST share
    weights. Two ambiguous shapes therefore share weights SILENTLY and
    need an explicit `name` per logical layer: a LOOP calling split on
    one line, and a shared HELPER function whose one split line serves
    several distinct logical layers (no stack heuristic can tell either
    apart from the legitimate train/eval re-entry above — both change
    only outer frames)."""
    if name is None:
        import sys

        f = sys._getframe(1)
        name = "split@%s:%d" % (f.f_code.co_filename, f.f_lineno)
    key = (name, operation, tuple(size), axis, bool(gather_out),
           num_partitions, bias_attr is not False)
    cached = _split_layers.get(key)
    if cached is not None:
        layer, made_with_attr = cached
        if made_with_attr is not weight_attr:
            raise ValueError(
                "distributed.split: cached layer %r was created with a "
                "different weight_attr; pass a distinct name per layer"
                % (name,))
        return layer(x)
    if operation == "linear":
        if axis == 1:  # split the output features -> column parallel
            layer = ColumnParallelLinear(
                size[0], size[1], weight_attr=weight_attr,
                has_bias=bias_attr is not False,
                gather_output=gather_out, name=name)
        elif axis == 0:  # split the reduce dim -> row parallel
            layer = RowParallelLinear(
                size[0], size[1], weight_attr=weight_attr,
                has_bias=bias_attr is not False,
                input_is_parallel=False, name=name)
        else:
            raise ValueError("linear split axis must be 0 or 1")
    elif operation == "embedding":
        layer = VocabParallelEmbedding(
            size[0], size[1], weight_attr=weight_attr, name=name)
    else:
        raise ValueError(
            "split operation must be 'linear' or 'embedding', got %r"
            % (operation,))
    _split_layers[key] = (layer, weight_attr)
    return layer(x)
