"""Tensor-parallel (Megatron-style) layers.

Parity: reference fleet/layers/mpu/mp_layers.py — VocabParallelEmbedding
(:35), ColumnParallelLinear (:173), RowParallelLinear (:332),
ParallelCrossEntropy (:498) and mp_ops.py's _c_identity/_mp_allreduce.

TPU-native: params carry PartitionSpecs over the 'mp' mesh axis; under pjit
the GSPMD partitioner inserts exactly the identity/all-reduce pairs the
reference codes by hand (c_identity forward + allreduce backward for column;
allreduce forward for row). Eager single-host execution still computes the
full math. with_sharding_constraint marks the activation boundaries.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..core.dispatch import primitive
from ..core.tensor import Tensor
from ..nn import functional as F
from ..nn import initializer as I
from ..nn.layer import Layer

_A = jnp.asarray


@primitive
def _sharded(x, spec_tuple):
    """Annotate an activation with a sharding constraint (no-op outside jit)."""
    x = _A(x)
    try:
        return jax.lax.with_sharding_constraint(x, P(*spec_tuple))
    except Exception:
        return x


def mark_sharding(t, *spec):
    if isinstance(t, Tensor):
        return _sharded(t, spec_tuple=tuple(spec))
    return t


class ColumnParallelLinear(Layer):
    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, gather_output=True, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.gather_output = gather_output
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=I.XavierNormal())
        self.weight._sharding_spec = P(None, "mp")  # split columns
        self.bias = None
        if has_bias:
            self.bias = self.create_parameter([out_features], is_bias=True)
            self.bias._sharding_spec = P("mp")

    def forward(self, x):
        out = F.linear(x, self.weight, self.bias)
        if not self.gather_output:
            # keep activation sharded over mp on the feature dim
            nd = out.ndim
            spec = [None] * nd
            spec[-1] = "mp"
            out = mark_sharding(out, *spec)
        return out


class RowParallelLinear(Layer):
    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=False,
                 fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.input_is_parallel = input_is_parallel
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=I.XavierNormal())
        self.weight._sharding_spec = P("mp", None)  # split rows
        self.bias = None
        if has_bias:
            self.bias = self.create_parameter([out_features], is_bias=True)
            self.bias._sharding_spec = P()

    def forward(self, x):
        if self.input_is_parallel:
            nd = x.ndim
            spec = [None] * nd
            spec[-1] = "mp"
            x = mark_sharding(x, *spec)
        out = F.linear(x, self.weight, self.bias)
        # partial sums are all-reduced by the partitioner; mark replicated
        out = mark_sharding(out, *([None] * out.ndim))
        return out


class VocabParallelEmbedding(Layer):
    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group=None, name=None):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=I.Normal(0.0, 0.02))
        self.weight._sharding_spec = P("mp", None)  # split vocab rows

    def forward(self, x):
        return F.embedding(x, self.weight)


class ParallelCrossEntropy(Layer):
    """Cross entropy over mp-sharded logits (reference mp_layers.py:498 —
    c_softmax_with_cross_entropy). Under pjit the partitioner handles the
    sharded max/sum reductions; the expression is the stable fused form."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, input, label):
        return F.cross_entropy(input, label, reduction="none",
                               ignore_index=self.ignore_index)


class ParallelEmbedding(VocabParallelEmbedding):
    pass


def get_rng_state_tracker():
    """reference mpu/random.py RNGStatesTracker: dropout seeds differ per mp
    rank. JAX keys are deterministic per position via fold_in(axis_index)."""

    class _Tracker:
        def rng_state(self, name="global_seed"):
            import contextlib

            return contextlib.nullcontext()

        def add(self, name, seed):
            pass

    return _Tracker()
