"""Pipeline parallelism.

Parity: reference fleet/meta_parallel/parallel_layers/pp_layers.py
(LayerDesc:57, SharedLayerDesc:93, PipelineLayer:209 — stage partitioning by
uniform or param-weighted cut) and pipeline_parallel.py:31 (1F1B schedule at
:117, interleaved at :461) with p2p over send_v2/recv_v2.

TPU-native execution: a single controller owns all stages, so the schedule
is not process choreography but program structure. Two modes:

- eager (this file): GPipe-style microbatch loop — forward all micro-batches
  stage by stage, backward in reverse; correct on any mesh, used for
  correctness tests and small runs.
- compiled (`ring_pipeline` + `PipelinedTrainStep` below): stage params
  stacked on a leading dim sharded over 'pp'; per step all stages compute
  in parallel and the activation buffer rotates (collective-permute over
  ICI) — the 1F1B steady state as program structure, with interleaved
  virtual stages via vpp>1. This is the TPU analog of the reference's
  interceptor runtime and what the Llama configs use.
"""
from __future__ import annotations

import time

import jax
import jax.numpy as jnp

from .. import monitor as _monitor
from ..core.tensor import Tensor
from ..nn.layer import Layer
from ..nn.layers.container import LayerList


class LayerDesc:
    def __init__(self, layer_cls, *inputs, **kwargs):
        self.layer_cls = layer_cls
        self.inputs = inputs
        self.kwargs = kwargs

    def build_layer(self):
        return self.layer_cls(*self.inputs, **self.kwargs)


class SharedLayerDesc(LayerDesc):
    """Tied layers across stages (e.g. embedding/unembedding). On a single
    controller the same Layer object is simply reused — weight tying is free
    (the reference must all-reduce tied grads across stages)."""

    def __init__(self, key, layer_cls, *inputs, forward_func=None,
                 shared_weight_attr="weight", **kwargs):
        super().__init__(layer_cls, *inputs, **kwargs)
        self.key = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class SegmentLayers:
    """Partition N layers into `num_parts` stages (reference
    pp_layers.py:57 SegmentLayers).

    Methods:
      "uniform"        — equal layer counts (reference default);
      "parameter"      — balance total parameter count per stage
                         (optimal contiguous partition minimizing the
                         max-stage weight, the reference's
                         _segment_network weighted mode);
      "layer:<Name>"   — equal counts of the named layer class per
                         stage, boundaries at matches (reference
                         seg_method="layer:TransformerLayer").
    Unknown methods raise (accept-and-ignore is banned)."""

    def __init__(self, layers_desc, num_parts, method="uniform"):
        self.descs = layers_desc
        self.num_parts = num_parts
        self.method = method

    @staticmethod
    def _entry_layer(d):
        if isinstance(d, tuple):  # PipelineLayer's built (layer, ffunc)
            d = d[0]
        return d

    def _param_count(self, d):
        layer = self._entry_layer(d)
        if isinstance(layer, LayerDesc):
            layer = layer.build_layer()
        if hasattr(layer, "parameters"):
            total = 0
            for p in layer.parameters():
                k = 1
                for s in p.shape:
                    k *= int(s)
                total += k
            return total
        return 0

    def _uniform(self, n):
        base = n // self.num_parts
        extra = n % self.num_parts
        bounds = [0]
        for i in range(self.num_parts):
            bounds.append(bounds[-1] + base + (1 if i < extra else 0))
        return bounds

    def _by_weight(self, weights):
        """Optimal contiguous partition: minimize max stage weight
        (DP over prefix sums; n and num_parts are small)."""
        n, k = len(weights), self.num_parts
        prefix = [0]
        for w in weights:
            prefix.append(prefix[-1] + w)

        def seg(a, b):
            return prefix[b] - prefix[a]

        INF = float("inf")
        # best[j][i] = minimal max-weight splitting first i entries into
        # j stages, each non-empty
        best = [[INF] * (n + 1) for _ in range(k + 1)]
        cut = [[0] * (n + 1) for _ in range(k + 1)]
        best[0][0] = 0.0
        for j in range(1, k + 1):
            for i in range(j, n - (k - j) + 1):
                for m in range(j - 1, i):
                    v = max(best[j - 1][m], seg(m, i))
                    if v < best[j][i]:
                        best[j][i] = v
                        cut[j][i] = m
        bounds = [n]
        i = n
        for j in range(k, 0, -1):
            i = cut[j][i]
            bounds.append(i)
        return list(reversed(bounds))

    def do_segment(self):
        n = len(self.descs)
        if n < self.num_parts:
            raise ValueError(
                "cannot segment %d layers into %d pipeline stages"
                % (n, self.num_parts))
        if self.method == "uniform":
            return self._uniform(n)
        if self.method == "parameter":
            # zero-param glue (activations, lambdas) attaches to its
            # neighbours; give it a tiny weight so ordering is kept but
            # it never dominates a cut
            weights = [max(self._param_count(d), 1) for d in self.descs]
            return self._by_weight(weights)
        if self.method.startswith("layer:"):
            name = self.method.split(":", 1)[1]
            matches = [i for i, d in enumerate(self.descs)
                       if type(self._entry_layer(d)).__name__ == name
                       or (isinstance(self._entry_layer(d), LayerDesc)
                           and self._entry_layer(d).layer_cls.__name__
                           == name)]
            if len(matches) < self.num_parts:
                raise ValueError(
                    "seg_method %r: %d matching layers < %d stages"
                    % (self.method, len(matches), self.num_parts))
            per = self._uniform(len(matches))
            bounds = [0]
            for b in per[1:-1]:
                bounds.append(matches[b])
            bounds.append(n)
            return bounds
        raise ValueError(
            "unknown seg_method %r (expected 'uniform', 'parameter' or "
            "'layer:<ClassName>')" % (self.method,))


class PipelineLayer(Layer):
    def __init__(self, layers, num_stages=None, topology=None,
                 loss_fn=None, seg_method="uniform", recompute_interval=0,
                 **kwargs):
        super().__init__()
        self._loss_fn = loss_fn
        self.descs = layers
        if topology is not None:
            self.num_stages = topology.get_dim("pipe")
        else:
            self.num_stages = num_stages or 1
        built = []
        self._shared = {}
        for d in self.descs:
            if isinstance(d, SharedLayerDesc):
                if d.key not in self._shared:
                    self._shared[d.key] = d.build_layer()
                built.append((self._shared[d.key], d.forward_func))
            elif isinstance(d, LayerDesc):
                built.append((d.build_layer(), None))
            elif isinstance(d, Layer):
                built.append((d, None))
            else:  # plain callable (lambda)
                built.append((d, None))
        self.run_function = built
        bounds = SegmentLayers(
            built, self.num_stages, seg_method).do_segment()
        self.stage_bounds = bounds
        self._layers_list = LayerList(
            [l for l, _ in built if isinstance(l, Layer)])

    def get_stage_layers(self, stage_id):
        lo, hi = self.stage_bounds[stage_id], self.stage_bounds[stage_id + 1]
        return self.run_function[lo:hi]

    def forward(self, x):
        for fn, ffunc in self.run_function:
            if ffunc is not None:
                x = ffunc(fn, x)
            elif isinstance(fn, Layer) or callable(fn):
                x = fn(x)
        return x


class PipelineParallel(Layer):
    """Micro-batched pipeline training driver (reference
    pipeline_parallel.py:31 train_batch/forward_backward_pipeline)."""

    def __init__(self, layers, hcg=None, strategy=None):
        super().__init__()
        if not isinstance(layers, PipelineLayer):
            raise TypeError(
                "PipelineParallel expects a PipelineLayer (reference "
                "requires the same)")
        self._layers = layers
        self._hcg = hcg
        self.accumulate_steps = 1
        self.micro_batch_size = 1
        if strategy is not None:
            cfg = strategy.pipeline_configs
            self.accumulate_steps = cfg.get("accumulate_steps", 1)
            self.micro_batch_size = cfg.get("micro_batch_size", 1)

    def forward(self, x):
        return self._layers(x)

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None,
                    schedule="1f1b"):
        """Micro-batched accumulation step (reference train_batch →
        forward_backward_pipeline, pipeline_parallel.py:117).

        schedule='1f1b': warmup of (num_stages-1) forwards, then steady-state
        alternating forward/backward, then cooldown — the reference's 1F1B
        order, which bounds live microbatch activations at num_stages instead
        of n_micro. schedule='gpipe': all forwards, then all backwards.
        On a single controller both are numerically identical to sequential
        accumulation; the compiled ring (PipelinedTrainStep) is the
        performance path — this loop is the eager/debugging analog.
        """
        inputs, labels = data
        n_micro = self.accumulate_steps
        batch = inputs.shape[0]
        micro = max(batch // n_micro, 1)
        slices = [(inputs[i:i + micro], labels[i:i + micro])
                  for i in range(0, batch, micro)]
        n = len(slices)
        total_loss = None
        optimizer.clear_grad()

        def fwd(i):
            nonlocal total_loss
            x, y = slices[i]
            out = self._layers(x)
            loss = self._layers._loss_fn(out, y) / n
            total_loss = loss if total_loss is None else total_loss + loss
            return loss

        def bwd(loss):
            if scaler is not None:
                scaler.scale(loss).backward()
            else:
                loss.backward()

        if schedule == "gpipe":
            pending = [fwd(i) for i in range(n)]
            for loss in pending:
                bwd(loss)
        else:  # 1f1b
            warmup = min(self._layers.num_stages - 1, n)
            pending = [fwd(i) for i in range(warmup)]
            for i in range(warmup, n):
                pending.append(fwd(i))
                bwd(pending.pop(0))
            while pending:
                bwd(pending.pop(0))

        if scaler is not None:
            scaler.step(optimizer)
        else:
            optimizer.step()
        if lr_scheduler is not None:
            lr_scheduler.step()
        optimizer.clear_grad()
        return total_loss

    def eval_batch(self, data, compute_loss=True):
        inputs, labels = data
        out = self._layers(inputs)
        if compute_loss:
            return self._layers._loss_fn(out, labels)
        return out

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, prefix="", include_sublayers=True):
        return self._layers.named_parameters(prefix, include_sublayers)

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, sd, **k):
        return self._layers.set_state_dict(sd, **k)


def ring_pipeline(stage_fn, stacked_params, micro_x, n_pp, vpp=1,
                  constrain=None, remat=True):
    """Compiled circular pipeline (the TPU-native 1F1B).

    Parity: reference pipeline_parallel.py:117 (forward_backward_pipeline,
    1F1B) and :461 (PipelineParallelWithInterleave, virtual stages) + the
    send_v2/recv_v2 p2p ops. Here the whole schedule is ONE differentiable
    program: stage params are stacked on a leading dim sharded over 'pp';
    per step every stage applies its chunk in parallel (vmap over the stage
    dim) and the activation buffer rotates one position (jnp.roll on the
    'pp'-sharded dim -> XLA collective-permute over ICI). jax.grad through
    the scan gives the backward pipeline in reverse ring order; per-stage
    jax.checkpoint keeps live activations at O(n_pp + n_micro) — the 1F1B
    memory profile — instead of GPipe's O(n_micro * L).

    stage_fn(chunk_params, x) -> y; chunk_params leaves [layers_per_chunk,…].
    stacked_params leaves: [n_pp, vpp, layers_per_chunk, ...].
    micro_x: [n_micro, micro_batch, ...].
    vpp > 1 = interleaved virtual stages (Megatron layout: chunk c on stage s
    holds layers (c*n_pp + s)*lpc ...): microbatches go around the ring vpp
    times, shrinking the bubble fraction from (n_pp-1)/n_micro to
    (n_pp-1)/(vpp*n_micro); requires n_micro % n_pp == 0.
    """
    n_micro = micro_x.shape[0]
    if vpp > 1 and n_micro % n_pp != 0:
        raise ValueError(
            "interleaved schedule needs n_micro %% n_pp == 0 (got %d, %d)"
            % (n_micro, n_pp))
    cycle = vpp * n_pp
    total = n_micro * vpp + n_pp - 1
    sfn = jax.checkpoint(stage_fn) if remat else stage_fn
    _c = constrain if constrain is not None else (lambda a: a)

    def apply_stage(s_idx, t, chunks_s, x):
        # chunks_s leaves: [vpp, lpc, ...]; pick this stage's current chunk
        if vpp == 1:
            params = jax.tree_util.tree_map(lambda p: p[0], chunks_s)
        else:
            u = t - s_idx
            c = jnp.clip(jnp.mod(u, cycle) // n_pp, 0, vpp - 1)
            params = jax.tree_util.tree_map(
                lambda p: jax.lax.dynamic_index_in_dim(
                    p, c, 0, keepdims=False), chunks_s)
        return sfn(params, x)

    vstage = jax.vmap(apply_stage, in_axes=(0, None, 0, 0))
    s_ids = jnp.arange(n_pp)

    state = _c(jnp.zeros((n_pp,) + micro_x.shape[1:], micro_x.dtype))
    outputs = jnp.zeros_like(micro_x)

    def step(carry, t):
        state, outputs = carry
        # inject into stage 0 while fresh microbatches remain
        if vpp == 1:
            m_in = jnp.clip(t, 0, n_micro - 1)
            do_inject = t < n_micro
        else:
            q0 = jnp.mod(t, cycle)
            m_in = jnp.clip((t // cycle) * n_pp + q0, 0, n_micro - 1)
            do_inject = (q0 < n_pp) & (t < n_micro * vpp)
        inj = jax.lax.dynamic_index_in_dim(micro_x, m_in, 0, keepdims=False)
        state = state.at[0].set(jnp.where(do_inject, inj, state[0]))
        y = _c(vstage(s_ids, t, stacked_params, _c(state)))
        # extract finished microbatch from the last stage
        u = t - (n_pp - 1)
        if vpp == 1:
            m_out = jnp.clip(u, 0, n_micro - 1)
            do_out = (u >= 0) & (u < n_micro)
        else:
            q = jnp.mod(u, cycle)
            m_out = jnp.clip((u // cycle) * n_pp + jnp.mod(q, n_pp),
                             0, n_micro - 1)
            do_out = (u >= 0) & (q // n_pp == vpp - 1) & (u < n_micro * vpp)
        cur = jax.lax.dynamic_index_in_dim(outputs, m_out, 0, keepdims=False)
        outputs = jax.lax.dynamic_update_index_in_dim(
            outputs, jnp.where(do_out, y[-1], cur), m_out, 0)
        state = jnp.roll(y, 1, axis=0)  # stage s output -> stage s+1 input
        return (state, outputs), None

    (state, outputs), _ = jax.lax.scan(
        step, (state, outputs), jnp.arange(total))
    return outputs


class PipelinedTrainStep:
    """jit-compiled pipeline-parallel train step over the current mesh.

    Wires ring_pipeline into a decoder model that exposes the pipeline
    protocol (pipeline_blocks / forward_embed / forward_head — e.g.
    LlamaForCausalLM): block params are stacked [n_pp, vpp, lpc, ...] and
    sharded over 'pp'; embed/head stay outside the ring (replicated or
    mp-sharded); forward+backward+update is ONE XLA module, composing with
    dp/mp shardings on the other mesh axes. This replaces the reference's
    process-choreographed 1F1B (pipeline_parallel.py:117) with program
    structure.
    """

    def __init__(self, model, loss_fn, optimizer, n_micro, vpp=1, mesh=None,
                 donate=True, remat=True, zero_stage=0,
                 fused_loss_tail=False):
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..distributed import mesh as _mesh
        from .engine import _normalize_spec

        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.mesh = mesh or _mesh.get_mesh()
        self.n_micro = n_micro
        self.vpp = vpp
        self.remat = remat
        self.donate = donate
        # ZeRO composed with PP+TP+DP (the reference GroupSharded +
        # PipelineLayer hybrid; Megatron-LM "distributed optimizer"):
        # stage 1 shards optimizer slots over the 'sharding' mesh axis,
        # stage 2 additionally reduce-scatters gradients onto it
        self.zero_stage = zero_stage
        # EXPLICIT opt-in: route the loss through the model's
        # forward_head_loss (e.g. llama's fused lm_head+CE kernel) —
        # this REPLACES loss_fn, so it is never keyed on a global flag
        # alone (a non-plain-CE loss_fn would silently change
        # objective otherwise)
        self.fused_loss_tail = fused_loss_tail
        if fused_loss_tail and not hasattr(model, "forward_head_loss"):
            raise ValueError(
                "fused_loss_tail=True but the model does not define "
                "forward_head_loss")
        if "pp" not in self.mesh.axis_names:
            raise ValueError("PipelinedTrainStep needs a 'pp' mesh axis")
        self.n_pp = self.mesh.shape["pp"]

        blocks = list(model.pipeline_blocks())
        L = len(blocks)
        n_chunks = self.n_pp * vpp
        if L % n_chunks != 0:
            raise ValueError(
                "num layers %d not divisible by pp*vpp=%d" % (L, n_chunks))
        self.lpc = L // n_chunks
        self.template = blocks[0]
        _sfx, _vals = self.template.functional_state()
        self.suffixes = _sfx
        # template param ranks: lets the stacked-grad clip recover the
        # per-LAYER view (leading axes are stack dims, trailing axes are
        # the parameter) so per-parameter clip semantics match eager
        self._tpl_ndim = {s: jnp.ndim(v) for s, v in zip(_sfx, _vals)}
        # block buffers / frozen params ride through the pipeline but are
        # NOT optimized (mirrors _nb_trainable filtering below)
        self._train_sfx = [
            n for n, prm in self.template.named_parameters()
            if not prm.stop_gradient]

        # Megatron interleaved layout: chunk c on stage s holds layers
        # (c*n_pp + s)*lpc ... +lpc  (reference pp_layers.py:209 interleave)
        def layer_values(suffix):
            per = []
            for s in range(self.n_pp):
                row = []
                for c in range(vpp):
                    lo = (c * self.n_pp + s) * self.lpc
                    row.append(jnp.stack(
                        [blocks[lo + j].raw_state_tensors()[suffix]._value
                         for j in range(self.lpc)]))
                per.append(jnp.stack(row))
            return jnp.stack(per)  # [n_pp, vpp, lpc, ...]

        self._blocks = blocks
        self._stacked = {sfx: layer_values(sfx) for sfx in self.suffixes}

        # non-block params/buffers (embed, final norm, lm head)
        block_ids = set()
        for b in blocks:
            for t in b.raw_state_tensors().values():
                block_ids.add(id(t))
        tensors = model.raw_state_tensors()
        all_names = model.functional_state()[0]
        self._nb_names = [n for n in all_names
                          if id(tensors[n]) not in block_ids]
        self._nb_trainable = [
            n for n, p in model.named_parameters()
            if id(p) not in block_ids and not p.stop_gradient]

        # shardings: stacked leaves get ('pp', None, None) + the template
        # param's own spec (mp for mpu layers); non-block via explicit spec
        def stacked_spec(sfx):
            t = self.template.raw_state_tensors()[sfx]
            base = _normalize_spec(t._sharding_spec, len(t.shape)) \
                if t._sharding_spec is not None else [None] * len(t.shape)
            return P("pp", None, None, *base)

        self._stacked_specs = {s: stacked_spec(s) for s in self.suffixes}
        self._nb_specs = {}
        for n in self._nb_names:
            t = tensors[n]
            self._nb_specs[n] = (t._sharding_spec
                                 if t._sharding_spec is not None else P())
        self._ns = lambda spec: NamedSharding(self.mesh, spec)
        # place
        for n in self._nb_names:
            tensors[n]._value = jax.device_put(
                tensors[n]._value, self._ns(self._nb_specs[n]))
        for s in self.suffixes:
            self._stacked[s] = jax.device_put(
                self._stacked[s], self._ns(self._stacked_specs[s]))

        pdict = {n: tensors[n]._value for n in self._nb_trainable}
        pdict.update({"pp_blocks." + s: self._stacked[s]
                      for s in self._train_sfx})
        self._opt_state = optimizer.functional_init(pdict)
        # decay-exclusion hooks resolve per functional name; stacked
        # block entries have no single Parameter — map them to the
        # template block's parameter so name-based exclusions (AdamW
        # apply_decay_param_fun) behave uniformly across the stack
        fmap = {n: tensors[n] for n in self._nb_trainable}
        tpl_params = dict(self.template.named_parameters())
        for s_ in self._train_sfx:
            if s_ in tpl_params:
                fmap["pp_blocks." + s_] = tpl_params[s_]
        optimizer.set_functional_params(fmap)
        if (getattr(optimizer, "_apply_decay_param_fun", None) is not None
                or getattr(optimizer, "_exclude_fn", None) is not None
                or getattr(optimizer, "_exclude", None)):
            import warnings

            warnings.warn(
                "PipelinedTrainStep: per-parameter decay exclusions are "
                "evaluated on the TEMPLATE (first) block's parameters "
                "and applied uniformly to every pipelined layer in the "
                "stack; a predicate that distinguishes individual layers "
                "cannot act layer-wise on the stacked representation.")
        for name, slots in self._opt_state.items():
            self._opt_state[name] = [
                jax.device_put(sl, self._ns(self._slot_spec(
                    name, jnp.shape(sl))))
                if jnp.shape(sl) else sl for sl in slots]

        # The reference data-parallel world = dp * sharding degree, and
        # batch ALWAYS splits over it — except one scoped workaround:
        # at stage 0/1 WITH a real dp axis, batch stays on dp only.
        # Sharding is then purely an optimizer-state partitioning axis
        # and the ring carry avoids a known XLA partitioner reshard
        # inefficiency (spmd_partitioner involuntary-remat on mixed
        # (dp,sharding) batch groupings, b/433785288). Stage>=2 accepts
        # that cost for the reduce-scatter win; a mesh with ONLY a
        # sharding axis keeps the batch split over it — replicated
        # compute would be a far worse regression than the reshard.
        def _deg(a):
            return (self.mesh.shape[a]
                    if a in self.mesh.axis_names else 1)

        if self.zero_stage >= 2 or _deg("dp") <= 1:
            wanted = ("dp", "sharding")
        else:
            wanted = ("dp",)
        batch_axes = tuple(a for a in wanted if _deg(a) > 1)
        self._dp = batch_axes if batch_axes else None
        self.batch_spec = P(batch_axes) if batch_axes else P()
        # checkpoint continuity, mirroring CompiledTrainStep: seed slots
        # from accumulators restored via set_state_dict (per-block slots
        # restack into the Megatron layout), resume the step counter,
        # and register the lazy state_dict sync hook
        self._seed_opt_state_from_accumulators(optimizer, tensors)
        self._step_count = int(optimizer._global_step)
        optimizer._functional_sync = self._sync_opt_state_out
        optimizer._functional_load = self._load_opt_state_in
        self._compiled = None
        # MFU/phase attribution (monitor/perf.py), opt-in via
        # FLAGS_perf_attribution — same discipline as CompiledTrainStep
        self._perf_attr = None

    # -- ZeRO slot/grad sharding -------------------------------------------

    def _param_shape(self, name):
        if name.startswith("pp_blocks."):
            return tuple(jnp.shape(self._stacked[name[len("pp_blocks."):]]))
        # cached walk: raw_state_tensors() recurses the whole module
        # tree and _slot_spec calls here per slot per name
        tensors = self.__dict__.get("_model_tensors")
        if tensors is None:
            tensors = self._model_tensors = self.model.raw_state_tensors()
        return tuple(tensors[name].shape)

    def _slot_spec(self, name, slot_shape):
        """Optimizer-slot (and, at stage>=2, gradient) sharding: param-
        shaped slots take the param's spec plus — at zero_stage>=1 — the
        'sharding' axis on the largest divisible free dim (engine
        zero_spec); non-param-shaped slots stay replicated."""
        from jax.sharding import PartitionSpec as P

        from .engine import zero_spec

        if name.startswith("pp_blocks."):
            base = self._stacked_specs[name[len("pp_blocks."):]]
        else:
            base = self._nb_specs[name]
        pshape = self._param_shape(name)
        if tuple(slot_shape) != pshape:
            return P()
        if self.zero_stage >= 1:
            return zero_spec(pshape, base, self.mesh)
        return base

    # -- optimizer-state checkpoint bridge ---------------------------------

    def _block_param(self, sfx, idx):
        return self._blocks[idx].raw_state_tensors()[sfx]

    def _stack_layout(self):
        """(stage, chunk, local) -> flat block index, Megatron layout
        (same walk as sync_to_model)."""
        for st in range(self.n_pp):
            for c in range(self.vpp):
                for j in range(self.lpc):
                    yield st, c, j, (c * self.n_pp + st) * self.lpc + j

    def _seed_opt_state_from_accumulators(self, opt, tensors):
        slots = opt._slots()
        for n in self._nb_trainable:
            for j, slot in enumerate(slots):
                key = (slot, id(tensors[n]))
                if key in opt._accumulators:
                    arr = jnp.asarray(opt._accumulators[key])
                    self._opt_state[n][j] = jax.device_put(
                        arr, self._ns(self._slot_spec(n, jnp.shape(arr))))
        for sfx in self._train_sfx:
            name = "pp_blocks." + sfx
            for j, slot in enumerate(slots):
                per_block = {}
                for st, c, k, idx in self._stack_layout():
                    key = (slot, id(self._block_param(sfx, idx)))
                    if key not in opt._accumulators:
                        break
                    per_block[(st, c, k)] = opt._accumulators[key]
                else:
                    arr = jnp.stack([
                        jnp.stack([
                            jnp.stack([jnp.asarray(per_block[(st, c, k)])
                                       for k in range(self.lpc)])
                            for c in range(self.vpp)])
                        for st in range(self.n_pp)])
                    self._opt_state[name][j] = jax.device_put(
                        arr, self._ns(self._slot_spec(name,
                                                      jnp.shape(arr))))

    def _load_opt_state_in(self):
        """Reverse bridge (optimizer _functional_load hook): re-seed the
        functional slots from accumulators restored by set_state_dict
        AFTER this step object was built (resume-after-compile)."""
        self._seed_opt_state_from_accumulators(
            self.optimizer, self.model.raw_state_tensors())
        self._step_count = int(self.optimizer._global_step)

    def _sync_opt_state_out(self):
        """Mirror functional slots into the optimizer's accumulators —
        stacked entries unstack to the per-block Parameters (the same
        walk sync_to_model uses for weights). Lazy: runs only when
        state_dict() reads the optimizer."""
        opt = self.optimizer
        tensors = self.model.raw_state_tensors()
        slots = opt._slots()
        for n in self._nb_trainable:
            for j, slot in enumerate(slots):
                opt._accumulators[(slot, id(tensors[n]))] = jnp.copy(
                    self._opt_state[n][j])
        for sfx in self._train_sfx:
            name = "pp_blocks." + sfx
            tpl_nd = self._tpl_ndim[sfx]
            for j, slot in enumerate(slots):
                arr = self._opt_state[name][j]
                if jnp.ndim(arr) != tpl_nd + 3:
                    # a slot that is not per-block-param shaped has no
                    # per-block view; silently dropping it would make
                    # checkpoints lie for a future optimizer
                    raise NotImplementedError(
                        "pipeline optimizer checkpoint: slot %r for %r "
                        "has ndim %d (expected template ndim %d + 3 "
                        "stack dims); per-block unstacking is undefined "
                        "for this shape" % (slot, name, jnp.ndim(arr),
                                            tpl_nd))
                for st, c, k, idx in self._stack_layout():
                    opt._accumulators[
                        (slot, id(self._block_param(sfx, idx)))] =                         arr[st, c, k]
        opt._global_step = self._step_count

    # -- forward pieces ----------------------------------------------------

    def _stage_fn(self):
        template, suffixes = self.template, self.suffixes

        def stage(chunk_params, x):
            # chunk_params: list of leaves [lpc, ...] aligned with suffixes
            def body(h, per_layer):
                out = template.functional_call(per_layer, Tensor(h),
                                               state_names=suffixes)
                return (out._value if isinstance(out, Tensor) else out), None

            h, _ = jax.lax.scan(body, x, chunk_params)
            return h

        return stage

    def _constrain(self):
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh, dp = self.mesh, self._dp

        def c(a):
            spec = P("pp", dp, *([None] * (a.ndim - 2)))
            return jax.lax.with_sharding_constraint(
                a, NamedSharding(mesh, spec))

        return c

    def _build(self):
        from ..core.dispatch import no_grad

        model, loss_fn, opt = self.model, self.loss_fn, self.optimizer
        nb_names, nb_trainable = self._nb_names, self._nb_trainable
        suffixes = self.suffixes
        n_micro, n_pp, vpp = self.n_micro, self.n_pp, self.vpp
        stage = self._stage_fn()
        constrain = self._constrain()
        remat = self.remat

        train_sfx = self._train_sfx
        fused_tail = self.fused_loss_tail
        grad_sh = None
        if self.zero_stage >= 2:
            grad_sh = {
                n: self._ns(self._slot_spec(n, self._param_shape(n)))
                for n in (list(self._nb_trainable)
                          + ["pp_blocks." + s for s in train_sfx])}

        def step(nb_vals, stacked_vals, opt_state, step_i, lr_i, rng_key,
                 batch):
            nb_state = dict(zip(nb_names, nb_vals))
            stacked_state = dict(zip(suffixes, stacked_vals))

            def loss_of(train, batch):
                from ..framework import random as _random

                nb_train, st_train = train
                stacked = dict(stacked_state)
                stacked.update(st_train)
                full = dict(nb_state)
                full.update(dict(zip(nb_trainable, nb_train)))
                ids, labels = batch
                # per-step RNG threading (same frozen-dropout-mask fix
                # as CompiledTrainStep; rng_key is a traced ARGUMENT so
                # paddle.seed after compilation still steers masks)
                with _random.replay_base(
                        jax.random.fold_in(rng_key, step_i)), \
                        model.bind_state(nb_names,
                                         [full[n] for n in nb_names]):
                    with no_grad():
                        x = model.forward_embed(Tensor(ids))
                        x = x._value if isinstance(x, Tensor) else x
                        B = x.shape[0]
                        mb = B // n_micro
                        micro = x.reshape((n_micro, mb) + x.shape[1:])
                        out = ring_pipeline(
                            stage, [stacked[s] for s in suffixes], micro,
                            n_pp, vpp=vpp, constrain=constrain, remat=remat)
                        h = out.reshape((B,) + out.shape[2:])
                        loss = None
                        if fused_tail:
                            loss = model.forward_head_loss(
                                Tensor(h), Tensor(labels))
                        if loss is None:
                            logits = model.forward_head(Tensor(h))
                            loss = loss_fn(logits, Tensor(labels))
                return loss._value if isinstance(loss, Tensor) else loss

            train = ([nb_state[n] for n in nb_trainable],
                     {s: stacked_state[s] for s in train_sfx})
            loss, grads = jax.value_and_grad(loss_of)(train, batch)
            g_nb, g_stacked = grads
            pdict = {n: nb_state[n] for n in nb_trainable}
            pdict.update({"pp_blocks." + s: train[1][s] for s in train_sfx})
            gdict = dict(zip(nb_trainable, g_nb))
            gdict.update({"pp_blocks." + s: g_stacked[s] for s in train_sfx})
            if grad_sh is not None:
                # ZeRO-2: constraining the raw grads to the 'sharding'
                # axis makes XLA emit reduce-scatter (not all-reduce)
                # for the data-parallel grad combine
                gdict = {n: jax.lax.with_sharding_constraint(g, grad_sh[n])
                         if g is not None else g
                         for n, g in gdict.items()}
            gdict = self._clip_grads(opt, gdict)
            clip_save = opt._grad_clip
            opt._grad_clip = None  # clipped above with per-layer
            try:                   # semantics; don't re-clip jointly
                # lr as an ARGUMENT: a trace-time lr would freeze the
                # scheduler's value into the executable
                new_p, new_s = opt.functional_apply(pdict, gdict,
                                                    opt_state, lr=lr_i,
                                                    step=step_i)
            finally:
                opt._grad_clip = clip_save
            out_nb = [new_p.get(n, nb_state[n]) for n in nb_names]
            out_stacked = [new_p.get("pp_blocks." + s, stacked_state[s])
                           for s in suffixes]
            return loss, out_nb, out_stacked, new_s

        from jax.sharding import NamedSharding, PartitionSpec as P

        nb_sh = [self._ns(self._nb_specs[n]) for n in nb_names]
        st_sh = [self._ns(self._stacked_specs[s]) for s in suffixes]
        opt_sh = {}
        for name, slots in self._opt_state.items():
            opt_sh[name] = [
                self._ns(self._slot_spec(name, jnp.shape(sl)))
                if jnp.shape(sl) else self._ns(P()) for sl in slots]
        self._compiled = jax.jit(
            step,
            in_shardings=(nb_sh, st_sh, opt_sh, None, None, None,
                          self._ns(self.batch_spec)),
            out_shardings=(self._ns(P()), nb_sh, st_sh, opt_sh),
            donate_argnums=(0, 1, 2) if self.donate else (),
        )

    def _clip_grads(self, opt, gdict):
        """Apply the optimizer's grad_clip with PER-LAYER semantics on
        the stacked 'pp_blocks.*' entries (leading axes are stack dims):
        ClipGradByNorm must clip each logical layer parameter to its own
        norm, exactly as the eager/non-pipeline paths do — clipping the
        stacked array jointly would shrink every layer by ~sqrt(n_pp)
        too much. ByValue is elementwise and GlobalNorm reduces over
        everything, so both are stack-agnostic and delegate as-is."""
        clip = opt._grad_clip
        if clip is None:
            return gdict
        present = {n: g for n, g in gdict.items() if g is not None}
        reduce_axes = {}
        for n, g in present.items():
            if n.startswith("pp_blocks."):
                tpl_nd = self._tpl_ndim[n[len("pp_blocks."):]]
                reduce_axes[n] = tuple(range(g.ndim - tpl_nd, g.ndim))
        return {**gdict,
                **clip.functional_clip(present, reduce_axes=reduce_axes)}

    def __call__(self, input_ids, labels):
        from ..core.dispatch import no_grad

        if self._compiled is None:
            self._build()
        with no_grad():
            batch = tuple(
                jax.device_put(b._value if isinstance(b, Tensor)
                               else jnp.asarray(b),
                               self._ns(self.batch_spec))
                for b in (input_ids, labels))
            tensors = self.model.raw_state_tensors()
            nb_vals = [tensors[n]._value for n in self._nb_names]
            stacked_vals = [self._stacked[s] for s in self.suffixes]
            self._step_count += 1
            from ..framework import random as _random

            t0 = time.perf_counter()
            loss, new_nb, new_stacked, new_opt = self._compiled(
                nb_vals, stacked_vals, self._opt_state,
                jnp.asarray(self._step_count, jnp.int32),
                jnp.asarray(self.optimizer.get_lr(), jnp.float32),
                _random._key(), batch)
            t1 = time.perf_counter()
            for n, v in zip(self._nb_names, new_nb):
                tensors[n]._value = v
            self._stacked = dict(zip(self.suffixes, new_stacked))
            self._opt_state = new_opt
            self._note_perf(batch, t1 - t0, loss, t0, t1)
            # span journal (monitor/trace.py): per-step span + comm
            # child spans, same discipline as CompiledTrainStep
            if _monitor.trace.is_enabled():
                from .engine import _batch_tokens

                _monitor.trace.record_train_step(
                    "train_pp", self._step_count, t1 - t0,
                    tokens=_batch_tokens(batch))
            return Tensor(loss)

    def perf_analysis(self, input_ids, labels):
        """XLA cost/memory analysis of the pipelined step executable
        (monitor/perf.py; AOT lower+compile, perf-flag / bench only)."""
        from ..framework import random as _random
        from ..monitor import perf as _perf

        if self._compiled is None:
            self._build()
        batch = tuple(
            jax.device_put(b._value if isinstance(b, Tensor)
                           else jnp.asarray(b),
                           self._ns(self.batch_spec))
            for b in (input_ids, labels))
        tensors = self.model.raw_state_tensors()
        nb_vals = [tensors[n]._value for n in self._nb_names]
        stacked_vals = [self._stacked[s] for s in self.suffixes]
        compiled = self._compiled.lower(
            nb_vals, stacked_vals, self._opt_state,
            jnp.asarray(0, jnp.int32), jnp.asarray(0.0, jnp.float32),
            _random._key(), batch).compile()
        return _perf.executable_analysis(compiled, steps=1)

    def graph_report(self, input_ids, labels):
        """Raw graph-analysis artifact of the pipelined step for the
        offline analyzer (paddle_tpu/analysis/graph, tools/pthlo.py):
        jaxpr + StableHLO + compiled-HLO text, donated leaf census,
        per-param shardings, XLA cost analysis. AOT lower+compile —
        fixture tooling only, same discipline as perf_analysis."""
        from ..framework import random as _random
        from ..monitor import perf as _perf

        if self._compiled is None:
            self._build()
        batch = tuple(
            jax.device_put(b._value if isinstance(b, Tensor)
                           else jnp.asarray(b),
                           self._ns(self.batch_spec))
            for b in (input_ids, labels))
        tensors = self.model.raw_state_tensors()
        nb_vals = [tensors[n]._value for n in self._nb_names]
        stacked_vals = [self._stacked[s] for s in self.suffixes]
        from ..analysis.graph.artifact import arg_leaf_census, \
            param_census

        args = (nb_vals, stacked_vals, self._opt_state,
                jnp.asarray(0, jnp.int32), jnp.asarray(0.0, jnp.float32),
                _random._key(), batch)
        lowered = self._compiled.lower(*args)
        compiled = lowered.compile()
        leaves = jax.tree_util.tree_leaves
        carried = len(leaves((args[0], args[1], args[2])))
        total = len(leaves(args))
        spans = [("state" if self.donate else "input", carried),
                 ("input", total - carried)]
        named = [(n, tensors[n]._value) for n in self._nb_names]
        named += [("pp_blocks." + s, self._stacked[s])
                  for s in self.suffixes]
        spec_strs = {n: str(self._nb_specs[n]) for n in self._nb_names}
        spec_strs.update({"pp_blocks." + s: str(self._stacked_specs[s])
                          for s in self.suffixes})
        return {
            "kind": "pipeline",
            "steps": {
                "step": {
                    "hlo": compiled.as_text(),
                    "stablehlo": lowered.as_text(),
                    "jaxpr": "",    # the jitted fn is rebuilt per
                                    # _build; the stablehlo text is the
                                    # fingerprint substrate here
                    "arg_leaves": arg_leaf_census(
                        leaves(lowered.args_info), spans),
                    "cost": _perf.executable_analysis(compiled,
                                                      steps=1),
                },
            },
            "params": param_census(named,
                                   spec_of=lambda n: spec_strs[n]),
            "mesh_axes": dict(self.mesh.shape),
            "qsync_buckets": None,
        }

    def _note_perf(self, batch, dt, loss, t0, t1):
        from ..monitor import perf as _perf

        if not (_monitor.is_enabled() and _perf.attribution_enabled()):
            return
        try:
            if self._perf_attr is None:
                self._perf_attr = _perf.TrainStepPerf(
                    "train_pp",
                    analysis_fn=lambda b=batch: self.perf_analysis(*b))
            tokens = 1
            for d in batch[0].shape[:2]:
                tokens *= int(d)
            self._perf_attr.on_step(dt, steps=1, tokens=tokens,
                                    loss=loss, t_start=t0, t_end=t1)
        except Exception as e:
            from ..monitor.registry import warn_once

            warn_once(
                "pipeline.perf_attr",
                "paddle_tpu.parallel: pipeline perf attribution "
                "failed (train step unaffected, MFU/goodput series "
                "stop): %r" % (e,))

    def sync_to_model(self):
        """Write the stacked block params back into the per-layer tensors
        (for state_dict / checkpoint save)."""
        for sfx in self.suffixes:
            arr = self._stacked[sfx]
            for s in range(self.n_pp):
                for c in range(self.vpp):
                    lo = (c * self.n_pp + s) * self.lpc
                    for j in range(self.lpc):
                        t = self._blocks[lo + j].raw_state_tensors()[sfx]
                        t._value = arr[s, c, j]
