"""Pipeline parallelism.

Parity: reference fleet/meta_parallel/parallel_layers/pp_layers.py
(LayerDesc:57, SharedLayerDesc:93, PipelineLayer:209 — stage partitioning by
uniform or param-weighted cut) and pipeline_parallel.py:31 (1F1B schedule at
:117, interleaved at :461) with p2p over send_v2/recv_v2.

TPU-native execution: a single controller owns all stages, so the schedule
is not process choreography but program structure. Two modes:

- eager (this file): GPipe-style microbatch loop — forward all micro-batches
  stage by stage, backward in reverse; correct on any mesh, used for
  correctness tests and small runs.
- compiled (`scan_pipeline` below): stages stacked into one extra leading
  dim sharded over 'pp'; lax.scan + ppermute shift micro-batch activations
  around the ring — the 1F1B steady state emerges from XLA pipelining the
  collective-permute with the per-stage matmuls. This is the TPU analog of
  the reference's interceptor runtime and what the Llama configs use.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..nn.layer import Layer
from ..nn.layers.container import LayerList


class LayerDesc:
    def __init__(self, layer_cls, *inputs, **kwargs):
        self.layer_cls = layer_cls
        self.inputs = inputs
        self.kwargs = kwargs

    def build_layer(self):
        return self.layer_cls(*self.inputs, **self.kwargs)


class SharedLayerDesc(LayerDesc):
    """Tied layers across stages (e.g. embedding/unembedding). On a single
    controller the same Layer object is simply reused — weight tying is free
    (the reference must all-reduce tied grads across stages)."""

    def __init__(self, key, layer_cls, *inputs, forward_func=None,
                 shared_weight_attr="weight", **kwargs):
        super().__init__(layer_cls, *inputs, **kwargs)
        self.key = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class SegmentLayers:
    """Partition N layers into `num_parts` stages (reference pp_layers.py:
    SegmentLayers — 'uniform' or 'layer'-weighted)."""

    def __init__(self, layers_desc, num_parts, method="uniform"):
        self.descs = layers_desc
        self.num_parts = num_parts
        self.method = method

    def do_segment(self):
        n = len(self.descs)
        base = n // self.num_parts
        extra = n % self.num_parts
        bounds = [0]
        for i in range(self.num_parts):
            bounds.append(bounds[-1] + base + (1 if i < extra else 0))
        return bounds


class PipelineLayer(Layer):
    def __init__(self, layers, num_stages=None, topology=None,
                 loss_fn=None, seg_method="uniform", recompute_interval=0,
                 **kwargs):
        super().__init__()
        self._loss_fn = loss_fn
        self.descs = layers
        if topology is not None:
            self.num_stages = topology.get_dim("pipe")
        else:
            self.num_stages = num_stages or 1
        built = []
        self._shared = {}
        for d in self.descs:
            if isinstance(d, SharedLayerDesc):
                if d.key not in self._shared:
                    self._shared[d.key] = d.build_layer()
                built.append((self._shared[d.key], d.forward_func))
            elif isinstance(d, LayerDesc):
                built.append((d.build_layer(), None))
            elif isinstance(d, Layer):
                built.append((d, None))
            else:  # plain callable (lambda)
                built.append((d, None))
        self.run_function = built
        bounds = SegmentLayers(
            built, self.num_stages, seg_method).do_segment()
        self.stage_bounds = bounds
        self._layers_list = LayerList(
            [l for l, _ in built if isinstance(l, Layer)])

    def get_stage_layers(self, stage_id):
        lo, hi = self.stage_bounds[stage_id], self.stage_bounds[stage_id + 1]
        return self.run_function[lo:hi]

    def forward(self, x):
        for fn, ffunc in self.run_function:
            if ffunc is not None:
                x = ffunc(fn, x)
            elif isinstance(fn, Layer) or callable(fn):
                x = fn(x)
        return x


class PipelineParallel(Layer):
    """Micro-batched pipeline training driver (reference
    pipeline_parallel.py:31 train_batch/forward_backward_pipeline)."""

    def __init__(self, layers, hcg=None, strategy=None):
        super().__init__()
        if not isinstance(layers, PipelineLayer):
            raise TypeError(
                "PipelineParallel expects a PipelineLayer (reference "
                "requires the same)")
        self._layers = layers
        self._hcg = hcg
        self.accumulate_steps = 1
        self.micro_batch_size = 1
        if strategy is not None:
            cfg = strategy.pipeline_configs
            self.accumulate_steps = cfg.get("accumulate_steps", 1)
            self.micro_batch_size = cfg.get("micro_batch_size", 1)

    def forward(self, x):
        return self._layers(x)

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        """GPipe accumulation: forward+backward per micro-batch, grads
        accumulate in .grad, then one optimizer step."""
        import paddle_tpu as P

        inputs, labels = data
        n_micro = self.accumulate_steps
        batch = inputs.shape[0]
        micro = max(batch // n_micro, 1)
        total_loss = None
        optimizer.clear_grad()
        for i in range(0, batch, micro):
            x = inputs[i:i + micro]
            y = labels[i:i + micro]
            out = self._layers(x)
            loss = self._layers._loss_fn(out, y)
            loss = loss / n_micro
            if scaler is not None:
                scaler.scale(loss).backward()
            else:
                loss.backward()
            total_loss = loss if total_loss is None else total_loss + loss
        if scaler is not None:
            scaler.step(optimizer)
        else:
            optimizer.step()
        if lr_scheduler is not None:
            lr_scheduler.step()
        optimizer.clear_grad()
        return total_loss

    def eval_batch(self, data, compute_loss=True):
        inputs, labels = data
        out = self._layers(inputs)
        if compute_loss:
            return self._layers._loss_fn(out, labels)
        return out

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, prefix="", include_sublayers=True):
        return self._layers.named_parameters(prefix, include_sublayers)

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, sd, **k):
        return self._layers.set_state_dict(sd, **k)


def scan_pipeline(stage_fn, stacked_params, x_micro, num_stages, axis="pp"):
    """Compiled ring pipeline: `stage_fn(params, x) -> x` applied across
    `num_stages` stages whose params are stacked on dim 0 (sharded over the
    pp mesh axis inside shard_map). Micro-batches stream through with
    collective-permute shifts; total steps = n_micro + num_stages - 1.

    Used inside shard_map(..., axis_names={'pp'}): each pp position holds one
    stage's params; activations rotate via ppermute — the XLA analog of the
    reference's send_v2/recv_v2 chain (operators/collective/send_v2_op).
    """
    n_micro = x_micro.shape[0]
    stage_idx = jax.lax.axis_index(axis)
    perm = [(i, (i + 1) % num_stages) for i in range(num_stages)]

    buf = jnp.zeros_like(x_micro[0])
    outputs = jnp.zeros_like(x_micro)

    def step(carry, t):
        buf, outputs = carry
        # stage 0 injects micro-batch t (while it exists)
        inject = jnp.where(t < n_micro, t, n_micro - 1)
        x_in = jnp.where(stage_idx == 0, x_micro[inject], buf)
        y = stage_fn(jax.tree_util.tree_map(lambda p: p, stacked_params), x_in)
        # last stage writes result for micro-batch (t - num_stages + 1)
        out_t = t - (num_stages - 1)
        ok = (stage_idx == num_stages - 1) & (out_t >= 0)
        outputs = jax.lax.cond(
            ok,
            lambda o: o.at[jnp.maximum(out_t, 0)].set(y),
            lambda o: o,
            outputs)
        buf = jax.lax.ppermute(y, axis, perm)
        return (buf, outputs), None

    (buf, outputs), _ = jax.lax.scan(
        step, (buf, outputs), jnp.arange(n_micro + num_stages - 1))
    return outputs
