"""GroupSharded / ZeRO.

Parity: reference fleet/meta_parallel/sharding/group_sharded_stage2.py /
stage3.py and distributed/sharding/group_sharded.py:37
(group_sharded_parallel).

TPU-native: ZeRO stages are sharding decisions, not new runtimes —
  stage 1: optimizer state sharded over 'sharding'
  stage 2: + gradients (XLA reduce-scatters instead of all-reduce)
  stage 3: + parameters (XLA all-gathers weights on use, frees after)
The engine (parallel/engine.py) applies these as PartitionSpecs on params /
opt-state; XLA buffer donation gives the memory release the reference codes
manually (group_sharded_storage.py). The wrapper marks params so the engine
knows the stage.
"""
from __future__ import annotations

from jax.sharding import PartitionSpec as P

from ..distributed import mesh as _mesh
from ..nn.layer import Layer


def _mark_params_sharded(model, axis="sharding"):
    mesh = _mesh.get_mesh()
    n = mesh.shape.get(axis, 1)
    if n <= 1:
        return
    for p in model.parameters():
        if p._sharding_spec is not None:
            continue
        shape = tuple(p.shape)
        for i, s in enumerate(shape):
            if s % n == 0 and s >= n:
                spec = [None] * len(shape)
                spec[i] = axis
                p._sharding_spec = P(*spec)
                break


class ShardingParallel(Layer):
    def __init__(self, layers, hcg=None, strategy=None):
        super().__init__()
        self._layers = layers
        self._hcg = hcg
        stage = 2
        if strategy is not None:
            stage = strategy.sharding_configs.get("stage", 2)
        self.zero_stage = stage
        if stage >= 3:
            _mark_params_sharded(layers)

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, prefix="", include_sublayers=True):
        return self._layers.named_parameters(prefix, include_sublayers)

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, sd, **kwargs):
        return self._layers.set_state_dict(sd, **kwargs)

    def train(self):
        self._layers.train()
        return self

    def eval(self):
        self._layers.eval()
        return self


class GroupShardedOptimizerStage2:
    """API-compat shim over the engine's sharded opt state."""

    def __init__(self, params, optim, group=None, **kwargs):
        self._optim = optim

    def step(self):
        self._optim.step()

    def clear_grad(self):
        self._optim.clear_grad()


def group_sharded_parallel(model, optimizer, level="os_g", scaler=None,
                           group=None, offload=False, sync_buffers=False,
                           buffer_max_size=2**23, segment_size=2**20,
                           sync_comm=False):
    """reference distributed/sharding/group_sharded.py:37. level: 'os' (ZeRO1),
    'os_g' (ZeRO2), 'p_g_os' (ZeRO3)."""
    stage = {"os": 1, "os_g": 2, "p_g_os": 3}[level]
    wrapped = ShardingParallel(model, strategy=None)
    wrapped.zero_stage = stage
    if stage >= 3:
        _mark_params_sharded(model)
    return wrapped, optimizer, scaler


def save_group_sharded_model(model, output, optimizer=None):
    from ..framework.io import save

    layers = model._layers if isinstance(model, ShardingParallel) else model
    save(layers.state_dict(), output + ".pdmodel")
    if optimizer is not None:
        save(optimizer.state_dict(), output + ".pdopt")
