"""Compiled hybrid-parallel train step.

This is the TPU-native replacement for the whole tower the reference builds
out of Reducer bucketing (imperative/reducer.cc), comm streams, 1F1B host
scheduling and ZeRO partitioning python: the model's forward+backward+update
is traced into ONE XLA module over the hybrid mesh; every parallelism choice
enters as a sharding:

- dp:        batch dim sharded over 'dp' → XLA inserts grad all-reduces
             (riding ICI, overlapped by the latency-hiding scheduler).
- mp (TP):   mpu layer params sharded over 'mp' (column/row) → XLA inserts
             the identity/allreduce pairs of Megatron TP.
- sharding:  ZeRO (reference group_sharded_stage{2,3}.py semantics):
               stage 1: optimizer state sharded over 'sharding'
               stage 2: + gradients reduce-scattered (sharding constraint on
                        the grads makes XLA emit reduce-scatter, not
                        all-reduce + slice)
               stage 3: + parameters sharded, all-gathered on use
- sep (SP):  sequence dim sharded over 'sep'; ring attention in kernels/.
- pp:        lax.scan over stage-stacked weights (see pipeline_parallel).
"""
from __future__ import annotations

import time

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from .. import monitor as _monitor
from ..core.dispatch import no_grad
from ..core.tensor import Tensor
from ..distributed import mesh as _mesh

# training telemetry on the same registry as serving (monitor/):
# step time, token throughput, trace counts, device memory — the
# north-star numbers bench.py reads, live on /metrics.
_STEP_TIME = _monitor.histogram(
    "train_step_seconds",
    "host wall time of one compiled train-step call (dispatch + any "
    "host-side blocking)",
    buckets=(.001, .0025, .005, .01, .025, .05, .1, .25, .5, 1.0, 2.5,
             5.0, 10.0, 30.0, 60.0))
_STEPS = _monitor.counter("train_steps_total", "optimizer steps taken")
_TRAIN_TOKENS = _monitor.counter(
    "train_tokens_total",
    "batch elements consumed (batch x seq for >=2-d inputs)")
_TOK_RATE = _monitor.gauge("train_tokens_per_s",
                           "tokens/s of the last step window")
_TRAIN_COMPILES = _monitor.counter(
    "train_compiles_total", "XLA traces of the train step",
    labelnames=("kind",))
_DEV_MEM = _monitor.gauge(
    "device_memory_bytes", "device allocator stats (first local device)",
    labelnames=("stat",))
# watchdog heartbeat: each compiled call runs inside a busy bracket so
# a hung dispatch (wedged tunnel, XLA deadlock) is a detectable stall
# while the idle time BETWEEN steps never is (monitor/watchdog.py)
_HB_TRAIN = _monitor.heartbeat("train_step")


def _batch_tokens(vals, stacked=False):
    """Token-count approximation for throughput telemetry: product of
    the leading (K,) batch and sequence dims of the first input."""
    b = vals[0]
    dims = b.shape[:3] if stacked else b.shape[:2]
    n = 1
    for d in dims:
        n *= int(d)
    return n


def _record_step(vals, steps, dt, stacked=False):
    if not _monitor.is_enabled():
        return
    _STEP_TIME.observe(dt)
    _STEPS.inc(steps)
    tokens = _batch_tokens(vals, stacked)
    _TRAIN_TOKENS.inc(tokens)
    if dt > 0:
        _TOK_RATE.set(tokens / dt)
    try:
        # device-memory probe only in single-process worlds: under a
        # multi-process gloo/CPU runtime a per-step device query races
        # the in-flight collective transport and aborts the process
        # (gloo preamble mismatch) — and cross-process memory telemetry
        # belongs to each process's own registry anyway
        if jax.process_count() == 1:
            stats = jax.local_devices()[0].memory_stats() or {}
            for key in ("bytes_in_use", "peak_bytes_in_use",
                        "bytes_limit"):
                if key in stats:
                    _DEV_MEM.labels(stat=key).set(stats[key])
    except Exception:
        pass


def _normalize_spec(spec, ndim):
    """PartitionSpec → list of length ndim (entries: axis name | None)."""
    entries = list(spec) if spec is not None else []
    entries += [None] * (ndim - len(entries))
    return entries[:ndim]


def param_spec(param, zero_stage=0, mesh=None):
    """Sharding spec for one parameter: explicit layer annotation first
    (mpu layers), else — only at ZeRO stage 3 — sharded over 'sharding'
    on the largest divisible dim, else replicated."""
    mesh = mesh or _mesh.get_mesh()
    if param._sharding_spec is not None:
        return param._sharding_spec
    if zero_stage >= 3 and "sharding" in mesh.axis_names:
        return zero_spec(tuple(param.shape), P(), mesh)
    return P()


def zero_spec(shape, base_spec, mesh):
    """Add the 'sharding' axis to base_spec on the largest dim that is
    divisible by the sharding degree and not already sharded. Used for
    opt-state slots (stage>=1), grads (stage>=2), params (stage 3)."""
    n = mesh.shape.get("sharding", 1)
    if n <= 1:
        return base_spec
    entries = _normalize_spec(base_spec, len(shape))
    flat = [a for e in entries if e is not None
            for a in (e if isinstance(e, tuple) else (e,))]
    if "sharding" in flat:
        return base_spec
    best = None
    for i, s in enumerate(shape):
        if entries[i] is None and s % n == 0 and s >= n:
            if best is None or s > shape[best]:
                best = i
    if best is None:
        return base_spec
    entries[best] = "sharding"
    return P(*entries)


class CompiledTrainStep:
    """jit-compiled (loss, new_params, new_opt_state) step for a Layer +
    loss_fn + Optimizer over the current mesh."""

    def __init__(self, model, loss_fn, optimizer, mesh=None, zero_stage=0,
                 donate=True, batch_spec=None, labels_to_model=False):
        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        # labels_to_model: the model's forward computes the loss itself
        # (model(*inputs, labels) -> scalar) — the path that lets a
        # model fuse its loss tail (e.g. FLAGS_fused_lm_head_ce streams
        # lm_head+CE in one Pallas kernel, kernels/fused_ce.py).
        # loss_fn may be None in this mode.
        self.labels_to_model = labels_to_model
        self.mesh = mesh or _mesh.get_mesh()
        self.zero_stage = zero_stage
        self.donate = donate
        self._names, values = model.functional_state()
        self._tensors = model.raw_state_tensors()
        trainable = {n: p for n, p in model.named_parameters()
                     if not p.stop_gradient}
        self._trainable_names = list(trainable.keys())
        self._opt_state = optimizer.functional_init(
            {n: p._value for n, p in trainable.items()})
        # per-parameter hooks (decay exclusions) resolve through the
        # functional names on the compiled path
        optimizer.set_functional_params(trainable)
        self._trainable = trainable
        # checkpoint continuity (reference optimizer state_dicts carry
        # accumulators + step): seed slots from the optimizer's eager
        # accumulators (set_state_dict -> resume), start the step counter
        # from its global step, and register the lazy sync hook so
        # optimizer.state_dict() stays truthful
        slots = optimizer._slots()
        for n, p in trainable.items():
            for j, slot in enumerate(slots):
                key = (slot, id(p))
                if key in optimizer._accumulators:
                    self._opt_state[n][j] = jnp.asarray(
                        optimizer._accumulators[key])
        self._step_count = int(optimizer._global_step)
        optimizer._functional_sync = self._sync_opt_state_out
        optimizer._functional_load = self._load_opt_state_in
        if batch_spec is not None:
            self.batch_spec = batch_spec
        else:
            # the 'sharding' axis is a data-parallel axis too (reference
            # topology.py: data-parallel world = dp * sharding) — batch is
            # split over both, so grads become partial sums that XLA
            # reduce-scatters (ZeRO-2) over 'sharding'.
            batch_axes = [a for a in ("dp", "sharding")
                          if a in self.mesh.axis_names]
            self.batch_spec = P(tuple(batch_axes)) if batch_axes else P()
        self._shard_params()
        self._compiled = None
        self._compiled_multi = None
        self._step_fn = None

    # -- sharding specs ----------------------------------------------------

    def _specs(self):
        return {n: param_spec(self._tensors[n], self.zero_stage, self.mesh)
                for n in self._names}

    def _grad_spec(self, name, specs):
        """Gradient sharding for stage>=2: reduce-scatter over 'sharding'."""
        base = specs[name]
        if self.zero_stage >= 2:
            return zero_spec(tuple(self._tensors[name].shape), base,
                             self.mesh)
        return base

    def _opt_slot_spec(self, name, slot_shape, specs):
        """Opt-state slot sharding: moment-like slots (same rank as the
        param) follow the ZeRO spec at stage>=1; scalar/other slots stay
        replicated-compatible with the param spec."""
        pshape = tuple(self._tensors[name].shape)
        base = specs[name]
        if tuple(slot_shape) != pshape:
            return P()
        if self.zero_stage >= 1:
            return zero_spec(pshape, base, self.mesh)
        return base

    def _opt_specs(self, specs):
        out = {}
        for n, slots in self._opt_state.items():
            out[n] = [self._opt_slot_spec(n, jnp.shape(s), specs)
                      for s in slots]
        return out

    def _shard_params(self):
        specs = self._specs()
        tensors = self._tensors
        for n in self._names:
            t = tensors[n]
            t._value = jax.device_put(
                t._value, NamedSharding(self.mesh, specs[n]))
        opt_specs = self._opt_specs(specs)
        for n, slots in self._opt_state.items():
            self._opt_state[n] = [
                jax.device_put(s, NamedSharding(self.mesh, spec))
                for s, spec in zip(slots, opt_specs[n])]

    # -- compiled step -----------------------------------------------------

    def _build(self):
        model, loss_fn, opt = self.model, self.loss_fn, self.optimizer
        labels_to_model = self.labels_to_model
        names = self._names
        trainable_names = self._trainable_names
        mesh = self.mesh
        zero_stage = self.zero_stage
        specs = self._specs()
        opt_specs = self._opt_specs(specs)
        grad_shardings = {
            n: NamedSharding(mesh, self._grad_spec(n, specs))
            for n in trainable_names}
        state_shardings = [NamedSharding(mesh, specs[n]) for n in names]
        opt_shardings = {n: [NamedSharding(mesh, s) for s in slots]
                         for n, slots in opt_specs.items()}
        batch_sharding = NamedSharding(mesh, self.batch_spec)
        repl = NamedSharding(mesh, P())

        def step(state_vals, opt_state, step_i, lr_i, rng_key,
                 batch):
            _TRAIN_COMPILES.labels(kind="step").inc()  # trace-time
            state = dict(zip(names, state_vals))

            def loss_of(train_vals, batch):
                from ..framework import random as _random

                full = dict(state)
                full.update(dict(zip(trainable_names, train_vals)))
                wrapped = [Tensor(b) for b in batch]
                # thread per-step randomness: without a replay base,
                # next_key() splits the global root AT TRACE TIME and
                # every compiled step replays the same dropout masks
                # (the frozen-mask caveat in framework/random.py).
                # rng_key is an ARGUMENT (like lr): paddle.seed after
                # compilation must steer the masks; folding the traced
                # step counter gives fresh masks each step
                with _random.replay_base(
                        jax.random.fold_in(rng_key, step_i)):
                    with model.bind_state(names,
                                          [full[n] for n in names]):
                        with no_grad():
                            if labels_to_model:
                                out = model(*wrapped)
                            else:
                                out = model(*wrapped[:-1]) \
                                    if len(wrapped) > 1 \
                                    else model(wrapped[0])
                        if labels_to_model:
                            loss = out if loss_fn is None \
                                else loss_fn(out, wrapped[-1])
                        else:
                            loss = loss_fn(out, wrapped[-1])
                return loss._value if isinstance(loss, Tensor) else loss

            train_vals = [state[n] for n in trainable_names]
            loss, grads = jax.value_and_grad(loss_of)(train_vals, batch)
            if zero_stage >= 2:
                grads = [jax.lax.with_sharding_constraint(
                    g, grad_shardings[n])
                    for n, g in zip(trainable_names, grads)]
            gdict = dict(zip(trainable_names, grads))
            pdict = {n: state[n] for n in trainable_names}
            # lr threaded as an ARGUMENT: an lr captured at trace time
            # would freeze the scheduler's value into the executable
            new_p, new_s = opt.functional_apply(pdict, gdict, opt_state,
                                                lr=lr_i, step=step_i)
            out_state = []
            for n in names:
                out_state.append(new_p[n] if n in new_p else state[n])
            return loss, out_state, new_s

        self._step_fn = step
        self._shardings = (state_shardings, opt_shardings, batch_sharding,
                           repl)
        self._compiled = jax.jit(
            step,
            in_shardings=(state_shardings, opt_shardings, None, None,
                          None, batch_sharding),
            out_shardings=(repl, state_shardings, opt_shardings),
            donate_argnums=(0, 1) if self.donate else (),
        )

    def _build_multi(self):
        """K train steps inside ONE compiled module: fori_loop over
        batches stacked on a leading axis. This is the device-side input
        pipeline pattern (host stages K batches, the chip loops) — it
        amortizes per-call host->device dispatch, which through a
        tunneled/remote device can cost several ms per call."""
        if self._step_fn is None:
            self._build()
        step_fn = self._step_fn
        (state_shardings, opt_shardings, _batch_sharding, repl) = \
            self._shardings
        stacked_sharding = self._batch_sharding(stacked=True)

        def multi(state_vals, opt_state, step0, lr_i, rng_key, batches):
            _TRAIN_COMPILES.labels(kind="multi").inc()  # trace-time
            k = batches[0].shape[0]

            def body(i, carry):
                sv, ost, _ = carry
                batch = tuple(b[i] for b in batches)
                loss, new_sv, new_ost = step_fn(
                    sv, ost, step0 + i.astype(jnp.int32), lr_i, rng_key,
                    batch)
                return (new_sv, new_ost, loss.astype(jnp.float32))

            init = (state_vals, opt_state, jnp.float32(0))
            sv, ost, loss = jax.lax.fori_loop(0, k, body, init)
            return loss, sv, ost

        self._compiled_multi = jax.jit(
            multi,
            in_shardings=(state_shardings, opt_shardings, None, None,
                          None, stacked_sharding),
            out_shardings=(repl, state_shardings, opt_shardings),
            donate_argnums=(0, 1) if self.donate else (),
        )

    @no_grad()
    def run_steps(self, *stacked_batch):
        """Run K = leading-dim train steps in one device call.

        Each element of `stacked_batch` carries a leading K axis
        ([K, batch, ...]); step i consumes slice i. Matches K sequential
        __call__s in everything EXCEPT the learning rate: lr is sampled
        ONCE per window (host-side, before dispatch), so an LRScheduler
        stepped per train step advances per WINDOW here — all K steps in
        a window share one lr. Pick K small relative to the schedule's
        time constant, or use __call__ when per-step lr matters. The
        optimizer step counter still advances per step (bias correction
        is exact). Returns the LAST step's loss.
        """
        if getattr(self, "_compiled_multi", None) is None:
            self._build_multi()
        vals = self._prep_batch(stacked_batch, stacked=True)
        k = int(vals[0].shape[0])
        tensors = self._tensors
        state_vals = [tensors[n]._value for n in self._names]
        from ..framework import random as _random

        t0 = time.perf_counter()
        with _HB_TRAIN.busy("train.run_steps", steps=k,
                            step0=self._step_count + 1):
            loss, new_state, new_opt = self._compiled_multi(
                state_vals, self._opt_state,
                jnp.asarray(self._step_count + 1, jnp.int32),
                jnp.asarray(self.optimizer.get_lr(), jnp.float32),
                _random._key(), vals)
        _record_step(vals, k, time.perf_counter() - t0, stacked=True)
        self._step_count += k
        for n, v in zip(self._names, new_state):
            tensors[n]._value = v
        self._opt_state = new_opt
        return Tensor(loss)

    def _sync_opt_state_out(self):
        """Mirror the functional slots into the optimizer's eager
        accumulators. Registered as the optimizer's _functional_sync
        hook: state_dict() pulls it lazily, keeping the per-step host
        path free of O(params x slots) dict rebuilds. COPIES each slot:
        with donate=True the next compiled step donates the live
        _opt_state buffers, and a state_dict snapshot must survive that."""
        opt = self.optimizer
        slots = opt._slots()
        for n, p in self._trainable.items():
            for j, slot in enumerate(slots):
                opt._accumulators[(slot, id(p))] = jnp.copy(
                    self._opt_state[n][j])
        opt._global_step = self._step_count

    def _load_opt_state_in(self):
        """Reverse bridge: re-seed the compiled step's functional slots
        from the optimizer's eager accumulators. Registered as the
        optimizer's _functional_load hook so set_state_dict() called
        AFTER this CompiledTrainStep was constructed still takes effect
        on the compiled path (resume-after-compile)."""
        opt = self.optimizer
        slots = opt._slots()
        specs = self._specs()
        opt_specs = self._opt_specs(specs)
        for n, p in self._trainable.items():
            for j, slot in enumerate(slots):
                key = (slot, id(p))
                if key in opt._accumulators:
                    self._opt_state[n][j] = jax.device_put(
                        jnp.asarray(opt._accumulators[key]),
                        NamedSharding(self.mesh, opt_specs[n][j]))
        self._step_count = int(opt._global_step)

    def _batch_sharding(self, stacked=False):
        spec = P(*((None,) + tuple(self.batch_spec))) if stacked \
            else self.batch_spec
        return NamedSharding(self.mesh, spec)

    def _prep_batch(self, batch, stacked=False):
        sharding = self._batch_sharding(stacked)
        return tuple(
            jax.device_put(b._value if isinstance(b, Tensor)
                           else jnp.asarray(b), sharding)
            for b in batch)

    def lowered_hlo(self, *batch):
        """Compiled HLO text of the step for these batch shapes (for tests
        and profiling: lets callers assert which collectives XLA inserted)."""
        if self._compiled is None:
            self._build()
        vals = self._prep_batch(batch)
        state_vals = [self._tensors[n]._value for n in self._names]
        from ..framework import random as _random

        return self._compiled.lower(
            state_vals, self._opt_state, jnp.asarray(0, jnp.int32),
            jnp.asarray(0.0, jnp.float32), _random._key(),
            vals).compile().as_text()

    @no_grad()
    def __call__(self, *batch):
        """batch = (*inputs, labels) as Tensors or arrays; returns loss."""
        if self._compiled is None:
            self._build()
        vals = self._prep_batch(batch)
        tensors = self._tensors
        state_vals = [tensors[n]._value for n in self._names]
        from ..framework import random as _random

        self._step_count += 1
        t0 = time.perf_counter()
        with _HB_TRAIN.busy("train.step", step=self._step_count):
            loss, new_state, new_opt = self._compiled(
                state_vals, self._opt_state,
                jnp.asarray(self._step_count, jnp.int32),
                jnp.asarray(self.optimizer.get_lr(), jnp.float32),
                _random._key(), vals)
        _record_step(vals, 1, time.perf_counter() - t0)
        for n, v in zip(self._names, new_state):
            tensors[n]._value = v
        self._opt_state = new_opt
        return Tensor(loss)


def compile_train_step(model, loss_fn, optimizer, **kwargs):
    return CompiledTrainStep(model, loss_fn, optimizer, **kwargs)
