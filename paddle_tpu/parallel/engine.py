"""Compiled hybrid-parallel train step.

This is the TPU-native replacement for the whole tower the reference builds
out of Reducer bucketing (imperative/reducer.cc), comm streams, 1F1B host
scheduling and ZeRO partitioning python: the model's forward+backward+update
is traced into ONE XLA module over the hybrid mesh; every parallelism choice
enters as a sharding:

- dp:        batch dim sharded over 'dp' → XLA inserts grad all-reduces
             (riding ICI, overlapped by the latency-hiding scheduler).
- mp (TP):   mpu layer params sharded over 'mp' (column/row) → XLA inserts
             the identity/allreduce pairs of Megatron TP.
- sharding:  ZeRO (reference group_sharded_stage{2,3}.py semantics):
               stage 1: optimizer state sharded over 'sharding'
               stage 2: + gradients reduce-scattered (sharding constraint on
                        the grads makes XLA emit reduce-scatter, not
                        all-reduce + slice)
               stage 3: + parameters sharded, all-gathered on use
- sep (SP):  sequence dim sharded over 'sep'; ring attention in kernels/.
- pp:        lax.scan over stage-stacked weights (see pipeline_parallel).

Gradient communication (FLAGS_quantized_grad_sync): by default the grad
all-reduce / ZeRO-2 reduce-scatter is IMPLICIT — XLA inserts it because
the batch is sharded and params replicated. With the flag on (pure
data-parallel/ZeRO<=2 meshes), forward+backward instead run inside a
shard_map manual over the batch axes and the reduction is an explicit
bucketed block-scaled-int8 all-reduce with per-param error-feedback
residuals (distributed/compress.py) — ~4x fewer gradient wire bytes,
loss trajectory pinned to fp32 by tests/test_compress.py.
"""
from __future__ import annotations

import time

import warnings
import weakref

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from .. import monitor as _monitor
from ..resilience import faultinject as _fi
from ..core.dispatch import no_grad
from ..core.tensor import Tensor
from ..distributed import compress as _compress
from ..distributed import mesh as _mesh
# the version-portable shard_map shim (check_rep -> check_vma on newer
# jax) lives in ONE place: distributed/collective.py
from ..distributed.collective import shard_map as _shard_map

# training telemetry on the same registry as serving (monitor/):
# step time, token throughput, trace counts, device memory — the
# north-star numbers bench.py reads, live on /metrics.
_STEP_TIME = _monitor.histogram(
    "train_step_seconds",
    "host wall time of one compiled train-step call (dispatch + any "
    "host-side blocking)",
    buckets=(.001, .0025, .005, .01, .025, .05, .1, .25, .5, 1.0, 2.5,
             5.0, 10.0, 30.0, 60.0))
_STEPS = _monitor.counter("train_steps_total", "optimizer steps taken")
_TRAIN_TOKENS = _monitor.counter(
    "train_tokens_total",
    "batch elements consumed (batch x seq for >=2-d inputs)")
_TOK_RATE = _monitor.gauge("train_tokens_per_s",
                           "tokens/s of the last step window")
_TRAIN_COMPILES = _monitor.counter(
    "train_compiles_total", "XLA traces of the train step",
    labelnames=("kind",))
_DEV_MEM = _monitor.gauge(
    "device_memory_bytes", "device allocator stats (first local device)"
    "; DEPRECATED round 14: the memory plane (monitor/memory.py) "
    "publishes the same witness as mem_device_bytes{component="
    "\"allocator\",job=\"device\"} — this series emits one more round "
    "(BASELINE.md deprecation note), then dashboards move",
    labelnames=("stat",))
# watchdog heartbeat: each compiled call runs inside a busy bracket so
# a hung dispatch (wedged tunnel, XLA deadlock) is a detectable stall
# while the idle time BETWEEN steps never is (monitor/watchdog.py)
_HB_TRAIN = _monitor.heartbeat("train_step")
# MFU/phase attribution (monitor/perf.py, FLAGS_perf_attribution):
# opt-in because it costs one AOT lower+compile of the step (for the
# XLA cost/memory analysis) and one loss-scalar host readback per step
_perf = _monitor.perf


def _batch_tokens(vals, stacked=False):
    """Token-count approximation for throughput telemetry: product of
    the leading (K,) batch and sequence dims of the first input."""
    b = vals[0]
    dims = b.shape[:3] if stacked else b.shape[:2]
    n = 1
    for d in dims:
        n *= int(d)
    return n


def _record_step(vals, steps, dt, stacked=False):
    if not _monitor.is_enabled():
        return
    _STEP_TIME.observe(dt)
    _STEPS.inc(steps)
    tokens = _batch_tokens(vals, stacked)
    _TRAIN_TOKENS.inc(tokens)
    if dt > 0:
        _TOK_RATE.set(tokens / dt)
    try:
        # device-memory probe only in single-process worlds: under a
        # multi-process gloo/CPU runtime a per-step device query races
        # the in-flight collective transport and aborts the process
        # (gloo preamble mismatch) — and cross-process memory telemetry
        # belongs to each process's own registry anyway
        if jax.process_count() == 1:
            stats = jax.local_devices()[0].memory_stats() or {}
            for key in ("bytes_in_use", "peak_bytes_in_use",
                        "bytes_limit"):
                if key in stats:
                    _DEV_MEM.labels(stat=key).set(stats[key])
    except Exception as e:
        from ..monitor.registry import warn_once

        warn_once(
            "engine.device_memory",
            "paddle_tpu.parallel: device memory stats unavailable "
            "(gauge stays empty): %r" % (e,))


def _normalize_spec(spec, ndim):
    """PartitionSpec → list of length ndim (entries: axis name | None)."""
    entries = list(spec) if spec is not None else []
    entries += [None] * (ndim - len(entries))
    return entries[:ndim]


def param_spec(param, zero_stage=0, mesh=None):
    """Sharding spec for one parameter: explicit layer annotation first
    (mpu layers), else — only at ZeRO stage 3 — sharded over 'sharding'
    on the largest divisible dim, else replicated."""
    mesh = mesh or _mesh.get_mesh()
    if param._sharding_spec is not None:
        return param._sharding_spec
    if zero_stage >= 3 and "sharding" in mesh.axis_names:
        return zero_spec(tuple(param.shape), P(), mesh)
    return P()


def zero_spec(shape, base_spec, mesh):
    """Add the 'sharding' axis to base_spec on the largest dim that is
    divisible by the sharding degree and not already sharded. Used for
    opt-state slots (stage>=1), grads (stage>=2), params (stage 3)."""
    n = mesh.shape.get("sharding", 1)
    if n <= 1:
        return base_spec
    entries = _normalize_spec(base_spec, len(shape))
    flat = [a for e in entries if e is not None
            for a in (e if isinstance(e, tuple) else (e,))]
    if "sharding" in flat:
        return base_spec
    best = None
    for i, s in enumerate(shape):
        if entries[i] is None and s % n == 0 and s >= n:
            if best is None or s > shape[best]:
                best = i
    if best is None:
        return base_spec
    entries[best] = "sharding"
    return P(*entries)


class CompiledTrainStep:
    """jit-compiled (loss, new_params, new_opt_state) step for a Layer +
    loss_fn + Optimizer over the current mesh."""

    def __init__(self, model, loss_fn, optimizer, mesh=None, zero_stage=0,
                 donate=True, batch_spec=None, labels_to_model=False,
                 loss_reduction="mean"):
        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        # labels_to_model: the model's forward computes the loss itself
        # (model(*inputs, labels) -> scalar) — the path that lets a
        # model fuse its loss tail (e.g. FLAGS_fused_lm_head_ce streams
        # lm_head+CE in one Pallas kernel, kernels/fused_ce.py).
        # loss_fn may be None in this mode.
        self.labels_to_model = labels_to_model
        self.mesh = mesh or _mesh.get_mesh()
        self.zero_stage = zero_stage
        self.donate = donate
        # how loss_fn reduces over the batch ("mean" | "sum"). Only the
        # quantized grad-sync path needs to know: it combines PER-RANK
        # losses/grads of per-shard batches, and mean-of-means equals
        # the global mean while sum-of-sums needs psum — declaring it
        # wrong would silently rescale gradients by 1/nranks. The exact
        # (flag-off) path is reduction-agnostic (GSPMD computes the
        # global loss directly).
        if loss_reduction not in ("mean", "sum"):
            raise ValueError(
                "loss_reduction must be 'mean' or 'sum', got %r"
                % (loss_reduction,))
        self.loss_reduction = loss_reduction
        self._names, values = model.functional_state()
        self._tensors = model.raw_state_tensors()
        trainable = {n: p for n, p in model.named_parameters()
                     if not p.stop_gradient}
        self._trainable_names = list(trainable.keys())
        self._opt_state = optimizer.functional_init(
            {n: p._value for n, p in trainable.items()})
        # per-parameter hooks (decay exclusions) resolve through the
        # functional names on the compiled path
        optimizer.set_functional_params(trainable)
        self._trainable = trainable
        # checkpoint continuity (reference optimizer state_dicts carry
        # accumulators + step): seed slots from the optimizer's eager
        # accumulators (set_state_dict -> resume), start the step counter
        # from its global step, and register the lazy sync hook so
        # optimizer.state_dict() stays truthful
        slots = optimizer._slots()
        for n, p in trainable.items():
            for j, slot in enumerate(slots):
                key = (slot, id(p))
                if key in optimizer._accumulators:
                    self._opt_state[n][j] = jnp.asarray(
                        optimizer._accumulators[key])
        self._step_count = int(optimizer._global_step)
        optimizer._functional_sync = self._sync_opt_state_out
        optimizer._functional_load = self._load_opt_state_in
        if batch_spec is not None:
            self.batch_spec = batch_spec
        else:
            # the 'sharding' axis is a data-parallel axis too (reference
            # topology.py: data-parallel world = dp * sharding) — batch is
            # split over both, so grads become partial sums that XLA
            # reduce-scatters (ZeRO-2) over 'sharding'.
            batch_axes = [a for a in ("dp", "sharding")
                          if a in self.mesh.axis_names]
            self.batch_spec = P(tuple(batch_axes)) if batch_axes else P()
        self._shard_params()
        self._compiled = None
        self._compiled_multi = None
        self._step_fn = None
        # quantized grad sync (distributed/compress.py): resolved at
        # first build from FLAGS_quantized_grad_sync; None = the exact
        # fp32 path (bit-identical to the flag-less build, test-pinned)
        self._qsync = None
        self._ef_state = {}
        # per-instance perf attribution (monitor/perf.py), created on
        # first step only while FLAGS_perf_attribution is on
        self._perf_attr = None
        # fleet identity beacon (monitor/fleet.py): under
        # FLAGS_monitor_fleet the scraped train series resolve to this
        # rank/host/job; one flag branch when off
        _monitor.fleet.note_identity("train")
        # memory-plane ledger (monitor/memory.py, FLAGS_monitor_memory),
        # LATCHED HERE: params / optimizer slots / EF residuals report
        # live nbytes (the donated step state IS these three carried
        # pytrees). None = flags-off; the step hot path only checks
        # the handle.
        self._mem = _monitor.memory.tracker(
            "train", self._mem_components(),
            context_fn=lambda: {"step_count": self._step_count})
        # ptprof step hook (monitor/profile.py, FLAGS_monitor_profile),
        # LATCHED HERE like the memory tracker: measured dispatch/
        # blocked/gap timers + device-capture-window lifecycle. None =
        # flags-off; the hot paths only ever check the handle.
        self._prof = _monitor.profile.step_hook("train")

    def _mem_components(self):
        """Ledger providers: every carried (donated) buffer class of
        the compiled step, tagged by functional name so an OOM
        postmortem's top-arrays table names real parameters. The
        providers hold the step WEAKLY — the global ledger must never
        pin a discarded step's params/slots (and their device
        buffers) alive; a dead step's components just report empty."""
        wself = weakref.ref(self)

        def model_params():
            s = wself()
            if s is None:
                return ()
            return [(n, s._tensors[n]._value) for n in s._names]

        def optimizer_slots():
            s = wself()
            if s is None:
                return ()
            return [("%s/slot%d" % (n, j), sl)
                    for n, slots in s._opt_state.items()
                    for j, sl in enumerate(slots)]

        def ef_residuals():
            s = wself()
            if s is None:
                return ()
            return list(s._ef_state.items())

        return {"model_params": model_params,
                "optimizer_slots": optimizer_slots,
                "ef_residuals": ef_residuals}

    # -- sharding specs ----------------------------------------------------

    def _specs(self):
        return {n: param_spec(self._tensors[n], self.zero_stage, self.mesh)
                for n in self._names}

    def _grad_spec(self, name, specs):
        """Gradient sharding for stage>=2: reduce-scatter over 'sharding'."""
        base = specs[name]
        if self.zero_stage >= 2:
            return zero_spec(tuple(self._tensors[name].shape), base,
                             self.mesh)
        return base

    def _opt_slot_spec(self, name, slot_shape, specs):
        """Opt-state slot sharding: moment-like slots (same rank as the
        param) follow the ZeRO spec at stage>=1; scalar/other slots stay
        replicated-compatible with the param spec."""
        pshape = tuple(self._tensors[name].shape)
        base = specs[name]
        if tuple(slot_shape) != pshape:
            return P()
        if self.zero_stage >= 1:
            return zero_spec(pshape, base, self.mesh)
        return base

    def _opt_specs(self, specs):
        out = {}
        for n, slots in self._opt_state.items():
            out[n] = [self._opt_slot_spec(n, jnp.shape(s), specs)
                      for s in slots]
        return out

    def _shard_params(self):
        specs = self._specs()
        tensors = self._tensors
        for n in self._names:
            t = tensors[n]
            t._value = jax.device_put(
                t._value, NamedSharding(self.mesh, specs[n]))
        opt_specs = self._opt_specs(specs)
        for n, slots in self._opt_state.items():
            self._opt_state[n] = [
                jax.device_put(s, NamedSharding(self.mesh, spec))
                for s, spec in zip(slots, opt_specs[n])]

    # -- quantized grad sync ----------------------------------------------

    def _batch_axes(self):
        """Mesh axes the batch dim is split over (the grad-reduce axes)."""
        entries = list(self.batch_spec)
        if not entries or entries[0] is None:
            return ()
        first = entries[0]
        axes = tuple(first) if isinstance(first, tuple) else (first,)
        if any(e is not None for e in entries[1:]):
            return None  # batch sharded beyond dim0: unsupported
        return axes

    def _resolve_qsync(self):
        """Decide whether this build replaces the implicit fp32 grad
        psum with the bucketed quantized all-reduce. Returns
        (axes, nranks, buckets) or None; unsupported configurations
        warn once and fall back to the exact path — the flag must never
        silently change math it cannot faithfully compress."""
        if not _compress.quantized_sync_enabled():
            return None

        def bail(why):
            warnings.warn(
                "FLAGS_quantized_grad_sync requested but unsupported "
                "for this step (%s); using the exact fp32 grad sync"
                % why)
            return None

        axes = self._batch_axes()
        if axes is None or not axes:
            return bail("batch is not sharded over leading mesh axes")
        nranks = 1
        for a in axes:
            nranks *= self.mesh.shape.get(a, 1)
        if nranks <= 1:
            return None  # nothing to reduce; exact path, no warning
        other = [a for a in self.mesh.axis_names if a not in axes
                 and self.mesh.shape[a] > 1]
        if other:
            return bail("non-batch mesh axes %s have size > 1 (params "
                        "are not replicated over the manual axes)"
                        % other)
        if self.zero_stage >= 3:
            return bail("ZeRO stage 3 shards parameters")
        for n in self._names:
            spec = getattr(self._tensors[n], "_sharding_spec", None)
            if spec is None:
                continue
            # annotations binding only size-1 axes (an mp-annotated
            # model on a pure data-parallel mesh) are effectively
            # replicated — only a REAL sharding blocks the manual path
            used = [a for e in spec if e is not None
                    for a in (e if isinstance(e, tuple) else (e,))]
            if any(self.mesh.shape.get(a, 1) > 1 for a in used):
                return bail(
                    "parameter %r is sharded over %s (params must be "
                    "replicated over the manual batch axes)" % (n, used))

        def numel(n):
            size = 1
            for d in self._tensors[n].shape:
                size *= int(d)
            return size

        # buckets hold INDICES into trainable_names (the grad list order)
        sized = [(i, numel(n) * 4)
                 for i, n in enumerate(self._trainable_names)]
        buckets = _compress.plan_buckets(sized)
        block = _compress.DEFAULT_BLOCK
        fp32 = sum(_compress.ring_allreduce_bytes(b // 4, nranks, False)
                   for _, b in sized)
        q8 = sum(_compress.ring_allreduce_bytes(b // 4, nranks, True,
                                                block)
                 for _, b in sized)
        if _monitor.is_enabled():
            _compress.GRAD_SYNC_BUCKETS.set(len(buckets))
            _compress.GRAD_SYNC_BYTES_STEP.labels(
                compressed="false").set(fp32)
            _compress.GRAD_SYNC_BYTES_STEP.labels(
                compressed="true").set(q8)
        return (axes, nranks, buckets)

    def _init_ef_state(self, axes, nranks):
        """Per-param error-feedback residuals: one f32 copy of each
        trainable param PER RANK, carried in the step's donated state
        next to the optimizer slots and threaded through every compiled
        call. Sharded over the batch axes so each device holds exactly
        its own rank's residual."""
        sharding = NamedSharding(self.mesh, P(axes))
        return {
            n: jax.device_put(
                jnp.zeros((nranks,) + tuple(self._tensors[n].shape),
                          jnp.float32), sharding)
            for n in self._trainable_names}

    # -- compiled step -----------------------------------------------------

    def _build(self):
        model, loss_fn, opt = self.model, self.loss_fn, self.optimizer
        labels_to_model = self.labels_to_model
        names = self._names
        trainable_names = self._trainable_names
        mesh = self.mesh
        zero_stage = self.zero_stage
        specs = self._specs()
        opt_specs = self._opt_specs(specs)
        grad_shardings = {
            n: NamedSharding(mesh, self._grad_spec(n, specs))
            for n in trainable_names}
        state_shardings = [NamedSharding(mesh, specs[n]) for n in names]
        opt_shardings = {n: [NamedSharding(mesh, s) for s in slots]
                         for n, slots in opt_specs.items()}
        batch_sharding = NamedSharding(mesh, self.batch_spec)
        repl = NamedSharding(mesh, P())
        qsync = self._resolve_qsync()
        self._qsync = qsync
        if qsync is not None and not self._ef_state:
            self._ef_state = self._init_ef_state(qsync[0], qsync[1])
        ef_shardings = (
            {n: NamedSharding(mesh, P(qsync[0]))
             for n in self._trainable_names}
            if qsync is not None else None)
        stochastic = _compress.stochastic_rounding_enabled()

        def loss_value(train_vals, state_vals, batch, rng_key, step_i,
                       rank_salt=None):
            """Pure loss of one (global or per-rank-local) batch: the
            SAME function backs the exact path (value_and_grad under
            GSPMD, XLA inserts the grad psum) and the quantized path
            (value_and_grad per rank inside shard_map, grads stay
            partial until OUR collective reduces them)."""
            from ..framework import random as _random

            full = dict(zip(names, state_vals))
            full.update(dict(zip(trainable_names, train_vals)))
            wrapped = [Tensor(b) for b in batch]
            # thread per-step randomness: without a replay base,
            # next_key() splits the global root AT TRACE TIME and
            # every compiled step replays the same dropout masks
            # (the frozen-mask caveat in framework/random.py).
            # rng_key is an ARGUMENT (like lr): paddle.seed after
            # compilation must steer the masks; folding the traced
            # step counter gives fresh masks each step
            key = jax.random.fold_in(rng_key, step_i)
            if rank_salt is not None:
                # manual-SPMD dropout: each rank draws its shard's
                # masks from a rank-salted key (under GSPMD one global
                # mask is sharded instead; the streams differ, which is
                # part of the documented flag-on approximation)
                key = jax.random.fold_in(key, rank_salt)
            with _random.replay_base(key):
                with model.bind_state(names,
                                      [full[n] for n in names]):
                    with no_grad():
                        if labels_to_model:
                            out = model(*wrapped)
                        else:
                            out = model(*wrapped[:-1]) \
                                if len(wrapped) > 1 \
                                else model(wrapped[0])
                    if labels_to_model:
                        loss = out if loss_fn is None \
                            else loss_fn(out, wrapped[-1])
                    else:
                        loss = loss_fn(out, wrapped[-1])
            return loss._value if isinstance(loss, Tensor) else loss

        def quantized_grads(state_vals, ef_state, step_i, rng_key,
                            batch):
            """Forward+backward inside a shard_map manual over the
            batch axes: grads come out as PARTIAL per-rank sums and the
            explicit bucketed quantized all-reduce (compress.py) is the
            only cross-rank traffic — int8 payloads + block scales on
            the wire instead of the implicit fp32 psum."""
            axes, nranks, buckets = qsync
            # mean loss: global mean == mean of per-shard means (equal
            # shards) and grads combine by pmean; sum loss: psum both
            sum_loss = self.loss_reduction == "sum"

            def body(state_vals_m, ef_m, step_m, rng_m, batch_m):
                train_m = dict(zip(names, state_vals_m))
                train_vals_m = [train_m[n] for n in trainable_names]
                salt = jax.lax.axis_index(axes)
                loss_l, grads_l = jax.value_and_grad(loss_value)(
                    train_vals_m, state_vals_m, batch_m, rng_m, step_m,
                    salt)
                loss = (jax.lax.psum(loss_l, axes) if sum_loss
                        else jax.lax.pmean(loss_l, axes))
                ef_l = [ef_m[n][0] for n in trainable_names]
                key = None
                if stochastic:
                    key = jax.random.fold_in(
                        jax.random.fold_in(rng_m, step_m), salt)
                new_grads, new_ef = _compress.reduce_grads_traced(
                    grads_l, ef_l, axes, nranks, buckets,
                    stochastic=stochastic, key=key, mean=not sum_loss)
                ef_out = {n: e[None] for n, e in
                          zip(trainable_names, new_ef)}
                return loss, new_grads, ef_out

            fn = _shard_map(
                body, mesh=mesh,
                in_specs=(P(), P(qsync[0]), P(), P(), self.batch_spec),
                out_specs=(P(), P(), P(qsync[0])),
                check_rep=False)
            return fn(state_vals, ef_state, step_i, rng_key, batch)

        def step(state_vals, opt_state, ef_state, step_i, lr_i, rng_key,
                 batch):
            _TRAIN_COMPILES.labels(kind="step").inc()  # trace-time
            state = dict(zip(names, state_vals))
            train_vals = [state[n] for n in trainable_names]
            if qsync is None:
                loss, grads = jax.value_and_grad(loss_value)(
                    train_vals, state_vals, batch, rng_key, step_i)
                new_ef = ef_state
            else:
                loss, grads, new_ef = quantized_grads(
                    state_vals, ef_state, step_i, rng_key, batch)
            if zero_stage >= 2:
                grads = [jax.lax.with_sharding_constraint(
                    g, grad_shardings[n])
                    for n, g in zip(trainable_names, grads)]
            gdict = dict(zip(trainable_names, grads))
            pdict = {n: state[n] for n in trainable_names}
            # lr threaded as an ARGUMENT: an lr captured at trace time
            # would freeze the scheduler's value into the executable
            new_p, new_s = opt.functional_apply(pdict, gdict, opt_state,
                                                lr=lr_i, step=step_i)
            out_state = []
            for n in names:
                out_state.append(new_p[n] if n in new_p else state[n])
            return loss, out_state, new_s, new_ef

        self._step_fn = step
        self._shardings = (state_shardings, opt_shardings, batch_sharding,
                           repl, ef_shardings)
        self._compiled = jax.jit(
            step,
            in_shardings=(state_shardings, opt_shardings, ef_shardings,
                          None, None, None, batch_sharding),
            out_shardings=(repl, state_shardings, opt_shardings,
                           ef_shardings),
            donate_argnums=(0, 1, 2) if self.donate else (),
        )

    def _build_multi(self):
        """K train steps inside ONE compiled module: fori_loop over
        batches stacked on a leading axis. This is the device-side input
        pipeline pattern (host stages K batches, the chip loops) — it
        amortizes per-call host->device dispatch, which through a
        tunneled/remote device can cost several ms per call."""
        if self._step_fn is None:
            self._build()
        step_fn = self._step_fn
        (state_shardings, opt_shardings, _batch_sharding, repl,
         ef_shardings) = self._shardings
        stacked_sharding = self._batch_sharding(stacked=True)

        def multi(state_vals, opt_state, ef_state, step0, lr_i, rng_key,
                  batches):
            _TRAIN_COMPILES.labels(kind="multi").inc()  # trace-time
            k = batches[0].shape[0]

            def body(i, carry):
                sv, ost, ef, _ = carry
                batch = tuple(b[i] for b in batches)
                loss, new_sv, new_ost, new_ef = step_fn(
                    sv, ost, ef, step0 + i.astype(jnp.int32), lr_i,
                    rng_key, batch)
                return (new_sv, new_ost, new_ef,
                        loss.astype(jnp.float32))

            init = (state_vals, opt_state, ef_state, jnp.float32(0))
            sv, ost, ef, loss = jax.lax.fori_loop(0, k, body, init)
            return loss, sv, ost, ef

        self._compiled_multi = jax.jit(
            multi,
            in_shardings=(state_shardings, opt_shardings, ef_shardings,
                          None, None, None, stacked_sharding),
            out_shardings=(repl, state_shardings, opt_shardings,
                           ef_shardings),
            donate_argnums=(0, 1, 2) if self.donate else (),
        )

    @no_grad()
    def run_steps(self, *stacked_batch):
        """Run K = leading-dim train steps in one device call.

        Each element of `stacked_batch` carries a leading K axis
        ([K, batch, ...]); step i consumes slice i. Matches K sequential
        __call__s in everything EXCEPT the learning rate: lr is sampled
        ONCE per window (host-side, before dispatch), so an LRScheduler
        stepped per train step advances per WINDOW here — all K steps in
        a window share one lr. Pick K small relative to the schedule's
        time constant, or use __call__ when per-step lr matters. The
        optimizer step counter still advances per step (bias correction
        is exact). Returns the LAST step's loss.
        """
        # fault-injection site (resilience/faultinject): fires BEFORE
        # the window dispatches — an injected error models a rank dying
        # / wedging at a step boundary, the failure ResilientTrainLoop
        # recovers from. One branch (and zero allocations) when disabled.
        if _fi.is_enabled():
            _fi.fire("train.run_steps", step0=self._step_count + 1)
        prof = self._prof
        try:
            # OOM forensics site (monitor/memory.py): armed only while
            # the tracker is latched; the postmortem wrapper below
            # treats the InjectedFault exactly like RESOURCE_EXHAUSTED
            if self._mem is not None and _fi.is_enabled():
                _fi.fire("mem.oom", step0=self._step_count + 1)
            if getattr(self, "_compiled_multi", None) is None:
                self._build_multi()
            vals = self._prep_batch(stacked_batch, stacked=True)
            k = int(vals[0].shape[0])
            tensors = self._tensors
            state_vals = [tensors[n]._value for n in self._names]
            from ..framework import random as _random

            if prof is not None:
                prof.step_begin()
            t0 = time.perf_counter()
            with _HB_TRAIN.busy("train.run_steps", steps=k,
                                step0=self._step_count + 1):
                loss, new_state, new_opt, new_ef = self._compiled_multi(
                    state_vals, self._opt_state, self._ef_state,
                    jnp.asarray(self._step_count + 1, jnp.int32),
                    jnp.asarray(self.optimizer.get_lr(), jnp.float32),
                    _random._key(), vals)
        except Exception as e:
            if self._mem is not None \
                    and _monitor.memory.looks_like_oom(e):
                self._mem.write_postmortem(e)
            if prof is not None:
                # a raising window must not leak the open capture
                # window (or its live device trace)
                prof.step_abort()
            raise
        t1 = time.perf_counter()
        if prof is not None:
            # measured split: dispatch (call issue -> handles back) vs
            # host-blocked (explicit block on the window's loss) vs
            # inter-window host gap — the measured side perf_report
            # diffs against the analytic perf_phase_seconds
            prof.step_end(t0, t1, block=loss)
        _record_step(vals, k, t1 - t0, stacked=True)
        self._note_perf(vals, k, t1 - t0, loss, t0, t1, stacked=True)
        # span journal (monitor/trace.py, FLAGS_monitor_trace): one
        # step span per engine call, child comm spans replayed from the
        # flight-recorder brackets — off = one attribute load + branch
        if _monitor.trace.is_enabled():
            _monitor.trace.record_train_step(
                "train", self._step_count + k, t1 - t0, steps=k,
                tokens=_batch_tokens(vals, stacked=True))
        self._step_count += k
        for n, v in zip(self._names, new_state):
            tensors[n]._value = v
        self._opt_state = new_opt
        self._ef_state = new_ef
        return Tensor(loss)

    def _sync_opt_state_out(self):
        """Mirror the functional slots into the optimizer's eager
        accumulators. Registered as the optimizer's _functional_sync
        hook: state_dict() pulls it lazily, keeping the per-step host
        path free of O(params x slots) dict rebuilds. COPIES each slot:
        with donate=True the next compiled step donates the live
        _opt_state buffers, and a state_dict snapshot must survive that."""
        opt = self.optimizer
        slots = opt._slots()
        for n, p in self._trainable.items():
            for j, slot in enumerate(slots):
                opt._accumulators[(slot, id(p))] = jnp.copy(
                    self._opt_state[n][j])
        opt._global_step = self._step_count

    def _load_opt_state_in(self):
        """Reverse bridge: re-seed the compiled step's functional slots
        from the optimizer's eager accumulators. Registered as the
        optimizer's _functional_load hook so set_state_dict() called
        AFTER this CompiledTrainStep was constructed still takes effect
        on the compiled path (resume-after-compile)."""
        opt = self.optimizer
        slots = opt._slots()
        specs = self._specs()
        opt_specs = self._opt_specs(specs)
        for n, p in self._trainable.items():
            for j, slot in enumerate(slots):
                key = (slot, id(p))
                if key in opt._accumulators:
                    self._opt_state[n][j] = jax.device_put(
                        jnp.asarray(opt._accumulators[key]),
                        NamedSharding(self.mesh, opt_specs[n][j]))
        self._step_count = int(opt._global_step)

    def _batch_sharding(self, stacked=False):
        spec = P(*((None,) + tuple(self.batch_spec))) if stacked \
            else self.batch_spec
        return NamedSharding(self.mesh, spec)

    def _prep_batch(self, batch, stacked=False):
        sharding = self._batch_sharding(stacked)
        return tuple(
            jax.device_put(b._value if isinstance(b, Tensor)
                           else jnp.asarray(b), sharding)
            for b in batch)

    def lowered_hlo(self, *batch):
        """Compiled HLO text of the step for these batch shapes (for tests
        and profiling: lets callers assert which collectives XLA inserted)."""
        if self._compiled is None:
            self._build()
        vals = self._prep_batch(batch)
        state_vals = [self._tensors[n]._value for n in self._names]
        from ..framework import random as _random

        return self._compiled.lower(
            state_vals, self._opt_state, self._ef_state,
            jnp.asarray(0, jnp.int32),
            jnp.asarray(0.0, jnp.float32), _random._key(),
            vals).compile().as_text()

    def perf_analysis(self, *batch):
        """XLA cost/memory analysis of the SINGLE-step executable for
        these batch shapes: {flops_per_step, hbm_peak_bytes, ...} via
        monitor/perf.py. AOT lower+compile — one extra compilation, so
        this is only reached under FLAGS_perf_attribution or from bench
        tooling, never on the default hot path."""
        if self._compiled is None:
            self._build()
        vals = self._prep_batch(batch)
        state_vals = [self._tensors[n]._value for n in self._names]
        from ..framework import random as _random

        compiled = self._compiled.lower(
            state_vals, self._opt_state, self._ef_state,
            jnp.asarray(0, jnp.int32),
            jnp.asarray(0.0, jnp.float32), _random._key(),
            vals).compile()
        analysis = _perf.executable_analysis(compiled, steps=1)
        # feed the memory ledger's headroom math: this donation-aware
        # peak is the "compiled transient" half of
        # mem_hbm_headroom_bytes (monitor/memory.py)
        if self._mem is not None and "hbm_peak_bytes" in analysis:
            self._mem.note_transient_peak(
                analysis["hbm_peak_bytes"],
                source="estimate" if analysis.get("hbm_peak_is_estimate")
                else "xla_memory_analysis")
        return analysis

    def graph_report(self, *batch):
        """Lower (never execute) the single-step program for these
        batch shapes and return the raw graph-analysis artifact the
        offline analyzer (paddle_tpu/analysis/graph, tools/pthlo.py)
        consumes: jaxpr + StableHLO + compiled-HLO text, the donated
        leaf census, per-param shardings, and the XLA cost analysis.
        AOT lower+compile like perf_analysis — fixture/bench tooling
        only, never the training hot path."""
        if self._compiled is None:
            self._build()
        vals = self._prep_batch(batch)
        state_vals = [self._tensors[n]._value for n in self._names]
        from ..framework import random as _random

        from ..analysis.graph.artifact import arg_leaf_census, \
            param_census

        args = (state_vals, self._opt_state, self._ef_state,
                jnp.asarray(0, jnp.int32),
                jnp.asarray(0.0, jnp.float32), _random._key(), vals)
        lowered = self._compiled.lower(*args)
        compiled = lowered.compile()
        leaves = jax.tree_util.tree_leaves
        carried = len(leaves((args[0], args[1], args[2])))
        total = len(leaves(args))
        # class spans in FLAT ARGUMENT ORDER (the carried pytrees lead
        # the signature): "state" must alias an output when donated;
        # "input" is fresh per call and exempt from the donation audit
        spans = [("state" if self.donate else "input", carried),
                 ("input", total - carried)]
        specs = self._specs()
        return {
            "kind": "train",
            "steps": {
                "step": {
                    "hlo": compiled.as_text(),
                    "stablehlo": lowered.as_text(),
                    "jaxpr": str(jax.make_jaxpr(self._step_fn)(*args)),
                    "arg_leaves": arg_leaf_census(
                        leaves(lowered.args_info), spans),
                    "cost": _perf.executable_analysis(compiled,
                                                      steps=1),
                },
            },
            "params": param_census(
                ((n, self._tensors[n]._value) for n in self._names),
                spec_of=lambda n: str(specs[n])),
            "mesh_axes": dict(self.mesh.shape),
            "qsync_buckets": (len(self._qsync[2])
                              if self._qsync is not None else None),
        }

    def _note_perf(self, vals, steps, dt, loss, t0, t1, stacked=False):
        """Feed one engine call into the MFU/phase attribution. The
        analysis always lowers the SINGLE-step executable (per-step
        FLOPs of a fori_loop body cannot be recovered from the
        multi-step module's cost analysis): run_steps passes slice 0 of
        its stacked batch as the representative shapes."""
        if not (_monitor.is_enabled() and _perf.attribution_enabled()):
            return
        try:
            if self._perf_attr is None:
                single = tuple(v[0] for v in vals) if stacked else vals
                self._perf_attr = _perf.TrainStepPerf(
                    "train",
                    analysis_fn=lambda b=single: self.perf_analysis(*b))
            self._perf_attr.on_step(
                dt, steps=steps, tokens=_batch_tokens(vals, stacked),
                loss=loss, t_start=t0, t_end=t1)
        except Exception as e:
            from ..monitor.registry import warn_once

            warn_once(
                "engine.perf_attr",
                "paddle_tpu.parallel: perf attribution failed (train "
                "step unaffected, MFU/goodput series stop): "
                "%r" % (e,))

    @no_grad()
    def __call__(self, *batch):
        """batch = (*inputs, labels) as Tensors or arrays; returns loss."""
        if _fi.is_enabled():
            _fi.fire("train.step", step=self._step_count + 1)
        prof = self._prof
        try:
            # OOM forensics site (monitor/memory.py): armed only while
            # the tracker is latched
            if self._mem is not None and _fi.is_enabled():
                _fi.fire("mem.oom", step=self._step_count + 1)
            if self._compiled is None:
                self._build()
            vals = self._prep_batch(batch)
            tensors = self._tensors
            state_vals = [tensors[n]._value for n in self._names]
            from ..framework import random as _random

            self._step_count += 1
            if prof is not None:
                prof.step_begin()
            t0 = time.perf_counter()
            with _HB_TRAIN.busy("train.step", step=self._step_count):
                loss, new_state, new_opt, new_ef = self._compiled(
                    state_vals, self._opt_state, self._ef_state,
                    jnp.asarray(self._step_count, jnp.int32),
                    jnp.asarray(self.optimizer.get_lr(), jnp.float32),
                    _random._key(), vals)
        except Exception as e:
            if self._mem is not None \
                    and _monitor.memory.looks_like_oom(e):
                self._mem.write_postmortem(e)
            if prof is not None:
                prof.step_abort()
            raise
        t1 = time.perf_counter()
        if prof is not None:
            prof.step_end(t0, t1, block=loss)
        _record_step(vals, 1, t1 - t0)
        self._note_perf(vals, 1, t1 - t0, loss, t0, t1)
        if _monitor.trace.is_enabled():
            _monitor.trace.record_train_step(
                "train", self._step_count, t1 - t0,
                tokens=_batch_tokens(vals))
        for n, v in zip(self._names, new_state):
            tensors[n]._value = v
        self._opt_state = new_opt
        self._ef_state = new_ef
        return Tensor(loss)


def compile_train_step(model, loss_fn, optimizer, **kwargs):
    return CompiledTrainStep(model, loss_fn, optimizer, **kwargs)
