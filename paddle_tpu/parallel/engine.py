"""Compiled hybrid-parallel train step.

This is the TPU-native replacement for the whole tower the reference builds
out of Reducer bucketing (imperative/reducer.cc), comm streams, 1F1B host
scheduling and ZeRO partitioning python: the model's forward+backward+update
is traced into ONE XLA module over the hybrid mesh; every parallelism choice
enters as a sharding:

- dp:        batch dim sharded over 'dp' → XLA inserts grad all-reduces
             (riding ICI, overlapped by the latency-hiding scheduler).
- mp (TP):   mpu layer params sharded over 'mp' (column/row) → XLA inserts
             the identity/allreduce pairs of Megatron TP.
- sharding:  ZeRO — params+opt state sharded over 'sharding', gathered
             on use (XLA all-gathers weights, reduce-scatters grads).
- sep (SP):  sequence dim sharded over 'sep'; ring attention in kernels/.
- pp:        lax.scan over stage-stacked weights (see pipeline_parallel).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core.dispatch import no_grad
from ..core.tensor import Tensor
from ..distributed import mesh as _mesh


def param_spec(param, zero_stage=0, mesh=None):
    """Sharding spec for one parameter: explicit layer annotation first
    (mpu layers), else ZeRO sharding of the largest divisible dim, else
    replicated."""
    mesh = mesh or _mesh.get_mesh()
    if param._sharding_spec is not None:
        return param._sharding_spec
    if zero_stage >= 2 and "sharding" in mesh.axis_names:
        n = mesh.shape["sharding"]
        shape = tuple(param.shape)
        for i, s in enumerate(shape):
            if s % n == 0 and s >= n:
                spec = [None] * len(shape)
                spec[i] = "sharding"
                return P(*spec)
    return P()


class CompiledTrainStep:
    """jit-compiled (loss, new_params, new_opt_state) step for a Layer +
    loss_fn + Optimizer over the current mesh."""

    def __init__(self, model, loss_fn, optimizer, mesh=None, zero_stage=0,
                 donate=True, batch_spec=None):
        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.mesh = mesh or _mesh.get_mesh()
        self.zero_stage = zero_stage
        self._names, values = model.functional_state()
        self._param_names = [n for n, _ in model.named_parameters()
                             if not dict(model.named_parameters())[n].stop_gradient]
        trainable = {n: p for n, p in model.named_parameters()
                     if not p.stop_gradient}
        self._trainable_names = list(trainable.keys())
        self._opt_state = optimizer.functional_init(
            {n: p._value for n, p in trainable.items()})
        self._step_count = 0
        self.batch_spec = batch_spec or P("dp") if (
            "dp" in self.mesh.axis_names) else P()
        self._shard_params()
        self._compiled = None

    def _specs(self):
        tensors = self.model.raw_state_tensors()
        return {n: param_spec(tensors[n], self.zero_stage, self.mesh)
                for n in self._names}

    def _shard_params(self):
        specs = self._specs()
        tensors = self.model.raw_state_tensors()
        for n in self._names:
            t = tensors[n]
            t._value = jax.device_put(
                t._value, NamedSharding(self.mesh, specs[n]))
        # opt state follows its parameter's sharding
        for n, slots in self._opt_state.items():
            spec = specs[n]
            self._opt_state[n] = [
                jax.device_put(s, NamedSharding(self.mesh, spec))
                for s in slots]

    def _build(self):
        model, loss_fn, opt = self.model, self.loss_fn, self.optimizer
        names = self._names
        trainable_names = self._trainable_names
        mesh = self.mesh
        specs = self._specs()
        state_shardings = {n: NamedSharding(mesh, specs[n]) for n in names}
        batch_sharding = NamedSharding(mesh, self.batch_spec)

        def step(state_vals, opt_state, step_i, *batch):
            state = dict(zip(names, state_vals))

            def loss_of(train_vals, batch):
                full = dict(state)
                full.update(dict(zip(trainable_names, train_vals)))
                wrapped = [Tensor(b) for b in batch]
                with model.bind_state(names, [full[n] for n in names]):
                    with no_grad():
                        out = model(*wrapped[:-1]) if len(wrapped) > 1 \
                            else model(wrapped[0])
                    loss = loss_fn(out, wrapped[-1])
                return loss._value if isinstance(loss, Tensor) else loss

            train_vals = [state[n] for n in trainable_names]
            loss, grads = jax.value_and_grad(loss_of)(train_vals, batch)
            gdict = dict(zip(trainable_names, grads))
            pdict = {n: state[n] for n in trainable_names}
            new_p, new_s = opt.functional_apply(pdict, gdict, opt_state,
                                                step=step_i)
            out_state = []
            for n in names:
                out_state.append(new_p[n] if n in new_p else state[n])
            return loss, out_state, new_s

        in_shardings = (
            [state_shardings[n] for n in names],
            jax.tree_util.tree_map(
                lambda _: None, self._opt_state),  # propagate from args
            None,
        )
        self._compiled = jax.jit(
            step,
            donate_argnums=(0, 1),
        )

    @no_grad()
    def __call__(self, *batch):
        """batch = (*inputs, labels) as Tensors or arrays; returns loss."""
        if self._compiled is None:
            self._build()
        vals = [b._value if isinstance(b, Tensor) else jnp.asarray(b)
                for b in batch]
        vals = [jax.device_put(v, NamedSharding(self.mesh, self.batch_spec))
                for v in vals]
        tensors = self.model.raw_state_tensors()
        state_vals = [tensors[n]._value for n in self._names]
        self._step_count += 1
        loss, new_state, new_opt = self._compiled(
            state_vals, self._opt_state,
            jnp.asarray(self._step_count, jnp.int32), *vals)
        for n, v in zip(self._names, new_state):
            tensors[n]._value = v
        self._opt_state = new_opt
        return Tensor(loss)


def compile_train_step(model, loss_fn, optimizer, **kwargs):
    return CompiledTrainStep(model, loss_fn, optimizer, **kwargs)
