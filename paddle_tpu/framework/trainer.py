"""Trainer / DeviceWorker hierarchy — the PS-style train-loop drivers.

Parity: reference paddle/fluid/framework/trainer.h:59 (TrainerBase),
:105 (MultiTrainer), :142 (DistMultiTrainer) and device_worker.h:164
(DeviceWorker), :265 (HogwildWorker), :300 (DownpourWorker); entry point
Executor::RunFromDataset (executor.cc:163) -> python
Executor.train_from_dataset.

TPU-native shape: worker threads drive the INPUT pipeline in parallel
(decode/shuffle/batch on host CPUs — where thread parallelism actually
pays) while program execution funnels through the one compiled XLA
step; device execution is serialized by the runtime anyway, so the
reference's thread-per-device op loop degenerates to overlap of host
ingestion with device steps. DownpourWorker's sparse pull/push becomes
pull_sparse/push_sparse against TheOnePSRuntime around each step.
"""
from __future__ import annotations

import queue
import threading

import numpy as np


class DeviceWorker:
    """Per-thread batch driver (reference device_worker.h:164)."""

    def __init__(self, trainer, wid):
        self.trainer = trainer
        self.wid = wid

    def train_batch(self, batch):
        raise NotImplementedError


class HogwildWorker(DeviceWorker):
    """Lock-free-style async worker (reference device_worker.h:265
    HogwildWorker): every worker steps the shared program; the XLA step
    itself is the critical section."""

    def train_batch(self, batch):
        return self.trainer._run_batch(batch)


class DownpourWorker(HogwildWorker):
    """PS worker (reference device_worker.h:300): pull sparse rows
    before the step, push grads after."""

    def train_batch(self, batch):
        t = self.trainer
        pulled = {}
        if t.ps_runtime is not None:
            for slot, table in t.sparse_tables.items():
                ids = np.asarray(batch[slot]).reshape(-1)
                pulled[slot] = (ids, t.ps_runtime.pull_sparse(table, ids))
        out = t._run_batch(batch, pulled=pulled)
        if t.ps_runtime is not None and t.push_grads_fn is not None:
            for slot, (ids, rows) in pulled.items():
                grads = t.push_grads_fn(slot, ids, rows, batch, out)
                if grads is not None:
                    t.ps_runtime.push_sparse(t.sparse_tables[slot], ids,
                                             grads)
        return out


class TrainerBase:
    """reference trainer.h:59. run() pulls batches from the dataset's
    feed and fans them over worker threads."""

    worker_cls = HogwildWorker

    def __init__(self, num_workers=2):
        self.num_workers = max(1, num_workers)
        self._run_lock = threading.Lock()
        self.losses = []
        self._program = None
        self._exe = None
        self._fetch = None
        self.ps_runtime = None
        self.sparse_tables = {}
        self.push_grads_fn = None

    def initialize(self, program=None, executor=None, fetch_list=None,
                   run_fn=None):
        self._program = program
        self._exe = executor
        self._fetch = fetch_list or []
        self._run_fn = run_fn

    def _run_batch(self, batch, pulled=None):
        if self._run_fn is not None:
            return self._run_fn(batch)
        with self._run_lock:
            outs = self._exe.run(self._program, feed=batch,
                                 fetch_list=self._fetch)
        if outs:
            self.losses.append(float(np.asarray(outs[0]).reshape(-1)[0]))
        return outs

    def run(self, batch_iter):
        q = queue.Queue(maxsize=self.num_workers * 2)
        stop = object()
        errors = []
        abort = threading.Event()

        def worker_loop(wid):
            w = self.worker_cls(self, wid)
            while True:
                item = q.get()
                if item is stop:
                    q.put(stop)
                    return
                if abort.is_set():
                    continue  # drain so the producer never blocks
                try:
                    w.train_batch(item)
                except Exception as e:  # propagate to the caller
                    errors.append(e)
                    abort.set()

        threads = [threading.Thread(target=worker_loop, args=(i,),
                                    daemon=True)
                   for i in range(self.num_workers)]
        for t in threads:
            t.start()
        try:
            for batch in batch_iter:
                if abort.is_set():
                    break
                while True:
                    try:
                        q.put(batch, timeout=0.5)
                        break
                    except queue.Full:
                        if abort.is_set():
                            break
        finally:
            q.put(stop)
            for t in threads:
                t.join()
        if errors:
            raise errors[0]
        return self


class MultiTrainer(TrainerBase):
    """reference trainer.h:105 (async CPU PS / plain multi-thread)."""


class DistMultiTrainer(TrainerBase):
    """reference trainer.h:142 — downpour PS training."""

    worker_cls = DownpourWorker

    def set_ps(self, ps_runtime, sparse_tables, push_grads_fn=None):
        self.ps_runtime = ps_runtime
        self.sparse_tables = dict(sparse_tables)
        self.push_grads_fn = push_grads_fn
        return self


class TrainerFactory:
    """reference trainer_factory.cc."""

    _TRAINERS = {
        "MultiTrainer": MultiTrainer,
        "DistMultiTrainer": DistMultiTrainer,
    }

    def create_trainer(self, name="MultiTrainer", **kwargs):
        return self._TRAINERS[name](**kwargs)
