"""Random state.

Analog of the reference's per-device Generator
(/root/reference/paddle/phi/core/generator.h, python/paddle/framework/random.py)
rebuilt on JAX's splittable counter-based PRNG: a process-global root key is
split per draw. Under `to_static` tracing the split happens at trace time, so
a compiled step re-uses its traced keys; compiled training loops should thread
keys explicitly (the nn layers accept a `seed` attr for that) — same caveat as
the reference's cudnn dropout state caching.
"""
from __future__ import annotations

import threading

import jax

_lock = threading.Lock()
# Lazy: materializing a key initializes the JAX backend; `import paddle_tpu`
# must stay device-free (the launcher parent and CLI tools never touch a chip).
_root_key = None
_counter = 0


def _key():
    global _root_key
    if _root_key is None:
        with _lock:
            if _root_key is None:
                _root_key = jax.random.key(0)
    return _root_key


def seed(s: int):
    """paddle.seed analog."""
    global _root_key, _counter
    with _lock:
        _root_key = jax.random.key(int(s))
        _counter = 0
    return s


def next_key():
    """Return a fresh PRNG key (thread-safe)."""
    global _counter
    root = _key()
    with _lock:
        _counter += 1
        c = _counter
    return jax.random.fold_in(root, c)


def get_rng_state():
    return (_key(), _counter)


def set_rng_state(state):
    global _root_key, _counter
    _root_key, _counter = state
