"""Random state.

Analog of the reference's per-device Generator
(/root/reference/paddle/phi/core/generator.h, python/paddle/framework/random.py)
rebuilt on JAX's splittable counter-based PRNG: a process-global root key is
split per draw. Under `to_static` tracing the split happens at trace time, so
a compiled step re-uses its traced keys; compiled training loops should thread
keys explicitly (the nn layers accept a `seed` attr for that) — same caveat as
the reference's cudnn dropout state caching.
"""
from __future__ import annotations

import contextlib
import threading

import jax

_lock = threading.Lock()
# Lazy: materializing a key initializes the JAX backend; `import paddle_tpu`
# must stay device-free (the launcher parent and CLI tools never touch a chip).
_root_key = None
_counter = 0


def _key():
    global _root_key
    if _root_key is None:
        with _lock:
            if _root_key is None:
                _root_key = jax.random.key(0)
    return _root_key


def seed(s: int):
    """paddle.seed analog."""
    global _root_key, _counter
    with _lock:
        _root_key = jax.random.key(int(s))
        _counter = 0
    return s


_replay = threading.local()


def set_replay_base(key):
    """Static-replay RNG base: while set (the Executor sets it around each
    tape replay, passing a fresh per-run key as a traced argument), every
    next_key() derives from it — so a compiled program draws NEW
    randomness each Executor.run instead of replaying the keys captured
    at trace time."""
    _replay.key = key
    _replay.counter = 0


@contextlib.contextmanager
def replay_base(key):
    """Scoped set_replay_base: saves/restores the previous base AND its
    counter, exception-safe. The compiled train steps wrap their traced
    model call in this with a per-step folded key (fresh dropout masks
    every step; a leaked traced key would poison every later eager
    draw)."""
    prev_k = getattr(_replay, "key", None)
    prev_c = getattr(_replay, "counter", 0)
    set_replay_base(key)
    try:
        yield
    finally:
        _replay.key = prev_k
        _replay.counter = prev_c


def next_key():
    """Return a fresh PRNG key (thread-safe). Inside an
    RNGStatesTracker.rng_state(...) context the named state supplies the
    key (mp-rank-local when the state is local, reference mpu/random.py);
    inside a static replay the per-run base key supplies it."""
    if _state_stack:
        return model_parallel_rng_key()
    if getattr(_replay, "key", None) is not None:
        _replay.counter += 1
        return jax.random.fold_in(_replay.key, _replay.counter)
    global _counter
    root = _key()
    with _lock:
        _counter += 1
        c = _counter
    return jax.random.fold_in(root, c)


def get_rng_state():
    return (_key(), _counter)


def set_rng_state(state):
    global _root_key, _counter
    _root_key, _counter = state


# -- named RNG states (model-parallel dropout) -------------------------------
#
# Reference fleet/layers/mpu/random.py RNGStatesTracker: under tensor
# parallelism, dropout on mp-SHARDED activations must draw a DIFFERENT
# mask per mp rank ('local_seed'), while dropout on replicated activations
# must draw the SAME mask ('global_seed'). Under GSPMD pjit this is
# automatic (one logical mask, each device materializes its shard), but
# per-shard programs (shard_map bodies: ring pipeline, expert dispatch)
# re-run the same code on every rank, so the local state additionally
# folds in axis_index(axis) — the JAX-native form of the reference's
# per-rank seed offset.

_tracker_states = {}   # name -> [key, counter, fold_axes]
_state_stack = []      # active rng_state(...) contexts (innermost last)


class RNGStatesTracker:
    def add(self, name, seed):
        if name in _tracker_states:
            raise ValueError("rng state %r already added" % name)
        axes = ("mp",) if name != "global_seed" else ()
        _tracker_states[name] = [jax.random.key(int(seed)), 0, axes]

    def reset(self):
        _tracker_states.clear()
        _process_mp_rank.clear()

    def get_states_tracker(self):
        return dict(_tracker_states)

    class _Ctx:
        def __init__(self, name):
            self.name = name

        def __enter__(self):
            if self.name not in _tracker_states:
                # auto-register from the global seed (reference raises;
                # we derive deterministically so layers work untracked).
                # crc32, NOT hash(): Python string hashes are
                # PYTHONHASHSEED-randomized per process.
                import zlib

                axes = ("mp",) if self.name != "global_seed" else ()
                _tracker_states[self.name] = [
                    jax.random.fold_in(
                        _key(), zlib.crc32(self.name.encode()) & 0x7FFFFFFF),
                    0, axes]
            _state_stack.append(self.name)
            return self

        def __exit__(self, *exc):
            _state_stack.pop()
            return False

    def rng_state(self, name="global_seed"):
        return self._Ctx(name)

    def set_mp_rank(self, rank):
        """Record the process-level mp rank for eager multi-process mode
        (reference mpu/random.py model_parallel_rng_tracker_name seeding):
        folded into every rank-local draw when no 'mp' mesh axis is
        bound."""
        _process_mp_rank.clear()
        if rank:
            _process_mp_rank.append(int(rank))


_tracker = RNGStatesTracker()


def get_rng_state_tracker():
    return _tracker


def model_parallel_rng_key():
    """Key for the active named state (fold per-draw counter, then the
    mp rank when the state is rank-local and the axis is bound). When a
    static replay base is active it is folded in too, so tracked dropout
    inside a compiled Program still draws fresh masks per Executor.run
    instead of baking the trace-time key as a constant."""
    st = _tracker_states[_state_stack[-1]]
    st[1] += 1
    key = jax.random.fold_in(st[0], st[1])
    replay = getattr(_replay, "key", None)
    if replay is not None:
        for d in jax.random.key_data(replay).ravel():
            key = jax.random.fold_in(key, d)
    for axis in st[2]:
        try:
            key = jax.random.fold_in(key, jax.lax.axis_index(axis))
        except Exception:
            # axis not bound. Multi-process eager: fold the process-level
            # mp rank (set by TensorParallel via set_mp_rank) so ranks
            # draw distinct masks. Single-process GSPMD: the global mask
            # is already per-position, nothing to fold.
            if axis == "mp" and _process_mp_rank:
                key = jax.random.fold_in(key, _process_mp_rank[0])
            break
    return key


_process_mp_rank = []  # [rank] when set (eager multi-process mode)


def in_tracked_rng_state():
    return bool(_state_stack)
