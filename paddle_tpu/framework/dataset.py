"""Fleet datasets — high-throughput file-backed ingestion for
train_from_dataset.

Parity: reference python/paddle/distributed/fleet/dataset/dataset.py
(InMemoryDataset: load_into_memory/local_shuffle/global_shuffle;
QueueDataset: streaming) over the C++ DataFeed
(framework/data_feed.h:1083,1325). Here both ride the native record
feed (csrc/feed.cc: multi-threaded file readers + shuffle buffer +
bounded queue) through io/datafeed.DataFeed.
"""
from __future__ import annotations

import numpy as np

from ..io.datafeed import DataFeed, RecordWriter


class DatasetBase:
    def __init__(self):
        self._filelist = []
        self._batch_size = 1
        self._thread_num = 2
        self._use_vars = []
        self._shuffle_buffer = 0

    def init(self, batch_size=1, thread_num=2, use_var=None, **kwargs):
        self._batch_size = batch_size
        self._thread_num = thread_num
        if use_var is not None:
            self.set_use_var(use_var)

    def set_filelist(self, filelist):
        self._filelist = list(filelist)

    def set_batch_size(self, batch_size):
        self._batch_size = batch_size

    def set_thread(self, thread_num):
        self._thread_num = thread_num

    def set_use_var(self, var_list):
        """Feed slot order: one dataset column per variable (reference
        dataset.set_use_var binding slots to program vars)."""
        self._use_vars = [getattr(v, "name", v) for v in var_list]

    def _feed(self):
        return DataFeed(self._filelist, num_threads=self._thread_num,
                        shuffle_buffer=self._shuffle_buffer)

    def batches(self):
        """Yield feed dicts {var_name: np.ndarray} of batch_size rows."""
        feed = self._feed()
        try:
            for cols in feed.batched(self._batch_size, drop_last=False):
                if isinstance(cols, dict):
                    yield cols
                    continue
                cols = cols if isinstance(cols, (list, tuple)) else [cols]
                names = self._use_vars or [
                    "slot_%d" % i for i in range(len(cols))]
                yield dict(zip(names, cols))
        finally:
            feed.close()


class QueueDataset(DatasetBase):
    """Streaming dataset (reference QueueDataset): records flow straight
    from the reader threads' bounded queue."""


class InMemoryDataset(DatasetBase):
    """reference InMemoryDataset: load once, shuffle in memory, iterate
    many epochs."""

    def __init__(self):
        super().__init__()
        self._records = None

    def load_into_memory(self):
        feed = self._feed()
        try:
            self._records = list(feed)
        finally:
            feed.close()

    def local_shuffle(self, seed=None):
        if self._records is None:
            raise RuntimeError("call load_into_memory() first")
        rng = np.random.RandomState(seed)
        rng.shuffle(self._records)

    def global_shuffle(self, fleet=None, thread_num=None, seed=None):
        # single-controller SPMD: the global view IS the local view
        self.local_shuffle(seed)

    def get_memory_data_size(self):
        return 0 if self._records is None else len(self._records)

    def release_memory(self):
        self._records = None

    def batches(self):
        if self._records is None:
            yield from super().batches()
            return
        from ..io.datafeed import _stack

        bs = self._batch_size
        for i in range(0, len(self._records), bs):
            chunk = self._records[i:i + bs]
            cols = _stack(chunk)
            cols = cols if isinstance(cols, (list, tuple)) else [cols]
            names = self._use_vars or [
                "slot_%d" % j for j in range(len(cols))]
            yield dict(zip(names, cols))


__all__ = ["DatasetBase", "InMemoryDataset", "QueueDataset",
           "RecordWriter"]
