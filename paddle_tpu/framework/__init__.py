from . import random  # noqa: F401
from .random import get_rng_state, seed, set_rng_state  # noqa: F401
