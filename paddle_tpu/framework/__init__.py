from . import random  # noqa: F401
from .random import get_rng_state, seed, set_rng_state  # noqa: F401
from . import dataset, trainer  # noqa: F401
from .dataset import InMemoryDataset, QueueDataset  # noqa: F401
from .trainer import (  # noqa: F401
    DistMultiTrainer,
    MultiTrainer,
    TrainerFactory,
)
