"""paddle.save / paddle.load.

Parity: reference python/paddle/framework/io.py:637,879 (pickle protocol with
tensor chunks). We serialize numpy arrays via pickle; nested state dicts,
optimizer states, and plain python objects round-trip. Sharded / distributed
checkpointing lives in paddle_tpu.distributed.checkpoint (orbax-backed).
"""
from __future__ import annotations

import os
import pickle

import numpy as np

from ..core.tensor import Tensor


def _to_serializable(obj):
    if isinstance(obj, Tensor):
        return _TensorPayload(np.asarray(obj._value), str(obj.dtype))
    if isinstance(obj, dict):
        return {k: _to_serializable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = [_to_serializable(v) for v in obj]
        return t if isinstance(obj, list) else tuple(t)
    return obj


def _from_serializable(obj, return_numpy=False):
    if isinstance(obj, _TensorPayload):
        if return_numpy:
            return obj.array
        import jax.numpy as jnp

        from ..core import dtype as _dt

        return Tensor(jnp.asarray(obj.array, _dt.to_jax(obj.dtype)))
    if isinstance(obj, dict):
        return {k: _from_serializable(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = [_from_serializable(v, return_numpy) for v in obj]
        return t if isinstance(obj, list) else tuple(t)
    return obj


class _TensorPayload:
    def __init__(self, array, dtype):
        # bfloat16 has no numpy dtype guarantee: store as uint16 view
        self.dtype = dtype
        if dtype == "bfloat16":
            self.array = array.view(np.uint16) if array.dtype != np.uint16 \
                else array
        else:
            self.array = array

    @property
    def _array(self):
        return self.array


def save(obj, path, protocol=4, **configs):
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    payload = _to_serializable(obj)
    with open(path, "wb") as f:
        pickle.dump(payload, f, protocol=protocol)


def load(path, return_numpy=False, **configs):
    with open(path, "rb") as f:
        payload = pickle.load(f)
    return _from_serializable(payload, return_numpy)
