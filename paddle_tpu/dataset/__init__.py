"""paddle.dataset — legacy dataset loaders.

Parity: reference python/paddle/dataset/ (uci_housing, mnist, imdb, ...
reader-creator functions that download to ~/.cache/paddle/dataset).
This environment has no network egress, so loaders read from a local
directory (PADDLE_DATASET_HOME or data_file=) when present and otherwise
generate a deterministic synthetic sample with the real schema — enough
to run every ported pipeline end to end; swap in real files for results.
"""
from __future__ import annotations

from . import uci_housing  # noqa: F401

__all__ = ["uci_housing"]
