"""paddle.dataset.uci_housing (reference dataset/uci_housing.py):
13-feature Boston-housing regression, normalized, reader-creator API."""
from __future__ import annotations

import os

import numpy as np

__all__ = ["train", "test", "feature_names"]

feature_names = [
    "CRIM", "ZN", "INDUS", "CHAS", "NOX", "RM", "AGE", "DIS", "RAD",
    "TAX", "PTRATIO", "B", "LSTAT",
]

_N_TRAIN, _N_TEST = 404, 102


import functools


@functools.lru_cache(maxsize=2)
def _load_cached(path):
    return _load_impl(path)


def _load():
    # copy: readers hand rows to user code that may mutate in place —
    # the cache must never leak a shared mutable array
    return _load_cached(os.environ.get("PADDLE_DATASET_HOME")).copy()


def _load_impl(path):
    if path:
        f = os.path.join(path, "housing.data")
        if os.path.exists(f):
            data = np.loadtxt(f)
            feats, target = data[:, :-1], data[:, -1:]
            feats = (feats - feats.mean(0)) / (feats.std(0) + 1e-8)
            return np.concatenate([feats, target], axis=1).astype("float32")
    # deterministic synthetic fallback with the real schema (13 + 1)
    rng = np.random.RandomState(7)
    feats = rng.randn(_N_TRAIN + _N_TEST, 13).astype("float32")
    w = rng.randn(13, 1).astype("float32")
    target = feats @ w + 0.1 * rng.randn(_N_TRAIN + _N_TEST, 1)
    return np.concatenate([feats, target.astype("float32")], axis=1)


def train():
    """Reader creator over the train split (reference uci_housing.train)."""

    def reader():
        for row in _load()[:_N_TRAIN]:
            yield row[:-1], row[-1:]

    return reader


def test():
    def reader():
        for row in _load()[_N_TRAIN:]:
            yield row[:-1], row[-1:]

    return reader
