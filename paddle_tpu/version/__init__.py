"""paddle.version (reference generated python/paddle/version.py)."""
from __future__ import annotations

full_version = "0.1.0"
major = "0"
minor = "1"
patch = "0"
rc = "0"
istaged = False
commit = "tpu-native"
with_mkl = "OFF"
cuda_version = "False"
cudnn_version = "False"
xpu_version = "False"


def show():
    """Print the version breakdown (reference version.py show())."""
    print("full_version:", full_version)
    print("major:", major)
    print("minor:", minor)
    print("patch:", patch)
    print("commit:", commit)


def cuda():
    return False


def cudnn():
    return False


def tpu():
    return True
