"""paddle.jit — dynamic-to-static.

The reference rewrites Python ASTs into ProgramDesc
(python/paddle/jit/dy2static, ProgramTranslator at
program_translator.py:1160). TPU-native design: our eager ops already *are*
jax-traceable expressions, so to_static is jax.jit tracing of the user's
forward with parameters lifted to arguments — one XLA module per input
signature, cached, donation-friendly. This collapses the reference's AST
transformer + ProgramDesc + executor pipeline into a trace-and-compile step
while keeping the same user API (@to_static, jit.save/load, input_spec).
"""
from __future__ import annotations

import functools
import os
import pickle

import jax
import jax.numpy as jnp

from ..core import dtype as _dtype
from ..core.dispatch import no_grad
from ..core.tensor import Tensor
from ..nn.layer import Layer


class InputSpec:
    """Static shape/dtype spec (reference python/paddle/static/input.py)."""

    def __init__(self, shape, dtype="float32", name=None):
        self.shape = list(shape)
        self.dtype = _dtype.canonical_name(dtype)
        self.name = name

    def __repr__(self):
        return "InputSpec(shape=%s, dtype=%s)" % (self.shape, self.dtype)


class StaticFunction:
    """Compiled wrapper around a Layer method or function."""

    def __init__(self, fn, layer=None, input_spec=None):
        self._fn = fn
        self._layer = layer
        self._input_spec = input_spec
        self._cache = {}
        functools.update_wrapper(self, fn)

    def _key(self, args):
        parts = []
        for a in args:
            if isinstance(a, Tensor):
                parts.append(("T", tuple(a.shape), a.dtype))
            else:
                parts.append(("S", repr(a)))
        return tuple(parts)

    def _compile(self, args):
        layer = self._layer
        if layer is not None:
            names, _ = layer.functional_state()

            def pure(state_vals, *in_vals):
                wrapped = [Tensor(v) for v in in_vals]
                with layer.bind_state(names, state_vals):
                    with no_grad():
                        out = self._fn(*wrapped)
                return jax.tree_util.tree_map(
                    lambda t: t._value if isinstance(t, Tensor) else t, out,
                    is_leaf=lambda x: isinstance(x, Tensor))

            return jax.jit(pure)

        def pure(*in_vals):
            wrapped = [Tensor(v) for v in in_vals]
            with no_grad():
                out = self._fn(*wrapped)
            return jax.tree_util.tree_map(
                lambda t: t._value if isinstance(t, Tensor) else t, out,
                is_leaf=lambda x: isinstance(x, Tensor))

        return jax.jit(pure)

    def __call__(self, *args, **kwargs):
        tensor_args = [a for a in args if isinstance(a, Tensor)]
        if len(tensor_args) != len(args):
            # non-tensor args: fall back to eager for simplicity
            return self._fn(*args, **kwargs)
        key = self._key(args)
        if key not in self._cache:
            self._cache[key] = self._compile(args)
        compiled = self._cache[key]
        in_vals = [a._value for a in args]
        if self._layer is not None:
            _, state_vals = self._layer.functional_state()
            out = compiled(state_vals, *in_vals)
        else:
            out = compiled(*in_vals)
        return jax.tree_util.tree_map(Tensor, out)

    @property
    def concrete_program(self):
        return self._cache


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None):
    """@paddle.jit.to_static analog (reference python/paddle/jit/api.py:222)."""

    def deco(fn):
        if isinstance(fn, Layer):
            layer = fn
            layer.forward = StaticFunction(
                layer.forward.__func__.__get__(layer)
                if hasattr(layer.forward, "__func__") else layer.forward,
                layer=layer, input_spec=input_spec)
            return layer
        return StaticFunction(fn, input_spec=input_spec)

    if function is not None:
        return deco(function)
    return deco


def save(layer, path, input_spec=None, **config):
    """jit.save: serialize params + a callable spec. The compiled artifact
    (StableHLO) is regenerated at load — XLA executables are
    hardware-keyed, mirroring how the reference regenerates engine plans."""
    import numpy as np

    state = {}
    if isinstance(layer, Layer):
        for name, t in layer.state_dict().items():
            state[name] = np.asarray(t._value)
    meta = {
        "class": type(layer).__name__,
        "input_spec": [
            {"shape": s.shape, "dtype": s.dtype} for s in (input_spec or [])
        ],
    }
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path + ".pdiparams", "wb") as f:
        pickle.dump(state, f, protocol=4)
    with open(path + ".pdmodel", "wb") as f:
        pickle.dump(meta, f, protocol=4)


class TranslatedLayer(Layer):
    """Loaded inference layer (reference python/paddle/jit/translated_layer.py).
    Holds the state dict; `forward` must be re-bound by the loading model, or
    used through paddle_tpu.static predictors."""

    def __init__(self, state, meta):
        super().__init__()
        self._loaded_state = state
        self._meta = meta

    def state_dict(self, *a, **k):
        return self._loaded_state

    def forward(self, *args):
        raise RuntimeError(
            "TranslatedLayer from jit.load holds weights only; bind it to a "
            "model class or use paddle_tpu.static.Predictor")


def load(path):
    with open(path + ".pdiparams", "rb") as f:
        state = pickle.load(f)
    meta = {}
    if os.path.exists(path + ".pdmodel"):
        with open(path + ".pdmodel", "rb") as f:
            meta = pickle.load(f)
    return TranslatedLayer(state, meta)


def not_to_static(fn):
    fn._not_to_static = True
    return fn


def ignore_module(modules):
    return None
