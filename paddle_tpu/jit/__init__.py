"""paddle.jit — dynamic-to-static.

The reference rewrites Python ASTs into ProgramDesc
(python/paddle/jit/dy2static, ProgramTranslator at
program_translator.py:1160). TPU-native design: our eager ops already *are*
jax-traceable expressions, so to_static is jax.jit tracing of the user's
forward with parameters lifted to arguments — one XLA module per input
signature, cached, donation-friendly. This collapses the reference's AST
transformer + ProgramDesc + executor pipeline into a trace-and-compile step
while keeping the same user API (@to_static, jit.save/load, input_spec).
"""
from __future__ import annotations

import functools
import os
import pickle

import jax
import jax.numpy as jnp

from ..core import dtype as _dtype
from ..core.dispatch import no_grad
from ..core.tensor import Tensor
from ..nn.layer import Layer


class InputSpec:
    """Static shape/dtype spec (reference python/paddle/static/input.py)."""

    def __init__(self, shape, dtype="float32", name=None):
        self.shape = list(shape)
        self.dtype = _dtype.canonical_name(dtype)
        self.name = name

    def __repr__(self):
        return "InputSpec(shape=%s, dtype=%s)" % (self.shape, self.dtype)


def export_with_dynamic_dims(pure_fn, specs, leading_args=()):
    """Serialize ``pure_fn(*leading_args_placeholder, *inputs)`` to portable
    StableHLO bytes (jax.export), with -1/None dims exported as symbolic
    dimensions when the traced graph supports them, else concretized to 1.

    ``specs``: [(shape, jax_dtype)] for the trailing (user input) args.
    ``leading_args``: concrete arrays/pytrees prepended verbatim (e.g. model
    state), exported with their own concrete shapes."""
    from jax import export as jex

    lead = [jax.tree_util.tree_map(
        lambda v: jax.ShapeDtypeStruct(v.shape, v.dtype), a)
        for a in leading_args]

    def concrete_args():
        return [jax.ShapeDtypeStruct(
            tuple(1 if d in (-1, None) else d for d in shape), jdt)
            for shape, jdt in specs]

    in_args, any_sym = [], False
    for shape, jdt in specs:
        dims, syms = [], 0
        for i, d in enumerate(shape):
            if d in (-1, None):
                syms += 1
                dims.append("b%d" % i)
            else:
                dims.append(str(d))
        if syms:
            try:
                in_args.append(jax.ShapeDtypeStruct(
                    jex.symbolic_shape(",".join(dims)), jdt))
                any_sym = True
                continue
            # ptlint: silent-except-ok — symbolic shapes are
            # opportunistic; the concrete-dim fallback is right below
            except Exception:
                pass
        in_args.append(jax.ShapeDtypeStruct(
            tuple(1 if d in (-1, None) else d for d in shape), jdt))
    try:
        return jex.export(jax.jit(pure_fn))(*lead, *in_args).serialize()
    except Exception:
        if not any_sym:
            raise
        # symbolic dims unsupported by some op in the graph → concrete
        return jex.export(jax.jit(pure_fn))(*lead,
                                            *concrete_args()).serialize()


class StaticFunction:
    """Compiled wrapper around a Layer method or function. The wrapped
    function is first run through the dy2static AST converter
    (dy2static.py) so tensor-dependent Python `if`/`while`/`for` lower
    to XLA control flow instead of failing at trace time (reference
    jit/dy2static program_translator.py:1160)."""

    def __init__(self, fn, layer=None, input_spec=None):
        from .dy2static import convert_control_flow

        self._original_fn = fn
        self._fn = convert_control_flow(fn)
        self._layer = layer
        self._input_spec = input_spec
        self._cache = {}
        functools.update_wrapper(self, fn)

    def _key(self, args):
        parts = []
        for a in args:
            if isinstance(a, Tensor):
                parts.append(("T", tuple(a.shape), a.dtype))
            else:
                parts.append(("S", repr(a)))
        return tuple(parts)

    def _compile(self, args):
        layer = self._layer
        if layer is not None:
            names, _ = layer.functional_state()

            def pure(state_vals, *in_vals):
                wrapped = [Tensor(v) for v in in_vals]
                with layer.bind_state(names, state_vals):
                    with no_grad():
                        out = self._fn(*wrapped)
                return jax.tree_util.tree_map(
                    lambda t: t._value if isinstance(t, Tensor) else t, out,
                    is_leaf=lambda x: isinstance(x, Tensor))

            return jax.jit(pure)

        def pure(*in_vals):
            wrapped = [Tensor(v) for v in in_vals]
            with no_grad():
                out = self._fn(*wrapped)
            return jax.tree_util.tree_map(
                lambda t: t._value if isinstance(t, Tensor) else t, out,
                is_leaf=lambda x: isinstance(x, Tensor))

        return jax.jit(pure)

    def _needs_grad(self, args, kwargs):
        """Training pass? The jitted inference trace runs under no_grad
        and would silently detach autograd — route through the eager
        tape instead (the reference's @to_static records fwd+bwd into
        one Program; here eager IS the differentiable engine, and
        CompiledTrainStep is the whole-graph-compiled training path)."""
        from ..core.dispatch import tape_enabled

        if not tape_enabled():
            return False
        if self._layer is not None:
            for p in self._layer.parameters():
                if not p.stop_gradient:
                    return True
        return any(isinstance(a, Tensor) and not a.stop_gradient
                   for a in list(args) + list(kwargs.values()))

    def __call__(self, *args, **kwargs):
        if not _TO_STATIC_ENABLED:
            # jit.enable_to_static(False): decorated fns run eagerly
            return self._fn(*args, **kwargs)
        if self._needs_grad(args, kwargs):
            return self._fn(*args, **kwargs)
        tensor_args = [a for a in args if isinstance(a, Tensor)]
        if kwargs or len(tensor_args) != len(args):
            # kwargs or non-tensor args: the compiled-path cache keys
            # and call only cover positional tensors — run eagerly
            # rather than silently tracing with defaults
            return self._fn(*args, **kwargs)
        key = self._key(args)
        if key not in self._cache:
            self._cache[key] = self._compile(args)
        compiled = self._cache[key]
        in_vals = [a._value for a in args]
        if self._layer is not None:
            _, state_vals = self._layer.functional_state()
            out = compiled(state_vals, *in_vals)
        else:
            out = compiled(*in_vals)
        return jax.tree_util.tree_map(Tensor, out)

    @property
    def concrete_program(self):
        return self._cache


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None):
    """@paddle.jit.to_static analog (reference python/paddle/jit/api.py:222)."""

    def deco(fn):
        if isinstance(fn, Layer):
            layer = fn
            layer.forward = StaticFunction(
                layer.forward.__func__.__get__(layer)
                if hasattr(layer.forward, "__func__") else layer.forward,
                layer=layer, input_spec=input_spec)
            return layer
        return StaticFunction(fn, input_spec=input_spec)

    if function is not None:
        return deco(function)
    return deco


def save(layer, path, input_spec=None, **config):
    """jit.save: serialize params + the traced program as portable StableHLO
    (jax.export) — the TPU-native saved-inference format (reference:
    ProgramDesc `.pdmodel` + `.pdiparams`, python/paddle/jit/api.py jit.save).

    With input_spec, the forward is exported with the state as leading
    arguments, so jit.load returns a runnable TranslatedLayer on any
    backend; without it, weights-only (the load must re-bind a model
    class)."""
    import numpy as np

    state = {}
    if isinstance(layer, Layer):
        for name, t in layer.state_dict().items():
            state[name] = np.asarray(t._value)
    meta = {
        "class": type(layer).__name__,
        "input_spec": [
            {"shape": s.shape, "dtype": s.dtype} for s in (input_spec or [])
        ],
    }
    blob = None
    if input_spec and isinstance(layer, Layer):
        names, values = layer.functional_state()
        meta["state_names"] = list(names)

        def pure(state_vals, *in_vals):
            wrapped = [Tensor(v) for v in in_vals]
            with layer.bind_state(names, list(state_vals)):
                with no_grad():
                    out = layer(*wrapped)
            return jax.tree_util.tree_map(
                lambda t: t._value if isinstance(t, Tensor) else t, out,
                is_leaf=lambda x: isinstance(x, Tensor))

        blob = export_with_dynamic_dims(
            pure,
            [(s.shape, _dtype.to_jax(s.dtype)) for s in input_spec],
            leading_args=(list(values),))
        meta["format"] = "stablehlo.jax_export.v1"
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path + ".pdiparams", "wb") as f:
        pickle.dump(state, f, protocol=4)
    with open(path + ".pdmodel", "wb") as f:
        pickle.dump({"meta": meta, "stablehlo": blob}, f, protocol=4)


class TranslatedLayer(Layer):
    """Loaded inference layer (reference python/paddle/jit/translated_layer.py).
    If the artifact carries a StableHLO program, forward runs it directly;
    otherwise it holds weights only and must be re-bound to a model class."""

    def __init__(self, state, meta, exported=None):
        super().__init__()
        self._loaded_state = state
        self._meta = meta
        self._exported = exported
        self._call = jax.jit(exported.call) if exported is not None else None
        if exported is not None:
            names = meta.get("state_names") or sorted(state.keys())
            self._state_vals = [jnp.asarray(state[n]) for n in names]

    def state_dict(self, *a, **k):
        return self._loaded_state

    def forward(self, *args):
        if self._call is None:
            raise RuntimeError(
                "this jit.save artifact holds weights only (no input_spec "
                "at save time); bind it to a model class or re-save with "
                "input_spec")
        in_vals = [a._value if isinstance(a, Tensor) else jnp.asarray(a)
                   for a in args]
        out = self._call(self._state_vals, *in_vals)
        return jax.tree_util.tree_map(Tensor, out)


def load(path):
    with open(path + ".pdiparams", "rb") as f:
        state = pickle.load(f)
    meta, exported = {}, None
    if os.path.exists(path + ".pdmodel"):
        with open(path + ".pdmodel", "rb") as f:
            payload = pickle.load(f)
        if isinstance(payload, dict) and "meta" in payload:
            meta = payload["meta"]
            blob = payload.get("stablehlo")
            if blob:
                from jax import export as jex

                exported = jex.deserialize(blob)
        else:
            meta = payload
    return TranslatedLayer(state, meta, exported)


def not_to_static(fn):
    fn._not_to_static = True
    return fn


def ignore_module(modules):
    return None


def enable_to_static(enable_to_static_bool=True):
    """reference jit.enable_to_static: global switch for @to_static
    (ProgramTranslator.enable analog)."""
    global _TO_STATIC_ENABLED
    _TO_STATIC_ENABLED = bool(enable_to_static_bool)


_TO_STATIC_ENABLED = True


def set_code_level(level=100, also_to_stdout=False):
    """reference jit.set_code_level: dy2static transformed-code logging.
    Trace-based capture has no AST rewriting stages to print; the knob is
    recorded for API compatibility."""
    import logging

    logging.getLogger("paddle_tpu.jit").setLevel(
        logging.DEBUG if level else logging.WARNING)


def set_verbosity(level=0, also_to_stdout=False):
    """reference jit.set_verbosity: dy2static logging verbosity."""
    import logging

    logging.getLogger("paddle_tpu.jit").setLevel(
        logging.DEBUG if level else logging.WARNING)
