"""dy2static: AST conversion of tensor-dependent Python control flow.

Parity: reference python/paddle/jit/dy2static (ifelse_transformer.py,
loop_transformer.py, break_continue_transformer.py, return_transformer.py,
program_translator.py:1160). The reference rewrites the user's AST so
`if`/`while`/`for` whose predicate is a Tensor become conditional_block /
while ops in a ProgramDesc. TPU-native design: the same AST rewrite, but
the rewritten statements call *runtime-dispatching* helpers that

  - run ordinary Python control flow when the value is concrete
    (plain bools, un-traced Tensors): zero semantic change off-trace;
  - lower to `jax.lax.cond` / `jax.lax.while_loop` (via static.cond /
    static.while_loop, the framework's control-flow ops) when the value
    is a tracer inside `@to_static`'s jax.jit trace.

The transform pipeline per function (one pass, applied lazily at first
compile, cached):

  1. interruption desugaring — `return`/`break`/`continue` become flag
     assignments (`_dy2st_ret_flag`, `_dy2st_brk_N`, ...) and every
     statement after a potentially-interrupting one is wrapped in an
     `if <no flag set>:` guard (reference break_continue_transformer /
     return_transformer use the same flag scheme);
  2. structural conversion — each `if` becomes two local branch
     functions + `_dy2st.convert_if(...)` over the carried variables
     (reference ifelse_transformer's true_fn/false_fn extraction); each
     `while`/`for` becomes cond/body functions + `_dy2st.convert_while`
     / `_dy2st.convert_for` (reference loop_transformer's
     loop-variable analysis).

Carried-variable analysis: a name is carried through a construct when it
is read or written inside it AND is a local of the enclosing function
(args + anything stored anywhere in the function). Globals/builtins
(modules, `len`, ...) resolve through the function's globals and are
never carried. Names possibly unbound before a converted construct are
bound to the `UNDEF` sentinel first (`x = locals().get('x', UNDEF)`),
and under tracing UNDEF inputs are replaced by shape-matched zeros once
the branch/body output structure is known (via jax.eval_shape).

`while/for ... else` converts via the break-flag's complement; `return`
inside a converted loop body is supported via the ret flag.

Container-carried variables (the reference's list->tensor_array analog,
convert_operators.py:738): carried lists/tuples/dicts are pytree-
flattened into per-leaf lax slots and written back into the ORIGINAL
container objects afterwards (aliases held outside the construct keep
eager semantics). Structure-preserving mutation (index/key assignment)
lowers to lax control flow; structure-CHANGING mutation (append/pop
under a traced bound or condition) has no static-shape equivalent on
XLA and raises a typed error naming the variable.

Known limits (each raises a typed UnimplementedError with the manual
routing hint, reference program_translator's error_data analog):
loop-carried variables that change shape/dtype across iterations are
not expressible in XLA; container structure changes under traced
control flow (above); two carried names aliasing one container object;
stores to `global`/`nonlocal` names inside converted blocks.
Closure values are snapshotted at conversion time (later rebinding of a
closed-over name is invisible); an unbound forward-referenced closure
falls back to trace-only conversion with a warning.
"""
from __future__ import annotations

import ast
import functools
import inspect
import logging
import textwrap
import warnings

import jax
import jax.numpy as jnp

from ..core.enforce import UnimplementedError
from ..core.tensor import Tensor

logger = logging.getLogger("paddle_tpu.jit")

_HINT = ("rewrite this construct with paddle_tpu.static.cond / "
         "static.while_loop / lax-compatible code, or move it out of the "
         "@to_static region")


def _warn_trace_only(fn, reason):
    """Loud, named, consequence-stating warning when a function reverts
    to trace-only conversion: users must know that tensor-dependent
    if/while/for in that function will raise a concretization error
    under jit rather than being converted to lax control flow."""
    name = "%s.%s" % (getattr(fn, "__module__", "?"),
                      getattr(fn, "__qualname__", fn.__name__))
    msg = ("dy2static: %s falls back to TRACE-ONLY conversion because %s. "
           "Consequence: tensor-dependent control flow (if/while/for on "
           "traced values) inside %s will fail with a concretization "
           "error under jit; %s." % (name, reason, name, _HINT))
    warnings.warn(msg, stacklevel=3)
    logger.warning(msg)


class _Undef:
    """Sentinel for 'variable not bound yet' in carried tuples. Loud on
    accidental use: common operations raise a NameError-style message."""

    _err = ("variable used before assignment inside a @to_static-"
            "converted control-flow construct (it was assigned on only "
            "some paths)")

    def __repr__(self):
        return "<dy2static undefined>"

    def __bool__(self):
        raise UnimplementedError(self._err, hint=_HINT)

    def __getattr__(self, name):
        if name.startswith("__"):
            raise AttributeError(name)
        raise UnimplementedError(self._err, hint=_HINT)

    def __iter__(self):
        raise UnimplementedError(self._err, hint=_HINT)


UNDEF = _Undef()


def _is_traced(v):
    if isinstance(v, Tensor):
        v = v._value
    return isinstance(v, jax.core.Tracer)


def _unwrap(v):
    if v is UNDEF:
        return v
    return v._value if isinstance(v, Tensor) else v


def _to_raw(v, name):
    """Carried value -> jax-compatible leaf (UNDEF passes through; it is
    substituted or rejected later with the variable's name)."""
    if v is UNDEF:
        return v
    if isinstance(v, Tensor):
        return v._value
    try:
        return jnp.asarray(v)
    except (TypeError, ValueError):
        raise UnimplementedError(
            "variable %r carried through tensor-dependent control flow "
            "has non-tensor type %s" % (name, type(v).__name__),
            hint=_HINT)


def _rewrap(raw, template):
    """Raw jax value -> Tensor unless the pre-construct value was a plain
    Python scalar/bool AND the raw is concrete (keep Python types stable
    on the un-traced path; on the traced path everything is Tensor)."""
    if isinstance(raw, _Undef):
        return UNDEF
    return Tensor(raw)


def truthy(v):
    if isinstance(v, Tensor):
        import numpy as np

        return bool(np.asarray(v._value))
    return bool(v)


def _shape_struct(fn, *arg_structs):
    return jax.eval_shape(fn, *arg_structs)


def _struct_of(vals):
    return [jax.ShapeDtypeStruct(jnp.shape(v), jnp.result_type(v))
            for v in vals]


def _to_raw_or_none(v, name):
    """Branch/body output leaf: UNDEF (never assigned on this path)
    becomes None so jax.eval_shape can type the output pytree."""
    if v is UNDEF:
        return None
    return _to_raw(v, name)


def _make_runner(branch, carried, names):
    """(raw-or-UNDEF full slots) -> tuple(raw-or-None full slots)."""

    def run(*vals):
        wrapped = [_rewrap(v, o) for v, o in zip(vals, carried)]
        out = branch(*wrapped)
        return tuple(_to_raw_or_none(v, n)
                     for v, n in zip(out, _names(names, out)))

    return run


def _partial_probe(run, raw):
    """Close the UNDEF slots over statically so nested converted
    constructs can resolve their shapes themselves; probe only the
    defined slots through eval_shape."""
    undef_pos = {i for i, v in enumerate(raw) if isinstance(v, _Undef)}

    def probe(*defined):
        it = iter(defined)
        vals = [UNDEF if i in undef_pos else next(it)
                for i in range(len(raw))]
        return run(*vals)

    defined_vals = [v for v in raw if not isinstance(v, _Undef)]
    return probe, defined_vals


def _included_runner(run, raw, included):
    """Restrict a full-slot runner to the lax-carried (included) slots;
    excluded slots stay UNDEF statically."""
    inc = list(included)

    def r(*inc_vals):
        it = iter(inc_vals)
        vals = [next(it) if i in inc else UNDEF
                for i in range(len(raw))]
        out = run(*vals)
        return tuple(out[i] for i in inc)

    return r


def convert_if(pred, true_fn, false_fn, carried, names=()):
    """Runtime dispatch for a converted `if`.

    true_fn/false_fn: (carried...) -> tuple(carried...) over Tensors.
    Concrete pred -> exactly one branch runs (Python semantics).
    Traced pred  -> jax.lax.cond over the carried tuple.
    """
    if isinstance(pred, Tensor) and not _is_traced(pred):
        import numpy as np

        pred = bool(np.asarray(pred._value))
    if isinstance(pred, _Undef):
        pred.__bool__()
    if not _is_traced(pred):
        out = true_fn(*carried) if truthy(pred) else false_fn(*carried)
        return tuple(out)

    nm = _names(names, carried)
    if any(_is_container(v) for v in carried):
        flat, fnm, spec = _flatten_slots(carried, nm)
        out = convert_if(pred, _structured_fn(true_fn, spec, nm, "if"),
                         _structured_fn(false_fn, spec, nm, "if"),
                         flat, names=fnm)
        return _restore_slots(out, spec, carried)
    raw = [_to_raw(v, n) for v, n in zip(carried, nm)]
    t_run = _make_runner(true_fn, carried, names)
    f_run = _make_runner(false_fn, carried, names)

    # classify slots, promoting one-sided UNDEF slots (assigned in only
    # one branch) to dummy zeros so the pass-through branch mirrors the
    # assigned branch's structure; the interruption-flag guards generated
    # by the transformer ensure such a dummy is never *read* on the path
    # that did not assign it (reference ifelse_transformer fills the same
    # hole with UndefinedVar)
    work = list(raw)
    for _ in range(len(raw) + 1):
        t_probe, defined = _partial_probe(t_run, work)
        f_probe, _ = _partial_probe(f_run, work)
        try:
            t_struct = _shape_struct(t_probe, *_struct_of(defined))
            f_struct = _shape_struct(f_probe, *_struct_of(defined))
        except UnimplementedError:
            raise
        except Exception as e:
            raise UnimplementedError(
                "cannot trace the branches of a tensor-dependent `if` "
                "(carried variables: %s): %s" % (nm, e), hint=_HINT)
        promoted = False
        for i, v in enumerate(work):
            if isinstance(v, _Undef):
                a, b = t_struct[i], f_struct[i]
                s = a if a is not None else b
                if s is None:
                    continue  # assigned on neither path: stays UNDEF
                work[i] = jnp.zeros(s.shape, s.dtype)
                # only a one-sided promotion changes the pass-through
                # branch's output structure and needs a re-probe
                promoted = promoted or (a is None) != (b is None)
        if not promoted:
            break

    included, mism = [], []
    for i, (n, a, b) in enumerate(zip(nm, t_struct, f_struct)):
        if a is None and b is None:
            continue  # never assigned on either path: stays UNDEF
        if (a.shape, a.dtype) != (b.shape, b.dtype):
            mism.append("%s (%s%s vs %s%s)" % (n, a.dtype, a.shape,
                                               b.dtype, b.shape))
        else:
            included.append(i)
    if mism:
        raise UnimplementedError(
            "branches of a tensor-dependent `if` produce mismatched "
            "shapes/dtypes for variable(s) %s — XLA conditionals need "
            "structurally identical branch outputs" % mism, hint=_HINT)

    inputs = [work[i] for i in included]
    t_inc = _included_runner(t_run, work, included)
    f_inc = _included_runner(f_run, work, included)
    p = pred._value if isinstance(pred, Tensor) else pred
    out = jax.lax.cond(jnp.reshape(p, ()), t_inc, f_inc, *inputs)
    full = [UNDEF] * len(raw)
    for j, i in enumerate(included):
        full[i] = Tensor(out[j])
    return tuple(full)


def _names(names, seq):
    if names and len(names) == len(seq):
        return list(names)
    return ["var%d" % i for i in range(len(seq))]


# -- container-carried variables (reference list->tensor_array analog) ------
#
# The reference converts list mutation inside converted control flow to
# LoDTensorArray ops (convert_operators.py convert_pop / tensor_array
# machinery) — a *dynamically sized* runtime structure. XLA has no
# dynamic sizes, so the TPU-native treatment is pytree flattening: a
# carried list/tuple/dict is expanded into its leaves (each leaf a
# normal lax-carried slot) and rebuilt afterwards. Structure-PRESERVING
# mutation (index/key assignment, same-length rebuilds) lowers to
# lax.cond/while_loop like any other carried value; structure-CHANGING
# mutation (append/pop under a traced bound) is not expressible and
# raises a typed error naming the variable.


def _is_container(v):
    return isinstance(v, (list, tuple, dict))


def _container_leaf(x):
    return isinstance(x, (Tensor, _Undef))


def _check_container_aliasing(carried, names):
    """Two carried names bound to the same (or a shared nested) container
    OBJECT would silently diverge once flattened into independent leaf
    slots — eager mutation through one alias is visible through the
    other, lax reconstruction is not. Fail loudly instead."""
    seen = {}
    for v, n in zip(carried, names):
        if not _is_container(v):
            continue
        stack = [v]
        while stack:
            node = stack.pop()
            prev = seen.get(id(node))
            if prev is not None:
                # ANY revisit — across names, within one container, or
                # a reference cycle — means flattening would split one
                # object into independent slots and silently diverge
                raise UnimplementedError(
                    "variable(s) %s carry the same (or a shared nested) "
                    "container object more than once through tensor-"
                    "dependent control flow — shared/cyclic containers "
                    "cannot keep eager aliasing semantics once lowered "
                    "to XLA; mutate through a single reference"
                    % sorted({prev, n}), hint=_HINT)
            seen[id(node)] = n
            vals = node.values() if isinstance(node, dict) else node
            stack.extend(x for x in vals if _is_container(x))


def _flatten_slots(carried, names):
    """Expand container slots into per-leaf slots.

    Returns (flat_vals, flat_names, spec); spec is one (treedef|None,
    leaf_count) per original slot — None marks a non-container slot
    passed through unchanged."""
    _check_container_aliasing(carried, names)
    flat_vals, flat_names, spec = [], [], []
    for v, n in zip(carried, names):
        if _is_container(v):
            try:
                leaves, treedef = jax.tree_util.tree_flatten(
                    v, is_leaf=_container_leaf)
            except (TypeError, ValueError) as e:
                raise UnimplementedError(
                    "cannot carry container variable %r through "
                    "tensor-dependent control flow: %s" % (n, e),
                    hint=_HINT)
            flat_vals.extend(leaves)
            flat_names.extend("%s[%d]" % (n, i)
                              for i in range(len(leaves)))
            spec.append((treedef, len(leaves)))
        else:
            flat_vals.append(v)
            flat_names.append(n)
            spec.append((None, 1))
    return flat_vals, flat_names, spec


def _unflatten_slots(flat, spec):
    out, it = [], iter(flat)
    for treedef, k in spec:
        leaves = [next(it) for _ in range(k)]
        if treedef is None:
            out.append(leaves[0])
        else:
            out.append(jax.tree_util.tree_unflatten(treedef, leaves))
    return out


def _copy_container(v):
    """Structural copy (containers rebuilt, leaves shared) — the rollback
    snapshot for retrying an aborted Python-mode loop as a lax loop."""
    if isinstance(v, list):
        return [_copy_container(x) if _is_container(x) else x for x in v]
    if isinstance(v, dict):
        return {k: _copy_container(x) if _is_container(x) else x
                for k, x in v.items()}
    if isinstance(v, tuple):
        vals = tuple(_copy_container(x) if _is_container(x) else x
                     for x in v)
        cls = type(v)
        if cls is tuple:
            return vals
        if hasattr(cls, "_fields"):
            return cls(*vals)
        return cls(vals)
    return v


def _inplace_update(orig, new):
    """Write `new`'s values into the ORIGINAL container object so
    aliases of it held outside the converted construct observe the
    mutation (eager aliasing semantics). Tuples are immutable in eager
    too, so rebuilding them cannot diverge from eager."""
    if isinstance(orig, list) and isinstance(new, list):
        for i in range(len(orig)):
            orig[i] = _inplace_update(orig[i], new[i]) \
                if _is_container(orig[i]) else new[i]
        return orig
    if isinstance(orig, dict) and isinstance(new, dict):
        for k in orig:
            orig[k] = _inplace_update(orig[k], new[k]) \
                if _is_container(orig[k]) else new[k]
        return orig
    if isinstance(orig, tuple) and isinstance(new, tuple):
        vals = tuple(_inplace_update(o, n) if _is_container(o) else n
                     for o, n in zip(orig, new))
        cls = type(new)  # tree_unflatten preserved namedtuple types
        if cls is tuple:
            return vals
        if hasattr(cls, "_fields"):
            return cls(*vals)
        return cls(vals)
    return new


def _restore_slots(out_flat, spec, carried):
    """Final construct-output rebuild: container slots update their
    original objects in place (alias-preserving); scalar slots pass
    through."""
    rebuilt = _unflatten_slots(out_flat, spec)
    return tuple(
        _inplace_update(orig, new)
        if (td is not None and _is_container(orig)) else new
        for (td, _k), orig, new in zip(spec, carried, rebuilt))


def _reflatten_out(out_slots, spec, names, what):
    """Flatten one construct-output slot list back to leaf slots,
    enforcing per-variable structure stability (the XLA analog of the
    reference's tensor-array contract)."""
    flat = []
    for v, (treedef, k), n in zip(out_slots, spec, names):
        if treedef is None:
            if _is_container(v):
                raise UnimplementedError(
                    "variable %r becomes a %s inside a tensor-dependent "
                    "%s but was not a container before it — XLA control "
                    "flow needs a fixed structure; initialize %r as a "
                    "container of the final shape before the %s"
                    % (n, type(v).__name__, what, n, what), hint=_HINT)
            flat.append(v)
            continue
        if isinstance(v, _Undef):
            flat.extend([UNDEF] * k)
            continue
        if not _is_container(v):
            raise UnimplementedError(
                "container variable %r is rebound to %s inside a "
                "tensor-dependent %s — XLA control flow needs a fixed "
                "structure" % (n, type(v).__name__, what), hint=_HINT)
        leaves, td2 = jax.tree_util.tree_flatten(v, is_leaf=_container_leaf)
        if td2 != treedef:
            raise UnimplementedError(
                "container variable %r changes structure inside a "
                "tensor-dependent %s (%s -> %s). list.append/pop (or "
                "adding/removing keys) under a traced condition or "
                "bound has no static-shape equivalent on XLA; use a "
                "fixed-length container, append under concrete bounds, "
                "or build the values and paddle.stack them afterwards"
                % (n, what, treedef, td2), hint=_HINT)
        flat.extend(leaves)
    return tuple(flat)


def _structured_fn(fn, spec, names, what, extra_args=0):
    """Adapt an original-slot branch/body fn to flat leaf slots."""

    def wrapped(*flat):
        extras = flat[:extra_args]
        args = _unflatten_slots(flat[extra_args:], spec)
        out = fn(*extras, *args)
        return _reflatten_out(out, spec, names, what)

    return wrapped


def _coerce_loop_init(raw, out_structs, names, what):
    """lax.while_loop needs init == body-output structure exactly.
    UNDEF inits take the body-output structure; shape changes across
    iterations are not expressible in XLA and raise by name."""
    init = []
    for v, out, name in zip(raw, out_structs, names):
        if isinstance(v, _Undef):
            init.append(jnp.zeros(out.shape, out.dtype))
            continue
        shape = jnp.shape(v)
        if shape == tuple(out.shape):
            init.append(jnp.asarray(v, out.dtype))  # weak->strong etc.
        else:
            raise UnimplementedError(
                "loop-carried variable %r changes shape across "
                "iterations of a tensor-dependent %s (%s -> %s) — XLA "
                "loops need static shapes" %
                (name, what, shape, tuple(out.shape)), hint=_HINT)
    return init


def loop_test(stop_flags, test_thunk):
    """Loop condition with interruption flags: stop when any flag is
    set; short-circuits the test in Python mode (matching `while` after
    `break`)."""
    flags = [f for f in stop_flags if f is not UNDEF]
    if not any(_is_traced(f) for f in flags):
        for f in flags:
            if truthy(f):
                return False
        return test_thunk()
    t = test_thunk()
    traw = t._value if isinstance(t, Tensor) else jnp.asarray(t)
    out = jnp.reshape(traw, ()).astype(jnp.bool_)
    for f in flags:
        fraw = f._value if isinstance(f, Tensor) else jnp.asarray(f)
        out = jnp.logical_and(out, jnp.logical_not(
            jnp.reshape(fraw, ()).astype(jnp.bool_)))
    return Tensor(out)


def convert_while(cond_fn, body_fn, carried, names=()):
    """Runtime dispatch for a converted `while`.

    cond_fn: (carried...) -> bool/Tensor;  body_fn: (carried...) ->
    tuple(carried...). Traced test -> jax.lax.while_loop.
    """
    first = cond_fn(*carried)
    if not _is_traced(first):
        cur = tuple(carried)
        if truthy(first):
            cur = tuple(body_fn(*cur))
            while truthy(cond_fn(*cur)):
                cur = tuple(body_fn(*cur))
        return cur

    nm = _names(names, carried)
    if any(_is_container(v) for v in carried):
        flat, fnm, spec = _flatten_slots(carried, nm)

        def cond_flat(*flat_vals):
            return cond_fn(*_unflatten_slots(flat_vals, spec))

        out = convert_while(
            cond_flat, _structured_fn(body_fn, spec, nm, "while"),
            flat, names=fnm)
        return _restore_slots(out, spec, carried)
    raw = [_to_raw(v, n) for v, n in zip(carried, nm)]
    body_run = _make_runner(body_fn, carried, names)
    probe, defined = _partial_probe(body_run, raw)
    try:
        out_struct = _shape_struct(probe, *_struct_of(defined))
    except UnimplementedError:
        raise
    except Exception as e:
        raise UnimplementedError(
            "cannot trace the body of a tensor-dependent `while` "
            "(carried variables: %s): %s" % (nm, e), hint=_HINT)
    one_sided = [n for n, v, o in zip(nm, raw, out_struct)
                 if o is None and not isinstance(v, _Undef)]
    if one_sided:  # defined input, None output cannot happen via
        # pass-through; guard anyway for diagnostics
        raise UnimplementedError(
            "loop-carried variable(s) %s lose their value inside a "
            "tensor-dependent `while`" % one_sided, hint=_HINT)
    included = [i for i, o in enumerate(out_struct) if o is not None]
    inc_nm = [nm[i] for i in included]
    init = _coerce_loop_init([raw[i] for i in included],
                             [out_struct[i] for i in included],
                             inc_nm, "while")
    body_inc = _included_runner(body_run, raw, included)
    # second pass from the coerced init: dtype promotion must converge
    out_struct2 = _shape_struct(lambda *v: body_inc(*v),
                                *_struct_of(init))
    init = tuple(_coerce_loop_init(init, out_struct2, inc_nm, "while"))

    def cond_inc(state):
        it = iter(state)
        vals = [next(it) if i in set(included) else UNDEF
                for i in range(len(raw))]
        wrapped = [_rewrap(v, o) for v, o in zip(vals, carried)]
        r = cond_fn(*wrapped)
        rraw = r._value if isinstance(r, Tensor) else jnp.asarray(r)
        return jnp.reshape(rraw, ()).astype(jnp.bool_)

    out = jax.lax.while_loop(cond_inc, lambda s: body_inc(*s), init)
    full = [UNDEF] * len(raw)
    for j, i in enumerate(included):
        full[i] = Tensor(out[j])
    return tuple(full)


def convert_for(iterable, body_fn, carried, stop_idx=(), names=(),
                _force_traced=False):
    """Runtime dispatch for a converted `for`.

    body_fn: (elem, carried...) -> tuple(carried...).
    stop_idx: indices in `carried` of interruption flags (break/return)
    that end the loop.
    Traced iteration domains (Tensor being traced, or range() with a
    traced bound) lower to jax.lax.while_loop with a counter; everything
    else runs a plain Python loop (including concrete Tensors, matching
    eager iteration). _force_traced: internal — the traced-flag retry
    re-enters with the SAME concrete iteration domain but must take the
    lax lowering, not the Python loop again.
    """
    traced_len = _force_traced
    seq = iterable
    if isinstance(iterable, Tensor):
        if _is_traced(iterable):
            traced_len = True
    elif isinstance(iterable, range):
        pass
    elif _is_traced(iterable):
        iterable = Tensor(iterable)
        traced_len = True
    if isinstance(iterable, _RangeProxy):
        traced_len = iterable.traced or _force_traced
        if not traced_len:
            seq = iterable.concrete()

    if not traced_len:
        # Python iteration first: concrete loop indices keep working
        # (list indexing by i, float(i), appends). Only when a
        # break/return FLAG turns out to be traced (flag concretization
        # error at the stop check) does the loop re-enter as a lax
        # lowering — the reference loop_transformer's for->while
        # conversion for tensor-dependent breaks. Container slots are
        # snapshotted so the aborted Python iterations' in-place
        # mutations can be rolled back before the traced re-run.
        snapshot = [_copy_container(v) if _is_container(v) else None
                    for v in carried]
        cur = tuple(carried)
        seq_list = seq
        if isinstance(seq, Tensor):
            import numpy as np

            arr2 = np.asarray(seq._value)
            seq_list = [Tensor(jnp.asarray(arr2[i]))
                        for i in range(arr2.shape[0])]
        try:
            for elem in seq_list:
                cur = tuple(body_fn(elem, *cur))
                if any(truthy(cur[i]) for i in stop_idx
                       if cur[i] is not UNDEF):
                    break
            return cur
        except (jax.errors.ConcretizationTypeError,
                jax.errors.TracerArrayConversionError,
                jax.errors.TracerBoolConversionError):
            if isinstance(iterable, _RangeProxy):
                retry = iterable
            elif isinstance(seq, range):
                retry = _RangeProxy(seq.start, seq.stop, seq.step)
            elif isinstance(iterable, Tensor):
                retry = iterable
            else:
                raise UnimplementedError(
                    "break/continue/return inside this `for` depends "
                    "on traced values, but the iterable (%s) cannot be "
                    "lowered to a traced loop — iterate a range() or a "
                    "Tensor instead" % type(seq).__name__, hint=_HINT)
            for v, snap in zip(carried, snapshot):
                if snap is not None:
                    _inplace_update(v, snap)
            return convert_for(retry, body_fn, carried,
                               stop_idx=stop_idx, names=names,
                               _force_traced=True)

    nm = _names(names, carried)
    if any(_is_container(v) for v in carried):
        flat, fnm, spec = _flatten_slots(carried, nm)
        offs, pos = [], 0
        for _, k in spec:
            offs.append(pos)
            pos += k
        out = convert_for(
            iterable, _structured_fn(body_fn, spec, nm, "for",
                                     extra_args=1),
            flat, stop_idx=tuple(offs[i] for i in stop_idx), names=fnm)
        return _restore_slots(out, spec, carried)
    raw = [_to_raw(v, n) for v, n in zip(carried, nm)]
    if isinstance(iterable, _RangeProxy):
        start, stop, step = iterable.raw()
        arr = None
    else:
        arr = iterable._value
        start, stop, step = 0, arr.shape[0], 1

    def elem_at(i):
        if arr is None:
            return Tensor(start + i * step)
        return Tensor(jax.lax.dynamic_index_in_dim(arr, i, 0,
                                                   keepdims=False))

    def full_run(i, *vals):
        wrapped = [_rewrap(v, o) for v, o in zip(vals, carried)]
        out = body_fn(elem_at(i), *wrapped)
        return tuple(_to_raw_or_none(v, n)
                     for v, n in zip(out, _names(names, out)))

    i0 = jnp.asarray(0)
    probe, defined = _partial_probe(lambda *v: full_run(i0, *v), raw)
    try:
        out_struct = _shape_struct(probe, *_struct_of(defined))
    except UnimplementedError:
        raise
    except Exception as e:
        raise UnimplementedError(
            "cannot trace the body of a tensor-dependent `for` "
            "(carried variables: %s): %s" % (nm, e), hint=_HINT)
    included = [i for i, o in enumerate(out_struct) if o is not None]
    inc_set = set(included)
    inc_nm = [nm[i] for i in included]
    init_vals = _coerce_loop_init([raw[i] for i in included],
                                  [out_struct[i] for i in included],
                                  inc_nm, "for")
    # map stop flags into the included-state coordinates; a flag slot is
    # always assigned (prologue False) hence always included
    stop_inc = [included.index(k) for k in stop_idx if k in inc_set]

    def body_state(state):
        i, vals = state[0], state[1:]
        it = iter(vals)
        full_vals = [next(it) if k in inc_set else UNDEF
                     for k in range(len(raw))]
        out_full = full_run(i, *full_vals)
        return (i + 1,) + tuple(out_full[k] for k in included)

    def cond_state(state):
        i, vals = state[0], state[1:]
        if arr is None:
            more = jnp.where(jnp.asarray(step) > 0,
                             i * step + start < stop,
                             i * step + start > stop)
        else:
            more = i < stop
        ok = jnp.reshape(jnp.asarray(more), ()).astype(jnp.bool_)
        for k in stop_inc:
            ok = jnp.logical_and(ok, jnp.logical_not(
                jnp.reshape(jnp.asarray(vals[k]), ()).astype(jnp.bool_)))
        return ok

    init = (i0,) + tuple(init_vals)
    out_struct2 = _shape_struct(body_state, _struct_of(init))
    init = (init[0],) + tuple(
        _coerce_loop_init(list(init[1:]), list(out_struct2[1:]),
                          inc_nm, "for"))
    out = jax.lax.while_loop(cond_state, body_state, init)
    full = [UNDEF] * len(raw)
    for j, k in enumerate(included):
        full[k] = Tensor(out[1 + j])
    return tuple(full)


class _RangeProxy:
    """range() whose bounds may be Tensors/tracers (reference
    convert_len/convert_range). Concrete bounds behave like range."""

    def __init__(self, *args):
        vals = [a._value if isinstance(a, Tensor) else a for a in args]
        self.traced = any(isinstance(v, jax.core.Tracer) for v in vals)
        if len(vals) == 1:
            self.start, self.stop, self.step = 0, vals[0], 1
        elif len(vals) == 2:
            self.start, self.stop, self.step = vals[0], vals[1], 1
        else:
            self.start, self.stop, self.step = vals

    def concrete(self):
        import numpy as np

        return range(int(np.asarray(self.start)),
                     int(np.asarray(self.stop)),
                     int(np.asarray(self.step)))

    def raw(self):
        return (jnp.asarray(self.start), jnp.asarray(self.stop),
                jnp.asarray(self.step))

    def __iter__(self):
        if self.traced:
            raise UnimplementedError(
                "iterating a range() with a traced tensor bound outside "
                "a converted `for`", hint=_HINT)
        return iter(self.concrete())


def make_range(*args):
    """`range` replacement inside converted functions: returns a real
    range for plain ints (zero behavior change) and a proxy when any
    bound is a Tensor/tracer."""
    if any(isinstance(a, Tensor)
           or isinstance(a, jax.core.Tracer) for a in args):
        return _RangeProxy(*args)
    return range(*args)


def not_(v):
    if isinstance(v, _Undef):
        return True  # unset flag == not interrupted
    if _is_traced(v):
        raw = v._value if isinstance(v, Tensor) else v
        return Tensor(jnp.logical_not(jnp.reshape(raw, ()).astype(
            jnp.bool_)))
    return not truthy(v)


def no_interrupt(*flags):
    """True when no interruption flag is set; tensor-aware AND."""
    out = True
    for f in flags:
        nf = not_(f)
        if isinstance(nf, Tensor):
            if out is True:
                out = nf
            else:
                oraw = out._value if isinstance(out, Tensor) else \
                    jnp.asarray(out)
                out = Tensor(jnp.logical_and(
                    jnp.reshape(oraw, ()), nf._value))
        else:
            if not nf:
                return False
    return out


def finalize_return(flag, val):
    del flag
    if val is UNDEF:
        return None
    return val


def bind_or_undef(local_map, name):
    return local_map.get(name, UNDEF)


# ---------------------------------------------------------------------------
# AST transformer (reference dy2static/*_transformer.py pipeline)
# ---------------------------------------------------------------------------


def _collect_fn_locals(fdef):
    """Names local to `fdef`: parameters + every name stored anywhere in
    its body, excluding the interiors of nested function/class scopes
    (whose def/class *name* is still an outer store). `global`/`nonlocal`
    declarations are returned separately (conversion refuses to carry
    them)."""
    locs, nonlocs = set(), set()
    a = fdef.args
    for arg in (list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)
                + ([a.vararg] if a.vararg else [])
                + ([a.kwarg] if a.kwarg else [])):
        locs.add(arg.arg)

    def walk(node, top=False):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)) and not top:
            locs.add(node.name)
            return
        if isinstance(node, ast.Lambda) and not top:
            return
        if isinstance(node, (ast.Global, ast.Nonlocal)):
            nonlocs.update(node.names)
            return
        if isinstance(node, ast.Name) and isinstance(node.ctx,
                                                     (ast.Store, ast.Del)):
            locs.add(node.id)
        if isinstance(node, ast.ExceptHandler) and node.name:
            locs.add(node.name)
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            for al in node.names:
                locs.add((al.asname or al.name).split(".")[0])
        for ch in ast.iter_child_nodes(node):
            walk(ch)

    walk(fdef, top=True)
    return locs - nonlocs, nonlocs


def _names_used(nodes):
    """All Name identifiers loaded or stored in `nodes`, recursing into
    nested scopes too (conservative superset — extra carried names are
    harmless)."""
    loads, stores = set(), set()
    for node in nodes:
        for n in ast.walk(node):
            if isinstance(n, ast.Name):
                if isinstance(n.ctx, ast.Load):
                    loads.add(n.id)
                else:
                    stores.add(n.id)
            elif isinstance(n, ast.ExceptHandler) and n.name:
                stores.add(n.name)
    return loads, stores


def _contains(node_or_list, kinds, stop_at_loops=False):
    """Does the statement (or list) contain any node of `kinds`,
    excluding nested function/class scopes — and, for break/continue
    (stop_at_loops), excluding nested loops they would bind to? The
    search starts *around* the given node(s): a loop passed in directly
    counts as a nested loop for its own breaks."""
    nodes = node_or_list if isinstance(node_or_list, list) \
        else [node_or_list]
    root = ast.Module(body=list(nodes), type_ignores=[])
    found = []

    def walk(n):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef, ast.Lambda)) and n is not root:
            return
        if stop_at_loops and isinstance(n, (ast.For, ast.While)):
            # breaks/continues inside bind to that inner loop; its
            # orelse block still binds outward
            for ch in n.orelse:
                walk(ch)
            return
        if isinstance(n, kinds):
            found.append(n)
        for ch in ast.iter_child_nodes(n):
            walk(ch)

    walk(root)
    return bool(found)


class _RangeRewriter(ast.NodeTransformer):
    """`range(...)` -> `_dy2st.make_range(...)` when `range` is not
    shadowed by a function local (reference convert_range)."""

    def __init__(self, fn_locals):
        self.fn_locals = fn_locals

    def visit_Call(self, node):
        self.generic_visit(node)
        if (isinstance(node.func, ast.Name) and node.func.id == "range"
                and isinstance(node.func.ctx, ast.Load)
                and "range" not in self.fn_locals):
            node.func = ast.Attribute(
                value=ast.Name(id="_dy2st", ctx=ast.Load()),
                attr="make_range", ctx=ast.Load())
        return node


def _name(id_, store=False):
    return ast.Name(id=id_, ctx=ast.Store() if store else ast.Load())


def _attr(obj, attr):
    return ast.Attribute(value=_name(obj), attr=attr, ctx=ast.Load())


def _call(fn_expr, args, keywords=()):
    return ast.Call(func=fn_expr, args=list(args),
                    keywords=list(keywords))


def _const(v):
    return ast.Constant(value=v)


def _assign(target_name, value_expr):
    return ast.Assign(targets=[_name(target_name, store=True)],
                      value=value_expr)


def _tuple_expr(names, store=False):
    return ast.Tuple(elts=[_name(n, store=store) for n in names],
                     ctx=ast.Store() if store else ast.Load())


class _Converter:
    """Statement-level conversion over a single function body."""

    RET_FLAG = "_dy2st_ret_flag"
    RET_VAL = "_dy2st_ret_val"

    def __init__(self, fn_locals, nonlocals=()):
        self.locals = set(fn_locals)
        self.nonlocals = set(nonlocals)
        self.n = 0
        self.ret_active = False

    def _check_nonlocal_stores(self, *stmt_lists):
        """Stores to global/nonlocal names inside a converted construct
        would silently bind a throwaway local in the extracted branch
        function — refuse loudly instead."""
        if not self.nonlocals:
            return
        _, stores = _names_used([s for sl in stmt_lists for s in sl])
        bad = sorted(stores & self.nonlocals)
        if bad:
            raise UnimplementedError(
                "assignment to global/nonlocal name(s) %s inside a "
                "control-flow construct converted by @to_static — the "
                "extracted branch function cannot rebind the outer "
                "name" % bad, hint=_HINT)

    def fresh(self, stem):
        self.n += 1
        return "_dy2st_%s_%d" % (stem, self.n)

    # -- interruption-flag queries ----------------------------------------

    def _flags_set_by(self, st, loop_ctx):
        flags = []
        if self.ret_active and _contains(st, (ast.Return,)):
            flags.append(self.RET_FLAG)
        if loop_ctx is not None:
            if _contains(st, (ast.Break,), stop_at_loops=True):
                flags.append(loop_ctx[0])
            if loop_ctx[1] and _contains(st, (ast.Continue,),
                                         stop_at_loops=True):
                flags.append(loop_ctx[1])
        return flags

    # -- function entry ----------------------------------------------------

    def convert_function(self, fdef):
        # the return transform is needed unless every return is a plain
        # top-level statement (in which case the flag machinery would be
        # pure overhead): a return nested inside any compound statement
        # may become conditional once that statement is converted
        self.ret_active = any(
            _contains(st, (ast.Return,)) and not isinstance(st, ast.Return)
            for st in fdef.body)
        body = self.convert_block(fdef.body, loop_ctx=None)
        if self.ret_active:
            prologue = [
                _assign(self.RET_VAL, _attr("_dy2st", "UNDEF")),
                _assign(self.RET_FLAG, _const(False)),
            ]
            body = prologue + body + [ast.Return(value=_call(
                _attr("_dy2st", "finalize_return"),
                [_name(self.RET_FLAG), _name(self.RET_VAL)]))]
        fdef.body = body
        fdef.decorator_list = []
        return fdef

    # -- blocks ------------------------------------------------------------

    def convert_block(self, stmts, loop_ctx):
        out = []
        for i, st in enumerate(stmts):
            new, may_int = self.convert_stmt(st, loop_ctx)
            out.extend(new)
            rest = stmts[i + 1:]
            if may_int and rest:
                flags = self._flags_set_by(st, loop_ctx)
                rest_c = self.convert_block(rest, loop_ctx)
                if flags:
                    test = _call(_attr("_dy2st", "no_interrupt"),
                                 [_name(f) for f in flags])
                    out.extend(self._emit_if(test, rest_c, []))
                else:
                    out.extend(rest_c)
                return out
        return out

    def convert_stmt(self, st, loop_ctx):
        if isinstance(st, ast.If):
            may = bool(self._flags_set_by(st, loop_ctx))
            body_c = self.convert_block(st.body, loop_ctx)
            orelse_c = self.convert_block(st.orelse, loop_ctx)
            return self._emit_if(st.test, body_c, orelse_c), may
        if isinstance(st, ast.While):
            return self._emit_while(st, loop_ctx)
        if isinstance(st, ast.For):
            return self._emit_for(st, loop_ctx)
        if isinstance(st, ast.Return):
            if not self.ret_active:
                return [st], False
            val = st.value if st.value is not None else _const(None)
            return [_assign(self.RET_VAL, val),
                    _assign(self.RET_FLAG, _const(True))], True
        if isinstance(st, ast.Break):
            if loop_ctx is None:
                return [st], False
            return [_assign(loop_ctx[0], _const(True))], True
        if isinstance(st, ast.Continue):
            if loop_ctx is None or not loop_ctx[1]:
                return [st], False
            return [_assign(loop_ctx[1], _const(True))], True
        if isinstance(st, (ast.Global, ast.Nonlocal)):
            return [st], False
        if isinstance(st, ast.With):
            may = bool(self._flags_set_by(st, loop_ctx))
            st.body = self.convert_block(st.body, loop_ctx)
            return [st], may
        if isinstance(st, ast.Try):
            may = bool(self._flags_set_by(st, loop_ctx))
            st.body = self.convert_block(st.body, loop_ctx)
            for h in st.handlers:
                h.body = self.convert_block(h.body, loop_ctx)
            st.orelse = self.convert_block(st.orelse, loop_ctx)
            st.finalbody = self.convert_block(st.finalbody, loop_ctx)
            return [st], may
        return [st], False

    # -- carried-variable plumbing -----------------------------------------

    def _carried(self, *stmt_lists):
        loads, stores = set(), set()
        for sl in stmt_lists:
            ld, stt = _names_used(sl)
            loads |= ld
            stores |= stt
        names = sorted(((loads | stores) & self.locals) | (
            stores & {n for n in stores if n.startswith("_dy2st_")}))
        self.locals.update(n for n in stores
                           if n.startswith("_dy2st_"))
        return names

    def _binds(self, names):
        return [ast.Assign(
            targets=[_name(n, store=True)],
            value=_call(_attr("_dy2st", "bind_or_undef"),
                        [_call(_name("locals"), []), _const(n)]))
            for n in names]

    def _branch_def(self, fname, carried, body_stmts, extra_arg=None):
        args = ([ast.arg(arg=extra_arg)] if extra_arg else []) + \
            [ast.arg(arg=n) for n in carried]
        body = list(body_stmts) + [ast.Return(
            value=_tuple_expr(carried))]
        return ast.FunctionDef(
            name=fname,
            args=ast.arguments(posonlyargs=[], args=args, vararg=None,
                               kwonlyargs=[], kw_defaults=[], kwarg=None,
                               defaults=[]),
            body=body, decorator_list=[], returns=None)

    def _result_assign(self, carried, call_expr):
        if not carried:
            return [_assign(self.fresh("void"), call_expr)]
        return [ast.Assign(targets=[_tuple_expr(carried, store=True)],
                           value=call_expr)]

    def _names_kw(self, carried):
        return ast.keyword(arg="names", value=ast.Tuple(
            elts=[_const(n) for n in carried], ctx=ast.Load()))

    # -- emitters ----------------------------------------------------------

    def _emit_if(self, test, body_c, orelse_c):
        self._check_nonlocal_stores(body_c, orelse_c)
        carried = self._carried(body_c, orelse_c)
        tname, fname = self.fresh("true"), self.fresh("false")
        stmts = [self._branch_def(tname, carried, body_c or [ast.Pass()]),
                 self._branch_def(fname, carried,
                                  orelse_c or [ast.Pass()])]
        stmts += self._binds(carried)
        call = _call(_attr("_dy2st", "convert_if"),
                     [test, _name(tname), _name(fname),
                      _tuple_expr(carried)],
                     [self._names_kw(carried)])
        return stmts + self._result_assign(carried, call)

    def _emit_while(self, st, loop_ctx):
        brk = self.fresh("brk") if _contains(
            st.body, (ast.Break,), stop_at_loops=True) else None
        cont = self.fresh("cont") if _contains(
            st.body, (ast.Continue,), stop_at_loops=True) else None
        inner_ctx = (brk or self.fresh("brk_unused"), cont)
        body_c = self.convert_block(st.body, inner_ctx)
        if cont:
            body_c = [_assign(cont, _const(False))] + body_c
        stop_flags = [f for f in (brk, self.RET_FLAG
                                  if self.ret_active and _contains(
                                      st.body, (ast.Return,)) else None)
                      if f]
        pre = [_assign(brk, _const(False))] if brk else []
        self._check_nonlocal_stores(body_c)
        carried = self._carried([st.test], body_c)
        cname, bname = self.fresh("cond"), self.fresh("body")
        test_lambda = ast.Lambda(
            args=ast.arguments(posonlyargs=[], args=[], vararg=None,
                               kwonlyargs=[], kw_defaults=[], kwarg=None,
                               defaults=[]),
            body=st.test)
        cond_body = [ast.Return(value=_call(
            _attr("_dy2st", "loop_test"),
            [ast.Tuple(elts=[_name(f) for f in stop_flags],
                       ctx=ast.Load()), test_lambda]))]
        cond_def = ast.FunctionDef(
            name=cname,
            args=ast.arguments(posonlyargs=[],
                               args=[ast.arg(arg=n) for n in carried],
                               vararg=None, kwonlyargs=[],
                               kw_defaults=[], kwarg=None, defaults=[]),
            body=cond_body, decorator_list=[], returns=None)
        body_def = self._branch_def(bname, carried,
                                    body_c or [ast.Pass()])
        call = _call(_attr("_dy2st", "convert_while"),
                     [_name(cname), _name(bname), _tuple_expr(carried)],
                     [self._names_kw(carried)])
        stmts = pre + [cond_def, body_def] + self._binds(carried) + \
            self._result_assign(carried, call)
        stmts += self._emit_loop_orelse(st, brk, loop_ctx)
        may = self.ret_active and _contains(st, (ast.Return,))
        return stmts, may

    def _emit_loop_orelse(self, st, brk, loop_ctx):
        """`while/for ... else` runs the else block iff the loop was not
        broken — exactly the break-flag's complement, so it converts to
        a (possibly tensor-dependent) guarded block."""
        if not st.orelse:
            return []
        orelse_c = self.convert_block(st.orelse, loop_ctx)
        if brk is None:
            return orelse_c  # no break in the loop: else always runs
        test = _call(_attr("_dy2st", "no_interrupt"), [_name(brk)])
        return self._emit_if(test, orelse_c, [])

    def _emit_for(self, st, loop_ctx):
        brk = self.fresh("brk") if _contains(
            st.body, (ast.Break,), stop_at_loops=True) else None
        cont = self.fresh("cont") if _contains(
            st.body, (ast.Continue,), stop_at_loops=True) else None
        inner_ctx = (brk or self.fresh("brk_unused"), cont)
        body_c = self.convert_block(st.body, inner_ctx)
        if cont:
            body_c = [_assign(cont, _const(False))] + body_c
        pre = [_assign(brk, _const(False))] if brk else []
        elem = self.fresh("elem")
        target_assign = ast.Assign(targets=[st.target],
                                   value=_name(elem))
        body_full = [target_assign] + body_c
        self._check_nonlocal_stores(body_full)
        carried = self._carried([st.target], body_full)
        bname = self.fresh("body")
        body_def = self._branch_def(bname, carried, body_full,
                                    extra_arg=elem)
        stop_names = [f for f in (
            brk, self.RET_FLAG if self.ret_active and _contains(
                st.body, (ast.Return,)) else None) if f]
        stop_idx = ast.Tuple(
            elts=[_const(carried.index(f)) for f in stop_names
                  if f in carried], ctx=ast.Load())
        call = _call(_attr("_dy2st", "convert_for"),
                     [st.iter, _name(bname), _tuple_expr(carried)],
                     [ast.keyword(arg="stop_idx", value=stop_idx),
                      self._names_kw(carried)])
        stmts = pre + [body_def] + self._binds(carried) + \
            self._result_assign(carried, call)
        stmts += self._emit_loop_orelse(st, brk, loop_ctx)
        may = self.ret_active and _contains(st, (ast.Return,))
        return stmts, may


def convert_control_flow(fn):
    """Return `fn` rewritten so tensor-dependent if/while/for lower to
    XLA control flow (see module docstring). Functions without
    control-flow statements, or whose source is unavailable, are
    returned unchanged."""
    instance = None
    if inspect.ismethod(fn):
        instance, fn = fn.__self__, fn.__func__
    if getattr(fn, "_dy2st_converted", False) or \
            getattr(fn, "_not_to_static", False):
        return fn.__get__(instance) if instance is not None else fn
    try:
        src = textwrap.dedent(inspect.getsource(fn))
        tree = ast.parse(src)
    except (OSError, TypeError, SyntaxError, IndentationError):
        return fn.__get__(instance) if instance is not None else fn
    fdef = tree.body[0]
    if not isinstance(fdef, (ast.FunctionDef,)):
        return fn.__get__(instance) if instance is not None else fn
    has_cf = any(isinstance(n, (ast.If, ast.While, ast.For))
                 for n in ast.walk(fdef))
    if not has_cf:
        return fn.__get__(instance) if instance is not None else fn

    fn_locals, nonlocs = _collect_fn_locals(fdef)
    fdef = _RangeRewriter(fn_locals).visit(fdef)
    conv = _Converter(fn_locals, nonlocals=nonlocs)
    freevars = list(fn.__code__.co_freevars)
    cells = []
    if fn.__closure__:
        try:
            cells = [c.cell_contents for c in fn.__closure__]
        except ValueError:
            # an empty cell (forward reference to a sibling defined
            # later): conversion cannot snapshot the closure safely —
            # fall back to trace-only rather than crash at decoration
            _warn_trace_only(fn, "it closes over a not-yet-bound name "
                             "(forward reference to a sibling defined later)")
            return fn.__get__(instance) if instance is not None else fn
    factory_name = "__dy2st_factory__"
    try:
        fdef = conv.convert_function(fdef)
        # Wrap in a factory so the converted function (a) resolves
        # free variables through factory parameters (closure) and
        # (b) keeps the LIVE module dict as its globals — names defined
        # later in the module (late binding) still resolve.
        factory = ast.FunctionDef(
            name=factory_name,
            args=ast.arguments(
                posonlyargs=[],
                args=[ast.arg(arg="_dy2st")]
                + [ast.arg(arg=n) for n in freevars],
                vararg=None, kwonlyargs=[], kw_defaults=[], kwarg=None,
                defaults=[]),
            body=[fdef, ast.Return(value=_name(fdef.name))],
            decorator_list=[], returns=None)
        module = ast.Module(body=[factory], type_ignores=[])
        ast.fix_missing_locations(module)
        code = compile(module, filename="<dy2static:%s>" % fn.__name__,
                       mode="exec")
    except UnimplementedError:
        raise
    except Exception as e:  # noqa: BLE001 — conversion must never brick
        _warn_trace_only(fn, "AST conversion failed: %s" % (e,))
        return fn.__get__(instance) if instance is not None else fn

    from . import dy2static as _self

    g = fn.__globals__
    had = factory_name in g
    prev = g.get(factory_name)
    exec(code, g)  # noqa: S102 — compiling the user's own source
    factory_fn = g.pop(factory_name)
    if had:
        g[factory_name] = prev
    new_fn = factory_fn(_self, *cells)
    new_fn._dy2st_converted = True
    new_fn.__wrapped__ = fn
    functools.update_wrapper(new_fn, fn, updated=())
    new_fn._dy2st_converted = True
    if instance is not None:
        return new_fn.__get__(instance)
    return new_fn
