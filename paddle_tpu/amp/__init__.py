"""Automatic mixed precision.

Parity: reference python/paddle/amp/{auto_cast.py,grad_scaler.py}
(O1 white/black-list casting, O2 pure low-precision; GradScaler with
found_inf). TPU-native stance: bfloat16 is the native MXU type and needs NO
loss scaling — GradScaler degenerates to a pass-through for bf16 and keeps
full dynamic-scaling semantics for float16.
"""
from __future__ import annotations

import threading
from contextlib import contextmanager

import jax.numpy as jnp

from ..core import dtype as _dtype
from ..core.tensor import Tensor

_state = threading.local()

# O1 lists (reference python/paddle/amp/fp16_lists.py): ops that are safe in
# low precision vs ops that must stay fp32.
WHITE_LIST = {
    "matmul", "mm", "bmm", "mv", "conv1d", "conv2d", "conv3d", "linear",
    "einsum", "addmm",
}
BLACK_LIST = {
    "exp", "log", "log2", "log10", "log1p", "softmax", "log_softmax",
    "cross_entropy", "nll_loss", "mean", "sum", "norm", "layer_norm",
    "rms_norm", "batch_norm_train", "batch_norm_infer", "cumsum",
    "logsumexp",
}


def amp_state():
    return getattr(_state, "amp", None)


@contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None,
              level="O1", dtype="bfloat16"):
    prev = amp_state()
    if enable:
        white = set(WHITE_LIST)
        black = set(BLACK_LIST)
        if custom_white_list:
            white |= set(custom_white_list)
            black -= set(custom_white_list)
        if custom_black_list:
            black |= set(custom_black_list)
            white -= set(custom_black_list)
        _state.amp = {
            "level": level,
            "dtype": _dtype.canonical_name(dtype),
            "white": white,
            "black": black,
        }
    else:
        _state.amp = None
    try:
        yield
    finally:
        _state.amp = prev


amp_guard = auto_cast


def maybe_cast_inputs(op_name, leaves):
    """Called by the dispatcher: cast Tensor leaves per AMP rules."""
    st = amp_state()
    if st is None:
        return leaves
    dt = _dtype.to_jax(st["dtype"])
    level = st["level"]
    cast_down = (op_name in st["white"]) or (
        level == "O2" and op_name not in st["black"])
    cast_up = op_name in st["black"]
    out = []
    for l in leaves:
        if isinstance(l, Tensor) and jnp.issubdtype(
                jnp.result_type(l._value), jnp.floating):
            v = l._value
            if cast_down and v.dtype != dt:
                out.append(_casted_view(l, dt))
                continue
            if cast_up and v.dtype in (jnp.bfloat16, jnp.float16):
                out.append(_casted_view(l, jnp.float32))
                continue
        out.append(l)
    return out


def _casted_view(t, dt):
    from ..ops.math import cast

    return cast(t, dtype=_dtype.canonical_name(dt))


def decorate(models=None, optimizers=None, level="O2", dtype="bfloat16",
             master_weight=None, save_dtype=None):
    """O2 decoration: cast model params to the AMP dtype (reference
    amp.decorate). Optimizer moments are float32 already (master weights)."""
    def _one(m):
        m.to(dtype=dtype)
        return m

    if models is None:
        return None
    single_model = not isinstance(models, (list, tuple))
    ms = [models] if single_model else list(models)
    ms = [_one(m) for m in ms]
    out_m = ms[0] if single_model else ms
    if optimizers is None:
        return out_m
    return out_m, optimizers


class GradScaler:
    """Dynamic loss scaling (reference python/paddle/amp/grad_scaler.py:149).

    For bfloat16 (TPU default) scaling is unnecessary — enable=True with
    bf16 behaves as identity, matching TPU practice."""

    def __init__(self, enable=True, init_loss_scaling=2.0**15,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=1000,
                 decr_every_n_nan_or_inf=2, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling)
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every = incr_every_n_steps
        self._decr_every = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False

    def is_enable(self):
        return self._enable

    def scale(self, var):
        if not self._enable:
            return var
        return var * self._scale

    def unscale_(self, optimizer):
        if not self._enable:
            return
        params = optimizer._get_params()
        found = False
        inv = 1.0 / self._scale
        for p in params:
            if p.grad is None:
                continue
            g = p.grad._value * inv
            finite = bool(jnp.all(jnp.isfinite(g)))
            found = found or not finite
            p.grad._value = g
        self._found_inf = found

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        self.unscale_(optimizer)
        if not self._found_inf:
            optimizer.step()
        self.update()

    def minimize(self, optimizer, scaled_loss):
        self.step(optimizer)

    def update(self):
        if not self._dynamic:
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every:
                self._scale *= self._incr_ratio
                self._good_steps = 0

    def state_dict(self):
        return {
            "scale": self._scale,
            "good_steps": self._good_steps,
            "bad_steps": self._bad_steps,
        }

    def load_state_dict(self, sd):
        self._scale = sd["scale"]
        self._good_steps = sd["good_steps"]
        self._bad_steps = sd["bad_steps"]

    def get_loss_scaling(self):
        from ..ops.creation import to_tensor

        return to_tensor(self._scale)
