"""paddle_tpu.profiler — unified host + device profiling.

Parity: reference python/paddle/profiler/profiler.py:344 (`Profiler` with
scheduler windows ProfilerState cycle at :79), RecordEvent annotations
threaded through executors/ops, chrome-trace export
(platform/profiler/chrometracing_logger.cc) and summary statistics
(profiler_statistic.py). TPU-native split: host events go through the C++
recorder (csrc/trace.cc, the host_event_recorder.h analog); device-side
tracing is delegated to jax.profiler (Xprof) which captures XLA/TPU
activity — the CUPTI analog is the TPU runtime's own tracer, reached via
jax.profiler.start_trace.
"""
from __future__ import annotations

import enum
import os
import threading
import time

from ..core import native
from ..monitor.registry import warn_once as _warn_once

__all__ = [
    "Profiler", "RecordEvent", "ProfilerState", "ProfilerTarget",
    "make_scheduler", "export_chrome_tracing", "load_profiler_result",
    "xprof_session_begin", "xprof_session_end", "xprof_session_owner",
]

# -- Xprof session guard -----------------------------------------------------
# jax.profiler allows exactly ONE live trace per process; a second
# start_trace raises and the first window's artifact is at the mercy of
# whoever calls stop_trace first. Every device-trace user in this repo
# (the manual Profiler below, ptprof's anomaly capture windows in
# monitor/profile.py) goes through this guard so two owners can never
# double-start or steal each other's stop.
_xprof_lock = threading.Lock()
_xprof_owner = None


def xprof_session_owner():
    """Name of the owner currently holding the live Xprof session, or
    None."""
    return _xprof_owner


def xprof_session_begin(owner, trace_dir):
    """Claim the process-wide Xprof session and start the device trace
    into ``trace_dir``. Returns True when THIS call started the trace;
    False when another owner already holds the session (the caller
    degrades to host-only — never an exception on the busy path). A
    ``start_trace`` failure releases the claim and re-raises so the
    caller can report the real cause."""
    global _xprof_owner
    with _xprof_lock:
        if _xprof_owner is not None:
            return False
        _xprof_owner = str(owner)
    try:
        import jax

        jax.profiler.start_trace(trace_dir)
    except BaseException:
        with _xprof_lock:
            _xprof_owner = None
        raise
    return True


def xprof_session_end(owner):
    """Stop the device trace IF ``owner`` holds the session (a no-op
    returning False otherwise — an owner can never stop a window it
    did not start). The historical broad silent-except here is narrowed
    to the types jax.profiler.stop_trace actually raises (RuntimeError
    "No profile started" when the backend already closed the window,
    ValueError from a torn-down profiler state) and routed through
    warn_once — the PR-10 discipline applied to the one module that
    predates it."""
    global _xprof_owner
    with _xprof_lock:
        if _xprof_owner != str(owner):
            return False
    # ownership is held UNTIL stop_trace returns: releasing first would
    # let a concurrent begin claim the session and start_trace into the
    # still-live old trace — the double-start this guard exists to stop
    try:
        import jax

        jax.profiler.stop_trace()
        ok = True
    except (RuntimeError, ValueError) as e:
        _warn_once(
            "profiler.stop_trace",
            "paddle_tpu.profiler: jax.profiler.stop_trace failed — the "
            "backend already closed the window; whatever landed in the "
            "trace dir is kept: %r" % (e,))
        ok = False
    finally:
        with _xprof_lock:
            if _xprof_owner == str(owner):
                _xprof_owner = None
    return ok


class ProfilerState(enum.Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


class ProfilerTarget(enum.Enum):
    CPU = 0
    TPU = 1  # reference: GPU


class SummaryView(enum.Enum):
    """reference profiler.SummaryView: which summary tables to print."""

    DeviceView = 0
    OverView = 1
    ModelView = 2
    DistributedView = 3
    KernelView = 4
    OperatorView = 5
    MemoryView = 6
    MemoryManipulationView = 7
    UDFView = 8


def make_scheduler(*, closed, ready, record, repeat=0, skip_first=0):
    """Step-window scheduler (reference profiler.py:170 make_scheduler)."""

    def sched(step):
        if step < skip_first:
            return ProfilerState.CLOSED
        s = step - skip_first
        period = closed + ready + record
        if repeat and s >= repeat * period:
            return ProfilerState.CLOSED
        pos = s % period
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == period - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return sched


class RecordEvent:
    """Scoped host annotation (reference platform/profiler/event_tracing.h
    RecordEvent; python API python/paddle/profiler/utils.py RecordEvent)."""

    def __init__(self, name, event_type=None, level=1):
        self.name = name
        self.level = level
        self._lib = None
        self._xprof = None

    def begin(self):
        self._lib = native.get_lib()
        self._lib.pt_trace_push(self.name.encode(), self.level)
        # bridge into the device timeline: the same span shows up in the
        # Xprof trace (reference merges host RecordEvents with CUPTI
        # events into one EventNode tree, chrometracing_logger.cc)
        try:
            import jax

            self._xprof = jax.profiler.TraceAnnotation(self.name)
            self._xprof.__enter__()
        except Exception:
            self._xprof = None

    def end(self):
        if self._xprof is not None:
            try:
                self._xprof.__exit__(None, None, None)
            # ptlint: silent-except-ok — profiler teardown is
            # best-effort; the trace dir keeps whatever landed
            except Exception:
                pass
            self._xprof = None
        if self._lib is not None:
            self._lib.pt_trace_pop()
            self._lib = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()


def _counter(name, value):
    native.get_lib().pt_trace_counter(name.encode(), int(value))


class Profiler:
    """Collect host (+ optional Xprof device) traces over scheduled steps.

    Usage matches the reference (profiler.py:344):
        with Profiler(scheduler=(2, 5), on_trace_ready=...) as p:
            for batch in loader:
                train_step(batch)
                p.step()
    """

    def __init__(self, *, targets=None, scheduler=None, on_trace_ready=None,
                 timer_only=False, with_xprof=False, trace_dir=None):
        if scheduler is None:
            self._sched = lambda step: ProfilerState.RECORD
        elif isinstance(scheduler, tuple):
            start, end = scheduler
            self._sched = make_scheduler(
                closed=max(start, 0), ready=0, record=end - start, repeat=1)
        else:
            self._sched = scheduler
        self.on_trace_ready = on_trace_ready
        self.timer_only = timer_only
        self.with_xprof = with_xprof and not timer_only
        self.trace_dir = trace_dir or os.path.join(".", "profiler_log")
        self._step = 0
        self._state = ProfilerState.CLOSED
        self._xprof_on = False
        self._step_times = []
        self._t0 = None

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        self._apply_state(self._sched(self._step))
        self._t0 = time.perf_counter()
        return self

    def stop(self):
        if self._state in (ProfilerState.RECORD,
                           ProfilerState.RECORD_AND_RETURN):
            self._finish_window()
        self._apply_state(ProfilerState.CLOSED)

    def step(self):
        now = time.perf_counter()
        if self._t0 is not None:
            self._step_times.append(now - self._t0)
        self._t0 = now
        prev = self._state
        self._step += 1
        new = self._sched(self._step)
        if prev in (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN) \
                and new in (ProfilerState.CLOSED, ProfilerState.READY):
            self._finish_window()
        self._apply_state(new)

    def _apply_state(self, state):
        if self.timer_only:
            self._state = state
            return
        lib = native.get_lib()
        recording = state in (ProfilerState.RECORD,
                              ProfilerState.RECORD_AND_RETURN)
        was = self._state in (ProfilerState.RECORD,
                              ProfilerState.RECORD_AND_RETURN)
        if recording and not was:
            lib.pt_trace_enable(2)
            if self.with_xprof and not self._xprof_on:
                # through the session guard: a ptprof capture window
                # (monitor/profile.py) holding the session degrades
                # this window to host-only instead of raising — and
                # vice versa
                try:
                    self._xprof_on = xprof_session_begin(
                        "profiler", self.trace_dir)
                except Exception as e:
                    self._xprof_on = False
                    _warn_once(
                        "profiler.start_trace",
                        "paddle_tpu.profiler: device trace unavailable "
                        "(host trace still records): %r" % (e,))
        elif not recording and was:
            lib.pt_trace_disable()
        self._state = state

    def _finish_window(self):
        if self._xprof_on:
            # the guard narrows the except to stop_trace's real raise
            # types and warns once instead of swallowing
            xprof_session_end("profiler")
            self._xprof_on = False
        if self.on_trace_ready is not None:
            self.on_trace_ready(self)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # -- results -----------------------------------------------------------
    def export_chrome_tracing(self, path):
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        rc = native.get_lib().pt_trace_dump(path.encode())
        if rc != 0:
            raise IOError("trace dump to %s failed" % path)
        return path

    def export_merged_chrome_tracing(self, path):
        """ONE chrome trace containing both timelines: the native host
        tracer's events (csrc/trace.cc) and the device/XLA events from
        the Xprof capture (jax writes tensorboard-plugin
        *.trace.json.gz files in trace_dir) — the unified EventNode view
        the reference builds in chrometracing_logger.cc from host +
        CUPTI streams."""
        import glob
        import gzip
        import json

        host_path = path + ".host.json"
        self.export_chrome_tracing(host_path)
        with open(host_path) as f:
            merged = json.load(f)
        events = merged.get("traceEvents", merged if isinstance(
            merged, list) else [])
        if isinstance(merged, list):
            merged = {"traceEvents": events}
        device_files = sorted(glob.glob(os.path.join(
            self.trace_dir, "plugins", "profile", "*", "*.trace.json.gz")))
        for i, df in enumerate(device_files):
            # ALL capture files merge in (a scheduler with repeat>1
            # produces one Xprof capture per record window); each file
            # gets its own pid namespace so windows don't overdraw each
            # other on one track
            tag = "xla%d" % i if len(device_files) > 1 else "xla"
            with gzip.open(df, "rt") as f:
                dev = json.load(f)
            for ev in dev.get("traceEvents", []):
                # keep device pids distinct from host pids
                if isinstance(ev, dict) and "pid" in ev:
                    ev = dict(ev)
                    ev["pid"] = "%s/%s" % (tag, ev["pid"])
                events.append(ev)
        merged["traceEvents"] = events
        with open(path, "w") as f:
            json.dump(merged, f)
        os.remove(host_path)
        return path

    def summary(self):
        """Step-time stats (reference profiler_statistic.py summary)."""
        ts = self._step_times
        if not ts:
            return {"steps": 0}
        ts_sorted = sorted(ts)
        n = len(ts_sorted)
        return {
            "steps": n,
            "avg_s": sum(ts) / n,
            "min_s": ts_sorted[0],
            "p50_s": ts_sorted[n // 2],
            "p99_s": ts_sorted[min(n - 1, int(n * 0.99))],
            "max_s": ts_sorted[-1],
        }


def export_chrome_tracing(dir_name, worker_name=None):
    """on_trace_ready factory (reference profiler.py export_chrome_tracing)."""

    def handler(prof):
        os.makedirs(dir_name, exist_ok=True)
        name = worker_name or "worker"
        path = os.path.join(dir_name, "%s_%d.json" % (name, prof._step))
        prof.export_chrome_tracing(path)

    return handler


def load_profiler_result(path):
    import json

    with open(path) as f:
        return json.load(f)
