"""paddle.audio.functional (reference python/paddle/audio/functional/
functional.py: hz_to_mel :22, mel_to_hz :78, mel_frequencies :123,
fft_frequencies :163, compute_fbank_matrix :186, power_to_db :259,
create_dct :303; window.py get_window). Filterbank construction happens on
host numpy (it runs once per feature layer, exactly like the reference
precomputing the fbank as a buffer); the per-frame math is jnp so feature
extraction fuses into the compiled model when jitted.
"""
from __future__ import annotations

import math

import numpy as np

import paddle_tpu as paddle
from ..core.tensor import Tensor


def _is_tensor(x):
    return isinstance(x, Tensor)


def hz_to_mel(freq, htk=False):
    """Slaney by default; htk=True uses 2595*log10(1+f/700)."""
    if htk:
        if _is_tensor(freq):
            return 2595.0 * paddle.log10(1.0 + freq / 700.0)
        return 2595.0 * math.log10(1.0 + freq / 700.0)
    f_min, f_sp = 0.0, 200.0 / 3
    min_log_hz = 1000.0
    min_log_mel = (min_log_hz - f_min) / f_sp
    logstep = math.log(6.4) / 27.0
    if _is_tensor(freq):
        lin = (freq - f_min) / f_sp
        log = min_log_mel + paddle.log(
            paddle.clip(freq, min=1e-10) / min_log_hz) / logstep
        return paddle.where(freq >= min_log_hz, log, lin)
    if freq >= min_log_hz:
        return min_log_mel + math.log(freq / min_log_hz) / logstep
    return (freq - f_min) / f_sp


def mel_to_hz(mel, htk=False):
    if htk:
        return 700.0 * (10.0 ** (mel / 2595.0) - 1.0)
    f_min, f_sp = 0.0, 200.0 / 3
    min_log_hz = 1000.0
    min_log_mel = (min_log_hz - f_min) / f_sp
    logstep = math.log(6.4) / 27.0
    if _is_tensor(mel):
        lin = f_min + f_sp * mel
        log = min_log_hz * paddle.exp(logstep * (mel - min_log_mel))
        return paddle.where(mel >= min_log_mel, log, lin)
    if mel >= min_log_mel:
        return min_log_hz * math.exp(logstep * (mel - min_log_mel))
    return f_min + f_sp * mel


def mel_frequencies(n_mels=64, f_min=0.0, f_max=11025.0, htk=False,
                    dtype="float32"):
    low = hz_to_mel(float(f_min), htk)
    high = hz_to_mel(float(f_max), htk)
    mels = np.linspace(low, high, n_mels)
    hz = np.array([mel_to_hz(float(m), htk) for m in mels], dtype=dtype)
    return paddle.to_tensor(hz)


def fft_frequencies(sr, n_fft, dtype="float32"):
    return paddle.to_tensor(
        np.linspace(0, sr / 2.0, 1 + n_fft // 2).astype(dtype))


def compute_fbank_matrix(sr, n_fft, n_mels=64, f_min=0.0, f_max=None,
                         htk=False, norm="slaney", dtype="float32"):
    """Triangular mel filterbank [n_mels, 1 + n_fft//2]."""
    if f_max is None:
        f_max = sr / 2.0
    fftfreqs = np.linspace(0, sr / 2.0, 1 + n_fft // 2)
    mel_f = mel_frequencies(n_mels + 2, f_min, f_max, htk).numpy()
    fdiff = np.diff(mel_f)
    ramps = mel_f[:, None] - fftfreqs[None, :]
    lower = -ramps[:-2] / fdiff[:-1, None]
    upper = ramps[2:] / fdiff[1:, None]
    weights = np.maximum(0.0, np.minimum(lower, upper))
    if norm == "slaney":
        enorm = 2.0 / (mel_f[2:n_mels + 2] - mel_f[:n_mels])
        weights *= enorm[:, None]
    return paddle.to_tensor(weights.astype(dtype))


def power_to_db(spect, ref_value=1.0, amin=1e-10, top_db=80.0):
    """10*log10(max(spect, amin)/ref), floored at max-top_db."""
    if ref_value <= 0 or amin <= 0:
        raise ValueError("ref_value and amin must be positive")
    x = spect if _is_tensor(spect) else paddle.to_tensor(spect)
    log_spec = 10.0 * paddle.log10(paddle.clip(x, min=amin))
    log_spec = log_spec - 10.0 * math.log10(max(amin, ref_value))
    if top_db is not None:
        if top_db < 0:
            raise ValueError("top_db must be non-negative")
        # tensor max (no host sync) so the op stays jit-traceable
        log_spec = paddle.maximum(log_spec, log_spec.max() - top_db)
    return log_spec


def create_dct(n_mfcc, n_mels, norm="ortho", dtype="float32"):
    """DCT-II basis [n_mels, n_mfcc] (reference create_dct :303)."""
    n = np.arange(n_mels)
    k = np.arange(n_mfcc)[:, None]
    dct = np.cos(math.pi / n_mels * (n + 0.5) * k)  # [n_mfcc, n_mels]
    if norm is None:
        dct *= 2.0
    else:
        assert norm == "ortho"
        dct[0] *= 1.0 / math.sqrt(2.0)
        dct *= math.sqrt(2.0 / n_mels)
    return paddle.to_tensor(dct.T.astype(dtype))


def get_window(window, win_length, fftbins=True, dtype="float64"):
    """reference audio/functional/window.py get_window subset."""
    if isinstance(window, tuple):
        name, args = window[0], window[1:]
    else:
        name, args = window, ()
    sym = not fftbins
    M = win_length + (0 if sym else 1)
    n = np.arange(M)
    if name in ("hann", "hanning"):
        w = 0.5 - 0.5 * np.cos(2 * np.pi * n / (M - 1))
    elif name == "hamming":
        w = 0.54 - 0.46 * np.cos(2 * np.pi * n / (M - 1))
    elif name == "blackman":
        w = (0.42 - 0.5 * np.cos(2 * np.pi * n / (M - 1))
             + 0.08 * np.cos(4 * np.pi * n / (M - 1)))
    elif name in ("rect", "boxcar", "ones"):
        w = np.ones(M)
    elif name == "triang":
        w = 1.0 - np.abs((n - (M - 1) / 2.0) / ((M - 1) / 2.0))
    elif name == "bartlett":
        w = np.bartlett(M)
    elif name == "gaussian":
        std = args[0] if args else 7.0
        w = np.exp(-0.5 * ((n - (M - 1) / 2.0) / std) ** 2)
    elif name == "exponential":
        tau = args[0] if args else 1.0
        w = np.exp(-np.abs(n - (M - 1) / 2.0) / tau)
    else:
        raise ValueError("unsupported window: %r" % (window,))
    if not sym:
        w = w[:-1]
    return paddle.to_tensor(w.astype(dtype))
