"""paddle.audio.features (reference python/paddle/audio/features/layers.py:
Spectrogram :25, MelSpectrogram :107, LogMelSpectrogram :207, MFCC :310).
Each layer precomputes its window/filterbank once at construction (host
numpy, like the reference registering buffers) and does per-frame math in
traced ops, so feature extraction jit-compiles and fuses with the model.
"""
from __future__ import annotations

import paddle_tpu as paddle
from .. import signal
from ..nn.layer import Layer
from . import functional as AF


class Spectrogram(Layer):
    def __init__(self, n_fft=512, hop_length=512, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 dtype="float32"):
        super().__init__()
        assert power > 0, "power must be positive"
        self.n_fft = n_fft
        self.hop_length = hop_length if hop_length is not None else n_fft // 4
        self.win_length = win_length or n_fft
        self.power = power
        self.center = center
        self.pad_mode = pad_mode
        self.fft_window = AF.get_window(window, self.win_length,
                                        fftbins=True, dtype=dtype)

    def forward(self, x):
        spec = signal.stft(x, self.n_fft, hop_length=self.hop_length,
                           win_length=self.win_length,
                           window=self.fft_window, center=self.center,
                           pad_mode=self.pad_mode)
        mag = paddle.abs(spec)
        if self.power == 1.0:
            return mag
        if self.power == 2.0:
            return mag * mag
        return mag ** self.power


class MelSpectrogram(Layer):
    def __init__(self, sr=22050, n_fft=512, hop_length=512, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 n_mels=64, f_min=50.0, f_max=None, htk=False,
                 norm="slaney", dtype="float32"):
        super().__init__()
        self._spectrogram = Spectrogram(
            n_fft=n_fft, hop_length=hop_length, win_length=win_length,
            window=window, power=power, center=center, pad_mode=pad_mode,
            dtype=dtype)
        self.n_mels = n_mels
        self.fbank_matrix = AF.compute_fbank_matrix(
            sr=sr, n_fft=n_fft, n_mels=n_mels, f_min=f_min, f_max=f_max,
            htk=htk, norm=norm, dtype=dtype)

    def forward(self, x):
        spect = self._spectrogram(x)  # [..., freq, time]
        return paddle.matmul(self.fbank_matrix, spect)


class LogMelSpectrogram(Layer):
    def __init__(self, sr=22050, n_fft=512, hop_length=512, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 n_mels=64, f_min=50.0, f_max=None, htk=False,
                 norm="slaney", ref_value=1.0, amin=1e-10, top_db=None,
                 dtype="float32"):
        super().__init__()
        self._melspectrogram = MelSpectrogram(
            sr=sr, n_fft=n_fft, hop_length=hop_length, win_length=win_length,
            window=window, power=power, center=center, pad_mode=pad_mode,
            n_mels=n_mels, f_min=f_min, f_max=f_max, htk=htk, norm=norm,
            dtype=dtype)
        self.ref_value = ref_value
        self.amin = amin
        self.top_db = top_db

    def forward(self, x):
        return AF.power_to_db(self._melspectrogram(x),
                              ref_value=self.ref_value, amin=self.amin,
                              top_db=self.top_db)


class MFCC(Layer):
    def __init__(self, sr=22050, n_mfcc=40, n_fft=512, hop_length=512,
                 win_length=None, window="hann", power=2.0, center=True,
                 pad_mode="reflect", n_mels=64, f_min=50.0, f_max=None,
                 htk=False, norm="slaney", ref_value=1.0, amin=1e-10,
                 top_db=None, dtype="float32"):
        super().__init__()
        assert n_mfcc <= n_mels, "n_mfcc cannot be larger than n_mels"
        self._log_melspectrogram = LogMelSpectrogram(
            sr=sr, n_fft=n_fft, hop_length=hop_length, win_length=win_length,
            window=window, power=power, center=center, pad_mode=pad_mode,
            n_mels=n_mels, f_min=f_min, f_max=f_max, htk=htk, norm=norm,
            ref_value=ref_value, amin=amin, top_db=top_db, dtype=dtype)
        self.dct_matrix = AF.create_dct(n_mfcc=n_mfcc, n_mels=n_mels,
                                        dtype=dtype)

    def forward(self, x):
        mel = self._log_melspectrogram(x)  # [..., n_mels, time]
        return paddle.matmul(self.dct_matrix, mel, transpose_x=True)
