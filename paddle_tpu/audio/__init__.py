"""paddle.audio (reference python/paddle/audio/__init__.py: functional,
features, datasets, backends + top-level load/info/save)."""
from . import backends, datasets, features, functional  # noqa: F401
from .backends import info, load, save  # noqa: F401
