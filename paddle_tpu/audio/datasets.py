"""paddle.audio.datasets (reference python/paddle/audio/datasets/: TESS,
ESC50 over AudioClassificationDataset in dataset.py). Zero-egress: loaders
read local WAV trees when present; `synthetic=True` (default when no files)
yields deterministic sine-wave clips with the right shapes — the same
pattern paddle_tpu.vision.datasets uses.
"""
from __future__ import annotations

import os

import numpy as np

from ..io import Dataset
from . import features as _features


class AudioClassificationDataset(Dataset):
    """reference audio/datasets/dataset.py AudioClassificationDataset."""

    def __init__(self, files=None, labels=None, feat_type="raw",
                 sample_rate=16000, duration=1.0, archive=None, **feat_kwargs):
        self.files = files or []
        self.labels = labels or []
        self.feat_type = feat_type
        self.sample_rate = sample_rate
        self.num_samples = int(duration * sample_rate)
        if feat_type == "raw":
            self.feature_extractor = None
        elif feat_type == "mfcc":
            self.feature_extractor = _features.MFCC(
                sr=sample_rate, **feat_kwargs)
        elif feat_type == "melspectrogram":
            self.feature_extractor = _features.MelSpectrogram(
                sr=sample_rate, **feat_kwargs)
        elif feat_type == "logmelspectrogram":
            self.feature_extractor = _features.LogMelSpectrogram(
                sr=sample_rate, **feat_kwargs)
        elif feat_type == "spectrogram":
            self.feature_extractor = _features.Spectrogram(**feat_kwargs)
        else:
            raise ValueError("unknown feat_type %r" % feat_type)

    def _load_waveform(self, idx):
        from . import backends

        path = self.files[idx]
        wav, _ = backends.load(path, channels_first=False)
        w = wav.numpy()[:, 0]
        if len(w) < self.num_samples:
            w = np.pad(w, (0, self.num_samples - len(w)))
        return w[:self.num_samples].astype(np.float32)

    def __getitem__(self, idx):
        import paddle_tpu as paddle

        w = self._load_waveform(idx)
        if self.feature_extractor is not None:
            feat = self.feature_extractor(paddle.to_tensor(w))
            return feat.numpy(), np.int64(self.labels[idx])
        return w, np.int64(self.labels[idx])

    def __len__(self):
        return len(self.files)


class _SyntheticAudioDataset(AudioClassificationDataset):
    """Deterministic sine clips, one frequency per class."""

    n_class = 2

    def __init__(self, mode="train", feat_type="raw", data_dir=None,
                 size=32, **kwargs):
        super().__init__(files=None, labels=None, feat_type=feat_type,
                         **kwargs)
        if data_dir and os.path.isdir(data_dir):
            for root, _, names in os.walk(data_dir):
                for name in sorted(names):
                    if name.endswith(".wav"):
                        self.files.append(os.path.join(root, name))
                        self.labels.append(self._label_of(name))
        if not self.files:
            self._synthetic = True
            rng = np.random.RandomState(0 if mode == "train" else 1)
            self._freqs = rng.randint(100, 1000, size)
            self.labels = (self._freqs % self.n_class).astype(np.int64)
            self.files = [None] * size
        else:
            self._synthetic = False

    def _label_of(self, name):
        return 0

    def _load_waveform(self, idx):
        if not self._synthetic:
            return super()._load_waveform(idx)
        t = np.arange(self.num_samples) / self.sample_rate
        return np.sin(2 * np.pi * self._freqs[idx] * t).astype(np.float32)


class TESS(_SyntheticAudioDataset):
    """Toronto emotional speech set (reference audio/datasets/tess.py).
    7 emotion classes parsed from filename."""

    n_class = 7
    labels_list = ["angry", "disgust", "fear", "happy", "neutral",
                   "ps", "sad"]

    def _label_of(self, name):
        emotion = name.rsplit("_", 1)[-1].split(".")[0].lower()
        return (self.labels_list.index(emotion)
                if emotion in self.labels_list else 0)


class ESC50(_SyntheticAudioDataset):
    """ESC-50 environmental sounds (reference audio/datasets/esc50.py),
    50 classes from the filename's last dash field."""

    n_class = 50

    def _label_of(self, name):
        try:
            return int(name.rsplit("-", 1)[-1].split(".")[0])
        except ValueError:
            return 0
