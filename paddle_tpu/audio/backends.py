"""paddle.audio.backends (reference python/paddle/audio/backends/
wave_backend.py: info :37, load :89, save :168 — stdlib `wave`-based WAV
IO; init_backend.py lists/sets backends). Pure host IO, no device work.
"""
from __future__ import annotations

import wave

import numpy as np

import paddle_tpu as paddle


class AudioInfo:
    """reference backends/backend.py AudioInfo."""

    def __init__(self, sample_rate, num_samples, num_channels,
                 bits_per_sample, encoding):
        self.sample_rate = sample_rate
        self.num_samples = num_samples
        self.num_channels = num_channels
        self.bits_per_sample = bits_per_sample
        self.encoding = encoding


def list_available_backends():
    return ["wave_backend"]


def get_current_backend():
    return "wave_backend"


def set_backend(backend_name):
    if backend_name != "wave_backend":
        raise NotImplementedError(
            "only the built-in wave_backend is available")


def info(filepath):
    with wave.open(filepath, "rb") as f:
        return AudioInfo(sample_rate=f.getframerate(),
                         num_samples=f.getnframes(),
                         num_channels=f.getnchannels(),
                         bits_per_sample=f.getsampwidth() * 8,
                         encoding="PCM_S")


def load(filepath, frame_offset=0, num_frames=-1, normalize=True,
         channels_first=True):
    """Returns (Tensor [C, T] (or [T, C]), sample_rate)."""
    with wave.open(filepath, "rb") as f:
        sr = f.getframerate()
        nch = f.getnchannels()
        width = f.getsampwidth()
        f.setpos(frame_offset)
        n = f.getnframes() - frame_offset if num_frames < 0 else num_frames
        raw = f.readframes(n)
    dtype = {1: np.uint8, 2: np.int16, 4: np.int32}[width]
    data = np.frombuffer(raw, dtype=dtype).reshape(-1, nch)
    if width == 1:  # 8-bit WAV is unsigned
        data = data.astype(np.int16) - 128
        scale = 128.0
    else:
        scale = float(2 ** (8 * width - 1))
    if normalize:
        out = data.astype(np.float32) / scale
    else:
        out = data
    if channels_first:
        out = out.T
    return paddle.to_tensor(np.ascontiguousarray(out)), sr


def save(filepath, src, sample_rate, channels_first=True,
         encoding="PCM_16", bits_per_sample=16):
    data = src.numpy() if hasattr(src, "numpy") else np.asarray(src)
    if channels_first:
        data = data.T  # -> [T, C]
    assert bits_per_sample == 16, "wave backend writes PCM_16"
    if np.issubdtype(data.dtype, np.floating):
        data = np.clip(data, -1.0, 1.0)
        data = (data * 32767.0).astype(np.int16)
    else:
        data = data.astype(np.int16)
    with wave.open(filepath, "wb") as f:
        f.setnchannels(data.shape[1] if data.ndim > 1 else 1)
        f.setsampwidth(2)
        f.setframerate(int(sample_rate))
        f.writeframes(np.ascontiguousarray(data).tobytes())
