"""paddle_tpu.hapi — high-level Model API (reference python/paddle/hapi)."""
from . import callbacks  # noqa: F401
from .callbacks import (  # noqa: F401
    Callback,
    EarlyStopping,
    LRScheduler,
    ModelCheckpoint,
    ProgBarLogger,
)
from .model import Model  # noqa: F401
from . import hub  # noqa: F401
from .dynamic_flops import flops  # noqa: F401
