"""Model hub: load entrypoints from a hubconf.py repo.

Parity: reference python/paddle/hapi/hub.py (list/help/load over a
`hubconf.py` exposing callables; `dependencies` checked before load).
The TPU build supports the `local` source (a directory); `github`/
`gitee` sources require network egress this environment lacks and raise
a clear error instead of half-downloading.
"""
from __future__ import annotations

import importlib.util
import os
import sys

__all__ = ["list", "help", "load"]

_HUBCONF = "hubconf.py"


def _import_hubconf(repo_dir):
    path = os.path.join(repo_dir, _HUBCONF)
    if not os.path.exists(path):
        raise FileNotFoundError(
            "no %s found in %s" % (_HUBCONF, repo_dir))
    spec = importlib.util.spec_from_file_location("paddle_tpu_hubconf", path)
    mod = importlib.util.module_from_spec(spec)
    sys.path.insert(0, repo_dir)
    try:
        spec.loader.exec_module(mod)
    finally:
        sys.path.remove(repo_dir)
    deps = getattr(mod, "dependencies", [])
    missing = [d for d in deps if importlib.util.find_spec(d) is None]
    if missing:
        raise RuntimeError(
            "hubconf dependencies not installed: %s" % ", ".join(missing))
    return mod


def _resolve(repo_dir, source):
    if source not in ("local", "github", "gitee"):
        raise ValueError(
            "unknown source %r (expected 'local', 'github' or 'gitee')"
            % (source,))
    if source != "local":
        raise RuntimeError(
            "source=%r needs network egress; clone the repo and use "
            "source='local' with its directory" % (source,))
    return repo_dir


def _entries(mod):
    return sorted(
        name for name, f in vars(mod).items()
        if callable(f) and not name.startswith("_"))


def list(repo_dir, source="local", force_reload=False):  # noqa: A001
    """Entrypoint names exposed by the repo's hubconf
    (reference hub.py:175)."""
    mod = _import_hubconf(_resolve(repo_dir, source))
    return _entries(mod)


def help(repo_dir, model, source="local", force_reload=False):  # noqa: A001
    """Entrypoint docstring (reference hub.py:223)."""
    mod = _import_hubconf(_resolve(repo_dir, source))
    entry = getattr(mod, model, None)
    if entry is None or not callable(entry):
        raise RuntimeError(
            "no callable %r in hubconf (have: %s)"
            % (model, ", ".join(_entries(mod))))
    return entry.__doc__


def load(repo_dir, model, source="local", force_reload=False, **kwargs):
    """Build the model by calling its entrypoint (reference hub.py:268)."""
    mod = _import_hubconf(_resolve(repo_dir, source))
    entry = getattr(mod, model, None)
    if entry is None or not callable(entry):
        raise RuntimeError(
            "no callable %r in hubconf (have: %s)"
            % (model, ", ".join(_entries(mod))))
    return entry(**kwargs)
