"""paddle_tpu.Model — the high-level train/eval/predict facade.

Parity: reference python/paddle/hapi/model.py:1004 (`Model`), fit at :1696,
evaluate/predict/save/load, prepare(optimizer, loss, metrics). The reference
switches between dygraph and static-graph adapters; here the eager path IS
the compiled path (ops trace into XLA), so one implementation serves both.
Distributed data parallelism comes from the engine/mesh instead of
fleet.distributed_model wrapping.
"""
from __future__ import annotations

import os
import pickle

import numpy as np

from ..core.tensor import Tensor
from ..metric import Metric
from .callbacks import config_callbacks


def _to_list(x):
    if x is None:
        return []
    if isinstance(x, (list, tuple)):
        return list(x)
    return [x]


def _to_tensor(x):
    if isinstance(x, Tensor):
        return x
    import paddle_tpu as paddle

    return paddle.to_tensor(np.asarray(x))


class Model:
    """Trainer facade over a Layer (reference hapi/model.py:1004)."""

    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._optimizer = None
        self._loss = None
        self._metrics = []
        self.stop_training = False

    # -- setup -------------------------------------------------------------
    def prepare(self, optimizer=None, loss=None, metrics=None, amp_configs=None):
        self._optimizer = optimizer
        self._loss = loss
        for m in _to_list(metrics):
            if not isinstance(m, Metric):
                raise TypeError("metric must be paddle_tpu.metric.Metric")
        self._metrics = _to_list(metrics)
        return self

    # -- single-batch ops (reference Model.train_batch/eval_batch) ---------
    def train_batch(self, inputs, labels=None):
        self.network.train()
        inputs = [_to_tensor(x) for x in _to_list(inputs)]
        labels = [_to_tensor(y) for y in _to_list(labels)]
        outs = self.network(*inputs)
        loss = self._compute_loss(outs, labels)
        loss.backward()
        self._optimizer.step()
        self._optimizer.clear_grad()
        metrics = self._update_metrics(outs, labels)
        return self._named_outputs(loss, metrics)

    def eval_batch(self, inputs, labels=None):
        import paddle_tpu as paddle

        self.network.eval()
        with paddle.no_grad():
            inputs = [_to_tensor(x) for x in _to_list(inputs)]
            labels = [_to_tensor(y) for y in _to_list(labels)]
            outs = self.network(*inputs)
            loss = self._compute_loss(outs, labels)
        metrics = self._update_metrics(outs, labels)
        return self._named_outputs(loss, metrics)

    def predict_batch(self, inputs):
        import paddle_tpu as paddle

        self.network.eval()
        with paddle.no_grad():
            inputs = [_to_tensor(x) for x in _to_list(inputs)]
            outs = self.network(*inputs)
        return [o.numpy() for o in _to_list(outs)]

    def _compute_loss(self, outs, labels):
        outs_l = _to_list(outs)
        if self._loss is None:
            # network computed its own loss
            return outs_l[0]
        return self._loss(*(outs_l + labels))

    def _update_metrics(self, outs, labels):
        res = {}
        outs_l = _to_list(outs)
        for m in self._metrics:
            interm = m.compute(*(outs_l + labels))
            m.update(*_to_list(interm))
            name = m.name()
            name = name[0] if isinstance(name, (list, tuple)) else name
            res[name] = m.accumulate()
        return res

    def _named_outputs(self, loss, metrics):
        logs = {"loss": float(loss)}
        for k, v in metrics.items():
            logs[k] = v
        return logs

    # -- loops -------------------------------------------------------------
    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=1,
            shuffle=True, callbacks=None, num_workers=0, drop_last=False):
        train_loader = self._make_loader(train_data, batch_size, shuffle,
                                         num_workers, drop_last)
        eval_loader = (self._make_loader(eval_data, batch_size, False,
                                         num_workers, False)
                       if eval_data is not None else None)
        steps = len(train_loader) if hasattr(train_loader, "__len__") else None
        cbks = config_callbacks(
            callbacks, model=self, epochs=epochs, steps=steps,
            verbose=verbose, log_freq=log_freq, save_freq=save_freq,
            save_dir=save_dir, metrics=[m.name() for m in self._metrics])
        self.stop_training = False
        cbks.on_train_begin()
        history = []
        for epoch in range(epochs):
            if self.stop_training:
                break
            cbks.on_epoch_begin(epoch)
            for m in self._metrics:
                m.reset()
            logs = {}
            for step, batch in enumerate(train_loader):
                cbks.on_train_batch_begin(step)
                ins, lbl = self._split_batch(batch)
                logs = self.train_batch(ins, lbl)
                cbks.on_train_batch_end(step, logs)
            cbks.on_epoch_end(epoch, logs)
            history.append(logs)
            if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                self.evaluate(eval_loader, callbacks=cbks, _inner=True)
        cbks.on_train_end()
        return history

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=1,
                 num_workers=0, callbacks=None, _inner=False):
        loader = self._make_loader(eval_data, batch_size, False, num_workers,
                                   False)
        cbks = callbacks if _inner else config_callbacks(
            callbacks, model=self, verbose=verbose, log_freq=log_freq)
        for m in self._metrics:
            m.reset()
        cbks.on_eval_begin()
        logs, losses = {}, []
        for step, batch in enumerate(loader):
            cbks.on_eval_batch_begin(step)
            ins, lbl = self._split_batch(batch)
            logs = self.eval_batch(ins, lbl)
            losses.append(logs["loss"])
            cbks.on_eval_batch_end(step, logs)
        if losses:
            logs["loss"] = float(np.mean(losses))
        cbks.on_eval_end(logs)
        return logs

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, callbacks=None, verbose=0):
        loader = self._make_loader(test_data, batch_size, False, num_workers,
                                   False)
        outputs = []
        for batch in loader:
            # a (x, ..., y) batch from a labeled dataset: drop the label,
            # matching the reference's input-spec-driven slicing
            ins, _ = self._split_batch(batch)
            outputs.append(self.predict_batch(ins))
        if not outputs:
            return []
        n_out = len(outputs[0])
        grouped = [[o[i] for o in outputs] for i in range(n_out)]
        if stack_outputs:
            grouped = [np.concatenate(g, axis=0) for g in grouped]
        return grouped

    def _make_loader(self, data, batch_size, shuffle, num_workers, drop_last):
        from ..io import DataLoader, Dataset

        if data is None:
            return []
        if isinstance(data, DataLoader):
            return data
        if isinstance(data, Dataset):
            return DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                              num_workers=num_workers, drop_last=drop_last)
        return data  # assume iterable of batches

    def _split_batch(self, batch, has_labels=True):
        if isinstance(batch, (list, tuple)):
            if has_labels and len(batch) >= 2:
                return list(batch[:-1]), [batch[-1]]
            return list(batch), []
        return [batch], []

    # -- persistence (reference Model.save/load) ---------------------------
    def save(self, path, training=True):
        import paddle_tpu as paddle

        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        paddle.save(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            paddle.save(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        import paddle_tpu as paddle

        state = paddle.load(path + ".pdparams")
        self.network.set_state_dict(state)
        opt_path = path + ".pdopt"
        if (not reset_optimizer and self._optimizer is not None
                and os.path.exists(opt_path)):
            self._optimizer.set_state_dict(paddle.load(opt_path))

    # -- introspection -----------------------------------------------------
    def parameters(self, *args, **kwargs):
        return self.network.parameters(*args, **kwargs)

    def summary(self, input_size=None, dtype=None):
        lines, total = [], 0
        for name, p in self.network.named_parameters():
            n = int(np.prod(p.shape)) if p.shape else 1
            total += n
            lines.append("%-40s %-20s %d" % (name, p.shape, n))
        out = "\n".join(lines) + "\nTotal params: %d" % total
        print(out)
        return {"total_params": total}
