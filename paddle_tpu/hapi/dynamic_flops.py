"""paddle.flops — dynamic FLOPs counter for Layer networks.

Parity: reference python/paddle/hapi/dynamic_flops.py (forward-hook
walk over leaf layers; per-type count rules from utils/flops.py) —
`paddle.flops(net, [1, 3, 224, 224], print_detail=True)`.

Convention matches the reference: one multiply-add counts as ONE flop
(so a Linear is in*out, not 2*in*out), bias adds out_features, and
parameter-free activations count their element count.
"""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor
from ..nn.layer import Layer

__all__ = ["flops"]


def _numel(t):
    v = t._value if isinstance(t, Tensor) else t
    return int(np.prod(v.shape))


def _shape(t):
    v = t._value if isinstance(t, Tensor) else t
    return tuple(v.shape)


def _count_conv(layer, inputs, output):
    # kernel_ops from the INPUT channel count (reference count_convNd):
    # correct for both conv ([out, in/g, *k]) and transpose-conv
    # ([in, out/g, *k]) weight layouts
    out_numel = _numel(output)
    in_ch = _shape(inputs[0])[1]
    k_spatial = _numel(layer.weight) // (
        layer.weight.shape[0] * layer.weight.shape[1])
    groups = getattr(layer, "_groups", None) or getattr(layer, "groups", 1)
    kernel_ops = (in_ch // groups) * k_spatial
    total = out_numel * kernel_ops
    if getattr(layer, "bias", None) is not None:
        total += out_numel
    return total


def _count_linear(layer, inputs, output):
    out_numel = _numel(output)
    total = out_numel * layer.weight.shape[0]  # in_features per output
    if getattr(layer, "bias", None) is not None:
        total += out_numel
    return total


def _count_norm(layer, inputs, output):
    # normalize (sub, div) + affine (mul, add) per element ≈ 2x numel
    return 2 * _numel(inputs[0])


def _count_act(layer, inputs, output):
    return _numel(inputs[0])


def _count_pool(layer, inputs, output):
    return _numel(output)


def _count_embedding(layer, inputs, output):
    return 0  # a gather; the reference counts embeddings as 0 flops


_RULES_CACHE = {}


def _default_rules():
    if _RULES_CACHE:
        return dict(_RULES_CACHE)
    from ..nn.layers import common, conv, norm, pooling

    rules = _RULES_CACHE
    for cls_name, fn in [
        ("Conv1D", _count_conv), ("Conv2D", _count_conv),
        ("Conv3D", _count_conv), ("Conv2DTranspose", _count_conv),
        ("Conv1DTranspose", _count_conv), ("Conv3DTranspose", _count_conv),
    ]:
        cls = getattr(conv, cls_name, None)
        if cls is not None:
            rules[cls] = fn
    rules[common.Linear] = _count_linear
    rules[common.Embedding] = _count_embedding
    for mod, names, fn in [
        (norm, ("BatchNorm1D", "BatchNorm2D", "BatchNorm3D", "BatchNorm",
                "LayerNorm", "GroupNorm", "InstanceNorm1D",
                "InstanceNorm2D", "InstanceNorm3D", "RMSNorm"),
         _count_norm),
        (pooling, ("MaxPool1D", "MaxPool2D", "MaxPool3D", "AvgPool1D",
                   "AvgPool2D", "AvgPool3D", "AdaptiveAvgPool1D",
                   "AdaptiveAvgPool2D", "AdaptiveAvgPool3D",
                   "AdaptiveMaxPool2D"),
         _count_pool),
    ]:
        for cname in names:
            cls = getattr(mod, cname, None)
            if cls is not None:
                rules[cls] = fn
    from ..nn.layers import activation

    for cname in ("ReLU", "ReLU6", "GELU", "Sigmoid", "Tanh", "Softmax",
                  "SiLU", "LeakyReLU", "Hardswish", "Hardsigmoid", "PReLU",
                  "ELU", "Swish", "Mish"):
        cls = getattr(activation, cname, None)
        if cls is not None:
            rules[cls] = _count_act
    return dict(rules)


def flops(net, input_size, custom_ops=None, print_detail=False):
    """Total FLOPs of `net` on a zeros input of `input_size` (reference
    hapi/dynamic_flops.py:28). custom_ops: {LayerClass: fn(layer,
    inputs, output) -> int} overrides/extends the built-in rules."""
    if not isinstance(net, Layer):
        raise TypeError(
            "paddle.flops counts nn.Layer networks; for a static Program "
            "export it via a Layer first (got %r)" % type(net).__name__)
    rules = _default_rules()
    rules.update(custom_ops or {})
    rows = []
    total = [0]
    handles = []

    def make_hook(rule):
        def hook(lyr, inputs, output):
            n = int(rule(lyr, inputs, output))
            params = sum(_numel(p) for p in lyr.parameters(
                include_sublayers=False))
            rows.append((type(lyr).__name__, _shape(inputs[0]),
                         _shape(output) if isinstance(output, Tensor)
                         else None, params, n))
            total[0] += n
        return hook

    for _, sub in net.named_sublayers(include_self=True):
        rule = rules.get(type(sub))
        if rule is not None:
            handles.append(sub.register_forward_post_hook(
                make_hook(rule)))
    import paddle_tpu as paddle

    was_training = net.training
    net.eval()
    try:
        x = paddle.zeros(list(input_size), dtype="float32")
        net(x)
    finally:
        if was_training:
            net.train()
        for h in handles:
            if hasattr(h, "remove"):
                h.remove()
    if print_detail:
        print("%-20s %-22s %-22s %12s %14s"
              % ("Layer", "Input Shape", "Output Shape", "Params",
                 "FLOPs"))
        for name, ishape, oshape, params, n in rows:
            print("%-20s %-22s %-22s %12d %14d"
                  % (name, ishape, oshape, params, n))
        print("Total FLOPs: %d" % total[0])
    return total[0]
