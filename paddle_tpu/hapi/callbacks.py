"""Training callbacks for paddle_tpu.Model.

Parity: reference python/paddle/hapi/callbacks.py — Callback base,
ProgBarLogger, ModelCheckpoint, LRScheduler, EarlyStopping, plus the
config_callbacks assembly helper (:59).
"""
from __future__ import annotations

import os
import sys
import time


class Callback:
    """Base callback (reference callbacks.py Callback)."""

    def set_params(self, params):
        self.params = params or {}

    def set_model(self, model):
        self.model = model

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_predict_begin(self, logs=None):
        pass

    def on_predict_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_batch_begin(self, step, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks):
        self.callbacks = list(callbacks)

    def set_params(self, params):
        for c in self.callbacks:
            c.set_params(params)

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def __getattr__(self, name):
        if not name.startswith("on_"):
            raise AttributeError(name)

        def call(*args, **kwargs):
            for c in self.callbacks:
                getattr(c, name)(*args, **kwargs)

        return call


class ProgBarLogger(Callback):
    """Per-epoch progress logging (reference ProgBarLogger)."""

    def __init__(self, log_freq=10, verbose=1):
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.steps = (self.params or {}).get("steps")
        self._t0 = time.monotonic()
        if self.verbose:
            print("Epoch %d/%d" % (epoch + 1,
                                   (self.params or {}).get("epochs", 1)))

    def on_train_batch_end(self, step, logs=None):
        if self.verbose and step % self.log_freq == 0:
            items = ", ".join(
                "%s: %.4f" % (k, float(v)) for k, v in (logs or {}).items()
                if not hasattr(v, "__len__"))
            total = "/%s" % self.steps if self.steps else ""
            print("  step %d%s - %s" % (step, total, items))
            sys.stdout.flush()

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            items = ", ".join(
                "%s: %.4f" % (k, float(v)) for k, v in (logs or {}).items()
                if not hasattr(v, "__len__"))
            print("  epoch %d done in %.1fs - %s"
                  % (epoch + 1, time.monotonic() - self._t0, items))

    def on_eval_end(self, logs=None):
        if self.verbose:
            items = ", ".join(
                "%s: %.4f" % (k, float(v)) for k, v in (logs or {}).items()
                if not hasattr(v, "__len__"))
            print("  eval - %s" % items)


class ModelCheckpoint(Callback):
    """Save model+optimizer every save_freq epochs (reference
    ModelCheckpoint)."""

    def __init__(self, save_freq=1, save_dir=None):
        self.save_freq = save_freq
        self.save_dir = save_dir or "checkpoint"

    def on_epoch_end(self, epoch, logs=None):
        if epoch % self.save_freq == 0:
            path = os.path.join(self.save_dir, "%d" % epoch)
            self.model.save(path)

    def on_train_end(self, logs=None):
        self.model.save(os.path.join(self.save_dir, "final"))


class LRScheduler(Callback):
    """Step the optimizer's LRScheduler (reference hapi LRScheduler cb)."""

    def __init__(self, by_step=True, by_epoch=False):
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        from ..optimizer.lr import LRScheduler as Sched

        lr = getattr(self.model._optimizer, "_learning_rate", None)
        return lr if isinstance(lr, Sched) else None

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if self.by_step and s is not None:
            s.step()

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if self.by_epoch and s is not None:
            s.step()


class EarlyStopping(Callback):
    """Stop when a monitored metric stops improving (reference
    EarlyStopping)."""

    def __init__(self, monitor="loss", mode="auto", patience=0,
                 min_delta=0, baseline=None, save_best_model=True):
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.save_best_model = save_best_model
        if mode == "auto":
            mode = "min" if "loss" in monitor or "err" in monitor else "max"
        self.mode = mode
        self.wait = 0
        self.best = None
        self.stopped_epoch = None

    def _better(self, cur, best):
        if self.mode == "min":
            return cur < best - self.min_delta
        return cur > best + self.min_delta

    def on_eval_end(self, logs=None):
        cur = (logs or {}).get(self.monitor)
        if cur is None:
            return
        cur = float(cur if not hasattr(cur, "__len__") else cur[0])
        if self.best is None or self._better(cur, self.best):
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.model.stop_training = True
                self.stopped_epoch = True


def config_callbacks(callbacks=None, model=None, epochs=None, steps=None,
                     verbose=1, log_freq=10, save_freq=1, save_dir=None,
                     metrics=None, mode="train"):
    cbks = list(callbacks or [])
    if not any(isinstance(c, ProgBarLogger) for c in cbks) and verbose:
        cbks = [ProgBarLogger(log_freq, verbose=verbose)] + cbks
    if not any(isinstance(c, LRScheduler) for c in cbks):
        cbks = cbks + [LRScheduler()]
    if save_dir and not any(isinstance(c, ModelCheckpoint) for c in cbks):
        cbks = cbks + [ModelCheckpoint(save_freq, save_dir)]
    lst = CallbackList(cbks)
    lst.set_model(model)
    lst.set_params({
        "epochs": epochs, "steps": steps, "verbose": verbose,
        "metrics": metrics or [],
    })
    return lst
