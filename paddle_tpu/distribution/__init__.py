"""paddle_tpu.distribution — probability distributions.

Parity: reference python/paddle/distribution/ (Distribution base
distribution.py:42, Normal, Uniform, Categorical, Beta, Dirichlet,
Multinomial, kl_divergence/register_kl kl.py:33). Math is jnp/jax.random;
sampling threads the framework's global RNG (framework/random.py).
"""
from __future__ import annotations

import math

import numpy as np

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..framework import random as _random

__all__ = [
    "Distribution", "Normal", "Uniform", "Categorical", "Bernoulli",
    "Beta", "Dirichlet", "Exponential", "Gamma", "Laplace", "LogNormal",
    "Multinomial", "Gumbel", "kl_divergence", "register_kl",
]


def _v(x):
    if isinstance(x, Tensor):
        return x._value
    return jnp.asarray(x, jnp.float32)


def _key():
    return _random.next_key()


class Distribution:
    """Base class (reference distribution/distribution.py:42)."""

    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = tuple(batch_shape)
        self._event_shape = tuple(event_shape)

    @property
    def batch_shape(self):
        return self._batch_shape

    @property
    def event_shape(self):
        return self._event_shape

    @property
    def mean(self):
        raise NotImplementedError

    @property
    def variance(self):
        raise NotImplementedError

    def sample(self, shape=()):
        raise NotImplementedError

    def rsample(self, shape=()):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        return Tensor(jnp.exp(_v(self.log_prob(value))))

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other):
        return kl_divergence(self, other)

    def _extend(self, shape):
        return tuple(shape) + self._batch_shape + self._event_shape


class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _v(loc)
        self.scale = _v(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape,
                                              self.scale.shape))

    @property
    def mean(self):
        return Tensor(jnp.broadcast_to(self.loc, self._batch_shape))

    @property
    def variance(self):
        return Tensor(jnp.broadcast_to(self.scale ** 2, self._batch_shape))

    @property
    def stddev(self):
        return Tensor(jnp.broadcast_to(self.scale, self._batch_shape))

    def sample(self, shape=()):
        eps = jax.random.normal(_key(), self._extend(shape))
        return Tensor(self.loc + self.scale * eps)

    rsample = sample

    def log_prob(self, value):
        v = _v(value)
        var = self.scale ** 2
        return Tensor(-((v - self.loc) ** 2) / (2 * var)
                      - jnp.log(self.scale) - 0.5 * math.log(2 * math.pi))

    def entropy(self):
        return Tensor(jnp.broadcast_to(
            0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(self.scale),
            self._batch_shape))


class LogNormal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _v(loc)
        self.scale = _v(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape,
                                              self.scale.shape))

    @property
    def mean(self):
        return Tensor(jnp.exp(self.loc + self.scale ** 2 / 2))

    @property
    def variance(self):
        s2 = self.scale ** 2
        return Tensor((jnp.exp(s2) - 1) * jnp.exp(2 * self.loc + s2))

    def sample(self, shape=()):
        eps = jax.random.normal(_key(), self._extend(shape))
        return Tensor(jnp.exp(self.loc + self.scale * eps))

    rsample = sample

    def log_prob(self, value):
        v = _v(value)
        logv = jnp.log(v)
        var = self.scale ** 2
        return Tensor(-((logv - self.loc) ** 2) / (2 * var) - logv
                      - jnp.log(self.scale) - 0.5 * math.log(2 * math.pi))


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = _v(low)
        self.high = _v(high)
        super().__init__(jnp.broadcast_shapes(self.low.shape,
                                              self.high.shape))

    @property
    def mean(self):
        return Tensor((self.low + self.high) / 2)

    @property
    def variance(self):
        return Tensor((self.high - self.low) ** 2 / 12)

    def sample(self, shape=()):
        u = jax.random.uniform(_key(), self._extend(shape))
        return Tensor(self.low + (self.high - self.low) * u)

    rsample = sample

    def log_prob(self, value):
        v = _v(value)
        inside = (v >= self.low) & (v < self.high)
        lp = -jnp.log(self.high - self.low)
        return Tensor(jnp.where(inside, lp, -jnp.inf))

    def entropy(self):
        return Tensor(jnp.log(self.high - self.low))


class Bernoulli(Distribution):
    def __init__(self, probs=None, logits=None, name=None):
        if (probs is None) == (logits is None):
            raise ValueError("pass exactly one of probs/logits")
        if probs is not None:
            self.probs = _v(probs)
            self.logits = jnp.log(self.probs) - jnp.log1p(-self.probs)
        else:
            self.logits = _v(logits)
            self.probs = jax.nn.sigmoid(self.logits)
        super().__init__(self.probs.shape)

    @property
    def mean(self):
        return Tensor(self.probs)

    @property
    def variance(self):
        return Tensor(self.probs * (1 - self.probs))

    def sample(self, shape=()):
        u = jax.random.uniform(_key(), self._extend(shape))
        return Tensor((u < self.probs).astype(jnp.float32))

    def log_prob(self, value):
        v = _v(value)
        return Tensor(v * jnp.log(jnp.clip(self.probs, 1e-12))
                      + (1 - v) * jnp.log(jnp.clip(1 - self.probs, 1e-12)))

    def entropy(self):
        p = jnp.clip(self.probs, 1e-12, 1 - 1e-12)
        return Tensor(-(p * jnp.log(p) + (1 - p) * jnp.log(1 - p)))


class Categorical(Distribution):
    def __init__(self, logits=None, probs=None, name=None):
        if logits is not None and probs is None:
            self.logits = _v(logits)
            self.probs = jax.nn.softmax(self.logits, -1)
        elif probs is not None:
            self.probs = _v(probs)
            self.probs = self.probs / jnp.sum(self.probs, -1, keepdims=True)
            self.logits = jnp.log(jnp.clip(self.probs, 1e-12))
        else:
            raise ValueError("pass logits or probs")
        super().__init__(self.probs.shape[:-1])

    def sample(self, shape=()):
        out = jax.random.categorical(
            _key(), self.logits, shape=tuple(shape) + self._batch_shape)
        return Tensor(out)

    def log_prob(self, value):
        v = _v(value).astype(jnp.int32)
        logp = jax.nn.log_softmax(self.logits, -1)
        return Tensor(jnp.take_along_axis(
            logp, v[..., None], axis=-1)[..., 0])

    def probs_of(self, value):
        return Tensor(jnp.exp(_v(self.log_prob(value))))

    def entropy(self):
        logp = jax.nn.log_softmax(self.logits, -1)
        return Tensor(-jnp.sum(jnp.exp(logp) * logp, -1))


class Beta(Distribution):
    def __init__(self, alpha, beta, name=None):
        self.alpha = _v(alpha)
        self.beta = _v(beta)
        super().__init__(jnp.broadcast_shapes(self.alpha.shape,
                                              self.beta.shape))

    @property
    def mean(self):
        return Tensor(self.alpha / (self.alpha + self.beta))

    @property
    def variance(self):
        s = self.alpha + self.beta
        return Tensor(self.alpha * self.beta / (s * s * (s + 1)))

    def sample(self, shape=()):
        return Tensor(jax.random.beta(
            _key(), self.alpha, self.beta, self._extend(shape)))

    def log_prob(self, value):
        v = _v(value)
        lbeta = (jax.scipy.special.gammaln(self.alpha)
                 + jax.scipy.special.gammaln(self.beta)
                 - jax.scipy.special.gammaln(self.alpha + self.beta))
        return Tensor((self.alpha - 1) * jnp.log(v)
                      + (self.beta - 1) * jnp.log1p(-v) - lbeta)


class Dirichlet(Distribution):
    def __init__(self, concentration, name=None):
        self.concentration = _v(concentration)
        super().__init__(self.concentration.shape[:-1],
                         self.concentration.shape[-1:])

    @property
    def mean(self):
        return Tensor(self.concentration
                      / jnp.sum(self.concentration, -1, keepdims=True))

    def sample(self, shape=()):
        return Tensor(jax.random.dirichlet(
            _key(), self.concentration,
            tuple(shape) + self._batch_shape))

    def log_prob(self, value):
        v = _v(value)
        a = self.concentration
        lnorm = (jnp.sum(jax.scipy.special.gammaln(a), -1)
                 - jax.scipy.special.gammaln(jnp.sum(a, -1)))
        return Tensor(jnp.sum((a - 1) * jnp.log(v), -1) - lnorm)


class Exponential(Distribution):
    def __init__(self, rate, name=None):
        self.rate = _v(rate)
        super().__init__(self.rate.shape)

    @property
    def mean(self):
        return Tensor(1.0 / self.rate)

    @property
    def variance(self):
        return Tensor(1.0 / self.rate ** 2)

    def sample(self, shape=()):
        u = jax.random.exponential(_key(), self._extend(shape))
        return Tensor(u / self.rate)

    def log_prob(self, value):
        v = _v(value)
        return Tensor(jnp.log(self.rate) - self.rate * v)

    def entropy(self):
        return Tensor(1.0 - jnp.log(self.rate))


class Gamma(Distribution):
    def __init__(self, concentration, rate, name=None):
        self.concentration = _v(concentration)
        self.rate = _v(rate)
        super().__init__(jnp.broadcast_shapes(self.concentration.shape,
                                              self.rate.shape))

    @property
    def mean(self):
        return Tensor(self.concentration / self.rate)

    @property
    def variance(self):
        return Tensor(self.concentration / self.rate ** 2)

    def sample(self, shape=()):
        g = jax.random.gamma(_key(), self.concentration,
                             self._extend(shape))
        return Tensor(g / self.rate)

    def log_prob(self, value):
        v = _v(value)
        a, b = self.concentration, self.rate
        return Tensor(a * jnp.log(b) + (a - 1) * jnp.log(v) - b * v
                      - jax.scipy.special.gammaln(a))


class Laplace(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _v(loc)
        self.scale = _v(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape,
                                              self.scale.shape))

    @property
    def mean(self):
        return Tensor(jnp.broadcast_to(self.loc, self._batch_shape))

    @property
    def variance(self):
        return Tensor(jnp.broadcast_to(2 * self.scale ** 2,
                                       self._batch_shape))

    def sample(self, shape=()):
        eps = jax.random.laplace(_key(), self._extend(shape))
        return Tensor(self.loc + self.scale * eps)

    rsample = sample

    def log_prob(self, value):
        v = _v(value)
        return Tensor(-jnp.abs(v - self.loc) / self.scale
                      - jnp.log(2 * self.scale))

    def entropy(self):
        return Tensor(1 + jnp.log(2 * self.scale)
                      + jnp.zeros(self._batch_shape))


class Gumbel(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _v(loc)
        self.scale = _v(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape,
                                              self.scale.shape))

    @property
    def mean(self):
        return Tensor(self.loc + self.scale * np.euler_gamma)

    def sample(self, shape=()):
        g = jax.random.gumbel(_key(), self._extend(shape))
        return Tensor(self.loc + self.scale * g)

    rsample = sample

    def log_prob(self, value):
        z = (_v(value) - self.loc) / self.scale
        return Tensor(-(z + jnp.exp(-z)) - jnp.log(self.scale))


class Multinomial(Distribution):
    def __init__(self, total_count, probs, name=None):
        self.total_count = int(total_count)
        self.probs = _v(probs)
        self.probs = self.probs / jnp.sum(self.probs, -1, keepdims=True)
        super().__init__(self.probs.shape[:-1], self.probs.shape[-1:])

    @property
    def mean(self):
        return Tensor(self.total_count * self.probs)

    def sample(self, shape=()):
        n_cat = self.probs.shape[-1]
        logits = jnp.log(jnp.clip(self.probs, 1e-12))
        draws = jax.random.categorical(
            _key(), logits,
            shape=(self.total_count,) + tuple(shape) + self._batch_shape)
        counts = jax.nn.one_hot(draws, n_cat).sum(0)
        return Tensor(counts)

    def log_prob(self, value):
        v = _v(value)
        logp = jnp.log(jnp.clip(self.probs, 1e-12))
        coef = (jax.scipy.special.gammaln(
            jnp.asarray(self.total_count + 1.0))
            - jnp.sum(jax.scipy.special.gammaln(v + 1.0), -1))
        return Tensor(coef + jnp.sum(v * logp, -1))


# -- KL divergence registry (reference distribution/kl.py:33) ---------------

_KL_REGISTRY = {}


def register_kl(p_cls, q_cls):
    def deco(fn):
        _KL_REGISTRY[(p_cls, q_cls)] = fn
        return fn

    return deco


def kl_divergence(p, q):
    for (pc, qc), fn in _KL_REGISTRY.items():
        if isinstance(p, pc) and isinstance(q, qc):
            return fn(p, q)
    raise NotImplementedError(
        "no KL registered for (%s, %s)"
        % (type(p).__name__, type(q).__name__))


@register_kl(Normal, Normal)
def _kl_normal_normal(p, q):
    var_ratio = (p.scale / q.scale) ** 2
    t1 = ((p.loc - q.loc) / q.scale) ** 2
    return Tensor(0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio)))


@register_kl(Uniform, Uniform)
def _kl_uniform_uniform(p, q):
    return Tensor(jnp.log((q.high - q.low) / (p.high - p.low)))


@register_kl(Categorical, Categorical)
def _kl_cat_cat(p, q):
    lp = jax.nn.log_softmax(p.logits, -1)
    lq = jax.nn.log_softmax(q.logits, -1)
    return Tensor(jnp.sum(jnp.exp(lp) * (lp - lq), -1))


@register_kl(Bernoulli, Bernoulli)
def _kl_bern_bern(p, q):
    pp = jnp.clip(p.probs, 1e-12, 1 - 1e-12)
    qq = jnp.clip(q.probs, 1e-12, 1 - 1e-12)
    return Tensor(pp * (jnp.log(pp) - jnp.log(qq))
                  + (1 - pp) * (jnp.log1p(-pp) - jnp.log1p(-qq)))


@register_kl(Exponential, Exponential)
def _kl_exp_exp(p, q):
    r = q.rate / p.rate
    return Tensor(jnp.log(p.rate) - jnp.log(q.rate) + r - 1)


class ExponentialFamily(Distribution):
    """Base for exponential-family distributions (reference
    distribution/exponential_family.py): entropy via the Bregman
    divergence of the log-normalizer (autodiff of _log_normalizer at the
    natural parameters)."""

    @property
    def _natural_parameters(self):
        raise NotImplementedError

    def _log_normalizer(self, *natural_params):
        raise NotImplementedError

    @property
    def _mean_carrier_measure(self):
        raise NotImplementedError

    def entropy(self):
        import jax

        nat = tuple(jnp.asarray(p) for p in self._natural_parameters)

        def f(ps):
            return jnp.sum(self._log_normalizer(*ps))

        lg = self._log_normalizer(*nat)
        gs = jax.grad(f)(nat)
        result = -self._mean_carrier_measure + lg
        for np_, g in zip(nat, gs):
            result = result - np_ * g
        return result if isinstance(result, Tensor) else Tensor(result)


class Independent(Distribution):
    """Reinterpret trailing batch dims as event dims (reference
    distribution/independent.py): log_prob sums over them."""

    def __init__(self, base, reinterpreted_batch_rank):
        if reinterpreted_batch_rank < 1:
            raise ValueError(
                "reinterpreted_batch_rank must be >= 1")
        self._base = base
        self._rank = int(reinterpreted_batch_rank)

    @property
    def mean(self):
        return self._base.mean

    @property
    def variance(self):
        return self._base.variance

    def sample(self, shape=()):
        return self._base.sample(shape)

    def rsample(self, shape=()):
        return self._base.rsample(shape)

    def log_prob(self, value):
        lp = self._base.log_prob(value)
        v = lp._value if isinstance(lp, Tensor) else jnp.asarray(lp)
        return Tensor(v.sum(axis=tuple(range(v.ndim - self._rank,
                                             v.ndim))))

    def prob(self, value):
        lp = self.log_prob(value)
        return Tensor(jnp.exp(lp._value))

    def entropy(self):
        e = self._base.entropy()
        v = e._value if isinstance(e, Tensor) else jnp.asarray(e)
        return Tensor(v.sum(axis=tuple(range(v.ndim - self._rank,
                                             v.ndim))))


class TransformedDistribution(Distribution):
    """Distribution of transform(base_sample) (reference
    distribution/transformed_distribution.py): log_prob via the inverse
    map and the log|det J| correction. `transforms` expose
    forward/inverse/forward_log_det_jacobian (paddle Transform protocol
    or any object with those callables)."""

    def __init__(self, base, transforms):
        self._base = base
        self._transforms = list(transforms)

    def sample(self, shape=()):
        x = self._base.sample(shape)
        for t in self._transforms:
            x = t.forward(x)
        return x

    def rsample(self, shape=()):
        x = self._base.rsample(shape) if hasattr(self._base, "rsample") \
            else self._base.sample(shape)
        for t in self._transforms:
            x = t.forward(x)
        return x

    def log_prob(self, value):
        y = value
        ldj = 0.0
        for t in reversed(self._transforms):
            x = t.inverse(y)
            term = t.forward_log_det_jacobian(x)
            term = term._value if isinstance(term, Tensor) else term
            ldj = ldj + term
            y = x
        base_lp = self._base.log_prob(y)
        blp = base_lp._value if isinstance(base_lp, Tensor) else base_lp
        return Tensor(blp - ldj)

    def prob(self, value):
        return Tensor(jnp.exp(self.log_prob(value)._value))
