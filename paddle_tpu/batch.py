"""paddle.batch — batched-reader combinator.

Parity: reference python/paddle/batch.py (legacy reader-decorator API:
wrap a sample generator into a mini-batch generator). Kept for code
ported from reader-style pipelines; new code uses paddle.io.DataLoader.
"""
from __future__ import annotations

__all__ = ["batch"]


def batch(reader, batch_size, drop_last=False):
    """Turn a sample reader into a batched reader (reference batch.py)."""
    if batch_size <= 0:
        raise ValueError(
            "batch_size should be a positive integer, got %r" % batch_size)

    def batch_reader():
        buf = []
        for sample in reader():
            buf.append(sample)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf

    return batch_reader
