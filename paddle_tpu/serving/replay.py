"""Deterministic request record/replay journal (ptreplay, ISSUE 20).

Every serving guarantee this repo ships — quant-kv greedy
token-identity, prefix/chunked flags-off bit-identity, compile-once —
is pinned only inside unit tests; a *running* engine keeps no record
of what it served, so a production divergence (wrong tokens after a
flag flip, a canary replica drifting from the fleet) is invisible and
unreproducible. This module is the record half of the answer:

1. **Recorder** — a bounded journal of served requests
   (``PT_REPLAY_CAPACITY``, finished-evicted-first like the trace
   store). At admission the engine's latched recorder handle captures
   everything deterministic re-execution needs: prompt token ids,
   sampling params (greedy today; the seed slot is where a sampler's
   RNG key lands), the engine's latched flag snapshot (prefix x
   chunked x quant axes), weights generation, and the capability
   snapshot (slots/pages/chunk — the shapes the compiled step was
   built for). At the terminal it stamps the outcome digest: output
   token ids + a rolling token hash, per-request phase timings,
   preempt/resume count, prefix-cache hit tokens, shed/expired
   reason.

2. **Artifact** — ``write_journal(path)`` emits a versioned JSONL
   artifact (header line with a wall<->monotonic clock anchor — the
   PR-6 trace_journal discipline — then one line per request);
   ``tools/ptreplay.py run`` re-drives a freshly built REAL engine
   from it and diffs token-for-token, ``--matrix`` bisects which flag
   axis introduced a divergence. Greedy decode is deterministic per
   slot (paged attention gathers each request's own pages), so replay
   order/batching doesn't matter and the one compiled step makes the
   re-execution cost no recompiles.

3. **Fleet cross-links** — the router journals its dispatch decisions
   (``note_dispatch``: request -> replica endpoint, reroute nonces)
   keyed by the same trace ids the engine entries carry, so a fleet
   capture can reassemble per-replica journals into one replayable
   workload; ``/debugz/replay`` serves the summary + per-request
   digests with ``trace_id`` cross-links into the trace plane.

Division of labor (README "Record/replay"): the flight recorder
replays *collectives*, the trace plane replays *journeys*, this plane
replays *execution* — it is the proof layer, not a telemetry layer.

Discipline (the PR-2/5/6 contract, test-pinned by
tests/test_replay.py): default OFF via ``FLAGS_serving_replay``;
while off the engine's recorder handle is ``None`` (zero journal
allocations on the hot path), this module NEVER has threads, the
``replay_*`` series stay unminted, and every payload the engine or
fleet wire produces is bit-identical to a build without this module.
Stdlib-only so worker processes can import it without an accelerator
backend.
"""
from __future__ import annotations

import os
import threading
import time

from ..core import flags as _coreflags
from ..monitor import counter as _mcounter
from ..monitor.registry import warn_once as _warn_once

JOURNAL_VERSION = 1
DEFAULT_CAPACITY = 256          # retained request entries
_DISPATCH_CAP = 1024            # router dispatch-decision ring

# the flag axes the recorder snapshots per entry and tools/ptreplay.py
# --matrix bisects over (one flip per axis vs the recorded baseline)
FLAG_AXES = (
    ("prefix", "FLAGS_serving_prefix_cache"),
    ("chunked", "FLAGS_serving_chunked_prefill"),
    ("quant_kv", "FLAGS_serving_quant_kv"),
    ("quant_weights", "FLAGS_serving_quant_weights"),
)

# registry metrics (lazy series: nothing exists until the first
# recorded admission with the plane enabled — the series-free pin)
_RECORDED = _mcounter(
    "replay_requests_recorded_total",
    "requests captured into the record/replay journal at admission")
_EVICTED = _mcounter(
    "replay_journal_evictions_total",
    "journal entries evicted past PT_REPLAY_CAPACITY "
    "(finished-first)")
_DIVERGED = _mcounter(
    "replay_divergences_total",
    "replayed requests whose tokens diverged from the recording, by "
    "the bisected axis (weights | prefix | chunked | quant_kv | "
    "quant_weights | unknown)", labelnames=("axis",))


def token_hash(tokens):
    """Rolling FNV-1a-64 over token ids, as a hex digest: the
    order-sensitive digest two artifacts compare for token identity
    without shipping full outputs. Incremental by construction —
    ``token_hash(a + b)`` picks up where ``token_hash(a)`` left off —
    so a future streaming recorder can fold tokens as they land."""
    h = 0xcbf29ce484222325
    for t in tokens:
        h ^= int(t) & 0xFFFFFFFFFFFFFFFF
        h = (h * 0x100000001b3) & 0xFFFFFFFFFFFFFFFF
    return "%016x" % h


class _ReplayState:
    __slots__ = ("enabled", "lock", "capacity", "entries", "order",
                 "recorded", "evictions", "dispatches", "engines",
                 "model_meta", "next_engine")

    def __init__(self):
        self.enabled = False
        self.lock = threading.Lock()
        self.capacity = int(os.environ.get("PT_REPLAY_CAPACITY",
                                           DEFAULT_CAPACITY) or
                            DEFAULT_CAPACITY)
        self.entries = {}       # request id -> entry dict (insertion-ordered)
        self.order = None       # unused; dict preserves admission order
        self.recorded = 0
        self.evictions = 0
        self.dispatches = []    # router dispatch decisions, bounded
        self.engines = {}       # engine id -> capability snapshot
        self.model_meta = None  # how to rebuild the model (note_model)
        self.next_engine = 0


_state = _ReplayState()


# -- lifecycle ---------------------------------------------------------------

def enable(capacity=None):
    """Turn the journal on (process-wide). Idempotent; capacity only
    affects future evictions. No threads are started — recording rides
    the engine's own call stack."""
    if capacity is not None:
        _state.capacity = max(int(capacity), 1)
    _state.enabled = True
    return _state


def disable():
    """Stop recording. Recorded entries are kept (inspectable
    post-incident); ``clear()`` drops them."""
    _state.enabled = False


def is_enabled():
    return _state.enabled


def clear():
    """Drop everything recorded AND restore the env-default capacity —
    a test/tool that narrowed the journal via ``enable(capacity=...)``
    must not leak that bound into the next recording."""
    with _state.lock:
        _state.entries = {}
        _state.recorded = 0
        _state.evictions = 0
        _state.dispatches = []
        _state.engines = {}
        _state.model_meta = None
        _state.capacity = int(os.environ.get("PT_REPLAY_CAPACITY",
                                             DEFAULT_CAPACITY) or
                              DEFAULT_CAPACITY)


def drop_entries():
    """Forget recorded request entries (and dispatch rows) while
    keeping engine capability snapshots and model meta. Benchmarks
    call this after compile warmup so the journal holds the measured
    workload only — warmup requests are shape-probes, not workload."""
    with _state.lock:
        _state.entries = {}
        _state.recorded = 0
        _state.evictions = 0
        _state.dispatches = []


# -- recorder ----------------------------------------------------------------

def _evict_locked():
    """Drop oldest entries past capacity — terminal ones first, but
    bounded beats complete: an all-open journal still evicts."""
    while len(_state.entries) > _state.capacity:
        victim = None
        for rid, ent in _state.entries.items():
            if ent["state"] != "open":
                victim = rid
                break
        if victim is None:
            victim = next(iter(_state.entries))
        del _state.entries[victim]
        _state.evictions += 1
        _EVICTED.inc()


class _Recorder:
    """Per-engine recorder handle, latched by ``Engine.__init__`` when
    FLAGS_serving_replay is on (``None`` otherwise — the hot-path
    branch). Holds the engine's capability + flag snapshot computed
    ONCE so per-request capture is dict assembly, never flag reads."""

    __slots__ = ("engine_id", "flags", "caps", "_engine")

    def __init__(self, engine):
        import weakref

        self._engine = weakref.ref(engine)
        with _state.lock:
            self.engine_id = _state.next_engine
            _state.next_engine += 1
        # the latched axes, read back from the ENGINE's own latches
        # (not the live flag table): the snapshot must name what this
        # engine actually compiled, surviving any later flag flip
        self.flags = {
            "FLAGS_serving_prefix_cache": engine.prefix_cache is not None,
            "FLAGS_serving_chunked_prefill": bool(engine.chunked_prefill),
            "FLAGS_serving_quant_kv": bool(engine.quant_kv),
            "FLAGS_serving_quant_weights": bool(engine.quant_weights),
        }
        self.caps = {
            "max_slots": engine.max_slots,
            "block_size": engine.block_size,
            "num_blocks": engine.cache.allocator.num_blocks,
            "max_model_len": engine.max_model_len,
            "prefill_chunk": engine.prefill_chunk,
            "max_queue": engine.max_queue,
        }
        with _state.lock:
            _state.engines[self.engine_id] = {
                "flags": dict(self.flags), "caps": dict(self.caps)}

    def admit(self, req, deadline_s=None):
        """Admission capture: everything deterministic re-execution
        needs, stamped the moment the engine owns the request."""
        if not _state.enabled:
            return
        eng = self._engine()
        entry = {
            "id": req.id,
            "engine": self.engine_id,
            "trace_id": req.trace_id,
            "admitted_wall": time.time(),
            "admitted_mono": time.monotonic(),
            "prompt": list(req.prompt),
            "max_new_tokens": req.max_new_tokens,
            "eos_token_id": req.eos_token_id,
            "deadline_s": deadline_s,
            # greedy decode takes no RNG; the seed slot is where a
            # future sampler records its key so replay can re-seed
            "sampling": {"mode": "greedy", "rng_seed": None},
            "flags": self.flags,
            "weights_generation": (0 if eng is None
                                   else eng.weights_generation),
            "state": "open",
        }
        with _state.lock:
            _state.entries[req.id] = entry
            _state.recorded += 1
            _evict_locked()
        _RECORDED.inc()

    def terminal(self, req):
        """Terminal capture (finished OR expired/shed/failed): the
        outcome digest replay compares against. A no-op when the entry
        was already evicted — bounded beats complete."""
        if not _state.enabled:
            return
        m = req.metrics
        with _state.lock:
            entry = _state.entries.get(req.id)
            if entry is None:
                return
            entry["state"] = req.state.value
            entry["reason"] = req.status_reason
            entry["output"] = list(req.generated)
            entry["output_token_hash"] = token_hash(req.generated)
            entry["preemptions"] = m.preemptions
            entry["prefix_cached_tokens"] = m.prefix_cached_tokens
            entry["completed_wall"] = time.time()
            d = m.to_dict()
            entry["timings_s"] = {
                "queue": d.get("queue_time_s"),
                "ttft": d.get("ttft_s"),
                "tpot": d.get("tpot_s"),
                "e2e": d.get("e2e_s"),
            }


def recorder(engine):
    """The Engine's latch point: a live ``_Recorder`` iff
    FLAGS_serving_replay is on at construction, else ``None`` — the
    flags-off hot path is one handle-is-None branch per site (the
    monitor memory/profile handle discipline)."""
    if not _coreflags.flag("FLAGS_serving_replay"):
        return None
    if not _state.enabled:
        enable()
    return _Recorder(engine)


# -- fleet cross-links -------------------------------------------------------

def note_dispatch(trace_id=None, nonce=None, rank=None, endpoint=None,
                  attempt=None, outcome=None, reason=None):
    """Router-side journal of one dispatch decision (request ->
    replica endpoint, reroute nonces), keyed by the same trace id the
    replica's engine entry will carry — the stitch a fleet capture
    reassembles per-replica journals with. Bounded ring; no-op while
    the plane is off (one attribute load + branch)."""
    if not _state.enabled:
        return
    rec = {"trace_id": trace_id, "nonce": nonce, "rank": rank,
           "endpoint": endpoint, "attempt": attempt,
           "outcome": outcome, "reason": reason, "wall": time.time()}
    with _state.lock:
        _state.dispatches.append(rec)
        if len(_state.dispatches) > _DISPATCH_CAP:
            del _state.dispatches[:len(_state.dispatches)
                                  - _DISPATCH_CAP]


def note_model(meta):
    """Record how to rebuild the model (config kwargs + init seed +
    preset name): ``tools/ptreplay.py`` reconstructs the weights from
    this, so it lands in the journal header. Merges over repeat
    calls."""
    if not _state.enabled:
        return
    with _state.lock:
        if _state.model_meta is None:
            _state.model_meta = {}
        _state.model_meta.update(meta)


def note_divergence(axis, count=1, report=None):
    """Count a replay divergence against its bisected axis and open a
    ``replay_divergence`` incident (no-op while FLAGS_monitor_slo is
    off — the incident plane's own discipline) with the divergence
    report as evidence."""
    _DIVERGED.labels(axis=axis).inc(count)
    try:
        from ..monitor import incidents as _incidents

        _incidents.open(
            "replay/divergence/%s" % axis, severity="ticket",
            kind="replay_divergence", source="replay",
            summary="%d replayed request(s) diverged from the "
                    "recording (axis: %s)" % (count, axis),
            evidence={"report": report} if report else None)
    except Exception as e:
        _warn_once("replay.incident",
                   "paddle_tpu.serving.replay: incident open failed: "
                   "%r" % (e,))


# -- export ------------------------------------------------------------------

def _digest_locked(entry):
    """One /debugz/replay row: the entry minus its token payloads."""
    out = {
        "id": entry["id"],
        "trace_id": entry["trace_id"],
        "state": entry["state"],
        "prompt_tokens": len(entry["prompt"]),
        "max_new_tokens": entry["max_new_tokens"],
        "weights_generation": entry["weights_generation"],
        "flags": {axis: entry["flags"][name]
                  for axis, name in FLAG_AXES},
    }
    if entry["state"] != "open":
        out["reason"] = entry.get("reason")
        out["output_tokens"] = len(entry.get("output") or ())
        out["output_token_hash"] = entry.get("output_token_hash")
        out["preemptions"] = entry.get("preemptions")
    return out


def payload():
    """The /debugz/replay JSON body. The disabled body is pinned
    bit-identical to the literal the exporter serves when this module
    was never imported (tests/test_debugz_routes.py)."""
    if not _state.enabled:
        return {"enabled": False, "requests": [], "dispatches": 0}
    with _state.lock:
        rows = [_digest_locked(e) for e in _state.entries.values()]
        n_disp = len(_state.dispatches)
        recent = [dict(d) for d in _state.dispatches[-16:]]
        model = (dict(_state.model_meta)
                 if _state.model_meta is not None else None)
    return {
        "enabled": True,
        "capacity": _state.capacity,
        "recorded_total": _state.recorded,
        "evictions": _state.evictions,
        "entries": len(rows),
        "open": sum(1 for r in rows if r["state"] == "open"),
        "model": model,
        "requests": rows,
        "dispatches": n_disp,
        "dispatches_recent": recent,
    }


def header():
    """The journal header (JSONL line 1): version + clock anchor (the
    trace_journal discipline: wall-stamped entries, the anchor is the
    same-process shift onto the monotonic timebase) + everything
    needed to rebuild the serving setup."""
    with _state.lock:
        engines = {str(eid): {"flags": dict(s["flags"]),
                              "caps": dict(s["caps"])}
                   for eid, s in _state.engines.items()}
        model = (dict(_state.model_meta)
                 if _state.model_meta is not None else None)
        n = len(_state.entries)
        disp = [dict(d) for d in _state.dispatches]
    return {
        "kind": "replay_journal",
        "version": JOURNAL_VERSION,
        "pid": os.getpid(),
        "written_at": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                    time.gmtime()),
        "clock_anchor": {"wall": time.time(),
                         "monotonic": time.monotonic()},
        "model": model,
        "engines": engines,
        "requests": n,
        "recorded_total": _state.recorded,
        "evictions": _state.evictions,
        "dispatches": disp,
    }


def write_journal(path):
    """Persist the journal as versioned JSONL: header line, then one
    line per request entry in admission order. Atomic (tmp + replace);
    returns (header, entries)."""
    import json

    head = header()
    with _state.lock:
        entries = [dict(e, flags=dict(e["flags"]))
                   for e in _state.entries.values()]
    d = os.path.dirname(os.path.abspath(path))
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(json.dumps(head, default=str) + "\n")
        for e in entries:
            f.write(json.dumps(e, default=str) + "\n")
    os.replace(tmp, path)
    return head, entries


def load_journal(path):
    """Parse a JSONL journal back into (header, entries); raises
    ValueError on a kind/version mismatch (a journal from a future
    schema must fail loudly, not replay garbage)."""
    import json

    with open(path) as f:
        lines = [ln for ln in f.read().splitlines() if ln.strip()]
    if not lines:
        raise ValueError("empty replay journal: %s" % path)
    head = json.loads(lines[0])
    if head.get("kind") != "replay_journal":
        raise ValueError("not a replay journal (kind=%r): %s"
                         % (head.get("kind"), path))
    if head.get("version") != JOURNAL_VERSION:
        raise ValueError(
            "replay journal version %r != supported %d: %s"
            % (head.get("version"), JOURNAL_VERSION, path))
    return head, [json.loads(ln) for ln in lines[1:]]


# env/FLAGS bootstrap (the trace/timeseries discipline): a process
# started with FLAGS_serving_replay=1 records from the first engine.
if _coreflags.flag("FLAGS_serving_replay"):
    enable()
