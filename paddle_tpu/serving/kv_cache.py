"""Block-paged KV cache: fixed page pools + per-request block tables.

Memory model (Ragged Paged Attention / vLLM, PAPERS.md arxiv
2604.15464): each layer owns a fixed pool of
``[num_blocks, block_size, kv_heads, head_dim]`` pages; a request holds
an ordered list of page ids (its block table row) covering positions
``0..seq_len-1`` via ``page = table[pos // block_size]``,
``offset = pos % block_size``. Pages are allocated on demand and
returned to the free list when the request finishes or is preempted —
KV memory scales with TOKENS IN FLIGHT, not with
``max_slots * max_model_len`` the way generation.py's dense
``DecodeCache`` does.

Page 0 is reserved as the TRASH page: block-table rows are 0-padded, so
writes for pad positions (right-padded prefill, idle decode slots) land
in trash instead of corrupting live pages, and every write stays a
single unconditional scatter — no masking inside the compiled step.

The ``PagedPrefillView`` / ``PagedDecodeView`` classes are the
per-layer external-cache attention hook: model attention layers that
see a cache object with ``update_and_attend`` hand it (q, k, v) and get
the attention context back (models/llama.py, models/gpt.py). The
ENGINE owns the pools, tables and lengths; the model never holds cache
state. Views are created inside the jitted step from traced pool
arrays and return updated views — functional, like DecodeCache.
"""
from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
import numpy as np

TRASH_BLOCK = 0


class KVBlockPool(NamedTuple):
    """One layer's page pools: k/v [num_blocks, block_size, Hkv, D]."""

    k: "object"
    v: "object"


class BlockAllocator:
    """Host-side free-list over page ids 1..num_blocks-1 (0 is trash).

    ``alloc`` returns None — the explicit out-of-blocks signal — instead
    of raising: the scheduler turns it into preempt-and-requeue."""

    def __init__(self, num_blocks):
        if num_blocks < 2:
            raise ValueError("need >= 2 blocks (page 0 is the trash page)")
        self.num_blocks = num_blocks
        # LIFO keeps recently-freed (cache-warm) pages in circulation
        self._free = list(range(num_blocks - 1, 0, -1))

    @property
    def free_blocks(self):
        return len(self._free)

    @property
    def usable_blocks(self):
        return self.num_blocks - 1

    def alloc(self, n=1):
        """n page ids, or None when fewer than n pages are free."""
        if n > len(self._free):
            return None
        return [self._free.pop() for _ in range(n)]

    def free(self, ids):
        for i in ids:
            if not 0 < i < self.num_blocks or i in self._free:
                raise ValueError("bad free of page %r" % (i,))
            self._free.append(i)


class PagedKVCache:
    """Pools for every layer + the host-side table/length bookkeeping."""

    def __init__(self, num_layers, num_blocks, block_size, num_kv_heads,
                 head_dim, max_slots, max_blocks_per_slot,
                 dtype="float32"):
        dt = jnp.dtype(dtype)
        self.block_size = block_size
        self.max_slots = max_slots
        self.max_blocks_per_slot = max_blocks_per_slot
        self.pools = [
            KVBlockPool(
                jnp.zeros((num_blocks, block_size, num_kv_heads,
                           head_dim), dt),
                jnp.zeros((num_blocks, block_size, num_kv_heads,
                           head_dim), dt))
            for _ in range(num_layers)]
        self.allocator = BlockAllocator(num_blocks)
        self.block_tables = np.zeros((max_slots, max_blocks_per_slot),
                                     np.int32)
        self.seq_lens = np.zeros((max_slots,), np.int32)
        self._slot_pages = [[] for _ in range(max_slots)]

    def pages_needed(self, num_tokens):
        return -(-num_tokens // self.block_size)  # ceil

    def slot_page_count(self, slot):
        return len(self._slot_pages[slot])

    def ensure_capacity(self, slot, num_tokens):
        """Allocate pages so positions 0..num_tokens-1 are covered.
        Returns True, or False on pool exhaustion (nothing allocated —
        all-or-nothing, so a failed admission leaves no partial state)."""
        need = self.pages_needed(num_tokens) - len(self._slot_pages[slot])
        if need <= 0:
            return True
        if num_tokens > self.max_blocks_per_slot * self.block_size:
            raise ValueError(
                "%d tokens exceed the per-slot capacity %d"
                % (num_tokens, self.max_blocks_per_slot * self.block_size))
        pages = self.allocator.alloc(need)
        if pages is None:
            return False
        start = len(self._slot_pages[slot])
        self._slot_pages[slot].extend(pages)
        self.block_tables[slot, start:start + need] = pages
        return True

    def release_slot(self, slot):
        """Free the slot's pages back to the pool (finish/preempt)."""
        if self._slot_pages[slot]:
            self.allocator.free(self._slot_pages[slot])
        self._slot_pages[slot] = []
        self.block_tables[slot, :] = TRASH_BLOCK
        self.seq_lens[slot] = 0


def _raw(x):
    return x._value if hasattr(x, "_value") else jnp.asarray(x)


class PagedPrefillView:
    """One layer's hook for single-request prefill ([1, P] right-padded
    prompt): writes every position's K/V through the (trash-padded)
    block-table row in one scatter, then runs dense causal attention —
    rows past the true length attend only forward of real tokens, so
    real rows are exactly the unpadded computation."""

    def __init__(self, pool, table_row, block_size):
        self.pool = pool
        self.table_row = table_row            # [MB] int32, trash-padded
        self.block_size = block_size

    def update_and_attend(self, q, k, v):
        from ..nn import functional as F

        qv, kv, vv = _raw(q), _raw(k), _raw(v)
        p = kv.shape[1]
        pos = jnp.arange(p)
        pages = self.table_row[pos // self.block_size]
        offs = pos % self.block_size
        new_pool = KVBlockPool(
            self.pool.k.at[pages, offs].set(kv[0].astype(self.pool.k.dtype)),
            self.pool.v.at[pages, offs].set(vv[0].astype(self.pool.v.dtype)))
        heads, kv_heads = qv.shape[2], kv.shape[2]
        if heads != kv_heads:
            rep = heads // kv_heads
            kv = jnp.repeat(kv, rep, axis=2)
            vv = jnp.repeat(vv, rep, axis=2)
        out = F.scaled_dot_product_attention(qv, kv, vv, is_causal=True,
                                             _warn_rect_causal=False)
        return out, PagedPrefillView(new_pool, self.table_row,
                                     self.block_size)


class PagedDecodeView:
    """One layer's hook for the batched decode step ([S, 1] tokens, one
    per slot): scatters each slot's new K/V into page
    ``table[slot, len // bs]`` at offset ``len % bs`` (idle slots write
    trash), then attends over the paged history including the new token
    (effective length ``len + 1``) via the ragged paged-attention
    kernel/fallback."""

    def __init__(self, pool, block_tables, seq_lens, block_size):
        self.pool = pool
        self.block_tables = block_tables      # [S, MB] int32
        self.seq_lens = seq_lens              # [S] int32
        self.block_size = block_size

    def update_and_attend(self, q, k, v):
        from ..core.tensor import Tensor
        from .kernels.paged_attention import paged_attention

        qv, kv, vv = _raw(q), _raw(k), _raw(v)
        s = qv.shape[0]
        lens = self.seq_lens
        pages = self.block_tables[jnp.arange(s), lens // self.block_size]
        offs = lens % self.block_size
        new_pool = KVBlockPool(
            self.pool.k.at[pages, offs].set(
                kv[:, 0].astype(self.pool.k.dtype)),
            self.pool.v.at[pages, offs].set(
                vv[:, 0].astype(self.pool.v.dtype)))
        out = paged_attention(qv[:, 0], new_pool.k, new_pool.v,
                              self.block_tables, lens + 1)
        return Tensor(out[:, None]), PagedDecodeView(
            new_pool, self.block_tables, lens, self.block_size)
