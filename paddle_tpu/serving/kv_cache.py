"""Block-paged KV cache: fixed page pools + per-request block tables.

Memory model (Ragged Paged Attention / vLLM, PAPERS.md arxiv
2604.15464): each layer owns a fixed pool of
``[num_blocks, block_size, kv_heads, head_dim]`` pages; a request holds
an ordered list of page ids (its block table row) covering positions
``0..seq_len-1`` via ``page = table[pos // block_size]``,
``offset = pos % block_size``. Pages are allocated on demand and
returned to the free list when the request finishes or is preempted —
KV memory scales with TOKENS IN FLIGHT, not with
``max_slots * max_model_len`` the way generation.py's dense
``DecodeCache`` does.

Page 0 is reserved as the TRASH page: block-table rows are 0-padded, so
writes for pad positions (right-padded prefill, idle decode slots) land
in trash instead of corrupting live pages, and every write stays a
single unconditional scatter — no masking inside the compiled step.

Ownership is REFCOUNTED (serving tier 2): pages leave ``alloc`` at
refcount 1; the radix prefix cache (serving/prefix_cache.py) increfs
pages shared between its tree and the requests mapping their
block-table head onto a cached prompt prefix; ``release_slot`` decrefs
instead of freeing, and a write into a still-shared page goes through
the ``make_writable`` copy-on-write guard. With
FLAGS_serving_prefix_cache off nothing ever increfs and the allocator
behaves exactly as the original exclusive-owner free list.

The ``PagedPrefillView`` / ``PagedDecodeView`` / ``PagedMixedView``
classes are the per-layer external-cache attention hook: model
attention layers that see a cache object with ``update_and_attend``
hand it (q, k, v) and get the attention context back (models/llama.py,
models/gpt.py). The ENGINE owns the pools, tables and lengths; the
model never holds cache state. Views are created inside the jitted
step from traced pool arrays and return updated views — functional,
like DecodeCache. ``PagedMixedView`` is the ragged superset the other
two are special cases of: [S, C] rows of q_len new tokens each at
positions hist..hist+q_len-1, serving chunked prefill, prefix-cache
suffix prefill, and decode rows through one code path.
"""
from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
import numpy as np

TRASH_BLOCK = 0


class KVBlockPool(NamedTuple):
    """One layer's page pools: k/v [num_blocks, block_size, Hkv, D].

    Under FLAGS_serving_quant_kv the k/v planes are int8 and the
    per-(page, position, head) fp32 scale planes
    ``k_scale``/``v_scale`` [num_blocks, block_size, Hkv] live
    alongside them — same page ids, same scatter indices, donated and
    COW-cloned together. Flags-off they are None, which jax treats as
    an EMPTY pytree node: the flattened leaves (and therefore every
    compiled step's jaxpr) are bit-identical to the pre-quant build."""

    k: "object"
    v: "object"
    k_scale: "object" = None
    v_scale: "object" = None


class BlockAllocator:
    """Host-side free-list over page ids 1..num_blocks-1 (0 is trash).

    ``alloc`` returns None — the explicit out-of-blocks signal — instead
    of raising: the scheduler turns it into preempt-and-requeue.

    Ownership model: every allocated page carries a REFCOUNT. ``alloc``
    hands out pages at refcount 1 (the exclusive-owner fast path —
    without a prefix cache nothing ever increfs, and behavior is
    exactly the pre-refcount allocator). The prefix cache increfs pages
    it shares between a radix-tree node and the requests mapping their
    block-table head onto it; ``free``/``decref`` only return a page to
    the free list when the last reference drops. A page is free XOR
    refcounted — the double-free check is an O(1) set probe, not the
    O(n) list scan that made page-heavy teardown quadratic."""

    def __init__(self, num_blocks):
        if num_blocks < 2:
            raise ValueError("need >= 2 blocks (page 0 is the trash page)")
        self.num_blocks = num_blocks
        # LIFO keeps recently-freed (cache-warm) pages in circulation
        self._free = list(range(num_blocks - 1, 0, -1))
        self._free_set = set(self._free)
        self._refs = {}                 # page id -> refcount (> 0)

    @property
    def free_blocks(self):
        return len(self._free)

    @property
    def usable_blocks(self):
        return self.num_blocks - 1

    def alloc(self, n=1):
        """n page ids at refcount 1, or None when fewer than n are free."""
        if n > len(self._free):
            return None
        pages = [self._free.pop() for _ in range(n)]
        for p in pages:
            self._free_set.discard(p)
            self._refs[p] = 1
        return pages

    def refcount(self, i):
        return self._refs.get(i, 0)

    def incref(self, i):
        """Add a reference to an allocated page (prefix-cache sharing)."""
        if i not in self._refs:
            raise ValueError("incref of unallocated page %r" % (i,))
        self._refs[i] += 1

    def decref(self, i):
        """Drop one reference; the page returns to the free list when
        the LAST reference drops. Returns True when the page was freed."""
        if (not 0 < i < self.num_blocks or i in self._free_set
                or i not in self._refs):
            raise ValueError("bad free of page %r" % (i,))
        self._refs[i] -= 1
        if self._refs[i] == 0:
            del self._refs[i]
            self._free.append(i)
            self._free_set.add(i)
            return True
        return False

    def free(self, ids):
        for i in ids:
            self.decref(i)


class PagedKVCache:
    """Pools for every layer + the host-side table/length bookkeeping."""

    def __init__(self, num_layers, num_blocks, block_size, num_kv_heads,
                 head_dim, max_slots, max_blocks_per_slot,
                 dtype="float32", quantized=False):
        dt = jnp.dtype("int8") if quantized else jnp.dtype(dtype)
        self.block_size = block_size
        self.max_slots = max_slots
        self.max_blocks_per_slot = max_blocks_per_slot
        self.quantized = bool(quantized)
        page = (num_blocks, block_size, num_kv_heads, head_dim)
        # zero scales x zero int8 pages dequantize to exact zeros, so
        # trash/idle reads match the fp32 zero-init pools bit-for-bit
        scale = ((num_blocks, block_size, num_kv_heads)
                 if quantized else None)
        self.pools = [
            KVBlockPool(
                jnp.zeros(page, dt), jnp.zeros(page, dt),
                jnp.zeros(scale, jnp.float32) if quantized else None,
                jnp.zeros(scale, jnp.float32) if quantized else None)
            for _ in range(num_layers)]
        self.allocator = BlockAllocator(num_blocks)
        self.block_tables = np.zeros((max_slots, max_blocks_per_slot),
                                     np.int32)
        self.seq_lens = np.zeros((max_slots,), np.int32)
        self._slot_pages = [[] for _ in range(max_slots)]
        self.cow_clones = 0             # copy-on-write page splits

    def pages_needed(self, num_tokens):
        return -(-num_tokens // self.block_size)  # ceil

    def slot_page_count(self, slot):
        return len(self._slot_pages[slot])

    def slot_pages(self, slot):
        """The slot's page ids in position order (prefix-cache insert
        reads them; treat as read-only)."""
        return self._slot_pages[slot]

    def ensure_capacity(self, slot, num_tokens):
        """Allocate pages so positions 0..num_tokens-1 are covered.
        Returns True, or False on pool exhaustion (nothing allocated —
        all-or-nothing, so a failed admission leaves no partial state)."""
        need = self.pages_needed(num_tokens) - len(self._slot_pages[slot])
        if need <= 0:
            return True
        if num_tokens > self.max_blocks_per_slot * self.block_size:
            raise ValueError(
                "%d tokens exceed the per-slot capacity %d"
                % (num_tokens, self.max_blocks_per_slot * self.block_size))
        pages = self.allocator.alloc(need)
        if pages is None:
            return False
        start = len(self._slot_pages[slot])
        self._slot_pages[slot].extend(pages)
        self.block_tables[slot, start:start + need] = pages
        return True

    def adopt_prefix(self, slot, pages, matched_tokens):
        """Map an (empty) slot's block-table head onto SHARED prefix
        pages from the radix cache: each page gains a reference for
        this slot, ``seq_lens`` starts at the matched token count, and
        the request only prefills the uncached suffix. The caller has
        already verified free-block capacity for that suffix."""
        assert not self._slot_pages[slot], "adopt into a non-empty slot"
        for p in pages:
            self.allocator.incref(p)
        self._slot_pages[slot] = list(pages)
        self.block_tables[slot, :len(pages)] = pages
        self.seq_lens[slot] = matched_tokens

    def make_writable(self, slot, start, end):
        """Copy-on-write guard: every page covering positions
        ``[start, end)`` the slot is about to WRITE must be exclusively
        owned. A shared page (a partially-matched prefix page, refcount
        > 1) is cloned — pool K/V copied for every layer, block table
        repointed, old reference dropped — so the write never corrupts
        the other holders' history. Returns False when the pool cannot
        supply a clone page (caller reclaims/preempts and retries) —
        already-cloned pages stay valid, so the retry is incremental."""
        if end <= start:
            return True
        ok = True
        src, dst = [], []
        for idx in range(start // self.block_size,
                         -(-end // self.block_size)):
            page = self._slot_pages[slot][idx]
            if self.allocator.refcount(page) <= 1:
                continue
            new = self.allocator.alloc(1)
            if new is None:
                ok = False          # partial progress kept (see above)
                break
            new = new[0]
            src.append(page)
            dst.append(new)
            self.allocator.decref(page)
            self._slot_pages[slot][idx] = new
            self.block_tables[slot, idx] = new
            self.cow_clones += 1
        if src:
            # ONE batched gather-scatter per pool for the whole call —
            # a functional .at[].set copies the entire pool buffer, so
            # per-page updates would pay that copy once per clone
            s = jnp.asarray(src, jnp.int32)
            d = jnp.asarray(dst, jnp.int32)
            # _replace keeps the scale planes; under quant they are
            # cloned with the same batched gather-scatter so a COW'd
            # page carries its scales (shared holders keep theirs)
            self.pools = [
                p._replace(
                    k=p.k.at[d].set(p.k[s]), v=p.v.at[d].set(p.v[s]),
                    **({} if p.k_scale is None else {
                        "k_scale": p.k_scale.at[d].set(p.k_scale[s]),
                        "v_scale": p.v_scale.at[d].set(p.v_scale[s])}))
                for p in self.pools]
        return ok

    def release_slot(self, slot):
        """Release the slot's page references (finish/preempt). A page
        the prefix cache still references survives — release DECREFS
        instead of freeing, so a finished request's prefix stays warm
        for the next request that shares it."""
        if self._slot_pages[slot]:
            self.allocator.free(self._slot_pages[slot])
        self._slot_pages[slot] = []
        self.block_tables[slot, :] = TRASH_BLOCK
        self.seq_lens[slot] = 0

    def pools_alive(self):
        """False once the pool buffers were CONSUMED by donation: the
        engine's compiled steps donate their input pools
        (``donate_argnums``), so a step that raises AFTER execution
        started leaves these arrays deleted — readable shape/dtype,
        unreadable data."""
        try:
            return not any(p.k.is_deleted() or p.v.is_deleted()
                           for p in self.pools)
        except AttributeError:      # non-jax pools (unit fixtures)
            return True

    def reset_pools(self):
        """Fresh zeroed pool plane + allocator + per-slot bookkeeping —
        the donated-pools failure recovery. When a compiled step
        consumes its input pools (donation) and then fails, every KV
        byte is gone and every page mapping refers to garbage; the
        caller requeues the occupied slots first (preempt-by-recompute
        re-prefills from host-side tokens, so nothing durable lived
        only in the pools) and then rebuilds the plane here. Shapes
        and dtypes survive a deleted jax array, so the new pools match
        the compiled steps' signatures exactly — no retrace."""
        self.pools = [
            KVBlockPool(*[None if x is None
                          else jnp.zeros(x.shape, x.dtype) for x in p])
            for p in self.pools]
        self.allocator = BlockAllocator(int(self.pools[0].k.shape[0]))
        self.block_tables[:] = TRASH_BLOCK
        self.seq_lens[:] = 0
        self._slot_pages = [[] for _ in range(self.max_slots)]


def _raw(x):
    return x._value if hasattr(x, "_value") else jnp.asarray(x)


def _write_pages(pool, pages, offs, kv, vv):
    """Scatter fresh K/V into the pool planes at ``(pages, offs)`` —
    the views' single unconditional write. With int8 pools (scale
    planes present) each (position, head) head_dim vector is quantized
    AT WRITE TIME and its scale lands in the scale plane at the same
    indices, so the trash-page discipline covers scales for free: a pad
    position's quantized garbage and its scale both land in page 0."""
    if pool.k_scale is None:
        return pool._replace(
            k=pool.k.at[pages, offs].set(kv.astype(pool.k.dtype)),
            v=pool.v.at[pages, offs].set(vv.astype(pool.v.dtype)))
    from ..kernels.quant import quantize_int8_page

    kq, ks = quantize_int8_page(kv)
    vq, vs = quantize_int8_page(vv)
    return pool._replace(
        k=pool.k.at[pages, offs].set(kq),
        v=pool.v.at[pages, offs].set(vq),
        k_scale=pool.k_scale.at[pages, offs].set(ks),
        v_scale=pool.v_scale.at[pages, offs].set(vs))


class PagedPrefillView:
    """One layer's hook for single-request prefill ([1, P] right-padded
    prompt): writes every position's K/V through the (trash-padded)
    block-table row in one scatter, then runs dense causal attention —
    rows past the true length attend only forward of real tokens, so
    real rows are exactly the unpadded computation."""

    def __init__(self, pool, table_row, block_size):
        self.pool = pool
        self.table_row = table_row            # [MB] int32, trash-padded
        self.block_size = block_size

    def update_and_attend(self, q, k, v):
        from ..nn import functional as F

        qv, kv, vv = _raw(q), _raw(k), _raw(v)
        p = kv.shape[1]
        pos = jnp.arange(p)
        pages = self.table_row[pos // self.block_size]
        offs = pos % self.block_size
        new_pool = _write_pages(self.pool, pages, offs, kv[0], vv[0])
        # prefill attends over the raw fp32 fresh K/V (dense causal),
        # never the pool — quantization error only enters on pool READS
        heads, kv_heads = qv.shape[2], kv.shape[2]
        if heads != kv_heads:
            rep = heads // kv_heads
            kv = jnp.repeat(kv, rep, axis=2)
            vv = jnp.repeat(vv, rep, axis=2)
        out = F.scaled_dot_product_attention(qv, kv, vv, is_causal=True,
                                             _warn_rect_causal=False)
        return out, PagedPrefillView(new_pool, self.table_row,
                                     self.block_size)


class PagedDecodeView:
    """One layer's hook for the batched decode step ([S, 1] tokens, one
    per slot): scatters each slot's new K/V into page
    ``table[slot, len // bs]`` at offset ``len % bs`` (idle slots write
    trash), then attends over the paged history including the new token
    (effective length ``len + 1``) via the ragged paged-attention
    kernel/fallback."""

    def __init__(self, pool, block_tables, seq_lens, block_size):
        self.pool = pool
        self.block_tables = block_tables      # [S, MB] int32
        self.seq_lens = seq_lens              # [S] int32
        self.block_size = block_size

    def update_and_attend(self, q, k, v):
        from ..core.tensor import Tensor
        from .kernels.paged_attention import paged_attention

        qv, kv, vv = _raw(q), _raw(k), _raw(v)
        s = qv.shape[0]
        lens = self.seq_lens
        pages = self.block_tables[jnp.arange(s), lens // self.block_size]
        offs = lens % self.block_size
        new_pool = _write_pages(self.pool, pages, offs, kv[:, 0], vv[:, 0])
        out = paged_attention(qv[:, 0], new_pool.k, new_pool.v,
                              self.block_tables, lens + 1,
                              k_scale=new_pool.k_scale,
                              v_scale=new_pool.v_scale)
        return Tensor(out[:, None]), PagedDecodeView(
            new_pool, self.block_tables, lens, self.block_size)


class PagedMixedView:
    """One layer's hook for the MIXED ragged step ([S, C] tokens): row
    ``s`` holds ``q_lens[s]`` valid new tokens at absolute positions
    ``hist_lens[s] .. hist_lens[s] + q_lens[s] - 1`` (0 = idle row). A
    decode row is the ``q_len == 1`` special case; a prefill chunk is
    ``1 < q_len <= C``; the prefix-cache suffix prefill is the ``S == 1``
    case with ``hist = cached tokens``. Every valid position's K/V
    scatters through the slot's block-table row; PAD positions
    (``j >= q_len``) route to the trash page — the same unconditional-
    scatter discipline as the prefill/decode views, so no masking is
    needed inside the compiled step. Attention runs over the POOL
    (history plus the chunk's own freshly-written K/V) with the ragged
    causal rule ``key position <= hist + j``."""

    def __init__(self, pool, block_tables, hist_lens, q_lens, block_size):
        self.pool = pool
        self.block_tables = block_tables      # [S, MB] int32
        self.hist_lens = hist_lens            # [S] int32 (pool history)
        self.q_lens = q_lens                  # [S] int32 (new tokens)
        self.block_size = block_size

    def update_and_attend(self, q, k, v):
        from ..core.tensor import Tensor
        from .kernels.paged_attention import mixed_paged_attention

        qv, kv, vv = _raw(q), _raw(k), _raw(v)
        s, c = qv.shape[0], qv.shape[1]
        mb = self.block_tables.shape[1]
        pos = self.hist_lens[:, None] + jnp.arange(c)[None, :]  # [S, C]
        valid = jnp.arange(c)[None, :] < self.q_lens[:, None]
        # pad positions may index past the table (clamped gather) but
        # their write is rerouted to the trash page anyway
        page_idx = jnp.clip(pos // self.block_size, 0, mb - 1)
        pages = jnp.where(
            valid, jnp.take_along_axis(self.block_tables, page_idx,
                                       axis=1), TRASH_BLOCK)
        offs = jnp.where(valid, pos % self.block_size, 0)
        new_pool = _write_pages(self.pool, pages, offs, kv, vv)
        out = mixed_paged_attention(qv, new_pool.k, new_pool.v,
                                    self.block_tables, self.hist_lens,
                                    self.q_lens,
                                    k_scale=new_pool.k_scale,
                                    v_scale=new_pool.v_scale)
        return Tensor(out), PagedMixedView(
            new_pool, self.block_tables, self.hist_lens, self.q_lens,
            self.block_size)
