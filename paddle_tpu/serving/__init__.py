"""Continuous-batching LLM serving engine (paged KV cache).

The training side of this framework compiles ONE XLA program per
(model, config) and streams batches through it; this package gives
inference the same shape discipline under serving traffic:

- ``kv_cache``: a block-paged KV cache — a fixed pool of
  ``[num_blocks, block_size, kv_heads, head_dim]`` pages per layer,
  per-request block tables, and a host-side allocator with an explicit
  out-of-blocks signal (the vLLM/Ragged-Paged-Attention memory model,
  PAPERS.md arxiv 2604.15464).
- ``kernels.paged_attention``: a Pallas ragged paged-attention decode
  kernel (one query token per slot, K/V gathered through the block
  table) with a jnp fallback that is exact against
  ``masked_decode_attention``.
- ``scheduler`` / ``engine``: request lifecycle (queued → prefill →
  decoding → finished/preempted), FCFS admission control, slot reuse on
  EOS, preemption-with-requeue on pool exhaustion — all driven by ONE
  jitted decode step over a fixed ``max_slots`` batch, so XLA compiles
  the decode exactly once per (model, engine config).
- ``metrics``: per-request TTFT/TPOT/queue-time and engine-level
  throughput/occupancy counters as plain dicts, plus chrome-trace spans
  through the csrc/trace.cc host recorder.
- graceful degradation (resilience layer, all knobs default-off):
  per-request queue-TTL deadlines (terminal ``expired`` status),
  bounded admission queue (``QueueFullError`` load shedding), a
  preemption-count cap (livelock breaker), poison-request quarantine
  (a step exception fails the one request, not the engine), and
  ``Engine.drain()`` — finish in-flight work while rejecting
  admissions (``DrainingError``), the fleet building block.

Reference analog: the AnalysisPredictor serving stack
(/root/reference/paddle/fluid/inference/api/analysis_predictor.cc) —
rebuilt TPU-first around paged blocks + a shape-stable compiled step.
"""
from .engine import (  # noqa: F401
    AdmissionError,
    DrainingError,
    Engine,
    QueueFullError,
)
from .kv_cache import BlockAllocator, PagedKVCache  # noqa: F401
from .prefix_cache import RadixPrefixCache  # noqa: F401
from .scheduler import Request, RequestState, Scheduler  # noqa: F401
