"""Radix prefix cache over the paged KV pool (FLAGS_serving_prefix_cache).

The dominant serving traffic shape at scale is requests sharing a long
prompt head — system prompts, few-shot headers, multi-turn context
(SGLang's RadixAttention, vLLM automatic prefix caching; the Ragged
Paged Attention paper's mixed batch is built to exploit exactly this).
This module keys a radix tree on BLOCK-SIZE TOKEN CHUNKS: one tree node
per full KV page, holding the page id whose pool slots contain the K/V
for that chunk's tokens at that prefix position. Because K/V at
position i depends only on tokens 0..i (causal attention), any request
whose prompt starts with the node path's tokens can map its block-table
head directly onto the cached pages and prefill only the suffix.

Ownership protocol (serving/kv_cache.py BlockAllocator refcounts):

- The TREE holds one reference per cached page (taken at ``insert``).
- Every request adopting a prefix holds its own reference per page
  (``PagedKVCache.adopt_prefix``); ``release_slot`` decrefs, so a
  finished/preempted request leaves its prefix warm in the tree.
- Only FULL pages are cached — a full page is immutable (writes happen
  at positions >= seq_len, always past every full page), so shared full
  pages never need copying. The ONE mutable sharing case is a partial
  match: ``match`` may hand out the tokens of a cached page's head
  (``matched % block_size != 0``); the adopting request's first write
  lands inside that shared page and goes through the allocator's
  copy-on-write guard (``PagedKVCache.make_writable``) first.
- ``reclaim`` is the eviction walk: leaf pages referenced ONLY by the
  tree (refcount == 1) are dropped in least-recently-used order until
  the requested number of pages is freed. The scheduler/engine call it
  when the pool runs dry BEFORE preempting a running request —
  preempt-by-recompute becomes the last resort, not the first.

Matching is capped at ``len(tokens) - 1``: at least one suffix token
must run through the model, because the next output token's logits come
from the last prompt position's forward pass — a 100% cached prompt
still pays a 1-token prefill.
"""
from __future__ import annotations

import itertools


class _Node:
    __slots__ = ("key", "page", "parent", "children", "last_used")

    def __init__(self, key, page, parent):
        self.key = key              # tuple of block_size token ids
        self.page = page            # pool page id (tree holds one ref)
        self.parent = parent
        self.children = {}          # key tuple -> _Node
        self.last_used = 0


class RadixPrefixCache:
    def __init__(self, cache):
        self.cache = cache          # PagedKVCache (owns the allocator)
        self.block_size = cache.block_size
        self.root = _Node(None, None, None)
        self._clock = itertools.count(1)
        self._nodes = 0
        # counters the engine mirrors into the metrics registry
        self.hit_tokens = 0
        self.lookup_tokens = 0
        self.inserted_pages = 0
        self.evicted_pages = 0

    @property
    def cached_pages(self):
        return self._nodes

    # -- lookup -----------------------------------------------------------

    def match(self, tokens, limit=None):
        """Longest cached prefix of ``tokens`` -> (pages, matched_len).

        Walks full-page chunks down the tree; the terminal step may be a
        PARTIAL match (a child page whose chunk shares a head with the
        remaining tokens) — its page is handed out too, and the caller's
        first write into it triggers copy-on-write. ``matched_len`` is
        capped at ``limit`` (callers pass ``len(tokens) - 1`` so at
        least one suffix token remains to prefill)."""
        limit = len(tokens) if limit is None else min(limit, len(tokens))
        stamp = next(self._clock)
        pages, matched = [], 0
        node = self.root
        bs = self.block_size
        while matched + bs <= limit:
            key = tuple(tokens[matched:matched + bs])
            child = node.children.get(key)
            if child is None:
                break
            child.last_used = stamp
            pages.append(child.page)
            matched += bs
            node = child
        # partial terminal match: the next chunk's head, inside one
        # cached child page (>= 1 token, < block_size)
        head = min(limit - matched, bs - 1)
        if head > 0:
            want = tuple(tokens[matched:matched + head])
            best, best_t = None, 0
            for ckey, child in node.children.items():
                t = 0
                while t < head and ckey[t] == want[t]:
                    t += 1
                if t > best_t:
                    best, best_t = child, t
            if best is not None:
                best.last_used = stamp
                pages.append(best.page)
                matched += best_t
        return pages, matched

    def note_lookup(self, lookup_tokens, hit_tokens):
        """Count one ADMITTED lookup. Deliberately separate from
        ``match``: a blocked queue head re-matches every engine step,
        and counting those retries would inflate the reported hit rate
        arbitrarily under pool pressure. (The retries still refresh the
        LRU stamps — the head admits soon, its prefix must stay hot.)"""
        self.lookup_tokens += int(lookup_tokens)
        self.hit_tokens += int(hit_tokens)

    # -- insert -----------------------------------------------------------

    def insert(self, tokens, pages, valid_tokens):
        """Register a request's FULL pages (the first
        ``valid_tokens // block_size`` of ``pages``, covering
        ``tokens[:...]``) in the tree. An existing node for a chunk wins
        — the request keeps its duplicate page privately and it frees
        normally at release; a new node increfs the request's page so it
        survives the request. Returns newly-inserted page count."""
        bs = self.block_size
        stamp = next(self._clock)
        node = self.root
        new = 0
        for i in range(valid_tokens // bs):
            key = tuple(tokens[i * bs:(i + 1) * bs])
            child = node.children.get(key)
            if child is None:
                page = pages[i]
                self.cache.allocator.incref(page)
                child = _Node(key, page, node)
                node.children[key] = child
                self._nodes += 1
                new += 1
            child.last_used = stamp
            node = child
        self.inserted_pages += new
        return new

    # -- eviction ---------------------------------------------------------

    def _evictable_leaves(self):
        out = []
        stack = [self.root]
        while stack:
            n = stack.pop()
            for c in n.children.values():
                if c.children:
                    stack.append(c)
                elif self.cache.allocator.refcount(c.page) == 1:
                    out.append(c)
        return out

    def _drop(self, node):
        del node.parent.children[node.key]
        self._nodes -= 1
        self.cache.allocator.decref(node.page)   # last ref -> free list
        self.evicted_pages += 1

    def reclaim(self, n_pages):
        """LRU eviction walk: drop leaf pages held ONLY by the tree
        until ``n_pages`` pages returned to the free list. ONE tree
        walk collects the candidates into a min-heap on ``last_used``;
        a dropped leaf that exposes its parent pushes the parent — so
        a multi-page reclaim (admission shortfall, warmup clear) is
        O(tree + freed·log tree), not a full re-walk per page. Returns
        the number actually freed — the caller re-checks
        ``free_blocks``."""
        import heapq

        freed = 0
        heap = [(leaf.last_used, id(leaf), leaf)
                for leaf in self._evictable_leaves()]
        heapq.heapify(heap)
        while freed < n_pages and heap:
            _, _, node = heapq.heappop(heap)
            if (node.children
                    or node.parent.children.get(node.key) is not node
                    or self.cache.allocator.refcount(node.page) != 1):
                continue            # stale entry (already dropped etc.)
            parent = node.parent
            self._drop(node)
            freed += 1
            if (parent is not self.root and not parent.children
                    and self.cache.allocator.refcount(parent.page) == 1):
                heapq.heappush(heap,
                               (parent.last_used, id(parent), parent))
        return freed

    def clear(self):
        """Drop every tree reference whose page is not also held by a
        live request (benchmark warmup isolation). Shared pages stay
        cached — a live request's mapping must not be pulled out from
        under it."""
        return self.reclaim(self._nodes)

    def stats(self):
        return {
            "cached_pages": self._nodes,
            "hit_tokens": self.hit_tokens,
            "lookup_tokens": self.lookup_tokens,
            "inserted_pages": self.inserted_pages,
            "evicted_pages": self.evicted_pages,
        }
