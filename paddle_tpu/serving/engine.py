"""Continuous-batching serving engine: ONE compiled decode step.

Shape discipline (the whole point, and what the reference
AnalysisPredictor stack cannot do): the decode step is a single jitted
function over a FIXED ``max_slots`` batch —

    decode(state, pools, tokens[S], block_tables[S, MB], seq_lens[S])
        -> (next_tokens[S], pools)

Requests arriving, finishing, and getting preempted never change a
shape, so XLA compiles the decode EXACTLY ONCE per (model, engine
config); ``Engine.stats()["decode_compiles"]`` is asserted in-test.
Prefill is jitted per power-of-two length bucket (right-padded; pad
rows are causally invisible to real rows and their K/V lands in the
trash page), so a serving lifetime compiles O(log max_len) prefills.

The engine OWNS the cache: models expose a per-layer external-cache
attention hook (a cache object with ``update_and_attend``,
serving/kv_cache.py views) and a ``paged_cache_spec()`` describing
their KV geometry — the model never allocates or stores KV state.

Greedy decoding (argmax, matching GenerationMixin.generate's
``do_sample=False`` semantics token-for-token) — the parity contract
tests/test_serving.py pins. Driving loop is host-side: one device
round-trip per decode step for the sampled tokens, which is what the
lifecycle (EOS, admission, preemption) needs to see anyway.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .. import monitor as _monitor
from .kv_cache import PagedDecodeView, PagedKVCache, PagedPrefillView
from .metrics import EngineMetrics, now, span
from .scheduler import Request, RequestState, Scheduler

# watchdog heartbeat (monitor/watchdog.py): every engine iteration runs
# inside a busy bracket, so a scheduler deadlock or a hung decode
# dispatch is a detectable stall; an engine with no queued work is idle,
# never stalled
_HB_SERVE = _monitor.heartbeat("serving_engine")


class Engine:
    def __init__(self, model, max_slots=4, num_blocks=64, block_size=16,
                 max_model_len=None):
        self.model = model
        spec = model.paged_cache_spec()
        limit = model.max_decode_len()
        if max_model_len is None:
            max_model_len = limit
        if max_model_len is None:
            raise ValueError("max_model_len required for an unbounded "
                             "model")
        if limit is not None:
            max_model_len = min(max_model_len, limit)
        self.max_slots = max_slots
        self.block_size = block_size
        self.max_model_len = max_model_len
        mb = -(-max_model_len // block_size)
        self.cache = PagedKVCache(
            num_layers=spec["num_layers"], num_blocks=num_blocks,
            block_size=block_size, num_kv_heads=spec["num_kv_heads"],
            head_dim=spec["head_dim"], max_slots=max_slots,
            max_blocks_per_slot=mb, dtype=spec.get("dtype", "float32"))
        self.scheduler = Scheduler(max_slots, self.cache)
        self.metrics = EngineMetrics(max_slots)
        self.requests = {}
        self._names, values = model.functional_state()
        self._state_vals = list(values)
        # slot_tokens[s]: last generated token, not yet written to KV —
        # the next decode step's input for that slot
        self._slot_tokens = np.zeros((max_slots,), np.int32)
        self._decode = jax.jit(self._decode_fn)
        self._prefill = jax.jit(self._prefill_fn)

    # -- public API -------------------------------------------------------

    def add_request(self, prompt, max_new_tokens=32, eos_token_id=None):
        """Queue a request; returns its id. Validates that the request
        can EVER run alone (admission control proper is per-step)."""
        prompt = list(map(int, prompt))
        if not prompt:
            raise ValueError("empty prompt")
        total = len(prompt) + max_new_tokens
        if total > self.max_model_len:
            raise ValueError(
                "prompt (%d) + max_new_tokens (%d) exceeds max_model_len"
                " (%d)" % (len(prompt), max_new_tokens,
                           self.max_model_len))
        pages_needed = self.cache.pages_needed(total)
        if pages_needed > self.cache.allocator.usable_blocks:
            raise ValueError(
                "request needs %d pages but the pool only has %d usable "
                "blocks — it could never be scheduled"
                % (pages_needed, self.cache.allocator.usable_blocks))
        req = Request(prompt, max_new_tokens, eos_token_id)
        self.requests[req.id] = req
        # span journal (FLAGS_monitor_trace): trace id assigned here —
        # the admission point — so the queue phase covers every second
        # the engine owned the request
        req.trace_begin()
        self.metrics.on_request_in()
        if max_new_tokens == 0:     # zero-length generation: trivially done
            req.finish()
            self.metrics.on_request_finished()
            req.trace_finish("finished")
            return req.id
        if req.trace_id is not None:
            req.trace_phase("queue")
            req.trace_event("admitted", kv_pages_needed=pages_needed)
        self.scheduler.add(req)
        return req.id

    def has_work(self):
        return self.scheduler.has_work()

    def step(self):
        """One engine iteration: admit+prefill, grow pages (preempting
        on exhaustion), one batched decode step. Returns has_work()."""
        with _HB_SERVE.busy("serving.step"):
            self._admit_and_prefill()
            self._grow_or_preempt()
            # perf attribution (FLAGS_perf_attribution): KV-page
            # occupancy + goodput per engine iteration, sampled at the
            # step's high-water point (pages grown, nothing released
            # yet) — pure host arithmetic, but still flag-gated so the
            # default serving hot path does no new work
            if _monitor.is_enabled() \
                    and _monitor.perf.attribution_enabled():
                alloc = self.cache.allocator
                self.metrics.on_kv_occupancy(
                    1.0 - alloc.free_blocks / max(alloc.usable_blocks, 1))
            active = self.scheduler.active()
            if active:
                self._decode_once(active)
        return self.has_work()

    def run(self):
        """Drain all queued work; returns {request_id: generated tokens}."""
        with _HB_SERVE.busy("serving.run"):
            while self.step():
                pass
        return {rid: list(r.generated) for rid, r in self.requests.items()}

    def output(self, rid):
        return list(self.requests[rid].generated)

    def request_metrics(self, rid):
        return self.requests[rid].metrics.to_dict()

    def request_trace(self, rid):
        """(trace_id, {phase: seconds}) of a request's span timeline —
        (None, None) while the journal (FLAGS_monitor_trace) is off OR
        when the bounded journal already evicted this request's trace
        (callers never have to distinguish the two absences)."""
        tid = self.requests[rid].trace_id
        if tid is None:
            return None, None
        phases = _monitor.trace.phase_breakdown(tid)
        if phases is None:      # evicted from the bounded journal
            return None, None
        return tid, phases

    def stats(self):
        return self.metrics.to_dict()

    # -- lifecycle --------------------------------------------------------

    def _admit_and_prefill(self):
        while True:
            admitted = self.scheduler.admit_next()
            if admitted is None:
                return
            slot, req = admitted
            self.metrics.on_admission()
            self._prefill_request(slot, req)

    def _prefill_request(self, slot, req):
        tokens = req.resume_tokens
        L = len(tokens)
        P = self._bucket(L)
        req.trace_phase("prefill", slot=slot, tokens=L, bucket=P,
                        resume=req.metrics.preemptions > 0)
        ids = np.zeros((1, P), np.int32)
        ids[0, :L] = tokens
        with span("serving.prefill"):
            tok, new_pools = self._run_eval(
                self._prefill, self._state_vals, self.cache.pools,
                jnp.asarray(ids),
                jnp.asarray(self.cache.block_tables[slot]),
                jnp.asarray(L, jnp.int32))
        self.cache.pools = new_pools
        self.cache.seq_lens[slot] = L
        self.metrics.on_prefill_run()
        req.state = RequestState.DECODING
        req.metrics.on_first_token(now())
        # decode phase opens BEFORE the first token is accepted: a
        # max_new_tokens=1 request finishes inside _accept_token and
        # its trace_finish must close the decode span, not prefill
        req.trace_phase("decode", slot=slot)
        self._accept_token(req, int(tok))

    def _grow_or_preempt(self):
        """Every decoding slot writes one K/V row this step at position
        seq_len — make sure its page exists, preempting the most recent
        other request on exhaustion (recompute-requeue)."""
        for slot, req in list(self.scheduler.active()):
            if self.scheduler.slots[slot] is not req:
                continue            # became a victim earlier in the loop
            while not self.cache.ensure_capacity(
                    slot, int(self.cache.seq_lens[slot]) + 1):
                victim = self.scheduler.preempt_victim(slot)
                if victim is None:
                    raise RuntimeError(
                        "KV pool exhausted by a single request — "
                        "add_request validation should have caught this")
                self.metrics.on_preemption()

    def _decode_once(self, active):
        bt = jnp.asarray(self.cache.block_tables)
        lens = jnp.asarray(self.cache.seq_lens)
        toks = jnp.asarray(self._slot_tokens)
        with span("serving.decode_step"):
            next_toks, new_pools = self._run_eval(
                self._decode, self._state_vals, self.cache.pools, toks,
                bt, lens)
        self.cache.pools = new_pools
        out = np.asarray(next_toks)
        self.metrics.on_decode_step(len(active))
        for slot, req in active:
            # the input token's K/V row landed at position seq_len
            self.cache.seq_lens[slot] += 1
            self._accept_token(req, int(out[slot]))

    def _accept_token(self, req, tok):
        req.generated.append(tok)
        self._slot_tokens[req.slot] = tok
        self.metrics.on_output_token()
        done = (req.remaining <= 0
                or (req.eos_token_id is not None
                    and tok == req.eos_token_id))
        if req.trace_id is not None:
            # token MILESTONES, not every token (bounded journal): the
            # first, every 8th, and the last, each stamped with the KV
            # and batch-slot occupancy the step saw
            n = len(req.generated)
            if n == 1 or done or n % 8 == 0:
                alloc = self.cache.allocator
                req.trace_event(
                    "token", n=n,
                    kv_pages_used=(alloc.usable_blocks
                                   - alloc.free_blocks),
                    slots_active=self.scheduler.slots_active())
        if done:
            self.scheduler.release(req)
            req.finish()
            self.metrics.on_request_finished(len(req.generated))
            req.trace_finish("finished")

    # -- compiled steps ---------------------------------------------------

    def _bucket(self, n):
        """Prefill length bucket: next power of two (>= 8), capped at
        max_model_len rounded up to a multiple of 8 AND at the block
        table's position capacity — a pad length past ``MB * bs`` would
        make the prefill scatter's clamped gather write pad K/V over
        the request's last real page."""
        p = 8
        while p < n:
            p *= 2
        cap = min(-(-self.max_model_len // 8) * 8,
                  self.cache.max_blocks_per_slot * self.block_size)
        return min(p, max(cap, n))

    def _run_eval(self, fn, *args):
        was_training = self.model.training
        self.model.eval()
        try:
            return fn(*args)
        finally:
            if was_training:
                self.model.train()

    def _prefill_fn(self, state_vals, pools, ids, table_row, true_len):
        from ..core.dispatch import no_grad
        from ..core.tensor import Tensor

        self.metrics.on_prefill_compile()       # trace-time counter
        with self.model.bind_state(self._names, list(state_vals)):
            with no_grad():
                views = [PagedPrefillView(p, table_row, self.block_size)
                         for p in pools]
                logits, views = self.model.generate_step(
                    Tensor(ids), views, 0)
        lv = logits._value if isinstance(logits, Tensor) else logits
        last = lv[0, true_len - 1].astype(jnp.float32)
        tok = jnp.argmax(last, axis=-1).astype(jnp.int32)
        return tok, [v.pool for v in views]

    def _decode_fn(self, state_vals, pools, tokens, block_tables,
                   seq_lens):
        from ..core.dispatch import no_grad
        from ..core.tensor import Tensor

        self.metrics.on_decode_compile()        # trace-time counter
        with self.model.bind_state(self._names, list(state_vals)):
            with no_grad():
                views = [PagedDecodeView(p, block_tables, seq_lens,
                                         self.block_size)
                         for p in pools]
                logits, views = self.model.generate_step(
                    Tensor(tokens[:, None]), views, seq_lens)
        lv = logits._value if isinstance(logits, Tensor) else logits
        nxt = jnp.argmax(lv[:, -1, :].astype(jnp.float32),
                         axis=-1).astype(jnp.int32)
        return nxt, [v.pool for v in views]
