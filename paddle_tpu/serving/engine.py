"""Continuous-batching serving engine: ONE compiled decode step.

Shape discipline (the whole point, and what the reference
AnalysisPredictor stack cannot do): the decode step is a single jitted
function over a FIXED ``max_slots`` batch —

    decode(state, pools, tokens[S], block_tables[S, MB], seq_lens[S])
        -> (next_tokens[S], pools)

Requests arriving, finishing, and getting preempted never change a
shape, so XLA compiles the decode EXACTLY ONCE per (model, engine
config); ``Engine.stats()["decode_compiles"]`` is asserted in-test.
Prefill is jitted per power-of-two length bucket (right-padded; pad
rows are causally invisible to real rows and their K/V lands in the
trash page), so a serving lifetime compiles O(log max_len) prefills.

Serving tier 2 (default-off flags, latched at construction):
``FLAGS_serving_prefix_cache`` adopts shared refcounted pages for
cached prompt prefixes and prefills only the uncached suffix (the
per-bucket prefill becomes the hist-parameterized suffix prefill);
``FLAGS_serving_chunked_prefill`` replaces the split decode/prefill
pair with ONE mixed ragged step over [max_slots, prefill_chunk] rows —
decode rows are q_len==1 chunks — so long prompts stream through the
decode batch one chunk per step instead of stalling it, and the
compile-once contract holds as ``decode_compiles == 1`` for the mixed
step. Both off: every compiled function, shape and output below is
bit-identical to the tier-1 engine (test-pinned).

The engine OWNS the cache: models expose a per-layer external-cache
attention hook (a cache object with ``update_and_attend``,
serving/kv_cache.py views) and a ``paged_cache_spec()`` describing
their KV geometry — the model never allocates or stores KV state.

Greedy decoding (argmax, matching GenerationMixin.generate's
``do_sample=False`` semantics token-for-token) — the parity contract
tests/test_serving.py pins. Driving loop is host-side: one device
round-trip per decode step for the sampled tokens, which is what the
lifecycle (EOS, admission, preemption) needs to see anyway.
"""
from __future__ import annotations

import time
import weakref

import jax
import jax.numpy as jnp
import numpy as np

from .. import monitor as _monitor
from ..resilience import faultinject as _fi
from .kv_cache import (
    PagedDecodeView,
    PagedKVCache,
    PagedMixedView,
    PagedPrefillView,
)
from . import replay as _replay
from .metrics import EngineMetrics, now, span
from .scheduler import Request, RequestState, Scheduler


class AdmissionError(RuntimeError):
    """Request rejected AT admission (load shed) — never enqueued, no
    id assigned; the caller retries elsewhere or backs off."""

    reason = "admission"


class QueueFullError(AdmissionError):
    """Bounded admission queue is full (``max_queue``)."""

    reason = "queue_full"


class DrainingError(AdmissionError):
    """Engine is draining (``Engine.drain()``): in-flight work
    completes, new admissions are rejected — the fleet layer's
    drain-and-reschedule building block."""

    reason = "draining"

# watchdog heartbeat (monitor/watchdog.py): every engine iteration runs
# inside a busy bracket, so a scheduler deadlock or a hung decode
# dispatch is a detectable stall; an engine with no queued work is idle,
# never stalled
_HB_SERVE = _monitor.heartbeat("serving_engine")

# weight-only quantized decode (FLAGS_serving_quant_weights) eligibility:
# 2-D projection weights of the attention/MLP stacks — the memory-bound
# decode matmuls. Embeddings, lm_head, norms and biases stay fp32 (the
# embedding gather and the final projection dominate accuracy, and
# 1-D params have no reduction axis to block-scale over).
_QUANT_PROJ_SEGMENTS = frozenset((
    "q_proj", "k_proj", "v_proj", "o_proj", "qkv_proj",       # llama attn
    "gate_proj", "up_proj", "down_proj", "gate_up_proj",      # llama mlp
    "qkv", "proj", "fc1", "fc2",                              # gpt
))


def _quantizable_weight(name, val):
    parts = name.split(".")
    return (getattr(val, "ndim", 0) == 2 and parts[-1] == "weight"
            and any(p in _QUANT_PROJ_SEGMENTS for p in parts[:-1]))


class Engine:
    def __init__(self, model, max_slots=4, num_blocks=64, block_size=16,
                 max_model_len=None, max_queue=None,
                 default_deadline_s=None, max_preemptions=None,
                 prefill_chunk=16):
        """Resilience knobs (all default-off — the engine behaves
        exactly as before unless asked):

        max_queue           bounded admission queue: add_request raises
                            QueueFullError (and counts a queue_full
                            shed) once this many requests wait
        default_deadline_s  queue-TTL for requests that don't pass
                            their own deadline_s: still WAITING past it
                            -> terminal EXPIRED status (never kills a
                            decoding request)
        max_preemptions     a request preempted this many times becomes
                            non-preemptible (runs to completion) — the
                            preempt-recompute livelock breaker; when NO
                            eligible victim remains, the grower is shed
                            (reason preempt_cap) instead of deadlocking

        Serving tier-2 flags, LATCHED HERE at construction (a mid-life
        flag flip never changes a live engine's compiled step set):

        FLAGS_serving_prefix_cache   radix prefix cache over the page
                            pool (serving/prefix_cache.py): shared
                            prompt heads map to shared refcounted
                            pages, admission charges only the uncached
                            suffix, release keeps prefixes warm, LRU
                            reclaim runs before any preemption
        FLAGS_serving_chunked_prefill  prompts prefill in
                            ``prefill_chunk``-token chunks interleaved
                            into the ONE compiled mixed step as ragged
                            rows next to the decode rows — a long
                            prefill no longer stalls the decode batch,
                            and ``decode_compiles`` stays exactly 1
        FLAGS_serving_quant_kv  the paged K/V pools are int8 planes
                            with per-(page, position, head) fp32 scale
                            planes riding alongside in KVBlockPool —
                            quantized at page-write time, dequantized
                            inside the attention gather; ~4x page
                            capacity at the same byte budget
        FLAGS_serving_quant_weights  projection weights quantized int8
                            block-scaled ONCE here at bind; the decode/
                            mixed steps bind the dequantize-fused
                            weights (memory-bound rows), the split
                            prefill steps keep fp32
        """
        from ..core import flags as _flags

        self.model = model
        spec = model.paged_cache_spec()
        limit = model.max_decode_len()
        if max_model_len is None:
            max_model_len = limit
        if max_model_len is None:
            raise ValueError("max_model_len required for an unbounded "
                             "model")
        if limit is not None:
            max_model_len = min(max_model_len, limit)
        self.max_slots = max_slots
        self.block_size = block_size
        self.max_model_len = max_model_len
        mb = -(-max_model_len // block_size)
        self.quant_kv = bool(_flags.flag("FLAGS_serving_quant_kv"))
        self.quant_weights = bool(
            _flags.flag("FLAGS_serving_quant_weights"))
        self.cache = PagedKVCache(
            num_layers=spec["num_layers"], num_blocks=num_blocks,
            block_size=block_size, num_kv_heads=spec["num_kv_heads"],
            head_dim=spec["head_dim"], max_slots=max_slots,
            max_blocks_per_slot=mb, dtype=spec.get("dtype", "float32"),
            quantized=self.quant_kv)
        # int8 bytes one page's k+v planes hold — the dequant-bytes
        # accounting unit for serving_quant_dequant_bytes_total
        self._quant_page_bytes = (2 * block_size * spec["num_kv_heads"]
                                  * spec["head_dim"])
        self.prefix_cache = None
        if _flags.flag("FLAGS_serving_prefix_cache"):
            from .prefix_cache import RadixPrefixCache

            self.prefix_cache = RadixPrefixCache(self.cache)
        self.chunked_prefill = bool(
            _flags.flag("FLAGS_serving_chunked_prefill"))
        self.prefill_chunk = int(prefill_chunk)
        if self.prefill_chunk < 1:
            raise ValueError("prefill_chunk must be >= 1")
        self.scheduler = Scheduler(max_slots, self.cache,
                                   self.prefix_cache)
        self.metrics = EngineMetrics(max_slots)
        # memory plane (monitor/memory.py, FLAGS_monitor_memory),
        # LATCHED HERE like the tier-2 flags: the step hot path only
        # ever checks the handle. None = flags-off, bit-identical.
        self._mem = None
        # fleet identity beacon (monitor/fleet.py): under
        # FLAGS_monitor_fleet the scraped serving series resolve to
        # this rank/host/job; one flag branch when off
        _monitor.fleet.note_identity("serving")
        self.requests = {}
        self.max_queue = max_queue
        self.default_deadline_s = default_deadline_s
        self.max_preemptions = max_preemptions
        self._draining = False
        # poison quarantine: request ids that were active in a FAILED
        # batched decode — re-admitted ONE AT A TIME so the next decode
        # failure is attributable to a single request (bisect-by-
        # serialization); empties as its members reach terminal states
        self._quarantine = set()
        self._names, values = model.functional_state()
        self._state_vals = list(values)
        # weight-only quantized decode (FLAGS_serving_quant_weights):
        # projection weights quantized ONCE here; _decode_vals is the
        # state the decode/mixed steps bind — each quantized leaf is an
        # (int8 q, f32 scales) pair the step dequantizes in-trace so
        # XLA fuses the broadcast-multiply into the consuming matmul's
        # operand read. Prefill steps keep binding _state_vals (fp32):
        # compute-bound rows gain nothing from a smaller weight read.
        # Flag off: _decode_vals IS _state_vals — same leaves, same
        # jaxpr, bit-identical (test-pinned).
        self._qw_dtypes = {}            # leaf index -> original dtype
        self._decode_vals = self._state_vals
        if self.quant_weights:
            from ..kernels.quant import quantize_int8_weight

            self._decode_vals = list(self._state_vals)
            for i, (name, val) in enumerate(zip(self._names, values)):
                if _quantizable_weight(name, val):
                    self._qw_dtypes[i] = val.dtype
                    self._decode_vals[i] = quantize_int8_weight(val)
        # slot_tokens[s]: last generated token, not yet written to KV —
        # the next decode step's input for that slot
        self._slot_tokens = np.zeros((max_slots,), np.int32)
        # donate_argnums=(1,): the KV pools are CARRIED state — every
        # step consumes the previous pools and returns the next, and
        # the caller rebinds self.cache.pools immediately — so the
        # buffers must alias in-place (input_output_aliases) instead of
        # doubling the pool's HBM footprint every step. The pthlo
        # donation audit (paddle_tpu/analysis/graph) pins this: an
        # un-donated pool in the hot step is a finding. Weights
        # (state_vals, arg 0) are deliberately NOT donated — the same
        # buffers feed every subsequent call.
        if self.chunked_prefill:
            # ONE mixed ragged step serves decode rows AND prefill
            # chunks (a decode row is the q_len==1 case); the split
            # decode/prefill functions are never traced
            self._mixed = jax.jit(self._mixed_fn, donate_argnums=(1,))
        else:
            self._decode = jax.jit(self._decode_fn, donate_argnums=(1,))
            if self.prefix_cache is not None:
                # cache-aware prefill: runs only the uncached suffix
                # over the adopted pool history (hist == 0 on a miss),
                # jitted per suffix-length bucket like _prefill was
                self._suffix_prefill = jax.jit(self._suffix_prefill_fn,
                                               donate_argnums=(1,))
            else:
                self._prefill = jax.jit(self._prefill_fn,
                                        donate_argnums=(1,))
        self._mem = _monitor.memory.tracker(
            "serving", self._mem_components(),
            context_fn=self._mem_context)
        # ptprof step hook (monitor/profile.py, FLAGS_monitor_profile),
        # LATCHED HERE like the tier-2 flags and the memory tracker:
        # per-iteration dispatch/gap timers, prefill/decode phase
        # timers, and the device-capture-window lifecycle. None =
        # flags-off; the step hot path only ever checks the handle.
        self._prof = _monitor.profile.step_hook("serving")
        # weight-swap generation (ROADMAP item 6): stamped into every
        # replay journal entry + benchmark requests_detail row so a
        # post-hot-swap divergence is attributable to the generation
        # that served it; the swap path will bump it
        self.weights_generation = 0
        # record/replay recorder (serving/replay.py,
        # FLAGS_serving_replay), LATCHED HERE like the tier-2 flags
        # and the monitor handles: None = flags-off — every capture
        # site below is one handle-is-None branch, zero journal
        # allocations, wire/result payloads bit-identical
        self._replay = _replay.recorder(self)

    def _mem_components(self):
        """Ledger providers (monitor/memory.py): the paged KV pools
        (every layer's k/v planes, with prefix-cache/COW page detail)
        and the resident model weights. Providers read live engine
        state at sample time, so pool resets and COW churn are always
        current — and hold the engine WEAKLY, so the global ledger
        never pins a discarded engine's pools/weights alive (a dead
        engine's components just report empty)."""
        wself = weakref.ref(self)

        def kv_pool():
            s = wself()
            if s is None:
                return ()
            cache = s.cache
            entries = []
            for i, pool in enumerate(cache.pools):
                entries.append(("kv_pool/layer%d/k" % i, pool.k))
                entries.append(("kv_pool/layer%d/v" % i, pool.v))
                if pool.k_scale is not None:
                    entries.append(("kv_pool/layer%d/k_scale" % i,
                                    pool.k_scale))
                    entries.append(("kv_pool/layer%d/v_scale" % i,
                                    pool.v_scale))
            alloc = cache.allocator
            detail = {
                "pages_used": alloc.usable_blocks - alloc.free_blocks,
                "pages_usable": alloc.usable_blocks,
                "cow_clones": cache.cow_clones,
            }
            if s.prefix_cache is not None:
                detail["prefix_cached_pages"] = \
                    s.prefix_cache.stats()["cached_pages"]
            return {"entries": entries, "detail": detail}

        def model_params():
            s = wself()
            if s is None:
                return ()
            entries = list(zip(s._names, s._state_vals))
            # quantized decode copies (FLAGS_serving_quant_weights) are
            # resident alongside the fp32 originals (prefill binds
            # fp32) — the ledger must see both
            for i in s._qw_dtypes:
                q, scales = s._decode_vals[i]
                entries.append(("int8/" + s._names[i], q))
                entries.append(("int8/" + s._names[i] + ".scales",
                                scales))
            return entries

        return {"kv_pool": kv_pool, "model_params": model_params}

    def _mem_context(self):
        """OOM-postmortem context: the pool/batch state at the moment
        of death — occupancy, slot fill, prefix-cache residency."""
        alloc = self.cache.allocator
        used = alloc.usable_blocks - alloc.free_blocks
        ctx = {
            "kv_page_occupancy": used / max(alloc.usable_blocks, 1),
            "kv_pages_used": used,
            "kv_pages_usable": alloc.usable_blocks,
            "slots_active": self.scheduler.slots_active(),
            "queue_depth": len(self.scheduler.queue),
            "cow_clones": self.cache.cow_clones,
        }
        if self.prefix_cache is not None:
            ctx["prefix_cached_pages"] = \
                self.prefix_cache.stats()["cached_pages"]
        return ctx

    # -- public API -------------------------------------------------------

    def add_request(self, prompt, max_new_tokens=32, eos_token_id=None,
                    deadline_s=None, trace_ctx=None):
        """Queue a request; returns its id. Validates that the request
        can EVER run alone (admission control proper is per-step).
        Raises DrainingError / QueueFullError when load-shedding (the
        request is never enqueued and gets no id). ``trace_ctx=(trace_id,
        parent_span_id)`` adopts a caller-minted trace context (the
        fleet router's traceparent) instead of minting a fresh id."""
        if self._draining:
            self.metrics.on_request_shed("draining")
            raise DrainingError(
                "engine is draining: new admissions rejected")
        if self.max_queue is not None \
                and len(self.scheduler.queue) >= self.max_queue:
            self.metrics.on_request_shed("queue_full")
            raise QueueFullError(
                "admission queue full (%d waiting, max_queue=%d)"
                % (len(self.scheduler.queue), self.max_queue))
        prompt = list(map(int, prompt))
        if not prompt:
            raise ValueError("empty prompt")
        total = len(prompt) + max_new_tokens
        if total > self.max_model_len:
            raise ValueError(
                "prompt (%d) + max_new_tokens (%d) exceeds max_model_len"
                " (%d)" % (len(prompt), max_new_tokens,
                           self.max_model_len))
        pages_needed = self.cache.pages_needed(total)
        if pages_needed > self.cache.allocator.usable_blocks:
            raise ValueError(
                "request needs %d pages but the pool only has %d usable "
                "blocks — it could never be scheduled"
                % (pages_needed, self.cache.allocator.usable_blocks))
        if deadline_s is None:
            deadline_s = self.default_deadline_s
        req = Request(prompt, max_new_tokens, eos_token_id,
                      deadline_s=deadline_s)
        self.requests[req.id] = req
        # span journal (FLAGS_monitor_trace): trace id assigned here —
        # the admission point — so the queue phase covers every second
        # the engine owned the request
        req.trace_begin(trace_ctx)
        # replay journal admission capture (FLAGS_serving_replay):
        # AFTER trace_begin so the entry cross-links the adopted
        # fleet-wide trace id, not a pre-adoption placeholder
        rec = self._replay
        if rec is not None:
            rec.admit(req, deadline_s=deadline_s)
        self.metrics.on_request_in()
        if max_new_tokens == 0:     # zero-length generation: trivially done
            req.finish()
            self.metrics.on_request_finished()
            req.trace_finish("finished")
            if rec is not None:
                rec.terminal(req)
            return req.id
        if req.trace_id is not None:
            req.trace_phase("queue")
            req.trace_event("admitted", kv_pages_needed=pages_needed)
        self.scheduler.add(req)
        return req.id

    def has_work(self):
        return self.scheduler.has_work()

    def step(self):
        """One engine iteration: admit+prefill, grow pages (preempting
        on exhaustion), one batched decode step. Returns has_work()."""
        with _HB_SERVE.busy("serving.step"):
            try:
                # engine-level injection site: a fault here models a
                # transient failure BETWEEN requests (scheduler glitch,
                # control-plane hiccup) — nothing owned it, no request
                # is harmed, the iteration is simply retried
                if _fi.is_enabled():
                    _fi.fire("serving.step")
            except _fi.InjectedFault:
                return self.has_work()
            prof = self._prof
            if prof is not None:
                # ptprof: open any queued capture window BEFORE the
                # iteration dispatches, so the Xprof trace covers it
                prof.step_begin()
                _pt0 = time.perf_counter()
            try:
                # OOM forensics (monitor/memory.py, latched at
                # construction): mem.oom is the deterministic
                # RESOURCE_EXHAUSTED stand-in; any OOM-shaped failure
                # writes oom_postmortem_rank{r}.json and RE-RAISES —
                # allocator state after a real OOM is unknowable, so
                # unlike the poison paths there is no recovery here
                if self._mem is not None and _fi.is_enabled():
                    _fi.fire("mem.oom")
                self._expire_waiting()
                self._timed_phase(prof, "prefill",
                                  self._admit_and_prefill)
                self._grow_or_preempt()
                # perf attribution (FLAGS_perf_attribution): KV-page
                # occupancy + goodput per engine iteration, sampled at
                # the step's high-water point (pages grown, nothing
                # released yet) — pure host arithmetic, but still
                # flag-gated so the default serving hot path does no
                # new work
                if _monitor.is_enabled() \
                        and _monitor.perf.attribution_enabled():
                    alloc = self.cache.allocator
                    self.metrics.on_kv_occupancy(
                        1.0 - alloc.free_blocks
                        / max(alloc.usable_blocks, 1))
                if self.chunked_prefill:
                    rows = self.scheduler.occupied()
                    if rows:
                        self._timed_phase(prof, "decode",
                                          self._mixed_once, rows)
                else:
                    active = self.scheduler.active()
                    if active:
                        self._timed_phase(prof, "decode",
                                          self._decode_once, active)
                if self.prefix_cache is not None:
                    self.metrics.on_prefix_stats(
                        self.prefix_cache.stats(),
                        self.cache.cow_clones)
            except Exception as e:
                if self._mem is not None \
                        and _monitor.memory.looks_like_oom(e):
                    self._mem.write_postmortem(e)
                if prof is not None:
                    # a raising step must not leak the open capture
                    # window (or its live device trace); the partial
                    # artifact lands marked aborted
                    prof.step_abort()
                raise
            if prof is not None:
                # no block arg: the decode path already synced the
                # step's outputs to host numpy — the iteration wall IS
                # the host-exposed time; gap covers the scheduler idle
                # between iterations
                prof.step_end(_pt0, time.perf_counter())
        return self.has_work()

    def _timed_phase(self, prof, phase, fn, *args):
        """Run one step phase, feeding its host wall into the ptprof
        per-phase timers when the handle is latched (one call site per
        phase instead of three copies of the stamp dance)."""
        if prof is None:
            fn(*args)
            return
        t = time.perf_counter()
        fn(*args)
        prof.note_phase(phase, time.perf_counter() - t)

    def run(self):
        """Drain all queued work; returns {request_id: generated tokens}."""
        with _HB_SERVE.busy("serving.run"):
            while self.step():
                pass
        return {rid: list(r.generated) for rid, r in self.requests.items()}

    @property
    def draining(self):
        return self._draining

    def drain(self):
        """Stop admitting, finish everything already accepted (active
        slots AND the queue), return the outputs. The fleet layer's
        drain-and-reschedule primitive: after drain() returns, the
        engine holds no work and every accepted request reached a
        terminal state; new add_request calls keep raising
        DrainingError. Waiting requests still honor their deadlines —
        a drain under overload sheds what it cannot serve in time."""
        self._draining = True
        return self.run()

    def output(self, rid):
        return list(self.requests[rid].generated)

    def request_metrics(self, rid):
        return self.requests[rid].metrics.to_dict()

    def request_trace(self, rid):
        """(trace_id, {phase: seconds}) of a request's span timeline —
        (None, None) while the journal (FLAGS_monitor_trace) is off OR
        when the bounded journal already evicted this request's trace
        (callers never have to distinguish the two absences)."""
        tid = self.requests[rid].trace_id
        if tid is None:
            return None, None
        phases = _monitor.trace.phase_breakdown(tid)
        if phases is None:      # evicted from the bounded journal
            return None, None
        return tid, phases

    def stats(self):
        return self.metrics.to_dict()

    def request_status(self, rid):
        """Terminal-status view of one request: state + machine-readable
        reason (finished | expired | shed | failed | a live state)."""
        r = self.requests[rid]
        return {
            "id": rid,
            "state": r.state.value,
            "reason": r.status_reason,
            "output_tokens": len(r.generated),
            "preemptions": r.metrics.preemptions,
            "error": repr(r.error) if r.error is not None else None,
        }

    # -- lifecycle --------------------------------------------------------

    def _expire_waiting(self):
        """Queue-TTL pass: waiting requests past their deadline get the
        EXPIRED terminal status (shed reason ``expired``) before any
        admission work is spent on them."""
        for req in self.scheduler.expire_waiting():
            req.close(RequestState.EXPIRED, "deadline")
            self._quarantine.discard(req.id)
            self.metrics.on_request_shed("expired")
            if self._replay is not None:
                self._replay.terminal(req)

    def _admit_and_prefill(self):
        while True:
            if self._quarantine and self.scheduler.slots_active() > 0:
                # poison bisect in progress: serialize admissions so a
                # failing decode names a single request
                return
            admitted = self.scheduler.admit_next()
            if admitted is None:
                return
            slot, req = admitted
            self.metrics.on_admission()
            if self._mem is not None:
                self._mem.note_decision(
                    "admit", request=req.id, slot=slot,
                    kv_pages_free=self.cache.allocator.free_blocks)
            if self.chunked_prefill:
                # no synchronous prefill: the request sits in PREFILL
                # state and its prompt streams through the mixed step
                # in prefill_chunk-token rows next to everyone else's
                # decode rows (resumable: prefill_pos is the cursor).
                # The per-request serving.prefill injection site fires
                # HERE — admission is the last moment a prefill fault
                # is attributable to this one request
                try:
                    if _fi.is_enabled():
                        _fi.fire("serving.prefill", request=req.id,
                                 slot=slot)
                except Exception as e:
                    self._fail_request(req, e)
                    continue
                self.metrics.on_prefill_run()
                req.trace_phase(
                    "prefill", slot=slot, tokens=len(req.resume_tokens),
                    cached=req.cached_tokens, chunked=True,
                    resume=req.metrics.preemptions > 0)
                continue
            try:
                self._prefill_request(slot, req)
            except Exception as e:  # poison quarantine: the request's
                self._fail_request(req, e)  # OWN step failed, not the engine

    def _fail_request(self, req, exc):
        """Poison quarantine: one request's step raised — fail IT with
        a terminal status and keep serving everyone else."""
        if req.slot is not None:
            self.scheduler.release(req)
        req.close(RequestState.FAILED, "poison", error=exc)
        self._quarantine.discard(req.id)
        self.metrics.on_request_shed("poison")
        if self._replay is not None:
            self._replay.terminal(req)
        self._recover_consumed_pools()

    def _recover_consumed_pools(self):
        """The donated-pools failure path: the compiled steps donate
        their input pools (``donate_argnums=(1,)``), so a step that
        raises AFTER execution started leaves ``cache.pools`` pointing
        at DELETED buffers — every slot's KV, not just the failing
        request's, is gone. (A pre-dispatch failure — fault injection,
        a trace-time error — never consumes anything and this is one
        cheap liveness check.) Recovery is preempt-by-recompute for
        every occupied slot over a fresh zeroed pool plane: recompute
        re-prefills from host-side tokens deterministically, so
        outputs stay bit-identical and a one-step transient cannot
        become permanent engine death. The prefix cache is REBUILT,
        not kept: its pages map into the dead pools, and the
        keep-warm release path must not re-serve garbage KV — which
        is also why the requeue below bypasses scheduler.release
        (its insert would cache those pages)."""
        if self.cache.pools_alive():
            return
        from ..monitor.registry import warn_once

        warn_once(
            "serving.pools_consumed",
            "paddle_tpu.serving: a compiled step failed after its "
            "donated KV pools were consumed; resetting the pool "
            "plane and requeueing every occupied slot "
            "(preempt-by-recompute)")
        # reversed + requeue_front, the _on_decode_failure idiom:
        # appendleft in reverse slot order keeps the survivors'
        # re-admission strictly FCFS
        for slot, req in reversed(list(self.scheduler.occupied())):
            if self.scheduler.slots[slot] is not req:
                continue
            self.cache.release_slot(slot)
            self.scheduler.slots[slot] = None
            req.slot = None
            req.state = RequestState.PREEMPTED
            req.metrics.preemptions += 1
            self.scheduler.requeue_front(req)
            self.metrics.on_preemption()
        if self.prefix_cache is not None:
            from .prefix_cache import RadixPrefixCache

            self.prefix_cache = RadixPrefixCache(self.cache)
            self.scheduler.prefix_cache = self.prefix_cache
        self.cache.reset_pools()

    def _prefill_request(self, slot, req):
        # per-request injection site: the poison-request model — an
        # error here is attributable to THIS request and fails only it
        if _fi.is_enabled():
            _fi.fire("serving.prefill", request=req.id, slot=slot)
        tokens = req.resume_tokens
        L = len(tokens)
        if self.prefix_cache is not None:
            # cache-aware path: only the uncached suffix runs through
            # the model (hist == 0 on a miss — same function, so a miss
            # and a hit share the per-bucket compile). A partially-
            # matched page is split copy-on-write first; admission
            # charged the clone page, so this cannot fail here.
            hist = req.cached_tokens
            if not self.cache.make_writable(slot, hist, L):
                raise AssertionError("COW clone raced the allocator")
            suffix = tokens[hist:]
            Ls = len(suffix)
            P = self._bucket(Ls)
            req.trace_phase("prefill", slot=slot, tokens=L, bucket=P,
                            cached=hist,
                            resume=req.metrics.preemptions > 0)
            ids = np.zeros((1, P), np.int32)
            ids[0, :Ls] = suffix
            with span("serving.prefill"):
                tok, new_pools = self._run_eval(
                    self._suffix_prefill, self._state_vals,
                    self.cache.pools, jnp.asarray(ids),
                    jnp.asarray(self.cache.block_tables[slot]),
                    jnp.asarray(hist, jnp.int32),
                    jnp.asarray(Ls, jnp.int32))
        else:
            P = self._bucket(L)
            req.trace_phase("prefill", slot=slot, tokens=L, bucket=P,
                            resume=req.metrics.preemptions > 0)
            ids = np.zeros((1, P), np.int32)
            ids[0, :L] = tokens
            with span("serving.prefill"):
                tok, new_pools = self._run_eval(
                    self._prefill, self._state_vals, self.cache.pools,
                    jnp.asarray(ids),
                    jnp.asarray(self.cache.block_tables[slot]),
                    jnp.asarray(L, jnp.int32))
        self.cache.pools = new_pools
        self.cache.seq_lens[slot] = L
        self.metrics.on_prefill_run()
        if self.prefix_cache is not None:
            # publish the freshly-computed prompt pages immediately —
            # the next queued request sharing this prompt head admits
            # against them, not against a finished-request race
            self.prefix_cache.insert(tokens, self.cache.slot_pages(slot),
                                     L)
        req.state = RequestState.DECODING
        req.metrics.on_first_token(now())
        # decode phase opens BEFORE the first token is accepted: a
        # max_new_tokens=1 request finishes inside _accept_token and
        # its trace_finish must close the decode span, not prefill
        req.trace_phase("decode", slot=slot)
        self._accept_token(req, int(tok))

    def _grow_or_preempt(self):
        """Every live row writes K/V this step — decode rows one
        position at seq_len, prefill-chunk rows their next chunk — make
        sure the pages exist AND are exclusively owned (copy-on-write
        splits a partially-shared prefix page before the first write).
        On pool exhaustion the ESCALATION ORDER is: (1) LRU-reclaim
        pages held only by the prefix cache — dropping cold cached
        state costs nothing already-computed in flight; (2) preempt the
        most recent other request (recompute-requeue) — now the LAST
        resort, not the first; (3) shed the grower when every victim is
        preemption-capped."""
        rows = (self.scheduler.occupied() if self.chunked_prefill
                else self.scheduler.active())
        for slot, req in list(rows):
            if self.scheduler.slots[slot] is not req:
                continue            # became a victim earlier in the loop
            while True:
                start = int(self.cache.seq_lens[slot])
                if req.state is RequestState.PREFILL:
                    end = start + min(
                        self.prefill_chunk,
                        len(req.resume_tokens) - req.prefill_pos)
                else:
                    end = start + 1
                ok = self.cache.ensure_capacity(slot, end)
                if ok and self.prefix_cache is not None:
                    ok = self.cache.make_writable(slot, start, end)
                if ok:
                    break
                if self.prefix_cache is not None:
                    # reclaim the WHOLE shortfall in one heap walk
                    # (+1 covers a possible COW clone page); calling
                    # reclaim(1) per loop turn would pay a full tree
                    # walk per page under sustained pressure
                    shortfall = max(
                        self.cache.pages_needed(end)
                        - self.cache.slot_page_count(slot) + 1
                        - self.cache.allocator.free_blocks, 1)
                    if self.prefix_cache.reclaim(shortfall):
                        continue
                victim = self.scheduler.preempt_victim(
                    slot, self.max_preemptions,
                    include_prefill=self.chunked_prefill)
                if victim is None:
                    others = [r for i, r in self.scheduler.occupied()
                              if i != slot]
                    if others:
                        # every other running request is at the
                        # preemption cap (non-preemptible by design):
                        # shed THIS grower rather than livelock or
                        # deadlock the pool
                        self.scheduler.release(req)
                        req.close(RequestState.SHED, "preempt_cap")
                        self._quarantine.discard(req.id)
                        self.metrics.on_request_shed("preempt_cap")
                        if self._replay is not None:
                            self._replay.terminal(req)
                        if self._mem is not None:
                            self._mem.note_decision(
                                "shed", request=req.id,
                                reason="preempt_cap")
                        break
                    raise RuntimeError(
                        "KV pool exhausted by a single request — "
                        "add_request validation should have caught this")
                self.metrics.on_preemption()
                if self._mem is not None:
                    self._mem.note_decision(
                        "preempt", victim=victim.id, grower=req.id,
                        kv_pages_free=self.cache.allocator.free_blocks)

    def _decode_once(self, active):
        try:
            # batched injection site: a decode failure is NOT
            # attributable to one request — the quarantine below
            # serializes the batch until it is
            if _fi.is_enabled():
                _fi.fire("serving.decode", batch=len(active))
            bt = jnp.asarray(self.cache.block_tables)
            lens = jnp.asarray(self.cache.seq_lens)
            toks = jnp.asarray(self._slot_tokens)
            with span("serving.decode_step"):
                next_toks, new_pools = self._run_eval(
                    self._decode, self._decode_vals, self.cache.pools,
                    toks, bt, lens)
        except Exception as e:  # poison quarantine (see _on_decode_failure)
            self._on_decode_failure(active, e)
            return
        self.cache.pools = new_pools
        out = np.asarray(next_toks)
        self.metrics.on_decode_step(len(active))
        self._note_quant_step()
        for slot, req in active:
            # the input token's K/V row landed at position seq_len
            self.cache.seq_lens[slot] += 1
            self._accept_token(req, int(out[slot]))

    def _mixed_once(self, rows):
        """ONE mixed ragged step (chunked prefill): decode rows feed
        their pending token (q_len 1), PREFILL rows feed their next
        prompt chunk (q_len up to prefill_chunk) — all through the ONE
        compiled step, so a long prefill costs the decode batch one
        chunk of latency per step instead of a full-prompt stall."""
        C = self.prefill_chunk
        tokens = np.zeros((self.max_slots, C), np.int32)
        q_lens = np.zeros((self.max_slots,), np.int32)
        chunk_rows = 0
        for slot, req in rows:
            if req.state is RequestState.PREFILL:
                toks = req.resume_tokens
                n = min(C, len(toks) - req.prefill_pos)
                tokens[slot, :n] = toks[req.prefill_pos:
                                        req.prefill_pos + n]
                q_lens[slot] = n
                chunk_rows += 1
            else:
                tokens[slot, 0] = self._slot_tokens[slot]
                q_lens[slot] = 1
        try:
            # same batched injection site as the split decode step: a
            # failure is not attributable to one request until the
            # quarantine serializes the batch
            if _fi.is_enabled():
                _fi.fire("serving.decode", batch=len(rows))
            bt = jnp.asarray(self.cache.block_tables)
            lens = jnp.asarray(self.cache.seq_lens)
            with span("serving.mixed_step"):
                next_toks, new_pools = self._run_eval(
                    self._mixed, self._decode_vals, self.cache.pools,
                    jnp.asarray(tokens), bt, lens, jnp.asarray(q_lens))
        except Exception as e:
            self._on_decode_failure(rows, e)
            return
        self.cache.pools = new_pools
        out = np.asarray(next_toks)
        self.metrics.on_decode_step(len(rows))
        self._note_quant_step()
        for _ in range(chunk_rows):
            self.metrics.on_prefill_chunk()
        for slot, req in rows:
            n = int(q_lens[slot])
            self.cache.seq_lens[slot] += n
            if req.state is RequestState.PREFILL:
                req.prefill_pos += n
                if req.prefill_pos < len(req.resume_tokens):
                    continue        # mid-prompt: sampled token discarded
                # final chunk: its last position's logits are the first
                # generated token — the request becomes a decode row
                if self.prefix_cache is not None:
                    self.prefix_cache.insert(
                        req.resume_tokens, self.cache.slot_pages(slot),
                        int(self.cache.seq_lens[slot]))
                req.state = RequestState.DECODING
                req.metrics.on_first_token(now())
                req.trace_phase("decode", slot=slot)
            self._accept_token(req, int(out[slot]))

    def _note_quant_step(self):
        """Per-step quant-KV accounting (FLAGS_serving_quant_kv; one
        attribute check when off): live int8 page count, plus the int8
        bytes this step's attention gathers dequantized — every live
        slot's full history pages, k and v planes, every layer."""
        if not self.quant_kv:
            return
        alloc = self.cache.allocator
        read_pages = sum(-(-int(n) // self.block_size)
                         for n in self.cache.seq_lens if n)
        self.metrics.on_quant_step(
            alloc.usable_blocks - alloc.free_blocks,
            read_pages * self._quant_page_bytes * len(self.cache.pools))

    def _on_decode_failure(self, active, exc):
        """A batched decode raised. With ONE active request the poison
        is named — fail it, keep the engine. With several, requeue them
        all (preempt-by-recompute keeps their output bit-identical) and
        enter serial quarantine: one request per batch until the set
        clears, so the next failure IS attributable. The engine never
        dies for one request's exception.

        Cost, by design: every quarantined request runs solo to
        completion, so one transient batched failure serializes its
        batch's remaining decode. Early exoneration (drop from
        quarantine after one clean solo step, then re-batch) was
        considered and rejected: a re-batched exonerated request
        decoding next to a still-quarantined poison makes the next
        failure unattributable again — with a deterministic poison that
        ping-pongs forever. Strict FCFS also means nothing behind the
        quarantined head could use the freed batch slots anyway."""
        # an OOM-shaped decode failure gets its forensics BEFORE the
        # recovery below mutates the pool state the postmortem must
        # describe (the quarantine path still runs — a transient OOM
        # in a batched decode is recoverable the same way any decode
        # failure is)
        if self._mem is not None and _monitor.memory.looks_like_oom(exc):
            self._mem.write_postmortem(exc)
        if len(active) == 1:
            _, req = active[0]
            self._fail_request(req, exc)
            return
        for slot, req in reversed(list(active)):
            if self.scheduler.slots[slot] is not req:
                continue
            seq_len = int(self.cache.seq_lens[slot])
            self.scheduler.release(req)
            req.state = RequestState.PREEMPTED
            req.metrics.preemptions += 1
            self.scheduler.requeue_front(req)
            self._quarantine.add(req.id)
            self.metrics.on_preemption()
            if req.trace_id is not None:
                req.trace_phase(
                    "preempted", seq_len=seq_len, quarantine=True,
                    slots_active=self.scheduler.slots_active())
        self._recover_consumed_pools()

    def _accept_token(self, req, tok):
        req.generated.append(tok)
        self._slot_tokens[req.slot] = tok
        self.metrics.on_output_token()
        done = (req.remaining <= 0
                or (req.eos_token_id is not None
                    and tok == req.eos_token_id))
        if req.trace_id is not None:
            # token MILESTONES, not every token (bounded journal): the
            # first, every 8th, and the last, each stamped with the KV
            # and batch-slot occupancy the step saw
            n = len(req.generated)
            if n == 1 or done or n % 8 == 0:
                alloc = self.cache.allocator
                req.trace_event(
                    "token", n=n,
                    kv_pages_used=(alloc.usable_blocks
                                   - alloc.free_blocks),
                    slots_active=self.scheduler.slots_active())
        if done:
            self.scheduler.release(req)
            req.finish()
            self._quarantine.discard(req.id)   # survived serial decode
            self.metrics.on_request_finished(len(req.generated))
            req.trace_finish("finished")
            if self._replay is not None:
                self._replay.terminal(req)

    # -- graph analysis ---------------------------------------------------

    def graph_report(self):
        """AOT-lower (never execute) every compiled step this engine
        configuration would run — the ONE mixed step under chunked
        prefill, else decode + the live prefill variant — and return
        the raw graph-analysis artifact for the offline analyzer
        (paddle_tpu/analysis/graph, tools/pthlo.py): jaxpr + StableHLO
        + compiled-HLO text per step, the donated-pool leaf census,
        and the weight census. Representative shapes are the engine's
        own fixed shapes (that fixedness IS the compile-once
        contract). Tracing counts into the compile metrics like any
        trace; call this on fixture engines, not mid-serve."""
        import jax.tree_util as jtu

        from ..analysis.graph.artifact import arg_leaf_census, \
            param_census
        from ..monitor import perf as _perf

        S = self.max_slots
        pools = self.cache.pools
        bt = jnp.asarray(self.cache.block_tables)
        lens = jnp.asarray(self.cache.seq_lens)

        def artifact(jit_fn, raw_fn, args):
            lowered = jit_fn.lower(*args)
            compiled = lowered.compile()
            # weights feed every call (never donated); pools are
            # carried state and MUST alias; the rest is per-call input
            spans = [("weights", len(jtu.tree_leaves(args[0]))),
                     ("state", len(jtu.tree_leaves(args[1]))),
                     ("input", len(jtu.tree_leaves(args[2:])))]
            return {
                "hlo": compiled.as_text(),
                "stablehlo": lowered.as_text(),
                "jaxpr": str(jax.make_jaxpr(raw_fn)(*args)),
                "arg_leaves": arg_leaf_census(
                    jtu.tree_leaves(lowered.args_info), spans),
                "cost": _perf.executable_analysis(compiled, steps=1),
            }

        steps = {}
        if self.chunked_prefill:
            toks = jnp.zeros((S, self.prefill_chunk), jnp.int32)
            ql = jnp.zeros((S,), jnp.int32)
            steps["mixed"] = artifact(
                self._mixed, self._mixed_fn,
                (self._decode_vals, pools, toks, bt, lens, ql))
        else:
            toks = jnp.zeros((S,), jnp.int32)
            steps["decode"] = artifact(
                self._decode, self._decode_fn,
                (self._decode_vals, pools, toks, bt, lens))
            P = self._bucket(8)
            ids = jnp.zeros((1, P), jnp.int32)
            row = jnp.asarray(self.cache.block_tables[0])
            if self.prefix_cache is not None:
                steps["suffix_prefill"] = artifact(
                    self._suffix_prefill, self._suffix_prefill_fn,
                    (self._state_vals, pools, ids, row,
                     jnp.asarray(0, jnp.int32),
                     jnp.asarray(P, jnp.int32)))
            else:
                steps["prefill"] = artifact(
                    self._prefill, self._prefill_fn,
                    (self._state_vals, pools, ids, row,
                     jnp.asarray(P, jnp.int32)))
        return {
            "kind": "serving",
            "params": param_census(zip(self._names, self._state_vals)),
            "steps": steps,
            "mesh_axes": None,
            "qsync_buckets": None,
            "flags": {"prefix_cache": self.prefix_cache is not None,
                      "chunked_prefill": self.chunked_prefill,
                      "quant_kv": self.quant_kv,
                      "quant_weights": self.quant_weights},
        }

    # -- compiled steps ---------------------------------------------------

    def _bucket(self, n):
        """Prefill length bucket: next power of two (>= 8), capped at
        max_model_len rounded up to a multiple of 8 AND at the block
        table's position capacity — a pad length past ``MB * bs`` would
        make the prefill scatter's clamped gather write pad K/V over
        the request's last real page."""
        p = 8
        while p < n:
            p *= 2
        cap = min(-(-self.max_model_len // 8) * 8,
                  self.cache.max_blocks_per_slot * self.block_size)
        return min(p, max(cap, n))

    def _dequant_state(self, state_vals):
        """Rebuild the fp32 weight list from the mixed quantized state
        (traced — runs INSIDE the decode/mixed steps, so the per-leaf
        dequant is a broadcast-multiply XLA fuses into the consuming
        matmul's operand read; the int8 planes are what crosses HBM).
        No quantized leaves (flag off): the list passes through
        untouched and the trace is unchanged."""
        if not self._qw_dtypes:
            return list(state_vals)
        from ..kernels.quant import dequantize_int8_weight

        out = list(state_vals)
        for i, dt in self._qw_dtypes.items():
            q, scales = out[i]
            out[i] = dequantize_int8_weight(q, scales, dt)
        return out

    def _run_eval(self, fn, *args):
        was_training = self.model.training
        self.model.eval()
        try:
            return fn(*args)
        finally:
            if was_training:
                self.model.train()

    def _prefill_fn(self, state_vals, pools, ids, table_row, true_len):
        from ..core.dispatch import no_grad
        from ..core.tensor import Tensor

        self.metrics.on_prefill_compile()       # trace-time counter
        with self.model.bind_state(self._names, list(state_vals)):
            with no_grad():
                views = [PagedPrefillView(p, table_row, self.block_size)
                         for p in pools]
                logits, views = self.model.generate_step(
                    Tensor(ids), views, 0)
        lv = logits._value if isinstance(logits, Tensor) else logits
        last = lv[0, true_len - 1].astype(jnp.float32)
        tok = jnp.argmax(last, axis=-1).astype(jnp.int32)
        return tok, [v.pool for v in views]

    def _decode_fn(self, state_vals, pools, tokens, block_tables,
                   seq_lens):
        from ..core.dispatch import no_grad
        from ..core.tensor import Tensor

        self.metrics.on_decode_compile()        # trace-time counter
        with self.model.bind_state(self._names,
                                   self._dequant_state(state_vals)):
            with no_grad():
                views = [PagedDecodeView(p, block_tables, seq_lens,
                                         self.block_size)
                         for p in pools]
                logits, views = self.model.generate_step(
                    Tensor(tokens[:, None]), views, seq_lens)
        lv = logits._value if isinstance(logits, Tensor) else logits
        nxt = jnp.argmax(lv[:, -1, :].astype(jnp.float32),
                         axis=-1).astype(jnp.int32)
        return nxt, [v.pool for v in views]

    def _suffix_prefill_fn(self, state_vals, pools, ids, table_row,
                           hist, true_len):
        """Cache-aware prefill: ids [1, P] (right-padded uncached
        suffix) runs at absolute positions hist..hist+true_len-1 over
        the slot's adopted pool history — the mixed ragged view with
        S == 1. ``hist`` and ``true_len`` are traced, so a hit and a
        miss (hist == 0) share the per-bucket compile."""
        from ..core.dispatch import no_grad
        from ..core.tensor import Tensor

        self.metrics.on_prefill_compile()       # trace-time counter
        hist_v = jnp.reshape(hist, (1,)).astype(jnp.int32)
        qlen_v = jnp.reshape(true_len, (1,)).astype(jnp.int32)
        with self.model.bind_state(self._names, list(state_vals)):
            with no_grad():
                views = [PagedMixedView(p, table_row[None, :], hist_v,
                                        qlen_v, self.block_size)
                         for p in pools]
                logits, views = self.model.generate_step(
                    Tensor(ids), views, hist_v)
        lv = logits._value if isinstance(logits, Tensor) else logits
        last = lv[0, true_len - 1].astype(jnp.float32)
        tok = jnp.argmax(last, axis=-1).astype(jnp.int32)
        return tok, [v.pool for v in views]

    def _mixed_fn(self, state_vals, pools, tokens, block_tables,
                  seq_lens, q_lens):
        """THE compiled step under chunked prefill: [S, C] ragged rows
        (decode rows q_len 1, prefill chunks up to C, idle rows 0) over
        fixed shapes — requests arriving, chunking, finishing and
        preempting never change a shape, so this traces EXACTLY once
        (it counts into decode_compiles; the compile-once contract
        holds with the flag on)."""
        from ..core.dispatch import no_grad
        from ..core.tensor import Tensor

        self.metrics.on_decode_compile()        # trace-time counter
        with self.model.bind_state(self._names,
                                   self._dequant_state(state_vals)):
            with no_grad():
                views = [PagedMixedView(p, block_tables, seq_lens,
                                        q_lens, self.block_size)
                         for p in pools]
                logits, views = self.model.generate_step(
                    Tensor(tokens), views, seq_lens)
        lv = logits._value if isinstance(logits, Tensor) else logits
        # each row's next token comes from its LAST VALID position's
        # logits (q_len-1; idle rows clamp to 0 and are ignored host-side)
        last = jnp.take_along_axis(
            lv.astype(jnp.float32),
            jnp.maximum(q_lens - 1, 0)[:, None, None], axis=1)[:, 0]
        nxt = jnp.argmax(last, axis=-1).astype(jnp.int32)
        return nxt, [v.pool for v in views]
