"""Router metrics: the ``router_*`` family on the shared registry.

Registered at import (idempotent, the serving/metrics.py idiom) but
series-free until first touch — with ``FLAGS_serving_fleet`` off no
router exists, nothing increments, and the registry snapshot carries
no ``router_*`` series (test-pinned). All five are documented in the
README metrics catalog (the metric pass's machine-checked contract).
The two histograms record trace-id exemplars through the registry
hook when the router journals (FLAGS_monitor_trace), so a p99 bucket
resolves to one request's fleet-wide timeline.

``router_requests_total{outcome}`` outcomes:

  accepted    request admitted by the router (a nonce exists; the
              never-lose-an-accepted-request property counts these)
  dispatched  enqueued on a replica (first placement)
  rerouted    re-dispatched after a failed dispatch / drain / evict
              (same nonce — the replica-side dedup makes this safe)
  finished    terminal success observed
  failed      terminal non-success observed (expired/shed/poisoned on
              the replica — the router reports, it does not retry a
              request the replica terminated)
  unroutable  no dispatchable replica after the bounded retry walk;
              the request STAYS queued router-side (not lost) and the
              next pump retries it
"""
from __future__ import annotations

from ...monitor import counter as _mcounter
from ...monitor import histogram as _mhistogram

REQUESTS = _mcounter(
    "router_requests_total",
    "router request lifecycle events", labelnames=("outcome",))
AFFINITY_HITS = _mcounter(
    "router_affinity_hits_total",
    "dispatches placed by the prefix-affinity radix index "
    "(vs pure least-loaded)")
EVICTIONS = _mcounter(
    "router_replica_evictions_total",
    "replicas evicted on a dead lease (affinity entries invalidated)")
DISPATCH_SECONDS = _mhistogram(
    "router_dispatch_seconds",
    "admission -> accepted-by-a-replica latency, including the "
    "bounded retry-with-reroute walk")
E2E_SECONDS = _mhistogram(
    "router_e2e_seconds",
    "router-observed admission -> terminal latency (queue + dispatch "
    "walk + replica residency, across reroutes)")
