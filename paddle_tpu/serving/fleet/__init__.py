"""Serving fleet: replicated engines + prefix-affinity router.

Three pieces (ISSUE-16):

- ``membership``: the register/renew/evict/drain protocol as pure
  functions over an injected store (TCPStore in production, SimStore
  under the ptcheck ``router_membership`` fixture), plus the
  ``ReplicaView`` liveness watcher (the elastic TTL lease, reused)
  and the pure ``pick_replica`` dispatch choice.
- ``Replica``: one engine behind the fleet HTTP protocol —
  nonce-idempotent enqueue, result polling, load signals, lease
  heartbeat (``replica.py``).
- ``Router``: admission -> dispatch with a prefix-affinity radix
  index, least-loaded tie-break, bounded retry-with-reroute,
  healthz-driven drain-and-reschedule, dead-lease eviction
  (``router.py``; hosted by ``tools/serving_router.py``).

Prefill/decode disaggregation is OUT of scope: the capability
snapshot's ``disaggregation`` field is the seam (membership.py).
Everything here is gated on ``FLAGS_serving_fleet`` (default off).
"""
from __future__ import annotations

from . import membership
from .membership import ReplicaView, pick_replica
from .replica import Replica
from .router import AffinityIndex, Router

__all__ = [
    "membership",
    "ReplicaView",
    "pick_replica",
    "Replica",
    "AffinityIndex",
    "Router",
]
