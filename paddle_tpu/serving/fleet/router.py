"""Fleet router: admission -> dispatch with prefix affinity + drain/evict.

The standalone dispatch process in front of N engine replicas
(``tools/serving_router.py`` hosts it; the fleet benchmark drives it
in-process). One router turn (``pump()``):

1. **Membership** — refresh the ``membership.ReplicaView`` (the
   elastic TTL lease over ``__sfleet/beat/{r}``): a newly-live rank's
   announced record is adopted; a dead lease EVICTS the replica
   (``router_replica_evictions_total`` + affinity invalidation +
   ``membership.evict_replica`` so every other router converges
   without waiting out its own TTL); a re-registration with a newer
   generation revives an evicted rank.
2. **Load + health** — scrape each live replica's ``/sfleet/load``
   (kv-page occupancy + queue depth, the gauges' values served by the
   replica) and ``/healthz``; a 503/stalled verdict or repeated scrape
   failure marks the replica DRAINING: it gets no new work and its
   queued-but-unstarted requests re-route. Draining is published via
   ``membership.mark_draining`` so peer routers agree.
3. **Dispatch** — prefix-affinity first: a router-side radix index
   over block_size token chunks (the SAME chunking as
   ``prefix_cache.py``) maps prompt prefixes to the replicas that
   served them, so shared-prefix requests land where their KV pages
   are already cached; least-loaded (occupancy + queue depth) breaks
   ties. A failed dispatch walks the next candidate, bounded by
   ``max_retries`` — idempotent, because every request carries a
   router-minted nonce and the replica dedups on it (a retried
   request is never double-admitted).
4. **Progress** — poll dispatched requests' ``/sfleet/result/{nonce}``;
   first observed output token stamps TTFT; terminal states count into
   ``router_requests_total{finished|failed}``.

Never-lose-an-accepted-request: a request that got a nonce is terminal
(finished/failed-by-the-replica) or still queued/dispatched somewhere
— eviction, drain and dispatch failure all re-route, never drop (the
ptcheck ``router_membership`` fixture explores exactly this against
crash/lost-ack interleavings of the membership half).

Tracing (FLAGS_monitor_trace, default off — every emitter below
no-ops on a None trace id): ``submit()`` mints the fleet-wide trace
and the router journals the dispatch half of the journey —
``router_queue`` phases, a ``placement`` span per candidate pick
(affinity depth / chosen replica / load score), a ``dispatch`` span
per HTTP attempt (nonce, outcome), a ``reroute`` span naming WHY work
moved (shed / 404 / lease-evicted / drain), and a ``settle`` span at
terminal accounting. Each enqueue POST carries a traceparent field
(``pt1-<trace_id>-<dispatch span id>``) so the replica engine's
phase spans land under the SAME id with the dispatch span as remote
parent; ``/sfleet/result`` hands the replica's span summary back for
e2e attribution, and ``trace_segments()`` federates the replica
fragments for ``/debugz/trace/{id}``.
"""
from __future__ import annotations

import http.client
import itertools
import json
import os
import threading
import time
import urllib.error
import urllib.request

from ...core import flags as _flags
from ...monitor import fleet as _mfleet
from ...monitor import trace as _trace
from ...monitor.registry import warn_once
from .. import replay as _replay
from . import membership
from .metrics import (AFFINITY_HITS, DISPATCH_SECONDS, E2E_SECONDS,
                      EVICTIONS, REQUESTS)

_ROUTER_THREAD = "pt-sfleet-router"

# terminal replica-side request states (engine RequestState values):
# the router reports these, it never retries a request the replica
# terminated on purpose
_REPLICA_TERMINAL_OK = ("finished",)
_REPLICA_TERMINAL_BAD = ("expired", "shed", "failed")
_SCRAPE_ERRORS = (OSError, ValueError, http.client.HTTPException)


def _require_flag(what):
    if not _flags.flag("FLAGS_serving_fleet"):
        raise RuntimeError(
            "%s requires FLAGS_serving_fleet=true (the serving-fleet "
            "plane is default-off; set it BEFORE construction — the "
            "flag is latched, the PR-9 convention)" % what)


def _http_get_json(url, timeout_s):
    """(status, payload) — HTTP error codes with a JSON body still
    parse (healthz 503, result 404); transport errors raise."""
    try:
        with urllib.request.urlopen(url, timeout=timeout_s) as r:
            return r.status, json.loads(r.read().decode())
    except urllib.error.HTTPError as e:
        body = e.read()
        try:
            return e.code, json.loads(body.decode())
        except ValueError:
            return e.code, {}


def _http_post_json(url, payload, timeout_s):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as r:
            return r.status, json.loads(r.read().decode())
    except urllib.error.HTTPError as e:
        body = e.read()
        try:
            return e.code, json.loads(body.decode())
        except ValueError:
            return e.code, {}


class AffinityIndex:
    """Router-side radix index over block_size token chunks.

    Same chunking as the engine's ``prefix_cache.py`` radix tree —
    full chunks of ``tuple(tokens[i*bs:(i+1)*bs])`` over at most
    ``len(tokens) - 1`` tokens (the cache never stores a prompt's last
    token, so matching past it could not hit pages anyway) — but the
    VALUES are replica ranks, not KV pages: the index remembers which
    replicas served which prefixes, so a shared-prefix request is
    dispatched to a replica whose radix cache is already warm.
    Depth-capped; ``invalidate(rank)`` drops an evicted replica's
    entries everywhere (its pages are gone with it)."""

    def __init__(self, block_size=16, max_chunks=64):
        self.block_size = int(block_size)
        self.max_chunks = int(max_chunks)
        self._root = {"children": {}, "ranks": set()}
        self._nodes = 0

    def _chunks(self, tokens):
        bs = self.block_size
        usable = max(len(tokens) - 1, 0)
        n = min(usable // bs, self.max_chunks)
        return [tuple(tokens[i * bs:(i + 1) * bs]) for i in range(n)]

    def match(self, tokens):
        """{rank: matched chunk depth} — the deepest node along the
        prompt's chunk path that each rank appears on."""
        out = {}
        node = self._root
        for depth, chunk in enumerate(self._chunks(tokens), start=1):
            node = node["children"].get(chunk)
            if node is None:
                break
            for rank in node["ranks"]:
                out[rank] = depth
        return out

    def note(self, tokens, rank):
        """Record that ``rank`` served a request with this prompt."""
        node = self._root
        for chunk in self._chunks(tokens):
            nxt = node["children"].get(chunk)
            if nxt is None:
                nxt = node["children"][chunk] = {
                    "children": {}, "ranks": set()}
                self._nodes += 1
            nxt["ranks"].add(rank)
            node = nxt

    def invalidate(self, rank):
        """Drop every entry for an evicted replica, pruning emptied
        subtrees (the dead replica's cached pages died with it)."""
        def walk(node):
            for chunk in list(node["children"]):
                child = node["children"][chunk]
                child["ranks"].discard(rank)
                walk(child)
                if not child["children"] and not child["ranks"]:
                    del node["children"][chunk]
                    self._nodes -= 1
        walk(self._root)

    def stats(self):
        return {"block_size": self.block_size, "nodes": self._nodes,
                "max_chunks": self.max_chunks}


class Router:
    """Admission -> dispatch over HTTP to the replica plane.

    Store mode (production): ``store`` + ``world_size`` — membership,
    records and drain markers ride the injected TCPStore client.
    Static mode (tests): ``endpoints`` = {rank: url}, no store traffic;
    drain/evict are driven purely by scrape results."""

    def __init__(self, store=None, world_size=None, endpoints=None,
                 block_size=16, ttl_s=3.0, http_timeout_s=2.0,
                 max_retries=3, suspect_after=2, clock=None):
        _require_flag("Router")
        if store is None and not endpoints:
            raise ValueError("Router needs store+world_size or "
                             "explicit endpoints")
        self._store = store
        self._view = (membership.ReplicaView(
            store, world_size, ttl_s=ttl_s, clock=clock)
            if store is not None else None)
        self._clock = clock if clock is not None else time.monotonic
        self.http_timeout_s = float(http_timeout_s)
        self.max_retries = int(max_retries)
        self.suspect_after = int(suspect_after)
        self.affinity = AffinityIndex(block_size)
        self._lock = threading.Lock()
        self._replicas = {}     # rank -> replica entry dict
        self._requests = {}     # nonce -> request dict
        self._order = []        # nonces in admission order
        self._seq = itertools.count()
        self._salt = os.urandom(4).hex()
        self._trace_index = {}  # trace_id -> nonce (federation lookup)
        self._stop = threading.Event()
        self._thread = None
        for rank, url in sorted((endpoints or {}).items()):
            self._replicas[int(rank)] = self._entry(
                int(rank), url, generation=0, capabilities=dict(
                    membership.DEFAULT_CAPABILITIES))
        _mfleet.set_router_hook(self)

    @staticmethod
    def _entry(rank, url, generation, capabilities):
        url = (url or "").rstrip("/")
        return {"rank": rank, "url": url, "generation": generation,
                "capabilities": capabilities, "state": "live",
                "occupancy": 0.0, "queue_depth": 0, "active_slots": 0,
                "decode_compiles": None, "requests_finished": None,
                "scrape_errors": 0, "dispatches": 0,
                "affinity_hits": 0, "last_load_at": None}

    # -- membership ------------------------------------------------------

    def refresh_membership(self):
        if self._view is None:
            return
        alive = set(self._view.alive())
        dead = set(self._view.dead())
        draining = set(self._view.draining())
        for rank in sorted(alive):
            ent = self._replicas.get(rank)
            if ent is None or ent["state"] == "evicted":
                rec = self._view.record(rank)
                if not rec:
                    continue
                if ent is not None and \
                        rec.get("generation", 0) <= ent["generation"]:
                    continue    # the evicted incarnation, not a rejoin
                self._replicas[rank] = self._entry(
                    rank, rec.get("url"),
                    rec.get("generation", 0),
                    dict(rec.get("capabilities") or {}))
                if ent is not None:
                    # a generation-fenced rejoin ends the eviction
                    # episode (monitor/incidents.py; no-op while off)
                    try:
                        from ...monitor import incidents as _incidents

                        _incidents.resolve(
                            "router/evicted/rank%d" % rank,
                            reason="replica rejoined (generation %d)"
                            % rec.get("generation", 0))
                    except Exception as e:
                        warn_once(
                            "sfleet.router.incident_resolve",
                            "paddle_tpu.serving.fleet: eviction "
                            "incident resolve failed (replica %d is "
                            "still re-adopted): %r" % (rank, e))
            elif rank in draining:
                ent["state"] = "draining"
        for rank, ent in sorted(self._replicas.items()):
            if ent["state"] != "evicted" and rank in dead:
                self.evict(rank)

    def evict(self, rank):
        """Dead lease: no dispatch ever again (this incarnation), drop
        its affinity entries, converge peers via the store."""
        ent = self._replicas.get(rank)
        if ent is None or ent["state"] == "evicted":
            return
        ent["state"] = "evicted"
        self.affinity.invalidate(rank)
        EVICTIONS.inc()
        # ptslo (monitor/incidents.py): a dead-lease eviction is an
        # incident naming the rank; a newer-generation rejoin resolves
        # it (refresh_membership). One flag branch while the plane is
        # off.
        try:
            from ...monitor import incidents as _incidents

            _incidents.open(
                "router/evicted/rank%d" % rank, severity="page",
                kind="replica_eviction", source="router", rank=rank,
                summary="replica rank %d evicted on dead lease"
                % rank,
                evidence={"url": ent["url"],
                          "generation": ent["generation"]})
        except Exception as e:
            warn_once(
                "sfleet.router.incident_open",
                "paddle_tpu.serving.fleet: eviction incident open "
                "failed (replica %d is still evicted): %r"
                % (rank, e))
        if self._store is not None:
            membership.evict_replica(self._store, rank)

    def drain(self, rank, reason="healthz"):
        """503/stalled/unreachable: no NEW work; queued-but-unstarted
        requests re-route on the next pump. Published to the store so
        peer routers stop dispatching too."""
        ent = self._replicas.get(rank)
        if ent is None or ent["state"] in ("draining", "evicted"):
            return
        ent["state"] = "draining"
        ent["drain_reason"] = reason
        if self._store is not None:
            membership.mark_draining(self._store, rank)

    # -- load + health scrape --------------------------------------------

    def scrape_loads(self):
        for rank, ent in sorted(self._replicas.items()):
            if ent["state"] == "evicted":
                continue
            try:
                _, load = _http_get_json(
                    ent["url"] + "/sfleet/load", self.http_timeout_s)
                code, hz = _http_get_json(
                    ent["url"] + "/healthz", self.http_timeout_s)
            except _SCRAPE_ERRORS as e:
                ent["scrape_errors"] += 1
                warn_once(
                    "sfleet.router.scrape.%d" % rank,
                    "paddle_tpu.serving.fleet: load scrape of replica "
                    "%d (%s) failed (%r) — draining it after %d "
                    "consecutive failures" % (
                        rank, ent["url"], e, self.suspect_after))
                if ent["scrape_errors"] >= self.suspect_after:
                    self.drain(rank, reason="unreachable")
                continue
            ent["scrape_errors"] = 0
            ent["occupancy"] = float(load.get("occupancy") or 0.0)
            ent["queue_depth"] = int(load.get("queue_depth") or 0)
            ent["active_slots"] = int(load.get("active_slots") or 0)
            ent["decode_compiles"] = load.get("decode_compiles")
            ent["requests_finished"] = load.get("requests_finished")
            ent["last_load_at"] = self._clock()
            if load.get("draining"):
                self.drain(rank, reason="engine_draining")
            elif code == 503 or (hz or {}).get("status") == "stalled":
                self.drain(rank, reason="healthz")
            elif ent["state"] == "draining":
                # drain recovery: the replica answers again, healthz is
                # clean and its engine is not draining — a transient
                # stall (first-step compile, GC pause, brief partition)
                # must not permanently halve the fleet
                ent["state"] = "live"
                ent.pop("drain_reason", None)
                if self._store is not None:
                    membership.clear_draining(self._store, rank)

    @staticmethod
    def _load_score(ent):
        # occupancy (0..1) + queue depth, normalized so one queued
        # request outweighs a full pool only past ~16 waiting — the
        # scraped-gauges tie-break, not a scheduler
        return ent["occupancy"] + ent["queue_depth"] / 16.0

    # -- admission + dispatch --------------------------------------------

    def submit(self, prompt, max_new_tokens=32, eos_token_id=None,
               deadline_s=None):
        """Admit one request; returns its nonce. The request is never
        lost after this point: dispatch failure leaves it queued
        router-side and every pump retries."""
        nonce = "%s-%06d" % (self._salt, next(self._seq))
        # fleet-wide trace (None while FLAGS_monitor_trace is off —
        # every span call below no-ops on it): the router owns the
        # trace id; replicas adopt it via the enqueue traceparent
        tid = _trace.new_trace("fleet_request", nonce=nonce,
                               prompt_tokens=len(prompt),
                               max_new_tokens=int(max_new_tokens))
        root = _trace.start_span("route", tid, kind="request",
                                 nonce=nonce)
        with self._lock:
            req = {"nonce": nonce, "prompt": list(prompt),
                   "max_new_tokens": int(max_new_tokens),
                   "eos_token_id": eos_token_id,
                   "deadline_s": deadline_s,
                   "state": "queued", "rank": None,
                   "replica_state": None, "reroutes": 0,
                   "submitted_at": self._clock(),
                   "first_token_at": None, "finished_at": None,
                   "output_tokens": 0, "tokens": None,
                   "affinity": False, "_dispatched_once": False,
                   "status_reason": None,
                   "trace_id": tid, "attempt_ranks": [],
                   "attempts": [], "reroute_reasons": [],
                   "replica_trace": None,
                   "_span_root": root, "_span_queue": None}
            self._requests[nonce] = req
            self._order.append(nonce)
            if tid is not None:
                self._trace_index[tid] = nonce
        req["_span_queue"] = _trace.start_span(
            "router_queue", tid, parent_id=root, kind="phase")
        REQUESTS.labels("accepted").inc()
        self._try_dispatch(req)
        return nonce

    def _candidates(self):
        return [r for r, ent in self._replicas.items()
                if ent["state"] == "live"]

    def _try_dispatch(self, req):
        candidates = self._candidates()
        affinity = self.affinity.match(req["prompt"])
        attempts = 0
        tid = req.get("trace_id")
        root = req.get("_span_root")
        while candidates and attempts < self.max_retries:
            load = {r: self._load_score(self._replicas[r])
                    for r in candidates}
            rank, used_affinity = membership.pick_replica(
                candidates, load=load, affinity=affinity)
            if rank is None:
                break
            attempts += 1
            ent = self._replicas[rank]
            psid = _trace.start_span(
                "placement", tid, parent_id=root, kind="placement",
                replica=rank, affinity_depth=affinity.get(rank, 0),
                load_score=round(load[rank], 4),
                candidates=len(candidates))
            _trace.end_span(psid)
            dsid = _trace.start_span(
                "dispatch", tid, parent_id=root, kind="dispatch",
                nonce=req["nonce"], replica=rank,
                attempt=len(req.get("attempts") or ()) + 1)
            payload = {"nonce": req["nonce"], "prompt": req["prompt"],
                       "max_new_tokens": req["max_new_tokens"],
                       "eos_token_id": req["eos_token_id"],
                       "deadline_s": req["deadline_s"]}
            # cross-process context: the dispatch span is the remote
            # parent of the replica engine's request span. Absent
            # while the journal is off — the wire stays bit-identical.
            tp = _trace.format_traceparent(tid, dsid)
            if tp is not None:
                payload["traceparent"] = tp
            try:
                code, resp = _http_post_json(
                    ent["url"] + "/sfleet/enqueue", payload,
                    self.http_timeout_s)
            except _SCRAPE_ERRORS:
                # unreachable mid-dispatch: suspect it, walk on — the
                # nonce makes the retry idempotent even if the replica
                # DID admit before the connection died
                _trace.end_span(dsid, outcome="unreachable")
                _replay.note_dispatch(
                    trace_id=tid, nonce=req["nonce"], rank=rank,
                    endpoint=ent["url"],
                    attempt=len(req["attempts"]) + 1,
                    outcome="unreachable")
                req["attempts"].append(
                    {"rank": rank, "outcome": "unreachable"})
                self.drain(rank, reason="dispatch_failed")
                candidates.remove(rank)
                continue
            if code == 200:
                _trace.end_span(
                    dsid, outcome="accepted",
                    deduped=bool(resp.get("deduped")))
                # replay journal (FLAGS_serving_replay; one enabled
                # branch when off): the dispatch decision keyed by the
                # fleet trace id — the stitch a fleet capture uses to
                # reassemble per-replica journals into one workload. A
                # reroute shows up as attempt > 1 under the SAME
                # nonce; the replica dedups admission on it, so the
                # serving replica still journals ONE entry
                _replay.note_dispatch(
                    trace_id=tid, nonce=req["nonce"], rank=rank,
                    endpoint=ent["url"],
                    attempt=len(req["attempts"]) + 1,
                    outcome="rerouted" if req["_dispatched_once"]
                    else "accepted")
                req["attempts"].append(
                    {"rank": rank, "outcome": "accepted"})
                req["attempt_ranks"].append(rank)
                req["rank"] = rank
                req["state"] = "dispatched"
                req["replica_state"] = resp.get("state") or "queued"
                REQUESTS.labels(
                    "rerouted" if req["_dispatched_once"]
                    else "dispatched").inc()
                if req["_dispatched_once"]:
                    req["reroutes"] += 1
                req["_dispatched_once"] = True
                req["affinity"] = used_affinity
                if used_affinity:
                    AFFINITY_HITS.inc()
                    ent["affinity_hits"] += 1
                ent["dispatches"] += 1
                ent["queue_depth"] += 1     # optimistic, until rescrape
                self.affinity.note(req["prompt"], rank)
                if req.get("_span_queue") is not None:
                    _trace.end_span(req["_span_queue"], replica=rank)
                    req["_span_queue"] = None
                with _trace.exemplar_context(tid):
                    DISPATCH_SECONDS.observe(
                        max(self._clock() - req["submitted_at"], 0.0))
                return True
            # 409 draining / queue_full, or any other refusal: walk on
            reason = (resp or {}).get("error")
            _trace.end_span(dsid, outcome="refused", reason=reason)
            _replay.note_dispatch(
                trace_id=tid, nonce=req["nonce"], rank=rank,
                endpoint=ent["url"], attempt=len(req["attempts"]) + 1,
                outcome="refused", reason=reason)
            req["attempts"].append(
                {"rank": rank, "outcome": "refused", "reason": reason})
            if reason == "draining":
                self.drain(rank, reason="admission_draining")
            candidates.remove(rank)
            affinity.pop(rank, None)
        _trace.add_event(root, "unroutable", attempts=attempts)
        REQUESTS.labels("unroutable").inc()
        return False

    # -- progress --------------------------------------------------------

    def _poll_request(self, req):
        ent = self._replicas.get(req["rank"])
        if ent is None:
            return
        try:
            code, resp = _http_get_json(
                "%s/sfleet/result/%s" % (ent["url"], req["nonce"]),
                self.http_timeout_s)
        except _SCRAPE_ERRORS:
            ent["scrape_errors"] += 1
            if ent["scrape_errors"] >= self.suspect_after:
                self.drain(req["rank"], reason="unreachable")
            return
        if code == 404:
            # the replica does not know the nonce (restarted with a
            # new generation): the work is gone, re-route it
            self._reroute(req, "404")
            return
        if code != 200:
            return
        req["replica_state"] = resp.get("state")
        n_out = int(resp.get("output_tokens") or 0)
        if n_out > 0 and req["first_token_at"] is None:
            req["first_token_at"] = self._clock()
        req["output_tokens"] = n_out
        if resp.get("state") == "shed" and \
                resp.get("reason") in ("draining", "queue_full"):
            # the replica shed it at admission (the pre-check raced a
            # drain): the request never ran — re-route, don't fail it
            self._reroute(req, "shed")
            return
        if resp.get("state") in _REPLICA_TERMINAL_OK:
            req["state"] = "finished"
            req["tokens"] = resp.get("tokens")
            req["finished_at"] = self._clock()
            REQUESTS.labels("finished").inc()
            self._settle(req, "finished", resp)
        elif resp.get("state") in _REPLICA_TERMINAL_BAD:
            req["state"] = "failed"
            req["status_reason"] = resp.get("reason")
            req["finished_at"] = self._clock()
            REQUESTS.labels("failed").inc()
            self._settle(req, "failed", resp)

    def _settle(self, req, status, resp):
        """Terminal accounting: e2e histogram (+ trace-id exemplar),
        the replica's span summary from the result payload, and the
        settle/root span closes."""
        e2e = max(req["finished_at"] - req["submitted_at"], 0.0)
        with _trace.exemplar_context(req.get("trace_id")):
            E2E_SECONDS.observe(e2e)
        if resp.get("trace_id") is not None:
            req["replica_trace"] = {
                "trace_id": resp.get("trace_id"),
                "phases_s": resp.get("phases_s")}
        tid = req.get("trace_id")
        if tid is None:
            return
        if req.get("_span_queue") is not None:
            _trace.end_span(req["_span_queue"])
            req["_span_queue"] = None
        ssid = _trace.start_span(
            "settle", tid, parent_id=req.get("_span_root"),
            kind="settle", replica=req["rank"], status=status,
            reroutes=req["reroutes"],
            replica_phases_s=(req["replica_trace"] or {}).get(
                "phases_s"))
        _trace.end_span(ssid)
        _trace.end_span(req.get("_span_root"), status=status,
                        replica=req["rank"],
                        output_tokens=req["output_tokens"],
                        reroutes=req["reroutes"],
                        e2e_s=round(e2e, 6))
        req["_span_root"] = None

    def _reroute(self, req, reason):
        """Move the work: the reroute span names WHY (shed / 404 /
        lease-evicted / drain) — the causality the merged fleet
        timeline pins."""
        rsid = _trace.start_span(
            "reroute", req.get("trace_id"),
            parent_id=req.get("_span_root"), kind="reroute",
            reason=reason, from_rank=req["rank"])
        _trace.end_span(rsid)
        req["reroute_reasons"].append(reason)
        req["state"] = "queued"
        req["rank"] = None
        req["replica_state"] = None
        if req.get("trace_id") is not None and \
                req.get("_span_queue") is None:
            req["_span_queue"] = _trace.start_span(
                "router_queue", req["trace_id"],
                parent_id=req.get("_span_root"), kind="phase")
        self._try_dispatch(req)

    def pump(self):
        """One router turn; returns progress counts."""
        self.refresh_membership()
        self.scrape_loads()
        outstanding = 0
        for nonce in list(self._order):
            req = self._requests[nonce]
            if req["state"] in ("finished", "failed"):
                continue
            outstanding += 1
            if req["state"] == "queued":
                self._try_dispatch(req)
                continue
            ent = self._replicas.get(req["rank"])
            if ent is None or ent["state"] == "evicted":
                # the replica died with the work: re-dispatch
                self._reroute(req, "lease-evicted")
            elif ent["state"] == "draining" and \
                    req["replica_state"] in (None, "queued"):
                # drain-and-reschedule: queued-but-unstarted work moves
                # off the draining replica (started work finishes there)
                self._reroute(req, "drain")
            else:
                self._poll_request(req)
        return {"outstanding": outstanding,
                "total": len(self._requests)}

    def wait_all(self, timeout_s=60.0, poll_interval_s=0.02):
        """Pump until every admitted request is terminal (benchmark /
        test driver). Returns True when all settled."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self.pump()["outstanding"] == 0:
                return True
            time.sleep(poll_interval_s)
        return self.pump()["outstanding"] == 0

    def request(self, nonce):
        return self._requests.get(nonce)

    def requests(self):
        return [self._requests[n] for n in self._order]

    # -- serve loop (tools/serving_router.py) ----------------------------

    def start(self, interval_s=0.05):
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._serve_loop, args=(float(interval_s),),
                name=_ROUTER_THREAD, daemon=True)
            self._thread.start()
        return self

    def _serve_loop(self, interval_s):
        while not self._stop.wait(interval_s):
            try:
                self.pump()
            except Exception as e:
                warn_once("sfleet.router.pump",
                          "paddle_tpu.serving.fleet: router pump "
                          "failed (loop continues): %r" % (e,))

    def close(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None
        if _mfleet._router_hook is self:
            _mfleet.clear_router_hook()

    # -- HTTP surface (rides the router process's MetricsServer) ---------

    def install_routes(self, server):
        """Register the router's own HTTP API on a MetricsServer:
        POST /sfleet/submit, GET /sfleet/status/{nonce} (the /debugz/
        router routes are process-wide via the monitor hook)."""
        server.add_post_route("sfleet/submit", self._http_submit)
        server.add_prefix_route("sfleet/status", self._http_status)

    def _http_submit(self, body):
        try:
            payload = json.loads(body.decode())
            prompt = payload["prompt"]
            if not isinstance(prompt, list):
                raise ValueError("prompt must be a token-id list")
        except (ValueError, KeyError, UnicodeDecodeError) as e:
            return (400, "application/json",
                    json.dumps({"error": repr(e)}).encode())
        nonce = self.submit(
            prompt, max_new_tokens=int(payload.get(
                "max_new_tokens", 32)),
            eos_token_id=payload.get("eos_token_id"),
            deadline_s=payload.get("deadline_s"))
        return (200, "application/json",
                json.dumps({"nonce": nonce}).encode())

    def _http_status(self, nonce):
        req = self._requests.get(nonce)
        if req is None:
            return (404, "application/json",
                    json.dumps({"error": "unknown nonce",
                                "nonce": nonce}).encode())
        out = {k: req[k] for k in (
            "nonce", "state", "rank", "replica_state", "reroutes",
            "output_tokens", "tokens", "affinity", "status_reason",
            "trace_id", "attempt_ranks", "reroute_reasons")}
        return (200, "application/json",
                json.dumps(out, default=str).encode())

    # -- debugz payloads (monitor/fleet.py hook protocol) ----------------

    def trace_segments(self, trace_id):
        """Federation fetch for ``/debugz/trace/{id}``: pull the
        replica-side fragments of one fleet trace on demand —
        ``GET {replica}/debugz/trace/{id}`` from the ranks the request
        was actually dispatched to (every non-evicted replica when the
        id is not a router-minted request trace). Best-effort: an
        unreachable replica contributes an error stub, never an
        exception (narrow-catch)."""
        nonce = self._trace_index.get(trace_id)
        req = self._requests.get(nonce) if nonce is not None else None
        if req is not None and req.get("attempt_ranks"):
            ranks = sorted(set(req["attempt_ranks"]))
        else:
            ranks = [r for r, e in sorted(self._replicas.items())
                     if e["state"] != "evicted"]
        segments = {}
        for rank in ranks:
            ent = self._replicas.get(rank)
            if ent is None or not ent["url"]:
                continue
            try:
                # ?local=1: ask for the replica's LOCAL fragment — a
                # fragment fetch must never trigger a nested federation
                code, seg = _http_get_json(
                    "%s/debugz/trace/%s?local=1" % (ent["url"],
                                                    trace_id),
                    self.http_timeout_s)
            except _SCRAPE_ERRORS as e:
                segments[str(rank)] = {"error": repr(e)}
                continue
            segments[str(rank)] = (
                seg if code == 200 else dict(
                    seg or {}, error="http %d" % code))
        return {"nonce": nonce, "segments": segments}

    def debug_payload(self):
        by_state = {}
        for ent in self._replicas.values():
            by_state[ent["state"]] = by_state.get(ent["state"], 0) + 1
        req_states = {}
        rerouted = 0
        for req in self._requests.values():
            req_states[req["state"]] = \
                req_states.get(req["state"], 0) + 1
            rerouted += req["reroutes"]
        dispatches = sum(e["dispatches"]
                         for e in self._replicas.values())
        hits = sum(e["affinity_hits"] for e in self._replicas.values())
        return {
            "world_size": (self._view.world_size
                           if self._view is not None
                           else len(self._replicas)),
            "store_backed": self._store is not None,
            "replicas": {"known": len(self._replicas),
                         "live": by_state.get("live", 0),
                         "draining": by_state.get("draining", 0),
                         "evicted": by_state.get("evicted", 0)},
            "requests": dict(req_states,
                             accepted=len(self._requests),
                             rerouted=rerouted),
            "affinity": dict(self.affinity.stats(),
                             hits=hits, dispatches=dispatches,
                             hit_rate=(hits / dispatches
                                       if dispatches else None)),
        }

    def replicas_debug_payload(self):
        rows = []
        now = self._clock()
        for rank, ent in sorted(self._replicas.items()):
            rows.append({k: ent[k] for k in (
                "rank", "url", "generation", "state", "occupancy",
                "queue_depth", "active_slots", "decode_compiles",
                "requests_finished", "dispatches", "affinity_hits",
                "scrape_errors")})
            rows[-1]["capabilities"] = dict(ent["capabilities"])
            rows[-1]["drain_reason"] = ent.get("drain_reason")
            rows[-1]["load_age_s"] = (
                round(now - ent["last_load_at"], 3)
                if ent["last_load_at"] is not None else None)
        return rows
