"""Fleet replica: one engine behind the serving-fleet HTTP protocol.

Wraps an already-built ``serving.Engine`` (the engine is passed in —
this module never imports it, so the fleet package stays importable
without jax): announces itself in the store via ``membership``
(endpoint URL + generation + capability snapshot), renews the liveness
lease from a heartbeat thread, and serves the router-facing API on its
own ``MetricsServer`` (which also gives the replica ``/healthz`` and
the gauges the router scrapes for load):

    POST /sfleet/enqueue        {nonce, prompt, max_new_tokens,
                                 eos_token_id, deadline_s} -> {state}.
                                 An optional ``traceparent`` field
                                 (``pt1-<trace_id>-<span id>``) makes
                                 the engine adopt the router's
                                 fleet-wide trace context; absent
                                 (journal off) the payload — and the
                                 engine's local-mint tracing path —
                                 is bit-identical to pre-trace.
                                 Nonce-idempotent: a retried dispatch
                                 (router saw a dead connection after
                                 we DID accept) maps to the existing
                                 request — an accepted request is
                                 never double-admitted. 409 +
                                 {"error": reason} on load shed
                                 (draining / queue_full).
    GET  /sfleet/result/{nonce} request progress: state, output token
                                 count, and the generated tokens once
                                 terminal (plus the span summary —
                                 trace_id + per-phase seconds — when
                                 the journal is on, so the router
                                 settles e2e attribution). 404 for an
                                 unknown nonce
                                 (a restarted replica answers 404 for
                                 pre-restart nonces — the router
                                 re-routes them).
    GET  /sfleet/load            the router's load signals: kv-page
                                 occupancy, queue depth, active slots,
                                 draining bit, decode_compiles,
                                 requests_finished, capabilities.

Threading: the engine is touched ONLY by the serve thread
(``pt-sfleet-serve``) — HTTP handlers talk to it through a pending
queue and a status cache under a plain mutex, so an enqueue/result/
load request never blocks behind a multi-second ``step()`` (the first
step compiles; a handler waiting on it would time the router out and
get healthy replicas drained). Engine steps additionally serialize on
a process-wide lock (see ``_STEP_LOCK``): tracing through a shared
model object is not thread-safe across engines in one process. The
lease heartbeat runs on ``pt-sfleet-lease``. Both threads exist only while the replica is
started; ``FLAGS_serving_fleet`` off refuses construction (no
threads, no store traffic, no series).
"""
from __future__ import annotations

import json
import threading
import time

from ...monitor import trace as _trace
from ...monitor.exporter import MetricsServer
from ...monitor.registry import warn_once
from . import membership
from .router import _require_flag

_SERVE_THREAD = "pt-sfleet-serve"
_LEASE_THREAD = "pt-sfleet-lease"

_TERMINAL = ("finished", "expired", "shed", "failed")

# Engines in ONE process may share the model object, and
# ``Engine.step`` traces through ``model.bind_state`` — which swaps
# traced values into that shared model. Two serve threads tracing at
# once leak each other's tracers (UnexpectedTracerError poisons every
# in-flight request). Steps therefore serialize on a process-wide
# lock: uncontended in the deployment shape (one engine per process,
# e.g. serving_benchmark --fleet forks), and correctness-over-overlap
# for in-process fleets (tests, single-host dev).
_STEP_LOCK = threading.Lock()


class Replica:
    """One data-parallel serving replica in the fleet."""

    def __init__(self, engine, rank, store=None, host="127.0.0.1",
                 port=0, ttl_s=3.0, heartbeat_interval_s=0.5,
                 capabilities=None, meta=None):
        _require_flag("Replica")
        self.engine = engine
        self.rank = int(rank)
        self._store = store
        self._host = host
        self._heartbeat_interval_s = float(heartbeat_interval_s)
        self._ttl_s = float(ttl_s)
        self.capabilities = dict(
            capabilities if capabilities is not None
            else membership.DEFAULT_CAPABILITIES)
        self._meta = dict(meta or {})
        self.generation = None
        # handler-side state: NEVER the engine itself. _pending feeds
        # the serve thread; _status is its published view back.
        self._mu = threading.Lock()
        self._pending = []              # [(nonce, payload), ...]
        self._status = {}               # nonce -> status dict
        self._stop = threading.Event()
        self._serve_thread = None
        self._lease_thread = None
        self._server = MetricsServer(port)
        self._server.add_post_route("sfleet/enqueue", self._enqueue)
        self._server.add_prefix_route("sfleet/result", self._result)
        self._server.add_route("sfleet/load", self._load)

    @property
    def port(self):
        return self._server.port

    @property
    def url(self):
        return "http://%s:%d" % (self._host, self._server.port)

    # -- lifecycle -------------------------------------------------------

    def start(self):
        self._server.start()
        if self._store is not None:
            self.generation = membership.register_replica(
                self._store, self.rank, self.url,
                capabilities=self.capabilities, meta=self._meta)
            self._lease_thread = threading.Thread(
                target=self._lease_loop, name=_LEASE_THREAD,
                daemon=True)
            self._lease_thread.start()
        self._serve_thread = threading.Thread(
            target=self._serve_loop, name=_SERVE_THREAD, daemon=True)
        self._serve_thread.start()
        return self

    def _lease_loop(self):
        while not self._stop.wait(self._heartbeat_interval_s):
            try:
                membership.renew_lease(self._store, self.rank)
            except (OSError, ValueError) as e:
                warn_once(
                    "sfleet.replica.lease.%d" % self.rank,
                    "paddle_tpu.serving.fleet: replica %d lease "
                    "renewal failed (%r) — watchers will age the "
                    "lease out after ttl=%.1fs" % (
                        self.rank, e, self._ttl_s))

    def _serve_loop(self):
        while not self._stop.is_set():
            self._admit_pending()
            worked = False
            if self.engine.has_work():
                with _STEP_LOCK:
                    worked = bool(self.engine.step())
            self._refresh_status()
            if not worked:
                time.sleep(0.005)

    def _admit_pending(self):
        with self._mu:
            pending, self._pending = self._pending, []
        for nonce, payload in pending:
            # cross-process trace context: the router's traceparent
            # field adopts its fleet-wide trace id here, so the
            # engine's phase spans land under it with the router's
            # dispatch span as remote parent. (None, None) — absent
            # or malformed — keeps the local-mint path.
            ctx = _trace.parse_traceparent(payload.get("traceparent"))
            try:
                rid = self.engine.add_request(
                    list(payload["prompt"]),
                    max_new_tokens=int(payload.get(
                        "max_new_tokens", 32)),
                    eos_token_id=payload.get("eos_token_id"),
                    deadline_s=payload.get("deadline_s"),
                    trace_ctx=ctx if ctx[0] is not None else None)
            except ValueError as e:
                upd = {"state": "failed", "reason": "invalid",
                       "error": repr(e), "tokens": []}
            except RuntimeError as e:
                # AdmissionError raced past the handler's lock-free
                # pre-check: surface it as a shed terminal — the
                # router re-routes sheds with an admission reason
                reason = getattr(e, "reason", None)
                if reason is None:
                    raise
                upd = {"state": "shed", "reason": reason,
                       "error": repr(e), "tokens": []}
            else:
                upd = {"rid": rid, "state": "queued"}
            with self._mu:
                self._status[nonce].update(upd)

    def _refresh_status(self):
        with self._mu:
            live = [(n, s["rid"]) for n, s in self._status.items()
                    if s["rid"] is not None
                    and s["state"] not in _TERMINAL]
        for nonce, rid in live:
            st = self.engine.request_status(rid)
            upd = {"state": st["state"], "reason": st["reason"],
                   "output_tokens": st["output_tokens"],
                   "error": st["error"]}
            if st["state"] in _TERMINAL:
                upd["tokens"] = self.engine.output(rid)
                # span summary for the router's e2e attribution —
                # computed here on the serve thread (handlers never
                # touch the engine); (None, None) while the journal
                # is off, and then the result payload carries no
                # trace keys at all
                tid, phases = self.engine.request_trace(rid)
                if tid is not None:
                    upd["trace_id"] = tid
                    upd["phases_s"] = {
                        k: round(v, 6)
                        for k, v in (phases or {}).items()}
            with self._mu:
                self._status[nonce].update(upd)

    def drain(self):
        """Stop admitting; the serve loop finishes accepted work.
        Published to the store so routers reschedule queued-but-
        unstarted requests instead of waiting on this replica."""
        self.engine._draining = True
        if self._store is not None:
            membership.mark_draining(self._store, self.rank)

    def stop(self, deregister=True):
        """Tear down threads + server; graceful exits delete the lease
        (immediate death for watchers, no TTL wait)."""
        self._stop.set()
        for t in (self._serve_thread, self._lease_thread):
            if t is not None:
                t.join(timeout=5)
        self._serve_thread = self._lease_thread = None
        if deregister and self._store is not None:
            try:
                membership.deregister_replica(self._store, self.rank)
            except (OSError, ValueError):
                pass
        self._server.stop()

    # -- router-facing HTTP API ------------------------------------------

    def _enqueue(self, body):
        try:
            payload = json.loads(body.decode())
            nonce = payload["nonce"]
            prompt = payload["prompt"]
            if not isinstance(prompt, list) or not prompt:
                raise ValueError("prompt must be a non-empty "
                                 "token-id list")
        except (ValueError, KeyError, UnicodeDecodeError) as e:
            return (400, "application/json",
                    json.dumps({"error": repr(e)}).encode())
        with self._mu:
            st = self._status.get(nonce)
            if st is not None:
                # the idempotent path: a retried dispatch after a lost
                # ack re-observes the existing acceptance, never a
                # second admission
                return (200, "application/json", json.dumps(
                    {"state": st["state"], "deduped": True}).encode())
        # admission pre-check: lock-free reads of engine scalars (the
        # GIL makes them atomic; the serve thread re-checks under
        # add_request, so a race sheds instead of corrupting)
        if self.engine.draining:
            return (409, "application/json",
                    json.dumps({"error": "draining"}).encode())
        mq = self.engine.max_queue
        with self._mu:
            if mq is not None and \
                    len(self.engine.scheduler.queue) \
                    + len(self._pending) >= mq:
                return (409, "application/json",
                        json.dumps({"error": "queue_full"}).encode())
            self._status[nonce] = {
                "rid": None, "state": "queued", "reason": None,
                "output_tokens": 0, "error": None, "tokens": None}
            self._pending.append((nonce, payload))
        return (200, "application/json", json.dumps(
            {"state": "queued", "deduped": False}).encode())

    def _result(self, nonce):
        with self._mu:
            st = self._status.get(nonce)
            if st is None:
                return (404, "application/json", json.dumps(
                    {"error": "unknown nonce",
                     "nonce": nonce}).encode())
            out = {k: st[k] for k in (
                "rid", "state", "reason", "output_tokens", "error",
                "tokens")}
            # replica span summary (present only when the journal was
            # on at finish — the journal-off payload is bit-identical)
            if "trace_id" in st:
                out["trace_id"] = st["trace_id"]
                out["phases_s"] = st["phases_s"]
        return 200, "application/json", json.dumps(out).encode()

    def _load(self):
        # scalar reads only — never blocks behind a running step
        alloc = self.engine.cache.allocator
        used = alloc.usable_blocks - alloc.free_blocks
        try:
            stats = self.engine.stats()
        except RuntimeError:    # dict mutated mid-iteration by a step
            stats = {}
        with self._mu:
            pending = len(self._pending)
        payload = {
            "rank": self.rank,
            "generation": self.generation,
            "draining": bool(self.engine.draining),
            "occupancy": used / max(alloc.usable_blocks, 1),
            "queue_depth": len(self.engine.scheduler.queue) + pending,
            "active_slots": self.engine.scheduler.slots_active(),
            "decode_compiles": stats.get("decode_compiles"),
            "requests_finished": stats.get("requests_finished"),
            "capabilities": self.capabilities,
        }
        return 200, "application/json", json.dumps(payload).encode()
