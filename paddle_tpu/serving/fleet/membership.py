"""Serving-fleet replica membership: pure protocol over an injected store.

The replica register/renew/evict/drain state machine, extracted into
module functions that take the store as an argument (the
``resilience/protocol.py`` discipline) so the SAME code runs in
production (over ``TCPStore``) and under ptcheck (over ``SimStore`` —
the ``router_membership`` fixture explores crash/lost-ack
interleavings of exactly these functions). This module is in the
ptlint ``store`` pass jurisdiction: it never constructs a store and
never holds a lock across a blocking store op.

Store namespace (all under ``__sfleet``):

- ``__sfleet/gen/{r}``      registration-generation counter. Claimed
                            with the nonce-idempotent ``add`` so a
                            RETRIED register (lost ack) never burns a
                            generation — the historical double-register
                            bug the ``router_register_legacy`` fixture
                            must re-find.
- ``__sfleet/replica/{r}``  JSON record: endpoint URL + generation +
                            capability snapshot (the ``disaggregation``
                            field is the explicit seam for streaming KV
                            pages between prefill/decode replicas — out
                            of scope for this layer, carried so the
                            router can route on it later).
- ``__sfleet/beat/{r}``     liveness lease: an incrementing beat
                            counter renewed by the replica and aged by
                            each watcher ON ITS OWN CLOCK (clocks are
                            not comparable across hosts — the
                            ElasticManager TTL machinery, reused here
                            verbatim). ``deregister`` deletes it:
                            immediate death, no TTL wait.
- ``__sfleet/drain/{r}``    drain marker (counter > 0 = draining): a
                            router that observed 503/stall publishes
                            the verdict so every router stops sending
                            new work, not just the one that saw it.
"""
from __future__ import annotations

import json

from ...distributed.elastic import ElasticManager

PREFIX = "__sfleet"

#: Default capability snapshot. ``disaggregation`` is the seam for
#: prefill/decode disaggregation (KV pages streamed via the store) —
#: explicitly out of scope here; a replica that implements it will
#: announce it and the router can begin routing on the split.
DEFAULT_CAPABILITIES = {"prefill": True, "decode": True,
                        "disaggregation": False}


def gen_key(rank):
    return "%s/gen/%d" % (PREFIX, rank)


def replica_key(rank):
    return "%s/replica/%d" % (PREFIX, rank)


def beat_key(rank):
    return "%s/beat/%d" % (PREFIX, rank)


def drain_key(rank):
    return "%s/drain/%d" % (PREFIX, rank)


def register_replica(store, rank, url, capabilities=None, meta=None):
    """Announce one replica; returns its registration generation.

    The generation is claimed via the nonce-idempotent ``add``: a
    retried register after a lost ack observes the SAME generation, so
    the record can never claim a phantom prior incarnation. The beat
    counter starts at >= 1 (``register() starts every live rank at
    count >= 1`` — the ElasticManager contract ``alive_nodes`` ages)."""
    generation = store.add(gen_key(rank), 1)
    record = {"rank": int(rank), "url": url,
              "generation": int(generation),
              "capabilities": dict(capabilities
                                   if capabilities is not None
                                   else DEFAULT_CAPABILITIES)}
    if meta:
        record.update(meta)
    store.set(replica_key(rank), json.dumps(
        record, sort_keys=True).encode())
    store.add(beat_key(rank), 1)
    return generation


def renew_lease(store, rank):
    """One lease renewal (the replica's heartbeat thread body)."""
    return store.add(beat_key(rank), 1)


def deregister_replica(store, rank):
    """Graceful exit: deleting the beat counter is immediate death for
    every watcher (no TTL wait) — the ``ElasticManager.exit`` shape."""
    store.delete(beat_key(rank))


def evict_replica(store, rank):
    """Router-side eviction of a dead-leased replica: same store effect
    as a graceful deregister (the beat counter disappears, so every
    OTHER router's view converges without waiting out its own TTL).
    The caller also invalidates its affinity entries for the rank."""
    store.delete(beat_key(rank))


def mark_draining(store, rank):
    """Publish the drain verdict (healthz 503/stalled): counter > 0
    means every router stops dispatching new work to the rank."""
    return store.add(drain_key(rank), 1)


def clear_draining(store, rank):
    """Lift the drain marker (a replica re-registering after recovery)."""
    store.delete(drain_key(rank))


def is_draining(store, rank):
    return (store.counter_get(drain_key(rank), default=0) or 0) > 0


def read_replica(store, rank, timeout_s=0.05):
    """The announced record, or None (never registered / not yet
    visible). Non-blocking-ish: the short timeout bounds the wait."""
    raw = store.get(replica_key(rank), timeout_s=timeout_s)
    if raw is None:
        return None
    try:
        return json.loads(bytes(raw).decode())
    except (ValueError, UnicodeDecodeError):
        return None


class ReplicaView:
    """A router's watcher-local liveness view over the beat counters.

    Wraps the ElasticManager TTL machinery (counter-advancement timed
    on THIS watcher's clock; deleted counter = immediately dead) rather
    than re-deriving it — the fleet lease is the elastic lease with a
    different key prefix. The view never registers or beats: a router
    is not a member."""

    def __init__(self, store, world_size, ttl_s=3.0, clock=None):
        self._store = store
        self._manager = ElasticManager(
            store=store, job_id=PREFIX, rank=0, np=int(world_size),
            ttl=ttl_s, clock=clock)

    @property
    def world_size(self):
        return self._manager.np

    def alive(self):
        """Ranks whose lease is live (beat advanced within ttl on this
        watcher's clock)."""
        return self._manager.alive_nodes()

    def dead(self):
        """Ranks whose lease lapsed (aged out) or was deleted
        (deregistered/evicted). Never-registered ranks count as dead."""
        return self._manager.dead_nodes()

    def draining(self):
        """Ranks carrying a published drain marker."""
        return [r for r in self._manager.members
                if is_draining(self._store, r)]

    def record(self, rank):
        return read_replica(self._store, rank)


def pick_replica(candidates, load=None, affinity=None):
    """Pure dispatch choice: prefix-affinity first, least-loaded as the
    tie-break. Returns ``(rank, used_affinity)`` — ``(None, False)``
    when no candidate is dispatchable.

    ``candidates``: live, non-draining, non-evicted ranks.
    ``affinity``:   {rank: matched prefix chunks} from the router's
                    radix index (0 or absent = no shared prefix).
    ``load``:       {rank: load score} (occupancy + normalized queue
                    depth from the scraped gauges); lower is better.
    """
    ranks = sorted(set(candidates))
    if not ranks:
        return None, False
    affinity = affinity or {}
    load = load or {}
    best = max((affinity.get(r, 0) for r in ranks), default=0)
    used_affinity = best > 0
    if used_affinity:
        ranks = [r for r in ranks if affinity.get(r, 0) == best]
    return min(ranks, key=lambda r: (load.get(r, 0.0), r)), used_affinity
