from .paged_attention import (  # noqa: F401
    paged_attention,
    paged_attention_kernel,
    paged_attention_reference,
)
