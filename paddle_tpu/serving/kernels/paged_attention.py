"""Ragged paged-attention decode kernel (Pallas, TPU).

Serving decode is one query token per slot attending over that slot's
whole history, which lives scattered across fixed-size pool pages
(serving/kv_cache.py). The dense alternative — gather every slot's
pages into a contiguous [slots, max_len, heads, head_dim] context —
moves the entire KV history through HBM every step; at serving batch
sizes that gather IS the decode step. This kernel instead walks the
block table: grid (slot, page), the page id for (slot, j) read from the
scalar-prefetched block table by the BlockSpec index map, so each K/V
page is DMA'd from the pool exactly once and the running online-softmax
statistics stay in VMEM (same recurrence as kernels/flash_attention.py).

Layout contract (shared with serving/kv_cache.py):
  q            [S, H, D]        one query token per slot
  k/v pools    [NB, bs, Hkv, D] page pools (page 0 is the trash page)
  block_tables [S, MB] int32    page ids per slot, trash-padded
  seq_lens     [S]     int32    valid history length per slot (0 = idle)

GQA (H > Hkv) is folded inside the kernel: q reshapes to
[Hkv, H/Hkv, D] and both dots batch over the kv-head axis, so the pool
never stores repeated heads.

MIXED MODE (serving tier 2, FLAGS_serving_chunked_prefill /
FLAGS_serving_prefix_cache): ``mixed_paged_attention`` generalizes the
decode kernel to ragged [S, C] rows — row s holds q_lens[s] new tokens
at absolute positions hist_lens[s]..hist_lens[s]+q_lens[s]-1, and the
causal rule becomes ``key position <= hist + chunk index``. A decode
row is the q_len == 1 case, a prefill chunk is 1 < q_len <= C, and the
prefix-cache suffix prefill is S == 1 with hist = cached tokens; the
compiled mixed step batches all of them in one call, which is exactly
the mixed prefill/decode batch the Ragged Paged Attention paper's
kernel is built for.

Status: exact in interpret mode against masked_decode_attention
(tests/test_serving.py::TestPagedAttentionKernel); on-chip Mosaic
compile + timing pending a tunnel window (tools/tunnel_battery.sh
serving row). The jnp fallback below is the CPU/engine default and is
bit-compatible with the dense decode path generation.py uses.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ...kernels.flash_attention import CompilerParams
from ...kernels.quant import dequantize_int8_block

NEG_INF = -1e30
_STAT_LANES = 128


def _pa_kernel(bt_ref, len_ref, q_ref, k_ref, v_ref, *rest,
               block_size, rep, scale, quantized=False):
    """One (slot, page) program. q [1, H, D]; k/v [1, bs, Hkv, D]
    (the page the index map picked via the block table); scratch
    m/l [H, 128], acc [H, D] — persisted across the page axis.
    ``quantized`` (FLAGS_serving_quant_kv): k/v blocks arrive int8 and
    two extra scale refs [1, bs, Hkv] ride the same block-table index
    map; dequant happens here, inside the gather, per the fused-dequant
    discipline (kernels/quant.py)."""
    if quantized:
        ks_ref, vs_ref, o_ref, m_scr, l_scr, acc_scr = rest
    else:
        o_ref, m_scr, l_scr, acc_scr = rest
        ks_ref = vs_ref = None
    s_i = pl.program_id(0)
    j = pl.program_id(1)
    num_j = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    length = len_ref[s_i]

    # pages at or past the slot's length hold no valid tokens: skip the
    # DMA'd block entirely (ragged early-out; idle slots skip all pages)
    @pl.when(j * block_size < length)
    def _compute():
        q = q_ref[0]                                  # [H, D]
        k = k_ref[0]                                  # [bs, Hkv, D]
        v = v_ref[0]
        if quantized:
            k = dequantize_int8_block(k, ks_ref[0], out_dtype=jnp.float32)
            v = dequantize_int8_block(v, vs_ref[0], out_dtype=jnp.float32)
        h, d = q.shape
        hkv = k.shape[1]
        qg = q.reshape(hkv, rep, d).astype(jnp.float32)
        kg = jnp.swapaxes(k, 0, 1).astype(jnp.float32)     # [Hkv, bs, D]
        s_blk = jax.lax.dot_general(
            qg, kg, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32) * scale    # [Hkv, rep, bs]
        s_blk = s_blk.reshape(h, block_size)
        pos = j * block_size + jax.lax.broadcasted_iota(
            jnp.int32, (h, block_size), 1)
        s_blk = jnp.where(pos < length, s_blk, NEG_INF)
        m_prev = m_scr[...][:, :1]
        l_prev = l_scr[...][:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s_blk, axis=1, keepdims=True))
        p = jnp.exp(s_blk - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        vg = jnp.swapaxes(v, 0, 1).astype(jnp.float32)     # [Hkv, bs, D]
        upd = jax.lax.dot_general(
            p.reshape(hkv, rep, block_size), vg,
            (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)            # [Hkv, rep, D]
        acc_scr[...] = alpha * acc_scr[...] + upd.reshape(h, d)
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(j == num_j - 1)
    def _emit():
        l = l_scr[...][:, :1]
        o_ref[0] = (acc_scr[...] / jnp.maximum(l, 1e-30)).astype(
            o_ref.dtype)


def paged_attention_kernel(q, k_pool, v_pool, block_tables, seq_lens,
                           scale=None, interpret=None, k_scale=None,
                           v_scale=None):
    """Pallas path. q [S, H, D] -> [S, H, D]; idle slots (len 0) emit 0.
    ``k_scale``/``v_scale`` [NB, bs, Hkv]: int8 pools, fused dequant."""
    s, h, d = q.shape
    nb, block_size, hkv, _ = k_pool.shape
    mb = block_tables.shape[1]
    quantized = k_scale is not None
    if h % hkv:
        raise ValueError("paged_attention: %d heads not a multiple of "
                         "%d kv heads" % (h, hkv))
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    page_spec = pl.BlockSpec((1, block_size, hkv, d),
                             lambda si, j, bt, ln: (bt[si, j], 0, 0, 0))
    in_specs = [
        pl.BlockSpec((1, h, d), lambda si, j, bt, ln: (si, 0, 0)),
        page_spec, page_spec,
    ]
    operands = [q, k_pool, v_pool]
    if quantized:
        # scale planes ride the SAME block-table index map as the pages
        scale_spec = pl.BlockSpec((1, block_size, hkv),
                                  lambda si, j, bt, ln: (bt[si, j], 0, 0))
        in_specs += [scale_spec, scale_spec]
        operands += [k_scale, v_scale]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(s, mb),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, h, d), lambda si, j, bt, ln: (si, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((h, _STAT_LANES), jnp.float32),
            pltpu.VMEM((h, _STAT_LANES), jnp.float32),
            pltpu.VMEM((h, d), jnp.float32),
        ],
    )
    return pl.pallas_call(
        functools.partial(_pa_kernel, block_size=block_size,
                          rep=h // hkv, scale=scale, quantized=quantized),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((s, h, d), q.dtype),
        compiler_params=CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(jnp.asarray(block_tables, jnp.int32),
      jnp.asarray(seq_lens, jnp.int32), *operands)


def paged_attention_reference(q, k_pool, v_pool, block_tables, seq_lens,
                              scale=None, k_scale=None, v_scale=None):
    """jnp fallback: gather pages into a dense context, then the same
    fp32-statistics attention as nn.functional's _sdpa_reference — kept
    operation-for-operation compatible with the dense decode path so the
    serving engine's greedy tokens match GenerationMixin.generate.
    With scale planes the dequant sits right after the gather — XLA
    fuses the broadcast-multiply into the gather's consumer, so int8
    pages decompress 'for free' on the way into the einsum."""
    s, h, d = q.shape
    nb, block_size, hkv, _ = k_pool.shape
    mb = block_tables.shape[1]
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    bt = jnp.asarray(block_tables, jnp.int32)
    lens = jnp.asarray(seq_lens, jnp.int32)
    k = k_pool[bt].reshape(s, mb * block_size, hkv, d)
    v = v_pool[bt].reshape(s, mb * block_size, hkv, d)
    if k_scale is not None:
        k = dequantize_int8_block(
            k, k_scale[bt].reshape(s, mb * block_size, hkv),
            out_dtype=jnp.float32)
        v = dequantize_int8_block(
            v, v_scale[bt].reshape(s, mb * block_size, hkv),
            out_dtype=jnp.float32)
    if h != hkv:
        rep = h // hkv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    logits = jnp.einsum("shd,smhd->shm", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    valid = (jnp.arange(mb * block_size)[None, None, :]
             < lens[:, None, None])
    logits = jnp.where(valid, logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    # idle slots (len 0) have an all-masked row -> uniform softmax over
    # trash; their output is ignored host-side but must stay finite
    out = jnp.einsum("shm,smhd->shd", probs.astype(v.dtype), v)
    return out


def _mixed_kernel(bt_ref, hist_ref, qlen_ref, q_ref, k_ref, v_ref, *rest,
                  block_size, rep, chunk, scale, quantized=False):
    """One (slot, page) program of the MIXED ragged step. q [1, C, H, D]
    (row s's chunk: q_len valid new tokens at absolute positions
    hist..hist+q_len-1); k/v [1, bs, Hkv, D] (the page the index map
    picked via the block table). The ragged causal rule is
    ``key position <= hist + ci`` per chunk row ci — a decode row is the
    C == q_len == 1 degenerate case. Stats flatten the (H, C) query rows
    to H*C online-softmax rows; scratch m/l [H*C, 128], acc [H*C, D].
    ``quantized``: int8 k/v blocks + scale refs [1, bs, Hkv] on the same
    index map, dequantized here inside the gather (_pa_kernel note)."""
    if quantized:
        ks_ref, vs_ref, o_ref, m_scr, l_scr, acc_scr = rest
    else:
        o_ref, m_scr, l_scr, acc_scr = rest
        ks_ref = vs_ref = None
    s_i = pl.program_id(0)
    j = pl.program_id(1)
    num_j = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    hist = hist_ref[s_i]
    q_len = qlen_ref[s_i]

    # pages at or past hist + q_len hold nothing this row can see: skip
    # the DMA'd block (ragged early-out; idle rows q_len=0 skip every
    # page and emit exact zeros, same as the decode kernel)
    @pl.when(j * block_size < hist + q_len)
    def _compute():
        q = q_ref[0]                                  # [C, H, D]
        k = k_ref[0]                                  # [bs, Hkv, D]
        v = v_ref[0]
        if quantized:
            k = dequantize_int8_block(k, ks_ref[0], out_dtype=jnp.float32)
            v = dequantize_int8_block(v, vs_ref[0], out_dtype=jnp.float32)
        c, h, d = q.shape
        hkv = k.shape[1]
        # group for GQA: [C, H, D] -> [H, C, D] -> [Hkv, rep*C, D]
        qg = jnp.swapaxes(q, 0, 1).reshape(
            hkv, rep * c, d).astype(jnp.float32)
        kg = jnp.swapaxes(k, 0, 1).astype(jnp.float32)     # [Hkv, bs, D]
        s_blk = jax.lax.dot_general(
            qg, kg, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32) * scale    # [Hkv, rep*C, bs]
        s_blk = s_blk.reshape(h, c, block_size)
        kpos = j * block_size + jax.lax.broadcasted_iota(
            jnp.int32, (h, c, block_size), 2)
        qpos = hist + jax.lax.broadcasted_iota(
            jnp.int32, (h, c, block_size), 1)
        s_blk = jnp.where(kpos <= qpos, s_blk, NEG_INF)
        s_blk = s_blk.reshape(h * c, block_size)
        m_prev = m_scr[...][:, :1]
        l_prev = l_scr[...][:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s_blk, axis=1, keepdims=True))
        p = jnp.exp(s_blk - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        vg = jnp.swapaxes(v, 0, 1).astype(jnp.float32)     # [Hkv, bs, D]
        upd = jax.lax.dot_general(
            p.reshape(hkv, rep * c, block_size), vg,
            (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)            # [Hkv, rep*C, D]
        acc_scr[...] = alpha * acc_scr[...] + upd.reshape(h * c, d)
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(j == num_j - 1)
    def _emit():
        l = l_scr[...][:, :1]
        h = o_ref.shape[2]
        o = (acc_scr[...] / jnp.maximum(l, 1e-30)).reshape(
            h, chunk, o_ref.shape[3])
        o_ref[0] = jnp.swapaxes(o, 0, 1).astype(o_ref.dtype)


def mixed_paged_attention_kernel(q, k_pool, v_pool, block_tables,
                                 hist_lens, q_lens, scale=None,
                                 interpret=None, k_scale=None,
                                 v_scale=None):
    """Pallas path for the mixed step. q [S, C, H, D] -> [S, C, H, D];
    rows past q_len and idle rows emit unspecified-but-finite values the
    host ignores. ``k_scale``/``v_scale`` [NB, bs, Hkv]: int8 pools,
    fused dequant inside the gather."""
    s, c, h, d = q.shape
    nb, block_size, hkv, _ = k_pool.shape
    mb = block_tables.shape[1]
    quantized = k_scale is not None
    if h % hkv:
        raise ValueError("mixed_paged_attention: %d heads not a multiple"
                         " of %d kv heads" % (h, hkv))
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    page_spec = pl.BlockSpec((1, block_size, hkv, d),
                             lambda si, j, bt, hl, ql: (bt[si, j], 0, 0, 0))
    in_specs = [
        pl.BlockSpec((1, c, h, d),
                     lambda si, j, bt, hl, ql: (si, 0, 0, 0)),
        page_spec, page_spec,
    ]
    operands = [q, k_pool, v_pool]
    if quantized:
        scale_spec = pl.BlockSpec(
            (1, block_size, hkv),
            lambda si, j, bt, hl, ql: (bt[si, j], 0, 0))
        in_specs += [scale_spec, scale_spec]
        operands += [k_scale, v_scale]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(s, mb),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (1, c, h, d), lambda si, j, bt, hl, ql: (si, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((h * c, _STAT_LANES), jnp.float32),
            pltpu.VMEM((h * c, _STAT_LANES), jnp.float32),
            pltpu.VMEM((h * c, d), jnp.float32),
        ],
    )
    return pl.pallas_call(
        functools.partial(_mixed_kernel, block_size=block_size,
                          rep=h // hkv, chunk=c, scale=scale,
                          quantized=quantized),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((s, c, h, d), q.dtype),
        compiler_params=CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(jnp.asarray(block_tables, jnp.int32),
      jnp.asarray(hist_lens, jnp.int32),
      jnp.asarray(q_lens, jnp.int32), *operands)


def mixed_paged_attention_reference(q, k_pool, v_pool, block_tables,
                                    hist_lens, q_lens, scale=None,
                                    k_scale=None, v_scale=None):
    """jnp fallback for the mixed ragged step (chunked prefill + prefix-
    cache suffix prefill + decode rows in ONE call): gather each row's
    pages into a dense context — which already contains the chunk's own
    freshly-scattered K/V — and apply the ragged causal mask
    ``key position <= hist + ci``. Same fp32-statistics discipline as
    paged_attention_reference (einsum -> NEG_INF mask -> softmax), so
    greedy outputs stay consistent with the dense paths."""
    s, c, h, d = q.shape
    nb, block_size, hkv, _ = k_pool.shape
    mb = block_tables.shape[1]
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    bt = jnp.asarray(block_tables, jnp.int32)
    hist = jnp.asarray(hist_lens, jnp.int32)
    k = k_pool[bt].reshape(s, mb * block_size, hkv, d)
    v = v_pool[bt].reshape(s, mb * block_size, hkv, d)
    if k_scale is not None:
        k = dequantize_int8_block(
            k, k_scale[bt].reshape(s, mb * block_size, hkv),
            out_dtype=jnp.float32)
        v = dequantize_int8_block(
            v, v_scale[bt].reshape(s, mb * block_size, hkv),
            out_dtype=jnp.float32)
    if h != hkv:
        rep = h // hkv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    logits = jnp.einsum("schd,smhd->shcm", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    qpos = hist[:, None] + jnp.arange(c)[None, :]          # [S, C]
    valid = (jnp.arange(mb * block_size)[None, None, :]
             <= qpos[:, :, None])                          # [S, C, M]
    logits = jnp.where(valid[:, None], logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    # pad/idle rows see at least key position 0 (trash) -> finite
    out = jnp.einsum("shcm,smhd->schd", probs.astype(v.dtype), v)
    return out


def mixed_paged_attention(q, k_pool, v_pool, block_tables, hist_lens,
                          q_lens, scale=None, interpret=None,
                          k_scale=None, v_scale=None):
    """Dispatch for the mixed ragged step: the Pallas kernel on TPU when
    the geometry is Mosaic-tileable, the jnp gather fallback otherwise
    (CPU engine path and the parity-test oracle form). Quantized pools
    additionally need the scale block's lane dim (Hkv) tileable —
    on-chip Mosaic validation of the int8 path pending a tunnel window,
    so small-Hkv models take the reference (XLA still fuses the
    dequant into the gather)."""
    s, c, h, d = q.shape
    block_size = k_pool.shape[1]
    hkv = k_pool.shape[2]
    tileable = (d % 128 == 0 and block_size % 8 == 0
                and (h * c) % 8 == 0
                and (k_scale is None or hkv % 128 == 0))
    if jax.default_backend() == "tpu" and tileable:
        return mixed_paged_attention_kernel(
            q, k_pool, v_pool, block_tables, hist_lens, q_lens,
            scale=scale, interpret=interpret, k_scale=k_scale,
            v_scale=v_scale)
    return mixed_paged_attention_reference(
        q, k_pool, v_pool, block_tables, hist_lens, q_lens, scale=scale,
        k_scale=k_scale, v_scale=v_scale)


def paged_attention(q, k_pool, v_pool, block_tables, seq_lens,
                    scale=None, interpret=None, k_scale=None,
                    v_scale=None):
    """Dispatch: the Pallas kernel on TPU when the page geometry is
    Mosaic-tileable, the jnp gather fallback otherwise (CPU engine path,
    and the form the parity test pins against masked_decode_attention).
    Quantized-pool tileability note: see mixed_paged_attention."""
    s, h, d = q.shape
    block_size = k_pool.shape[1]
    hkv = k_pool.shape[2]
    tileable = (d % 128 == 0 and block_size % 8 == 0 and h % 8 == 0
                and (k_scale is None or hkv % 128 == 0))
    if jax.default_backend() == "tpu" and tileable:
        return paged_attention_kernel(q, k_pool, v_pool, block_tables,
                                      seq_lens, scale=scale,
                                      interpret=interpret,
                                      k_scale=k_scale, v_scale=v_scale)
    return paged_attention_reference(q, k_pool, v_pool, block_tables,
                                     seq_lens, scale=scale,
                                     k_scale=k_scale, v_scale=v_scale)
