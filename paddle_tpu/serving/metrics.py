"""Serving metrics: per-request latency breakdown + engine counters.

Schema (all plain dicts, json-ready — tools/serving_benchmark.py dumps
them verbatim):

per-request (``RequestMetrics.to_dict()``):
  queue_time_s     arrival -> first admission
  ttft_s           arrival -> first token out of prefill
  tpot_s           mean inter-token time after the first token
  e2e_s            arrival -> finished
  prompt_tokens / output_tokens / preemptions

engine (``EngineMetrics.to_dict()``):
  requests_in / requests_finished / preemptions
  prefill_runs / decode_steps / output_tokens
  decode_compiles / prefill_compiles   (jit trace counts — the
      compile-once contract tests assert decode_compiles == 1)
  throughput_tok_s                     output tokens / wall time
  slot_occupancy                       mean active-slots / max_slots
      over decode steps (the 占用 utilization counter)

Chrome-trace spans: ``span("serving.decode_step")`` bridges into the
native host recorder (csrc/trace.cc via profiler.RecordEvent, which
also annotates the Xprof device timeline), so engine phases line up
with kernel activity in the merged trace. Guarded: a build without the
native lib degrades to a no-op, never breaks serving.
"""
from __future__ import annotations

import contextlib
import time


def now():
    return time.monotonic()


@contextlib.contextmanager
def span(name, level=1):
    """Scoped chrome-trace span through csrc/trace.cc; no-op without
    the native lib."""
    ev = None
    try:
        from ..profiler import RecordEvent

        ev = RecordEvent(name, level=level)
        ev.begin()
    except Exception:
        ev = None
    try:
        yield
    finally:
        if ev is not None:
            try:
                ev.end()
            except Exception:
                pass


def counter(name, value):
    """Named counter sample on the native trace timeline (no-op
    without the lib)."""
    try:
        from ..core import native

        native.get_lib().pt_trace_counter(name.encode(), int(value))
    except Exception:
        pass


class RequestMetrics:
    def __init__(self, arrival_t):
        self.arrival_t = arrival_t
        self.first_admit_t = None
        self.first_token_t = None
        self.finish_t = None
        self.prompt_tokens = 0
        self.output_tokens = 0
        self.preemptions = 0

    def on_admit(self, t):
        if self.first_admit_t is None:
            self.first_admit_t = t

    def to_dict(self):
        ttft = (None if self.first_token_t is None
                else self.first_token_t - self.arrival_t)
        tpot = None
        if (self.finish_t is not None and self.first_token_t is not None
                and self.output_tokens > 1):
            tpot = ((self.finish_t - self.first_token_t)
                    / (self.output_tokens - 1))
        return {
            "queue_time_s": (None if self.first_admit_t is None
                             else self.first_admit_t - self.arrival_t),
            "ttft_s": ttft,
            "tpot_s": tpot,
            "e2e_s": (None if self.finish_t is None
                      else self.finish_t - self.arrival_t),
            "prompt_tokens": self.prompt_tokens,
            "output_tokens": self.output_tokens,
            "preemptions": self.preemptions,
        }


class EngineMetrics:
    def __init__(self, max_slots):
        self.max_slots = max_slots
        self.start_t = now()
        self.requests_in = 0
        self.requests_finished = 0
        self.preemptions = 0
        self.prefill_runs = 0
        self.decode_steps = 0
        self.output_tokens = 0
        self.decode_compiles = 0
        self.prefill_compiles = 0
        self._occupancy_sum = 0

    def on_decode_step(self, active_slots):
        self.decode_steps += 1
        self._occupancy_sum += active_slots
        counter("serving.active_slots", active_slots)

    def to_dict(self):
        wall = max(now() - self.start_t, 1e-9)
        occ = (self._occupancy_sum / (self.decode_steps * self.max_slots)
               if self.decode_steps else 0.0)
        return {
            "requests_in": self.requests_in,
            "requests_finished": self.requests_finished,
            "preemptions": self.preemptions,
            "prefill_runs": self.prefill_runs,
            "decode_steps": self.decode_steps,
            "output_tokens": self.output_tokens,
            "decode_compiles": self.decode_compiles,
            "prefill_compiles": self.prefill_compiles,
            "wall_s": wall,
            "throughput_tok_s": self.output_tokens / wall,
            "slot_occupancy": occ,
        }
