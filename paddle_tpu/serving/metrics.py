"""Serving metrics: per-request latency breakdown + engine counters.

Schema (all plain dicts, json-ready — tools/serving_benchmark.py dumps
them verbatim):

per-request (``RequestMetrics.to_dict()``):
  queue_time_s     arrival -> first admission
  ttft_s           arrival -> first token out of prefill
  tpot_s           mean inter-token time after the first token
  e2e_s            arrival -> finished
  prompt_tokens / output_tokens / preemptions

engine (``EngineMetrics.to_dict()``):
  requests_in / requests_finished / preemptions
  prefill_runs / decode_steps / output_tokens
  decode_compiles / prefill_compiles   (jit trace counts — the
      compile-once contract tests assert decode_compiles == 1)
  throughput_tok_s                     output tokens / wall time since
      FIRST ADMISSION (not engine construction — an engine created
      before traffic arrives must not understate throughput)
  slot_occupancy                       mean active-slots / max_slots
      over decode steps (the 占用 utilization counter)

Every sample also flows through the framework-wide registry
(paddle_tpu.monitor): counters/gauges under ``serving_*`` plus
TTFT/TPOT/queue/e2e histograms, so serving shows up on the same
/metrics endpoint and JSON snapshots as training telemetry. The dict
API above stays — it is the benchmark-artifact schema.

Chrome-trace spans: ``span("serving.decode_step")`` bridges into the
native host recorder (csrc/trace.cc via profiler.RecordEvent, which
also annotates the Xprof device timeline), so engine phases line up
with kernel activity in the merged trace. Guarded: a build without the
native lib degrades to a no-op, never breaks serving.
"""
from __future__ import annotations

import contextlib
import itertools
import time

from ..monitor import counter as _mcounter
from ..monitor import gauge as _mgauge
from ..monitor import histogram as _mhistogram
from ..monitor import trace as _mtrace

# shared-registry series (idempotent: re-imports / engine re-creation
# reuse the registered metric). Counters and histograms are cumulative
# across every engine in the process; instantaneous gauges
# (active slots, throughput) are labeled per engine instance —
# per-engine windows come from EngineMetrics.to_dict().
_REQUESTS = _mcounter(
    "serving_requests_total", "request lifecycle events",
    labelnames=("event",))
# graceful-degradation accounting (resilience layer): every request
# that terminates WITHOUT full service, by reason — queue_full /
# draining (load shed at admission), expired (queue-TTL deadline),
# preempt_cap (no eligible victim under the preemption cap), poison
# (its own step raised). The SLO reads shed rate next to goodput.
_SHED = _mcounter(
    "serving_requests_shed_total",
    "requests terminated without full service, by reason",
    labelnames=("reason",))
_PREFILLS = _mcounter("serving_prefill_runs_total",
                      "prefill executions (admissions + resumes)")
# radix prefix cache (FLAGS_serving_prefix_cache) + chunked prefill
# (FLAGS_serving_chunked_prefill) accounting: hit/lookup token counters
# give the cache hit RATE, eviction/insert/COW counters describe pool
# churn, chunk counter sizes the mixed step's prefill interleave. All
# zero (and series-free until first touch) with the flags off.
_PREFIX_HIT = _mcounter("serving_prefix_cache_hit_tokens_total",
                        "prompt tokens served from the radix prefix "
                        "cache instead of prefill compute")
_PREFIX_LOOKUP = _mcounter("serving_prefix_cache_lookup_tokens_total",
                           "prompt tokens looked up in the prefix cache "
                           "at admission")
_PREFIX_EVICT = _mcounter("serving_prefix_cache_evictions_total",
                          "cached pages reclaimed by the LRU walk")
_PREFIX_INSERT = _mcounter("serving_prefix_cache_insert_pages_total",
                           "full pages registered in the radix tree")
_COW_CLONES = _mcounter("serving_kv_cow_clones_total",
                        "copy-on-write page splits (shared prefix page "
                        "cloned before a divergent write)")
_PREFIX_PAGES = _mgauge("serving_prefix_cache_pages",
                        "pages currently held by the radix tree",
                        labelnames=("engine",))
_CHUNKS = _mcounter("serving_prefill_chunks_total",
                    "prefill chunks interleaved into the mixed step")
_DECODE_STEPS = _mcounter("serving_decode_steps_total",
                          "batched decode steps")
_TOKENS = _mcounter("serving_output_tokens_total", "tokens generated")
_COMPILES = _mcounter("serving_compiles_total",
                      "XLA traces of serving step functions",
                      labelnames=("fn",))
_ACTIVE = _mgauge("serving_active_slots",
                  "decoding slots in the current step",
                  labelnames=("engine",))
_THROUGHPUT = _mgauge("serving_throughput_tok_s",
                      "engine-lifetime output tokens/s",
                      labelnames=("engine",))
# perf attribution (monitor/perf.py, FLAGS_perf_attribution): goodput
# counts only FINISHED requests' tokens — work discarded by
# preempt-by-recompute is throughput but not goodput, so the gap
# between these two gauges IS the preemption tax
_GOODPUT = _mgauge("serving_goodput_tokens_per_s",
                   "finished-request output tokens/s (recomputed/"
                   "discarded work excluded)", labelnames=("engine",))
_KV_OCC = _mgauge("serving_kv_page_occupancy",
                  "fraction of usable KV pages held by live requests",
                  labelnames=("engine",))
# KV quantization (FLAGS_serving_quant_kv): gauge bound lazily on the
# first quant sample — with the flag off no series exists at all, and
# the counter is registered-but-untouched (series-free), the PR-2/5/6
# flags-off discipline
_KV_QUANT_PAGES = _mgauge("serving_kv_quant_pages",
                          "KV pages held as int8 block-scaled planes",
                          labelnames=("engine",))
_QUANT_DEQ_BYTES = _mcounter(
    "serving_quant_dequant_bytes_total",
    "int8 KV bytes dequantized inside paged-attention gathers")
_ENGINE_IDS = itertools.count()
# engine-labeled gauge series are pruned to this many newest engines —
# a process that constructs engines repeatedly (test suites, rolling
# reloads) must not grow the registry without bound
_MAX_ENGINE_SERIES = 32


def _prune_engine_series():
    for g in (_ACTIVE, _THROUGHPUT, _GOODPUT, _KV_OCC, _PREFIX_PAGES):
        keys = sorted(g._children, key=lambda k: int(k[0]))
        for k in keys[:-_MAX_ENGINE_SERIES]:
            g.remove(*k)
_LAT_BUCKETS = (.0025, .005, .01, .025, .05, .1, .25, .5, 1.0, 2.5,
                5.0, 10.0, 30.0)
_TTFT = _mhistogram("serving_ttft_seconds", "arrival -> first token",
                    buckets=_LAT_BUCKETS)
_TPOT = _mhistogram("serving_tpot_seconds",
                    "mean inter-token time per request",
                    buckets=_LAT_BUCKETS)
_QUEUE = _mhistogram("serving_queue_time_seconds",
                     "arrival -> first admission", buckets=_LAT_BUCKETS)
_E2E = _mhistogram("serving_e2e_seconds", "arrival -> finished",
                   buckets=_LAT_BUCKETS)


def now():
    return time.monotonic()


@contextlib.contextmanager
def span(name, level=1):
    """Scoped chrome-trace span through csrc/trace.cc; no-op without
    the native lib."""
    ev = None
    try:
        from ..profiler import RecordEvent

        ev = RecordEvent(name, level=level)
        ev.begin()
    except Exception:
        ev = None
    try:
        yield
    finally:
        if ev is not None:
            try:
                ev.end()
            # ptlint: silent-except-ok — native trace-event teardown
            # is best-effort; the span simply ends unclosed
            except Exception:
                pass


def counter(name, value):
    """Named counter sample on the native trace timeline (no-op
    without the lib, and skipped entirely when the monitor is disabled
    — the disabled fast path must not touch native code)."""
    from ..monitor.registry import is_enabled

    if not is_enabled():
        return
    try:
        from ..core import native

        native.get_lib().pt_trace_counter(name.encode(), int(value))
    except Exception as e:
        from ..monitor.registry import warn_once

        warn_once(
            "serving.native_counter",
            "paddle_tpu.serving.metrics: native trace counter "
            "unavailable (registry metrics unaffected): %r" % (e,))


class RequestMetrics:
    def __init__(self, arrival_t):
        self.arrival_t = arrival_t
        self.first_admit_t = None
        self.first_token_t = None
        self.finish_t = None
        self.prompt_tokens = 0
        self.output_tokens = 0
        self.preemptions = 0
        # span-journal trace id (monitor/trace.py): set by the engine
        # at admission when FLAGS_monitor_trace is on; observations
        # below then record bucket EXEMPLARS so a p99 outlier in any
        # latency histogram resolves back to this request's timeline.
        # None while the journal is off — the observes below pay one
        # attribute check and nothing else (test-pinned).
        self.trace_id = None
        # prefix-cache accounting (FLAGS_serving_prefix_cache): tokens
        # of this request's prompt looked up / served from the radix
        # cache, summed across admissions (a preempted request's resume
        # looks up again — and usually re-hits its own inserted pages)
        self.prefix_lookup_tokens = 0
        self.prefix_cached_tokens = 0
        # cached tokens at the FIRST admission only: the hit/miss
        # CLASSIFICATION bit. The cumulative count above also absorbs
        # resume re-matches (a preempted miss re-hits its own inserted
        # pages), which must not reclassify a miss-TTFT as a hit.
        self.prefix_cached_tokens_first = None

    def on_prefix_lookup(self, lookup_tokens, hit_tokens):
        if self.prefix_cached_tokens_first is None:
            self.prefix_cached_tokens_first = int(hit_tokens)
        self.prefix_lookup_tokens += int(lookup_tokens)
        self.prefix_cached_tokens += int(hit_tokens)
        _PREFIX_LOOKUP.inc(int(lookup_tokens))
        if hit_tokens:
            _PREFIX_HIT.inc(int(hit_tokens))

    def on_admit(self, t):
        if self.first_admit_t is None:
            self.first_admit_t = t
            with _mtrace.exemplar_context(self.trace_id):
                _QUEUE.observe(t - self.arrival_t)

    def on_first_token(self, t):
        if self.first_token_t is None:
            self.first_token_t = t
            with _mtrace.exemplar_context(self.trace_id):
                _TTFT.observe(t - self.arrival_t)

    def on_finish(self, t, output_tokens):
        self.finish_t = t
        self.output_tokens = output_tokens
        with _mtrace.exemplar_context(self.trace_id):
            _E2E.observe(t - self.arrival_t)
            if self.first_token_t is not None and output_tokens > 1:
                _TPOT.observe((t - self.first_token_t)
                              / (output_tokens - 1))

    def to_dict(self):
        ttft = (None if self.first_token_t is None
                else self.first_token_t - self.arrival_t)
        tpot = None
        if (self.finish_t is not None and self.first_token_t is not None
                and self.output_tokens > 1):
            tpot = ((self.finish_t - self.first_token_t)
                    / (self.output_tokens - 1))
        return {
            "queue_time_s": (None if self.first_admit_t is None
                             else self.first_admit_t - self.arrival_t),
            "ttft_s": ttft,
            "tpot_s": tpot,
            "e2e_s": (None if self.finish_t is None
                      else self.finish_t - self.arrival_t),
            "prompt_tokens": self.prompt_tokens,
            "output_tokens": self.output_tokens,
            "preemptions": self.preemptions,
            "prefix_cached_tokens": self.prefix_cached_tokens,
            "prefix_cached_tokens_first": (
                self.prefix_cached_tokens_first or 0),
        }


class EngineMetrics:
    def __init__(self, max_slots):
        self.max_slots = max_slots
        # instantaneous gauges are per engine instance: two engines in
        # one process must not overwrite each other's last-write-wins
        # series (bind the children once — no per-step dict lookups)
        eid = str(next(_ENGINE_IDS))
        self._active_gauge = _ACTIVE.labels(engine=eid)
        self._throughput_gauge = _THROUGHPUT.labels(engine=eid)
        self._goodput_gauge = _GOODPUT.labels(engine=eid)
        self._kv_occ_gauge = _KV_OCC.labels(engine=eid)
        # bound lazily on the first prefix-cache sample: with the flags
        # off no serving_prefix_cache_pages series exists at all
        self._eid = eid
        self._prefix_pages_gauge = None
        self._quant_pages_gauge = None
        _prune_engine_series()
        # wall clock starts at FIRST ADMISSION, not construction: an
        # engine built ahead of traffic must not understate throughput
        self.start_t = None
        self.requests_in = 0
        self.requests_finished = 0
        self.requests_shed = 0
        self.shed_by_reason = {}
        self.preemptions = 0
        self.prefill_runs = 0
        self.decode_steps = 0
        self.output_tokens = 0
        self.finished_output_tokens = 0
        self.decode_compiles = 0
        self.prefill_compiles = 0
        self._occupancy_sum = 0
        self._kv_occupancy = 0.0
        # prefix cache / chunked prefill (FLAGS_serving_*; all stay 0
        # with the flags off)
        self.prefix_hit_tokens = 0
        self.prefix_lookup_tokens = 0
        self.prefix_evictions = 0
        self.prefix_insert_pages = 0
        self.prefix_cached_pages = 0
        self.cow_clones = 0
        self.prefill_chunks = 0
        # KV quantization (FLAGS_serving_quant_kv; 0 with the flag off)
        self.kv_quant_pages = 0
        self.quant_dequant_bytes = 0

    # -- engine hooks (mirror every sample into the shared registry) ---

    def on_request_in(self):
        self.requests_in += 1
        _REQUESTS.labels(event="in").inc()

    def on_request_finished(self, output_tokens=0):
        self.requests_finished += 1
        self.finished_output_tokens += int(output_tokens)
        _REQUESTS.labels(event="finished").inc()
        if self.start_t is not None:
            self._note_perf_job()

    def on_request_shed(self, reason):
        """One request terminated without full service (expired /
        queue_full / draining / preempt_cap / poison)."""
        self.requests_shed += 1
        self.shed_by_reason[reason] = \
            self.shed_by_reason.get(reason, 0) + 1
        _SHED.labels(reason=reason).inc()
        _REQUESTS.labels(event="shed").inc()

    def on_preemption(self):
        self.preemptions += 1
        _REQUESTS.labels(event="preempted").inc()

    def on_admission(self):
        if self.start_t is None:
            self.start_t = now()

    def on_prefill_run(self):
        self.prefill_runs += 1
        _PREFILLS.inc()

    def on_prefill_chunk(self):
        self.prefill_chunks += 1
        _CHUNKS.inc()

    def on_prefix_stats(self, pc_stats, cow_clones):
        """Engine-pushed snapshot of the radix cache counters (called
        once per engine step with the cache on; the registry series get
        the DELTAS so counters stay monotone across engines)."""
        if self._prefix_pages_gauge is None:
            self._prefix_pages_gauge = _PREFIX_PAGES.labels(
                engine=self._eid)
        # hit/lookup token counters are incremented per-request in
        # on_prefix_lookup — here only the engine-dict mirrors update
        d = pc_stats["evicted_pages"] - self.prefix_evictions
        if d:
            _PREFIX_EVICT.inc(d)
        d = pc_stats["inserted_pages"] - self.prefix_insert_pages
        if d:
            _PREFIX_INSERT.inc(d)
        d = cow_clones - self.cow_clones
        if d:
            _COW_CLONES.inc(d)
        self.prefix_hit_tokens = pc_stats["hit_tokens"]
        self.prefix_lookup_tokens = pc_stats["lookup_tokens"]
        self.prefix_evictions = pc_stats["evicted_pages"]
        self.prefix_insert_pages = pc_stats["inserted_pages"]
        self.prefix_cached_pages = pc_stats["cached_pages"]
        self.cow_clones = cow_clones
        self._prefix_pages_gauge.set(pc_stats["cached_pages"])

    def on_quant_step(self, pages_used, dequant_bytes):
        """Engine-pushed quant-KV sample, once per decode/mixed step
        with FLAGS_serving_quant_kv on: the live int8 page count and
        the int8 bytes the step's attention gathers dequantized."""
        if self._quant_pages_gauge is None:
            self._quant_pages_gauge = _KV_QUANT_PAGES.labels(
                engine=self._eid)
        self.kv_quant_pages = pages_used
        self._quant_pages_gauge.set(pages_used)
        if dequant_bytes:
            self.quant_dequant_bytes += int(dequant_bytes)
            _QUANT_DEQ_BYTES.inc(int(dequant_bytes))

    def on_output_token(self):
        self.output_tokens += 1
        _TOKENS.inc()

    def on_decode_compile(self):
        self.decode_compiles += 1
        _COMPILES.labels(fn="decode").inc()

    def on_prefill_compile(self):
        self.prefill_compiles += 1
        _COMPILES.labels(fn="prefill").inc()

    def on_decode_step(self, active_slots):
        self.decode_steps += 1
        self._occupancy_sum += active_slots
        _DECODE_STEPS.inc()
        self._active_gauge.set(active_slots)
        # the throughput gauge updates on the WRITE path (here, once per
        # step) so /metrics scrapes are live — not only when something
        # happens to call to_dict()
        if self.start_t is not None:
            self._throughput_gauge.set(self.output_tokens
                                       / max(now() - self.start_t, 1e-9))
        counter("serving.active_slots", active_slots)

    def on_kv_occupancy(self, occupancy):
        """Engine-reported KV-page occupancy (used pages / usable) —
        updated per step under FLAGS_perf_attribution, and mirrored
        into the /debugz/perf payload with the goodput numbers."""
        self._kv_occupancy = occupancy
        self._kv_occ_gauge.set(occupancy)
        self._note_perf_job()

    def _note_perf_job(self):
        """Goodput gauge + /debugz/perf mirror, uniformly flag-gated:
        with attribution off this is an early return — no gauge series
        appears, the payload stays empty (test-pinned), and a scraper
        can read the flag state from the series' presence."""
        try:
            from ..monitor import perf as _perf

            if not _perf.attribution_enabled():
                return
            wall = (max(now() - self.start_t, 1e-9)
                    if self.start_t is not None else 0.0)
            if wall:
                self._goodput_gauge.set(
                    self.finished_output_tokens / wall)
            _perf.note_job(
                "serving",
                goodput_tokens_per_s=(self.finished_output_tokens / wall
                                      if wall else 0.0),
                throughput_tokens_per_s=(self.output_tokens / wall
                                         if wall else 0.0),
                kv_page_occupancy=self._kv_occupancy,
                output_tokens=self.output_tokens,
                finished_output_tokens=self.finished_output_tokens,
                preemptions=self.preemptions,
                decode_steps=self.decode_steps,
                prefix_hit_tokens=self.prefix_hit_tokens,
                prefix_cached_pages=self.prefix_cached_pages,
                prefill_chunks=self.prefill_chunks)
        except Exception as e:
            from ..monitor.registry import warn_once

            warn_once(
                "serving.note_perf_job",
                "paddle_tpu.serving.metrics: perf-job attribution "
                "failed (serving unaffected, goodput series stop): "
                "%r" % (e,))

    def to_dict(self):
        wall = (max(now() - self.start_t, 1e-9)
                if self.start_t is not None else 0.0)
        occ = (self._occupancy_sum / (self.decode_steps * self.max_slots)
               if self.decode_steps else 0.0)
        throughput = self.output_tokens / wall if wall else 0.0
        return {
            "requests_in": self.requests_in,
            "requests_finished": self.requests_finished,
            "requests_shed": self.requests_shed,
            "shed_by_reason": dict(self.shed_by_reason),
            "preemptions": self.preemptions,
            "prefill_runs": self.prefill_runs,
            "decode_steps": self.decode_steps,
            "output_tokens": self.output_tokens,
            "finished_output_tokens": self.finished_output_tokens,
            "decode_compiles": self.decode_compiles,
            "prefill_compiles": self.prefill_compiles,
            "wall_s": wall,
            "throughput_tok_s": throughput,
            "goodput_tok_s": (self.finished_output_tokens / wall
                              if wall else 0.0),
            "slot_occupancy": occ,
            "kv_page_occupancy": self._kv_occupancy,
            "prefix_hit_tokens": self.prefix_hit_tokens,
            "prefix_lookup_tokens": self.prefix_lookup_tokens,
            "prefix_evictions": self.prefix_evictions,
            "prefix_insert_pages": self.prefix_insert_pages,
            "prefix_cached_pages": self.prefix_cached_pages,
            "cow_clones": self.cow_clones,
            "prefill_chunks": self.prefill_chunks,
            "kv_quant_pages": self.kv_quant_pages,
            "quant_dequant_bytes": self.quant_dequant_bytes,
        }
