"""Request lifecycle + FCFS continuous-batching scheduler.

Lifecycle: QUEUED -> PREFILL -> DECODING -> FINISHED, with
DECODING -> PREEMPTED when the page pool exhausts (the victim waits at
the queue front in PREEMPTED state until re-admission re-prefills it).

Policies (vLLM-style, kept deliberately simple and deterministic):

- Admission is strict FCFS with no head-of-line bypass: the queue head
  is admitted only when a slot is free AND the pool has pages for its
  whole (resume) prompt; nothing behind it jumps ahead. Deterministic
  order is what lets tests pin bit-identical outputs.
- Preemption victim = the most recently admitted OTHER running request
  (last-in, first-preempted). The victim's pages are freed, and it is
  requeued at the FRONT of the queue by recompute: its resume prompt is
  ``prompt + generated so far``, so greedy decoding continues
  bit-identically after re-prefill.
- A finished/preempted slot is immediately reusable (slot reuse on
  EOS) — the next admission claims the lowest free slot index.

Serving tier 2 (both default-off, latched at Engine construction):
with FLAGS_serving_prefix_cache the admission check charges only the
UNCACHED SUFFIX of the resume prompt (matched prefix pages are adopted
shared/refcounted from the radix tree, and an LRU reclaim of cold
cached pages runs before admission gives up); release inserts the
slot's full pages into the tree before decref'ing, so preempt-by-
recompute resumes mostly from cache. With
FLAGS_serving_chunked_prefill, PREFILL is a RESUMABLE state — the
request holds its slot across steps while ``prefill_pos`` walks its
prompt in chunks through the mixed step — and mid-prefill rows are
preemption candidates like decode rows.
"""
from __future__ import annotations

import itertools
from collections import deque
from enum import Enum

from ..monitor import trace as _trace
from .metrics import RequestMetrics, now


class RequestState(Enum):
    QUEUED = "queued"
    PREFILL = "prefill"
    DECODING = "decoding"
    PREEMPTED = "preempted"
    FINISHED = "finished"
    # terminal degraded outcomes (resilience layer): the request ended
    # WITHOUT full service, each with a machine-readable status_reason
    EXPIRED = "expired"      # queue-TTL deadline passed while waiting
    SHED = "shed"            # load-shed (queue bound / preemption cap)
    FAILED = "failed"        # poison: its own step raised; engine lives


class Request:
    _ids = itertools.count()

    def __init__(self, prompt, max_new_tokens, eos_token_id=None,
                 deadline_s=None):
        self.id = next(Request._ids)
        self.prompt = [int(t) for t in prompt]
        self.max_new_tokens = int(max_new_tokens)
        self.eos_token_id = eos_token_id
        self.state = RequestState.QUEUED
        self.generated = []
        self.slot = None
        self.admit_seq = None      # monotone admission stamp (victim pick)
        self.metrics = RequestMetrics(now())
        self.metrics.prompt_tokens = len(self.prompt)
        # queue-TTL deadline (monotonic absolute): a request still
        # WAITING (queued or preempted-requeued) past it is shed with
        # the EXPIRED terminal status; once decoding it runs to finish
        self.deadline_t = (None if deadline_s is None
                           else self.metrics.arrival_t + float(deadline_s))
        self.status_reason = None  # terminal detail for EXPIRED/SHED/FAILED
        self.error = None          # the exception of a FAILED request
        # span journal (monitor/trace.py, FLAGS_monitor_trace): the
        # request's trace id, assigned at admission to the engine; None
        # while the journal is off, and every trace_* helper below
        # no-ops on None — a mid-run flag flip never half-traces
        self.trace_id = None
        self._span_root = None
        self._span_phase = None
        # prefix-cache / chunked-prefill state (FLAGS_serving_*; both 0
        # and unused on the default paths):
        # cached_tokens — tokens of THIS admission's resume prompt that
        # came out of the radix cache (the prefill starts there);
        # prefill_pos — resumable chunked-prefill cursor: tokens of
        # resume_tokens already run through the mixed step. Both reset
        # at every (re-)admission.
        self.cached_tokens = 0
        self.prefill_pos = 0

    @property
    def resume_tokens(self):
        """Context to (re-)prefill: prompt plus everything generated —
        recompute-on-resume keeps greedy output bit-identical."""
        return self.prompt + self.generated

    @property
    def remaining(self):
        return self.max_new_tokens - len(self.generated)

    def finish(self):
        self.state = RequestState.FINISHED
        self.metrics.on_finish(now(), len(self.generated))

    def close(self, state, reason, error=None):
        """Terminal close for the degraded outcomes (EXPIRED / SHED /
        FAILED): stamps the finish time for wall accounting WITHOUT
        observing the latency histograms — a shed request's lifetime is
        not a service latency, and mixing them would poison the p99s
        the SLO reads."""
        self.state = state
        self.status_reason = reason
        self.error = error
        self.metrics.finish_t = now()
        self.metrics.output_tokens = len(self.generated)
        self.trace_finish(state.value, reason=reason)

    @property
    def terminal(self):
        return self.state in (RequestState.FINISHED, RequestState.EXPIRED,
                              RequestState.SHED, RequestState.FAILED)

    # -- span timeline (monitor/trace.py) ---------------------------------
    #
    # One root "request" span per request; lifecycle phases (queue ->
    # prefill -> decode -> preempted -> prefill(resume) -> ...) are
    # CONTIGUOUS child phase spans — each transition ends the previous
    # phase and starts the next at ONE timestamp, so the phase
    # durations sum to the request's e2e latency (the acceptance
    # contract tests/test_trace.py pins at +-5%).

    def trace_begin(self, trace_ctx=None):
        """``trace_ctx=(trace_id, parent_span_id)`` adopts a context
        minted by another process (the fleet router's traceparent): the
        engine's phase spans land under the SAME fleet-wide trace id,
        the root span naming the sender's dispatch span as its remote
        parent."""
        if not _trace.is_enabled():
            return
        remote_parent = None
        if trace_ctx is not None and trace_ctx[0] is not None:
            self.trace_id = _trace.adopt_trace(
                trace_ctx[0], "request", request_id=self.id,
                prompt_tokens=len(self.prompt),
                max_new_tokens=self.max_new_tokens)
            remote_parent = trace_ctx[1]
        else:
            self.trace_id = _trace.new_trace(
                "request", request_id=self.id,
                prompt_tokens=len(self.prompt),
                max_new_tokens=self.max_new_tokens)
        self._span_root = _trace.start_span(
            "request", self.trace_id, kind="request", request_id=self.id,
            remote_parent=remote_parent)
        self.metrics.trace_id = self.trace_id

    def trace_phase(self, phase, **attrs):
        if self.trace_id is None:
            return
        t = _trace.now()
        if self._span_phase is not None:
            _trace.end_span(self._span_phase, t=t)
        self._span_phase = _trace.start_span(
            phase, self.trace_id, parent_id=self._span_root,
            kind="phase", t=t, **attrs)

    def trace_event(self, name, **attrs):
        if self.trace_id is None:
            return
        _trace.add_event(self._span_phase
                         if self._span_phase is not None
                         else self._span_root, name, **attrs)

    def trace_finish(self, status="finished", **attrs):
        if self.trace_id is None:
            return
        t = _trace.now()
        if self._span_phase is not None:
            _trace.end_span(self._span_phase, t=t)
            self._span_phase = None
        _trace.end_span(self._span_root, t=t, status=status,
                        output_tokens=len(self.generated),
                        preemptions=self.metrics.preemptions, **attrs)


class Scheduler:
    def __init__(self, max_slots, cache, prefix_cache=None):
        self.max_slots = max_slots
        self.cache = cache
        # radix prefix cache (FLAGS_serving_prefix_cache; None = the
        # pre-cache admission path, bit-identical)
        self.prefix_cache = prefix_cache
        self.queue = deque()
        self.slots = [None] * max_slots    # slot -> Request or None
        self._admit_counter = itertools.count()

    # -- queue ------------------------------------------------------------

    def add(self, req):
        self.queue.append(req)

    def requeue_front(self, req):
        self.queue.appendleft(req)

    def expire_waiting(self, t=None):
        """Remove waiting requests (QUEUED or PREEMPTED — both hold no
        slot) whose queue-TTL deadline passed; returns them, oldest
        first, for the engine to close as EXPIRED. Decoding requests
        are never expired: their pages are live and finishing is
        strictly cheaper than recomputing a replacement."""
        t = now() if t is None else t
        expired = [r for r in self.queue
                   if r.deadline_t is not None and t >= r.deadline_t]
        if expired:
            dead = set(id(r) for r in expired)
            self.queue = deque(r for r in self.queue
                               if id(r) not in dead)
        return expired

    def has_work(self):
        return bool(self.queue) or any(
            r is not None for r in self.slots)

    def active(self):
        """(slot, req) for slots currently decoding, slot order."""
        return [(i, r) for i, r in enumerate(self.slots)
                if r is not None and r.state is RequestState.DECODING]

    def occupied(self):
        """(slot, req) for every slot holding live work — DECODING rows
        plus mid-prefill chunk rows (chunked prefill keeps PREFILL
        state across steps); the mixed ragged step batches them all."""
        return [(i, r) for i, r in enumerate(self.slots)
                if r is not None and r.state in (RequestState.PREFILL,
                                                 RequestState.DECODING)]

    def slots_active(self):
        """Occupied slot count (any state) — the batch-slot occupancy
        the trace events stamp."""
        return sum(1 for r in self.slots if r is not None)

    # -- admission --------------------------------------------------------

    def admit_next(self):
        """Admit the queue head if a slot is free and the pool can hold
        its resume prompt's UNCACHED SUFFIX (with the prefix cache off,
        that is the whole prompt — the pre-cache check, bit-identical).
        Returns (slot, req) or None. Strict FCFS: a blocked head blocks
        everything behind it. With the prefix cache on, the head's
        prefix is matched against the radix tree first: matched pages
        are adopted (shared, refcounted) instead of allocated, and when
        even the suffix doesn't fit, an LRU reclaim of unreferenced
        cached pages runs BEFORE giving up."""
        if not self.queue:
            return None
        free = [i for i, r in enumerate(self.slots) if r is None]
        if not free:
            return None
        req = self.queue[0]
        slot = free[0]
        tokens = req.resume_tokens
        matched_pages, matched = [], 0
        if self.prefix_cache is not None:
            matched_pages, matched = self.prefix_cache.match(
                tokens, limit=len(tokens) - 1)
        need = self.cache.pages_needed(len(tokens)) - len(matched_pages)
        if matched % self.cache.block_size:
            # a partially-matched page will be copy-on-write cloned at
            # first write — charge the clone page now so the prefill
            # can never fail mid-admission (all-or-nothing stays true)
            need += 1
        # adopt BEFORE any reclaim: the slot's reference (refcount 2)
        # protects the just-matched pages from the LRU walk — otherwise
        # an eviction pass triggered by THIS admission could free the
        # very prefix it matched
        if matched_pages:
            self.cache.adopt_prefix(slot, matched_pages, matched)
        if need > self.cache.allocator.free_blocks:
            if self.prefix_cache is not None:
                self.prefix_cache.reclaim(
                    need - self.cache.allocator.free_blocks)
            if need > self.cache.allocator.free_blocks:
                if matched_pages:   # undo: all-or-nothing admission
                    self.cache.release_slot(slot)
                return None
        self.queue.popleft()
        if not self.cache.ensure_capacity(slot, len(tokens)):
            raise AssertionError("admission raced the allocator")
        req.cached_tokens = matched
        req.prefill_pos = matched
        if self.prefix_cache is not None:
            self.prefix_cache.note_lookup(len(tokens), matched)
            req.metrics.on_prefix_lookup(len(tokens), matched)
        self.slots[slot] = req
        req.slot = slot
        req.state = RequestState.PREFILL
        req.admit_seq = next(self._admit_counter)
        req.metrics.on_admit(now())
        if req.trace_id is not None:    # attrs cost nothing when off
            req.trace_event(
                "scheduled", slot=slot, kv_pages=need,
                kv_cached_tokens=matched,
                kv_free_blocks=self.cache.allocator.free_blocks,
                slots_active=self.slots_active(),
                resume=req.metrics.preemptions > 0)
        return slot, req

    # -- slot release / preemption ---------------------------------------

    def release(self, req):
        """Release the request's slot + page references (finish or
        preempt). With the prefix cache on, the slot's FULL pages are
        inserted into the radix tree FIRST — release then decrefs, so
        the computed prefix (prompt and generated history both) stays
        warm: a preempted victim's resume re-matches its own pages and
        recomputes almost nothing, and the next request sharing the
        prompt head skips it entirely."""
        slot = req.slot
        if self.prefix_cache is not None:
            self.prefix_cache.insert(req.resume_tokens,
                                     self.cache.slot_pages(slot),
                                     int(self.cache.seq_lens[slot]))
        self.cache.release_slot(slot)
        self.slots[slot] = None
        req.slot = None

    def preempt_victim(self, exclude_slot, max_preemptions=None,
                       include_prefill=False):
        """Pick and preempt the most recently admitted running request
        other than ``exclude_slot``; requeues it at the front. Returns
        the victim or None when there is no ELIGIBLE other running
        request. With ``max_preemptions`` set, a request that already
        paid the cap is no longer a candidate — it runs to completion,
        which is what breaks the preempt-recompute livelock (two
        requests evicting each other forever make no progress; a capped
        request cannot be evicted, so it finishes and frees pages).
        ``include_prefill`` widens the candidate set to mid-prefill
        chunk rows (chunked prefill holds PREFILL slots across steps;
        on the default path prefill is synchronous and the wider set is
        identical to active())."""
        pool = self.occupied() if include_prefill else self.active()
        candidates = [r for i, r in pool if i != exclude_slot
                      and (max_preemptions is None
                           or r.metrics.preemptions < max_preemptions)]
        if not candidates:
            return None
        victim = max(candidates, key=lambda r: r.admit_seq)
        seq_len = (int(self.cache.seq_lens[victim.slot])
                   if victim.trace_id is not None else 0)
        self.release(victim)
        victim.state = RequestState.PREEMPTED
        victim.metrics.preemptions += 1
        self.requeue_front(victim)
        if victim.trace_id is not None:
            victim.trace_phase(
                "preempted", seq_len=seq_len,
                kv_pages_freed=self.cache.pages_needed(seq_len),
                kv_free_blocks=self.cache.allocator.free_blocks,
                slots_active=self.slots_active())
        return victim
