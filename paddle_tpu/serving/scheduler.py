"""Request lifecycle + FCFS continuous-batching scheduler.

Lifecycle: QUEUED -> PREFILL -> DECODING -> FINISHED, with
DECODING -> PREEMPTED when the page pool exhausts (the victim waits at
the queue front in PREEMPTED state until re-admission re-prefills it).

Policies (vLLM-style, kept deliberately simple and deterministic):

- Admission is strict FCFS with no head-of-line bypass: the queue head
  is admitted only when a slot is free AND the pool has pages for its
  whole (resume) prompt; nothing behind it jumps ahead. Deterministic
  order is what lets tests pin bit-identical outputs.
- Preemption victim = the most recently admitted OTHER running request
  (last-in, first-preempted). The victim's pages are freed, and it is
  requeued at the FRONT of the queue by recompute: its resume prompt is
  ``prompt + generated so far``, so greedy decoding continues
  bit-identically after re-prefill.
- A finished/preempted slot is immediately reusable (slot reuse on
  EOS) — the next admission claims the lowest free slot index.
"""
from __future__ import annotations

import itertools
from collections import deque
from enum import Enum

from .metrics import RequestMetrics, now


class RequestState(Enum):
    QUEUED = "queued"
    PREFILL = "prefill"
    DECODING = "decoding"
    PREEMPTED = "preempted"
    FINISHED = "finished"


class Request:
    _ids = itertools.count()

    def __init__(self, prompt, max_new_tokens, eos_token_id=None):
        self.id = next(Request._ids)
        self.prompt = [int(t) for t in prompt]
        self.max_new_tokens = int(max_new_tokens)
        self.eos_token_id = eos_token_id
        self.state = RequestState.QUEUED
        self.generated = []
        self.slot = None
        self.admit_seq = None      # monotone admission stamp (victim pick)
        self.metrics = RequestMetrics(now())
        self.metrics.prompt_tokens = len(self.prompt)

    @property
    def resume_tokens(self):
        """Context to (re-)prefill: prompt plus everything generated —
        recompute-on-resume keeps greedy output bit-identical."""
        return self.prompt + self.generated

    @property
    def remaining(self):
        return self.max_new_tokens - len(self.generated)

    def finish(self):
        self.state = RequestState.FINISHED
        self.metrics.on_finish(now(), len(self.generated))


class Scheduler:
    def __init__(self, max_slots, cache):
        self.max_slots = max_slots
        self.cache = cache
        self.queue = deque()
        self.slots = [None] * max_slots    # slot -> Request or None
        self._admit_counter = itertools.count()

    # -- queue ------------------------------------------------------------

    def add(self, req):
        self.queue.append(req)

    def requeue_front(self, req):
        self.queue.appendleft(req)

    def has_work(self):
        return bool(self.queue) or any(
            r is not None for r in self.slots)

    def active(self):
        """(slot, req) for slots currently decoding, slot order."""
        return [(i, r) for i, r in enumerate(self.slots)
                if r is not None and r.state is RequestState.DECODING]

    # -- admission --------------------------------------------------------

    def admit_next(self):
        """Admit the queue head if a slot is free and the pool can hold
        its whole resume prompt. Returns (slot, req) or None. Strict
        FCFS: a blocked head blocks everything behind it."""
        if not self.queue:
            return None
        free = [i for i, r in enumerate(self.slots) if r is None]
        if not free:
            return None
        req = self.queue[0]
        slot = free[0]
        need = self.cache.pages_needed(len(req.resume_tokens))
        if need > self.cache.allocator.free_blocks:
            return None
        self.queue.popleft()
        if not self.cache.ensure_capacity(slot, len(req.resume_tokens)):
            raise AssertionError("admission raced the allocator")
        self.slots[slot] = req
        req.slot = slot
        req.state = RequestState.PREFILL
        req.admit_seq = next(self._admit_counter)
        req.metrics.on_admit(now())
        return slot, req

    # -- slot release / preemption ---------------------------------------

    def release(self, req):
        """Free the request's slot + pages (finish or preempt)."""
        slot = req.slot
        self.cache.release_slot(slot)
        self.slots[slot] = None
        req.slot = None

    def preempt_victim(self, exclude_slot):
        """Pick and preempt the most recently admitted running request
        other than ``exclude_slot``; requeues it at the front. Returns
        the victim or None when there is no other running request."""
        candidates = [r for i, r in self.active() if i != exclude_slot]
        if not candidates:
            return None
        victim = max(candidates, key=lambda r: r.admit_seq)
        self.release(victim)
        victim.state = RequestState.PREEMPTED
        victim.metrics.preemptions += 1
        self.requeue_front(victim)
        return victim
