"""paddle_tpu.inference — the deployment API.

Reference: AnalysisPredictor + AnalysisConfig
(/root/reference/paddle/fluid/inference/api/analysis_predictor.cc,
 paddle_inference_api.h). The reference runs a 99k-LoC pass pipeline (IR
fusions, TensorRT subgraphs, memory planning) over a loaded ProgramDesc.
TPU-native: the saved artifact already IS a whole-program StableHLO module
(static.save_inference_model / jit.save), so the "analysis" stage collapses
into XLA compilation — fusion, layout, and memory planning are the
compiler's. The Config/Predictor/Tensor-handle API surface is preserved so
reference deployment code ports directly.
"""
from __future__ import annotations

import numpy as np

__all__ = [
    "Config", "create_predictor", "Predictor", "PlaceType",
    "PredictorPool", "PrecisionType", "convert_to_mixed_precision",
]


class PlaceType:
    CPU = "cpu"
    GPU = "gpu"
    TPU = "tpu"
    XPU = "xpu"


class PrecisionType:
    """reference paddle_infer.PrecisionType (paddle_inference_api.h)."""

    Float32 = "float32"
    Half = "float16"
    Bfloat16 = "bfloat16"
    Int8 = "int8"


def convert_to_mixed_precision(model_file, params_file, mixed_model_file,
                               mixed_params_file,
                               mixed_precision=PrecisionType.Bfloat16,
                               backend=PlaceType.TPU, keep_io_types=True,
                               black_list=None):
    """Convert a saved fp32 jit.save artifact to mixed precision
    (reference inference/wrapper.py:64 convert_to_mixed_precision →
    convert_to_mixed_precision.cc pass).

    TPU mapping: the jit.save format keeps params (.pdiparams) separate
    from the program, whose call signature is (state, *inputs). The
    converter casts float32 params to `mixed_precision` (black_list =
    param names kept fp32 — norm scales etc.) and re-exports the program
    with a cast-at-entry wrapper, halving the artifact and serve-time
    weight HBM; XLA folds the upcasts into first use. Op-level compute
    dtype is fixed at export time — for bf16 MXU compute, export under
    `amp.decorate(level='O2')` + jit.save (documented deviation: the
    reference rewrites op dtypes post-hoc in the ProgramDesc, which a
    serialized StableHLO module doesn't permit).

    model_file/params_file accept either the full filename
    (`prefix.pdmodel`) or the prefix, like Config.
    """
    import os
    import pickle

    import jax
    import jax.numpy as jnp
    import ml_dtypes  # noqa: F401  (numpy bf16 support)

    if mixed_precision == PrecisionType.Int8:
        raise ValueError(
            "int8 conversion is the quantization pipeline "
            "(paddle_tpu.quantization PTQ), not a dtype cast")
    black_list = set(black_list or ())
    target = jnp.dtype(mixed_precision)

    def _prefix(p, suffix):
        return p[: -len(suffix)] if p.endswith(suffix) else p

    src = _prefix(model_file, ".pdmodel")
    src_params = (_prefix(params_file, ".pdiparams")
                  if params_file else src)
    dst = _prefix(mixed_model_file, ".pdmodel")
    with open(src_params + ".pdiparams", "rb") as f:
        state = pickle.load(f)
    with open(src + ".pdmodel", "rb") as f:
        payload = pickle.load(f)
    if not (isinstance(payload, dict) and "meta" in payload):
        raise ValueError(
            "convert_to_mixed_precision needs the jit.save artifact "
            "format; static.save_inference_model freezes params into the "
            "compiled module — re-export that model under "
            "amp.decorate(level='O2') instead")
    meta = dict(payload["meta"])
    blob = payload.get("stablehlo")
    if not blob:
        raise ValueError(
            "this artifact holds weights only (jit.save without "
            "input_spec) — a converted copy could never serve; re-save "
            "with input_spec so the program is exported too")

    orig_dtypes = {}
    mixed_state = {}
    for name, v in state.items():
        arr = np.asarray(v)
        orig_dtypes[name] = str(arr.dtype)
        if arr.dtype == np.float32 and name not in black_list:
            arr = arr.astype(target)
        mixed_state[name] = arr

    # blob is guaranteed non-empty by the weights-only guard above
    from jax import export as jex

    from ..jit import export_with_dynamic_dims
    from ..core import dtype as _dtype

    exported = jex.deserialize(blob)
    names = meta.get("state_names") or sorted(state.keys())
    cast_back = [jnp.dtype(orig_dtypes[n]) for n in names]

    def mixed_call(state_vals, *in_vals):
        full = [v.astype(d) if v.dtype != d else v
                for v, d in zip(state_vals, cast_back)]
        out = exported.call(full, *in_vals)
        if not keep_io_types:
            out = jax.tree_util.tree_map(
                lambda o: o.astype(target)
                if o.dtype == jnp.float32 else o, out)
        return out

    specs = [(tuple(s["shape"]), _dtype.to_jax(s["dtype"]))
             for s in meta.get("input_spec", [])]
    lead = [jnp.asarray(mixed_state[n]) for n in names]
    meta["mixed_precision"] = mixed_precision
    blob = export_with_dynamic_dims(mixed_call, specs,
                                    leading_args=(lead,))

    os.makedirs(os.path.dirname(dst) or ".", exist_ok=True)
    params_dst = _prefix(mixed_params_file, ".pdiparams")
    os.makedirs(os.path.dirname(params_dst) or ".", exist_ok=True)
    with open(params_dst + ".pdiparams", "wb") as f:
        pickle.dump(mixed_state, f, protocol=4)
    with open(dst + ".pdmodel", "wb") as f:
        pickle.dump({"meta": meta, "stablehlo": blob}, f, protocol=4)


class Config:
    """AnalysisConfig analog. Accepts a path prefix (``prefix`` →
    ``prefix.pdmodel`` + ``prefix.pdmeta``/``.pdiparams``) or explicit
    model/params files."""

    def __init__(self, prog_file=None, params_file=None):
        if prog_file is not None and prog_file.endswith(".pdmodel"):
            prog_file = prog_file[: -len(".pdmodel")]
        self._prefix = prog_file
        self._params_file = params_file
        self._device = None
        self._memory_pool_mb = None
        self._ir_optim = True

    # device selection: XLA picks the default backend; these record intent
    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        self._device = PlaceType.GPU
        self._memory_pool_mb = memory_pool_init_size_mb

    def enable_tpu(self):
        self._device = PlaceType.TPU

    def disable_gpu(self):
        self._device = PlaceType.CPU

    def set_cpu_math_library_num_threads(self, n):
        self._cpu_threads = n

    def switch_ir_optim(self, flag=True):
        self._ir_optim = flag  # XLA always optimizes; recorded for compat

    def enable_memory_optim(self, flag=True):
        pass  # XLA buffer assignment

    def model_dir(self):
        return self._prefix

    def prog_file(self):
        return (self._prefix or "") + ".pdmodel"

    def params_file(self):
        return self._params_file or ""


class _TensorHandle:
    """Zero-copy-style IO handle (reference ZeroCopyTensor,
    inference/api/details/zero_copy_tensor.cc)."""

    def __init__(self, name):
        self.name = name
        self._value = None

    def reshape(self, shape):
        pass  # shape comes from the bound array

    def copy_from_cpu(self, arr):
        self._value = np.ascontiguousarray(arr)

    def copy_to_cpu(self):
        return np.asarray(self._value)

    def shape(self):
        return list(np.asarray(self._value).shape)


class Predictor:
    def __init__(self, config):
        from ..static import load_inference_model

        self._config = config
        prog, feeds, fetches = load_inference_model(config._prefix)
        # anonymous saved vars get stable synthesized names (the C API
        # and handle lookups need real strings)
        feeds = [n if n else "feed_%d" % i for i, n in enumerate(feeds)]
        fetches = [n if n else "fetch_%d" % i
                   for i, n in enumerate(fetches)]
        self._prog = prog
        self._inputs = {n: _TensorHandle(n) for n in feeds}
        self._outputs = {n: _TensorHandle(n) for n in fetches}
        self._feed_names = feeds
        self._fetch_names = fetches

    def get_input_names(self):
        return list(self._feed_names)

    def get_output_names(self):
        return list(self._fetch_names)

    def get_input_handle(self, name):
        return self._inputs[name]

    def get_output_handle(self, name):
        return self._outputs[name]

    def run(self, inputs=None):
        """Run the compiled module. With ``inputs`` (list of arrays in
        input-name order) returns the outputs directly; otherwise uses the
        bound IO handles."""
        if inputs is not None:
            for n, a in zip(self._feed_names, inputs):
                self._inputs[n].copy_from_cpu(np.asarray(a))
        feed_vals = [self._inputs[n]._value for n in self._feed_names]
        outs = self._prog.run(*feed_vals)
        for n, o in zip(self._fetch_names, outs):
            self._outputs[n]._value = np.asarray(o)
        if inputs is not None:
            return [np.asarray(o) for o in outs]
        return True

    def clear_intermediate_tensor(self):
        pass

    def _clone(self):
        """Share the loaded program; fresh IO handles (reference
        AnalysisPredictor::Clone — the pool building block)."""
        dup = Predictor.__new__(Predictor)
        dup._config = self._config
        dup._prog = self._prog
        dup._feed_names = list(self._feed_names)
        dup._fetch_names = list(self._fetch_names)
        dup._inputs = {n: _TensorHandle(n) for n in dup._feed_names}
        dup._outputs = {n: _TensorHandle(n) for n in dup._fetch_names}
        return dup


def create_predictor(config):
    return Predictor(config)


class PredictorPool:
    """Fixed pool of predictors over one loaded model (reference
    paddle_infer::services::PredictorPool, inference/api/
    paddle_inference_api.h): serving threads each retrieve their own
    predictor so bound IO handles never race. The compiled XLA executable
    is shared process-wide (jit cache); each pool member only carries its
    own IO-handle set, so size N costs N handle sets, not N compilations."""

    def __init__(self, config, size=1):
        if size < 1:
            raise ValueError("PredictorPool size must be >= 1")
        first = Predictor(config)
        self._preds = [first]
        for _ in range(size - 1):
            # reference Clone(): share the loaded program (one disk read,
            # one compiled executable), fresh IO handle set per member
            self._preds.append(first._clone())

    def retrieve(self, idx):
        """Predictor #idx (reference Retrive(idx) spelling is Retrieve
        here; bounds-checked, no negative wrap-around)."""
        if not 0 <= idx < len(self._preds):
            raise IndexError(
                "PredictorPool.retrieve(%d): pool size is %d"
                % (idx, len(self._preds)))
        return self._preds[idx]

    def __len__(self):
        return len(self._preds)
