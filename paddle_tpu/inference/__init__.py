"""paddle_tpu.inference — the deployment API.

Reference: AnalysisPredictor + AnalysisConfig
(/root/reference/paddle/fluid/inference/api/analysis_predictor.cc,
 paddle_inference_api.h). The reference runs a 99k-LoC pass pipeline (IR
fusions, TensorRT subgraphs, memory planning) over a loaded ProgramDesc.
TPU-native: the saved artifact already IS a whole-program StableHLO module
(static.save_inference_model / jit.save), so the "analysis" stage collapses
into XLA compilation — fusion, layout, and memory planning are the
compiler's. The Config/Predictor/Tensor-handle API surface is preserved so
reference deployment code ports directly.
"""
from __future__ import annotations

import numpy as np

__all__ = [
    "Config", "create_predictor", "Predictor", "PlaceType",
]


class PlaceType:
    CPU = "cpu"
    GPU = "gpu"
    TPU = "tpu"
    XPU = "xpu"


class Config:
    """AnalysisConfig analog. Accepts a path prefix (``prefix`` →
    ``prefix.pdmodel`` + ``prefix.pdmeta``/``.pdiparams``) or explicit
    model/params files."""

    def __init__(self, prog_file=None, params_file=None):
        if prog_file is not None and prog_file.endswith(".pdmodel"):
            prog_file = prog_file[: -len(".pdmodel")]
        self._prefix = prog_file
        self._params_file = params_file
        self._device = None
        self._memory_pool_mb = None
        self._ir_optim = True

    # device selection: XLA picks the default backend; these record intent
    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        self._device = PlaceType.GPU
        self._memory_pool_mb = memory_pool_init_size_mb

    def enable_tpu(self):
        self._device = PlaceType.TPU

    def disable_gpu(self):
        self._device = PlaceType.CPU

    def set_cpu_math_library_num_threads(self, n):
        self._cpu_threads = n

    def switch_ir_optim(self, flag=True):
        self._ir_optim = flag  # XLA always optimizes; recorded for compat

    def enable_memory_optim(self, flag=True):
        pass  # XLA buffer assignment

    def model_dir(self):
        return self._prefix

    def prog_file(self):
        return (self._prefix or "") + ".pdmodel"

    def params_file(self):
        return self._params_file or ""


class _TensorHandle:
    """Zero-copy-style IO handle (reference ZeroCopyTensor,
    inference/api/details/zero_copy_tensor.cc)."""

    def __init__(self, name):
        self.name = name
        self._value = None

    def reshape(self, shape):
        pass  # shape comes from the bound array

    def copy_from_cpu(self, arr):
        self._value = np.ascontiguousarray(arr)

    def copy_to_cpu(self):
        return np.asarray(self._value)

    def shape(self):
        return list(np.asarray(self._value).shape)


class Predictor:
    def __init__(self, config):
        from ..static import load_inference_model

        self._config = config
        prog, feeds, fetches = load_inference_model(config._prefix)
        # anonymous saved vars get stable synthesized names (the C API
        # and handle lookups need real strings)
        feeds = [n if n else "feed_%d" % i for i, n in enumerate(feeds)]
        fetches = [n if n else "fetch_%d" % i
                   for i, n in enumerate(fetches)]
        self._prog = prog
        self._inputs = {n: _TensorHandle(n) for n in feeds}
        self._outputs = {n: _TensorHandle(n) for n in fetches}
        self._feed_names = feeds
        self._fetch_names = fetches

    def get_input_names(self):
        return list(self._feed_names)

    def get_output_names(self):
        return list(self._fetch_names)

    def get_input_handle(self, name):
        return self._inputs[name]

    def get_output_handle(self, name):
        return self._outputs[name]

    def run(self, inputs=None):
        """Run the compiled module. With ``inputs`` (list of arrays in
        input-name order) returns the outputs directly; otherwise uses the
        bound IO handles."""
        if inputs is not None:
            for n, a in zip(self._feed_names, inputs):
                self._inputs[n].copy_from_cpu(np.asarray(a))
        feed_vals = [self._inputs[n]._value for n in self._feed_names]
        outs = self._prog.run(*feed_vals)
        for n, o in zip(self._fetch_names, outs):
            self._outputs[n]._value = np.asarray(o)
        if inputs is not None:
            return [np.asarray(o) for o in outs]
        return True

    def clear_intermediate_tensor(self):
        pass


def create_predictor(config):
    return Predictor(config)
