"""vision.transforms completions: color/geometry ops + their classes.

Parity: reference python/paddle/vision/transforms/{transforms,
functional}.py. Images are numpy/jnp arrays, HWC by default (the
reference's numpy backend convention); geometric warps ride
F.affine_grid + F.grid_sample — the same pair the reference's tensor
backend uses — so everything stays XLA-traceable.
"""
from __future__ import annotations

import numbers
import random as _pyrandom

import numpy as np

import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = [
    "hflip", "vflip", "crop", "center_crop", "pad", "adjust_brightness",
    "adjust_contrast", "adjust_saturation", "adjust_hue", "to_grayscale",
    "rotate", "affine", "perspective", "erase",
    "BaseTransform", "Transpose", "BrightnessTransform",
    "ContrastTransform", "SaturationTransform", "HueTransform",
    "ColorJitter", "Grayscale", "Pad", "RandomRotation", "RandomAffine",
    "RandomPerspective", "RandomErasing", "RandomResizedCrop",
]


def _arr(img):
    if isinstance(img, Tensor):
        return np.asarray(img._value)
    return np.asarray(img)


def _wrap(out, like):
    if isinstance(like, Tensor):
        return Tensor(jnp.asarray(out))
    return out


def _is_chw(img):
    a = _arr(img)
    return a.ndim == 3 and a.shape[0] in (1, 3) and a.shape[2] not in (1, 3)


# -- flips / crops / pad -----------------------------------------------------

def hflip(img):
    """reference functional.hflip (width axis)."""
    a = _arr(img)
    return _wrap(a[..., ::-1] if not _is_chw(img) and a.ndim == 2
                 else (a[:, :, ::-1] if _is_chw(img) else a[:, ::-1]),
                 img)


def vflip(img):
    a = _arr(img)
    if _is_chw(img):
        return _wrap(a[:, ::-1], img)
    return _wrap(a[::-1], img)


def crop(img, top, left, height, width):
    a = _arr(img)
    if _is_chw(img):
        return _wrap(a[:, top:top + height, left:left + width], img)
    return _wrap(a[top:top + height, left:left + width], img)


def center_crop(img, output_size):
    a = _arr(img)
    oh, ow = (output_size, output_size) if isinstance(
        output_size, numbers.Number) else output_size
    h, w = (a.shape[1], a.shape[2]) if _is_chw(img) else a.shape[:2]
    top = max((h - oh) // 2, 0)
    left = max((w - ow) // 2, 0)
    return crop(img, top, left, oh, ow)


def pad(img, padding, fill=0, padding_mode="constant"):
    """reference functional.pad: int | [lr_tb] | [l, t, r, b]."""
    if isinstance(padding, numbers.Number):
        pl = pr = pt = pb = int(padding)
    elif len(padding) == 2:
        pl = pr = int(padding[0])
        pt = pb = int(padding[1])
    else:
        pl, pt, pr, pb = [int(p) for p in padding]
    a = _arr(img)
    mode = {"constant": "constant", "edge": "edge", "reflect": "reflect",
            "symmetric": "symmetric"}[padding_mode]
    if _is_chw(img):
        widths = [(0, 0), (pt, pb), (pl, pr)]
    elif a.ndim == 3:
        widths = [(pt, pb), (pl, pr), (0, 0)]
    else:
        widths = [(pt, pb), (pl, pr)]
    kw = {"constant_values": fill} if mode == "constant" else {}
    return _wrap(np.pad(a, widths, mode=mode, **kw), img)


# -- color -------------------------------------------------------------------

def _chan_axis(img):
    return 0 if _is_chw(img) else -1


def adjust_brightness(img, brightness_factor):
    """reference functional.adjust_brightness: img * factor."""
    a = _arr(img).astype(np.float32)
    hi = 255.0 if _arr(img).dtype == np.uint8 else 1.0
    out = np.clip(a * brightness_factor, 0, hi)
    return _wrap(out.astype(_arr(img).dtype), img)


def adjust_contrast(img, contrast_factor):
    """Blend with the mean of the grayscale image."""
    a = _arr(img).astype(np.float32)
    hi = 255.0 if _arr(img).dtype == np.uint8 else 1.0
    mean = _grayscale_np(a, _chan_axis(img)).mean()
    out = np.clip(mean + contrast_factor * (a - mean), 0, hi)
    return _wrap(out.astype(_arr(img).dtype), img)


def adjust_saturation(img, saturation_factor):
    """Blend with the grayscale image."""
    a = _arr(img).astype(np.float32)
    hi = 255.0 if _arr(img).dtype == np.uint8 else 1.0
    gray = _grayscale_np(a, _chan_axis(img), keep_channels=True)
    out = np.clip(gray + saturation_factor * (a - gray), 0, hi)
    return _wrap(out.astype(_arr(img).dtype), img)


def _grayscale_np(a, ch_axis, keep_channels=False):
    w = np.asarray([0.299, 0.587, 0.114], np.float32)
    if a.ndim == 2:
        return a
    g = np.tensordot(np.moveaxis(a, ch_axis, -1)[..., :3], w, axes=1)
    if keep_channels:
        g = np.repeat(np.expand_dims(g, ch_axis), a.shape[ch_axis],
                      axis=ch_axis)
    return g


def adjust_hue(img, hue_factor):
    """Shift hue by hue_factor (in [-0.5, 0.5]) via HSV round-trip
    (reference functional.adjust_hue)."""
    if not -0.5 <= hue_factor <= 0.5:
        raise ValueError("hue_factor must be in [-0.5, 0.5]")
    a = _arr(img)
    dtype = a.dtype
    hi = 255.0 if dtype == np.uint8 else 1.0
    x = np.moveaxis(a.astype(np.float32) / hi, _chan_axis(img), -1)
    r, g, b = x[..., 0], x[..., 1], x[..., 2]
    maxc = x.max(-1)
    minc = x.min(-1)
    v = maxc
    d = maxc - minc
    s = np.where(maxc > 0, d / np.maximum(maxc, 1e-12), 0.0)
    rc = (maxc - r) / np.maximum(d, 1e-12)
    gc = (maxc - g) / np.maximum(d, 1e-12)
    bc = (maxc - b) / np.maximum(d, 1e-12)
    h = np.where(maxc == r, bc - gc,
                 np.where(maxc == g, 2.0 + rc - bc, 4.0 + gc - rc))
    h = np.where(d == 0, 0.0, (h / 6.0) % 1.0)
    h = (h + hue_factor) % 1.0
    i = np.floor(h * 6.0)
    f = h * 6.0 - i
    p = v * (1.0 - s)
    q = v * (1.0 - s * f)
    t = v * (1.0 - s * (1.0 - f))
    i = i.astype(np.int32) % 6
    conds = [i == k for k in range(6)]
    r2 = np.select(conds, [v, q, p, p, t, v])
    g2 = np.select(conds, [t, v, v, q, p, p])
    b2 = np.select(conds, [p, p, t, v, v, q])
    out = np.stack([r2, g2, b2], axis=-1)
    out = np.moveaxis(out, -1, _chan_axis(img)) * hi
    return _wrap(np.clip(out, 0, hi).astype(dtype), img)


def to_grayscale(img, num_output_channels=1):
    a = _arr(img).astype(np.float32)
    ax = _chan_axis(img)
    g = _grayscale_np(a, ax)
    g = np.expand_dims(g, ax)
    if num_output_channels == 3:
        g = np.repeat(g, 3, axis=ax)
    return _wrap(g.astype(_arr(img).dtype), img)


# -- geometric warps over grid_sample ----------------------------------------

def _warp(img, theta_2x3):
    """Apply an inverse-mapping affine via F.affine_grid + grid_sample."""
    import paddle_tpu.nn.functional as F

    a = _arr(img).astype(np.float32)
    chw = a if _is_chw(img) else np.moveaxis(a, -1, 0)
    x = Tensor(jnp.asarray(chw[None]))
    theta = Tensor(jnp.asarray(theta_2x3[None], jnp.float32))
    grid = F.affine_grid(theta, [1, chw.shape[0], chw.shape[1],
                                 chw.shape[2]], align_corners=False)
    out = F.grid_sample(x, grid, align_corners=False)
    res = np.asarray(out._value)[0]
    if not _is_chw(img):
        res = np.moveaxis(res, 0, -1)
    return _wrap(res.astype(_arr(img).dtype), img)


def _affine_theta(angle, translate, scale, shear, h, w):
    rot = np.deg2rad(angle)
    sx, sy = np.deg2rad(shear[0]), np.deg2rad(shear[1])
    # forward affine (center-anchored), normalized coords
    a = np.cos(rot - sy) / max(np.cos(sy), 1e-9)
    b = -np.cos(rot - sy) * np.tan(sx) / max(np.cos(sy), 1e-9) \
        - np.sin(rot)
    c = np.sin(rot - sy) / max(np.cos(sy), 1e-9)
    d = -np.sin(rot - sy) * np.tan(sx) / max(np.cos(sy), 1e-9) \
        + np.cos(rot)
    m = np.asarray([[a, b, 0.0], [c, d, 0.0]], np.float32) * scale
    m[0, 2] = translate[0] * 2.0 / w
    m[1, 2] = translate[1] * 2.0 / h
    # grid_sample consumes the INVERSE map
    full = np.eye(3, dtype=np.float32)
    full[:2] = m
    inv = np.linalg.inv(full)
    return inv[:2]


def rotate(img, angle, interpolation="nearest", expand=False, center=None,
           fill=0):
    """reference functional.rotate (expand/center subset: center-anchored,
    no canvas expansion — documented deviation; fill is 0)."""
    a = _arr(img)
    h, w = (a.shape[1], a.shape[2]) if _is_chw(img) else a.shape[:2]
    return _warp(img, _affine_theta(-angle, (0, 0), 1.0, (0, 0), h, w))


def affine(img, angle, translate, scale, shear, interpolation="nearest",
           center=None, fill=0):
    """reference functional.affine."""
    if isinstance(shear, numbers.Number):
        shear = (shear, 0.0)
    a = _arr(img)
    h, w = (a.shape[1], a.shape[2]) if _is_chw(img) else a.shape[:2]
    return _warp(img, _affine_theta(-angle, translate, 1.0 / scale, shear,
                                    h, w))


def perspective(img, startpoints, endpoints, interpolation="nearest",
                fill=0):
    """reference functional.perspective: warp mapping endpoints back to
    startpoints (least-squares homography, applied via a dense grid)."""
    import paddle_tpu.nn.functional as F

    a = _arr(img).astype(np.float32)
    chw = a if _is_chw(img) else np.moveaxis(a, -1, 0)
    h, w = chw.shape[1], chw.shape[2]
    # solve homography endpoints -> startpoints (inverse map)
    src = np.asarray(endpoints, np.float32)
    dst = np.asarray(startpoints, np.float32)
    A = []
    for (x, y), (u, v) in zip(src, dst):
        A.append([x, y, 1, 0, 0, 0, -u * x, -u * y])
        A.append([0, 0, 0, x, y, 1, -v * x, -v * y])
    A = np.asarray(A, np.float32)
    rhs = dst.reshape(-1)
    coef, *_ = np.linalg.lstsq(A, rhs, rcond=None)
    H = np.append(coef, 1.0).reshape(3, 3)
    ys, xs = np.meshgrid(np.arange(h), np.arange(w), indexing="ij")
    ones = np.ones_like(xs)
    pts = np.stack([xs, ys, ones], axis=-1).astype(np.float32)
    mapped = pts @ H.T
    mx = mapped[..., 0] / np.maximum(mapped[..., 2], 1e-9)
    my = mapped[..., 1] / np.maximum(mapped[..., 2], 1e-9)
    # normalize to [-1, 1] for grid_sample
    gx = mx / (w - 1) * 2.0 - 1.0
    gy = my / (h - 1) * 2.0 - 1.0
    grid = Tensor(jnp.asarray(
        np.stack([gx, gy], axis=-1)[None], jnp.float32))
    out = F.grid_sample(Tensor(jnp.asarray(chw[None])), grid,
                        align_corners=True)
    res = np.asarray(out._value)[0]
    if not _is_chw(img):
        res = np.moveaxis(res, 0, -1)
    return _wrap(res.astype(_arr(img).dtype), img)


def erase(img, i, j, h, w, v, inplace=False):
    """reference functional.erase: fill box [i:i+h, j:j+w] with v."""
    a = _arr(img).copy()
    if _is_chw(img):
        a[:, i:i + h, j:j + w] = v
    else:
        a[i:i + h, j:j + w] = v
    return _wrap(a, img)


# -- transform classes -------------------------------------------------------

class BaseTransform:
    """reference transforms.BaseTransform: keys-aware callable base."""

    def __init__(self, keys=None):
        self.keys = keys

    def _apply_image(self, img):
        raise NotImplementedError

    def __call__(self, inputs):
        if self.keys is None or not isinstance(inputs, (list, tuple)):
            return self._apply_image(inputs)
        out = []
        for key, data in zip(self.keys, inputs):
            fn = getattr(self, "_apply_" + key, None)
            out.append(fn(data) if fn is not None else data)
        return tuple(out)


class Transpose(BaseTransform):
    """HWC <-> CHW (reference transforms.Transpose)."""

    def __init__(self, order=(2, 0, 1), keys=None):
        super().__init__(keys)
        self.order = order

    def _apply_image(self, img):
        return _wrap(np.transpose(_arr(img), self.order), img)


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return img
        f = _pyrandom.uniform(max(0, 1 - self.value), 1 + self.value)
        return adjust_brightness(img, f)


class ContrastTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        if value < 0:
            raise ValueError("contrast value must be non-negative")
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return img
        f = _pyrandom.uniform(max(0, 1 - self.value), 1 + self.value)
        return adjust_contrast(img, f)


class SaturationTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return img
        f = _pyrandom.uniform(max(0, 1 - self.value), 1 + self.value)
        return adjust_saturation(img, f)


class HueTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        if not 0 <= value <= 0.5:
            raise ValueError("hue value must be in [0, 0.5]")
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return img
        return adjust_hue(img, _pyrandom.uniform(-self.value, self.value))


class ColorJitter(BaseTransform):
    """reference transforms.ColorJitter: random order of the four
    component jitters."""

    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0,
                 keys=None):
        super().__init__(keys)
        self.transforms = [
            BrightnessTransform(brightness),
            ContrastTransform(contrast),
            SaturationTransform(saturation),
            HueTransform(hue),
        ]

    def _apply_image(self, img):
        order = list(range(4))
        _pyrandom.shuffle(order)
        for i in order:
            img = self.transforms[i]._apply_image(img)
        return img


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1, keys=None):
        super().__init__(keys)
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        return to_grayscale(img, self.num_output_channels)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant",
                 keys=None):
        super().__init__(keys)
        self._args = (padding, fill, padding_mode)

    def _apply_image(self, img):
        return pad(img, *self._args)


class RandomRotation(BaseTransform):
    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0, keys=None):
        super().__init__(keys)
        if isinstance(degrees, numbers.Number):
            degrees = (-abs(degrees), abs(degrees))
        self.degrees = degrees
        self._kw = dict(interpolation=interpolation, expand=expand,
                        center=center, fill=fill)

    def _apply_image(self, img):
        angle = _pyrandom.uniform(*self.degrees)
        return rotate(img, angle, **self._kw)


class RandomAffine(BaseTransform):
    def __init__(self, degrees, translate=None, scale=None, shear=None,
                 interpolation="nearest", fill=0, center=None, keys=None):
        super().__init__(keys)
        if isinstance(degrees, numbers.Number):
            degrees = (-abs(degrees), abs(degrees))
        self.degrees = degrees
        self.translate = translate
        self.scale = scale
        self.shear = shear

    def _apply_image(self, img):
        a = _arr(img)
        h, w = (a.shape[1], a.shape[2]) if _is_chw(img) else a.shape[:2]
        angle = _pyrandom.uniform(*self.degrees)
        tx = ty = 0.0
        if self.translate is not None:
            tx = _pyrandom.uniform(-self.translate[0], self.translate[0]) * w
            ty = _pyrandom.uniform(-self.translate[1], self.translate[1]) * h
        sc = _pyrandom.uniform(*self.scale) if self.scale else 1.0
        sh = (_pyrandom.uniform(-self.shear[0], self.shear[0]), 0.0) \
            if self.shear else (0.0, 0.0)
        return affine(img, angle, (tx, ty), sc, sh)


class RandomPerspective(BaseTransform):
    def __init__(self, prob=0.5, distortion_scale=0.5,
                 interpolation="nearest", fill=0, keys=None):
        super().__init__(keys)
        self.prob = prob
        self.distortion_scale = distortion_scale

    def _apply_image(self, img):
        if _pyrandom.random() >= self.prob:
            return img
        a = _arr(img)
        h, w = (a.shape[1], a.shape[2]) if _is_chw(img) else a.shape[:2]
        d = self.distortion_scale
        dx, dy = int(d * w / 2), int(d * h / 2)

        def jit(x, y, sx, sy):
            return (x + _pyrandom.randint(0, max(dx, 1)) * sx,
                    y + _pyrandom.randint(0, max(dy, 1)) * sy)

        start = [(0, 0), (w - 1, 0), (w - 1, h - 1), (0, h - 1)]
        end = [jit(0, 0, 1, 1), jit(w - 1, 0, -1, 1),
               jit(w - 1, h - 1, -1, -1), jit(0, h - 1, 1, -1)]
        return perspective(img, start, end)


class RandomErasing(BaseTransform):
    """reference transforms.RandomErasing (the cutout regularizer)."""

    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3),
                 value=0, inplace=False, keys=None):
        super().__init__(keys)
        self.prob = prob
        self.scale = scale
        self.ratio = ratio
        self.value = value

    def _apply_image(self, img):
        if _pyrandom.random() >= self.prob:
            return img
        a = _arr(img)
        h, w = (a.shape[1], a.shape[2]) if _is_chw(img) else a.shape[:2]
        area = h * w
        for _ in range(10):
            target = _pyrandom.uniform(*self.scale) * area
            ar = _pyrandom.uniform(*self.ratio)
            eh = int(round(np.sqrt(target * ar)))
            ew = int(round(np.sqrt(target / ar)))
            if eh < h and ew < w:
                i = _pyrandom.randint(0, h - eh)
                j = _pyrandom.randint(0, w - ew)
                return erase(img, i, j, eh, ew, self.value)
        return img


class RandomResizedCrop(BaseTransform):
    """reference transforms.RandomResizedCrop: random area/aspect crop
    then resize."""

    def __init__(self, size, scale=(0.08, 1.0), ratio=(3. / 4, 4. / 3),
                 interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, numbers.Number) \
            else tuple(size)
        self.scale = scale
        self.ratio = ratio
        self.interpolation = interpolation

    def _apply_image(self, img):
        from .transforms import resize as _resize

        a = _arr(img)
        h, w = (a.shape[1], a.shape[2]) if _is_chw(img) else a.shape[:2]
        area = h * w
        for _ in range(10):
            target = _pyrandom.uniform(*self.scale) * area
            log_ratio = (np.log(self.ratio[0]), np.log(self.ratio[1]))
            ar = np.exp(_pyrandom.uniform(*log_ratio))
            ch = int(round(np.sqrt(target / ar)))
            cw = int(round(np.sqrt(target * ar)))
            if 0 < ch <= h and 0 < cw <= w:
                i = _pyrandom.randint(0, h - ch)
                j = _pyrandom.randint(0, w - cw)
                return _resize(crop(img, i, j, ch, cw), self.size,
                               self.interpolation)
        return _resize(center_crop(img, min(h, w)), self.size,
                       self.interpolation)
