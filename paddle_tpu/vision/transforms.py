"""Vision transforms (reference python/paddle/vision/transforms/) — numpy
host-side preprocessing feeding the DataLoader."""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, img):
        for t in self.transforms:
            img = t(img)
        return img


class ToTensor:
    """HWC uint8 [0,255] -> CHW float32 [0,1]."""

    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def __call__(self, img):
        arr = np.asarray(img)
        if arr.dtype == np.uint8:
            arr = arr.astype(np.float32) / 255.0
        if arr.ndim == 2:
            arr = arr[:, :, None]
        if self.data_format == "CHW":
            arr = np.transpose(arr, (2, 0, 1))
        return arr.astype(np.float32)


class Normalize:
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.data_format = data_format

    def __call__(self, img):
        arr = np.asarray(img, np.float32)
        shape = ([-1, 1, 1] if self.data_format == "CHW" else [1, 1, -1])
        return (arr - self.mean.reshape(shape)) / self.std.reshape(shape)


class Resize:
    def __init__(self, size, interpolation="bilinear"):
        self.size = size if isinstance(size, (list, tuple)) else (size, size)

    def __call__(self, img):
        arr = np.asarray(img)
        import jax

        import jax.numpy as jnp

        chw = arr.ndim == 3 and arr.shape[0] in (1, 3)
        h_ax, w_ax = (1, 2) if chw else (0, 1)
        shape = list(arr.shape)
        shape[h_ax], shape[w_ax] = self.size[0], self.size[1]
        out = jax.image.resize(jnp.asarray(arr, jnp.float32), shape,
                               method="linear")
        return np.asarray(out, arr.dtype if arr.dtype != np.uint8
                          else np.float32)


class CenterCrop:
    def __init__(self, size):
        self.size = size if isinstance(size, (list, tuple)) else (size, size)

    def __call__(self, img):
        arr = np.asarray(img)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3)
        h_ax, w_ax = (1, 2) if chw else (0, 1)
        h, w = arr.shape[h_ax], arr.shape[w_ax]
        th, tw = self.size
        i, j = max((h - th) // 2, 0), max((w - tw) // 2, 0)
        sl = [slice(None)] * arr.ndim
        sl[h_ax] = slice(i, i + th)
        sl[w_ax] = slice(j, j + tw)
        return arr[tuple(sl)]


class RandomCrop:
    def __init__(self, size, padding=0):
        self.size = size if isinstance(size, (list, tuple)) else (size, size)
        self.padding = padding

    def __call__(self, img):
        arr = np.asarray(img)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3)
        h_ax, w_ax = (1, 2) if chw else (0, 1)
        if self.padding:
            pads = [(0, 0)] * arr.ndim
            pads[h_ax] = (self.padding, self.padding)
            pads[w_ax] = (self.padding, self.padding)
            arr = np.pad(arr, pads)
        h, w = arr.shape[h_ax], arr.shape[w_ax]
        th, tw = self.size
        i = np.random.randint(0, h - th + 1)
        j = np.random.randint(0, w - tw + 1)
        sl = [slice(None)] * arr.ndim
        sl[h_ax] = slice(i, i + th)
        sl[w_ax] = slice(j, j + tw)
        return arr[tuple(sl)]


class RandomHorizontalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        arr = np.asarray(img)
        if np.random.rand() < self.prob:
            chw = arr.ndim == 3 and arr.shape[0] in (1, 3)
            return arr[..., ::-1] if not chw else arr[:, :, ::-1]
        return arr


class RandomVerticalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        arr = np.asarray(img)
        if np.random.rand() < self.prob:
            chw = arr.ndim == 3 and arr.shape[0] in (1, 3)
            return arr[:, ::-1] if not chw else arr[:, ::-1, :]
        return arr


def to_tensor(img, data_format="CHW"):
    return ToTensor(data_format)(img)


def normalize(img, mean, std, data_format="CHW"):
    return Normalize(mean, std, data_format)(img)


def resize(img, size, interpolation="bilinear"):
    return Resize(size, interpolation)(img)


from .transforms_extras import (  # noqa: F401,E402
    BaseTransform,
    BrightnessTransform,
    ColorJitter,
    ContrastTransform,
    Grayscale,
    HueTransform,
    Pad,
    RandomAffine,
    RandomErasing,
    RandomPerspective,
    RandomResizedCrop,
    RandomRotation,
    SaturationTransform,
    Transpose,
    adjust_brightness,
    adjust_contrast,
    adjust_hue,
    adjust_saturation,
    affine,
    center_crop,
    crop,
    erase,
    hflip,
    pad,
    perspective,
    rotate,
    to_grayscale,
    vflip,
)
