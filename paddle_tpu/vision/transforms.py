"""Vision transforms (reference python/paddle/vision/transforms/) — numpy
host-side preprocessing feeding the DataLoader."""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor


# _looks_chw: THE layout guess, bound at the bottom of this module to
# transforms_extras._is_chw (one copy of the rule): channels-first
# only when dim 0 is channel-like AND dim 2 is not — a (3, W, 3)
# array (e.g. a random crop of height 3 from an HWC image) must read
# as HWC, or a crop→resize chain silently flips layout on ~6% of crop
# draws (seed-dependent; regression-pinned in
# tests/test_vision_incubate_extras.py).


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, img):
        for t in self.transforms:
            img = t(img)
        return img


class ToTensor:
    """HWC uint8 [0,255] -> CHW float32 [0,1]."""

    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def __call__(self, img):
        arr = np.asarray(img)
        if arr.dtype == np.uint8:
            arr = arr.astype(np.float32) / 255.0
        if arr.ndim == 2:
            arr = arr[:, :, None]
        if self.data_format == "CHW":
            arr = np.transpose(arr, (2, 0, 1))
        return arr.astype(np.float32)


class Normalize:
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.data_format = data_format

    def __call__(self, img):
        arr = np.asarray(img, np.float32)
        shape = ([-1, 1, 1] if self.data_format == "CHW" else [1, 1, -1])
        return (arr - self.mean.reshape(shape)) / self.std.reshape(shape)


class Resize:
    def __init__(self, size, interpolation="bilinear"):
        self.size = size if isinstance(size, (list, tuple)) else (size, size)

    def __call__(self, img):
        arr = np.asarray(img)
        import jax

        import jax.numpy as jnp

        chw = _looks_chw(arr)
        h_ax, w_ax = (1, 2) if chw else (0, 1)
        shape = list(arr.shape)
        shape[h_ax], shape[w_ax] = self.size[0], self.size[1]
        out = jax.image.resize(jnp.asarray(arr, jnp.float32), shape,
                               method="linear")
        return np.asarray(out, arr.dtype if arr.dtype != np.uint8
                          else np.float32)


class CenterCrop:
    def __init__(self, size):
        self.size = size if isinstance(size, (list, tuple)) else (size, size)

    def __call__(self, img):
        arr = np.asarray(img)
        chw = _looks_chw(arr)
        h_ax, w_ax = (1, 2) if chw else (0, 1)
        h, w = arr.shape[h_ax], arr.shape[w_ax]
        th, tw = self.size
        i, j = max((h - th) // 2, 0), max((w - tw) // 2, 0)
        sl = [slice(None)] * arr.ndim
        sl[h_ax] = slice(i, i + th)
        sl[w_ax] = slice(j, j + tw)
        return arr[tuple(sl)]


class RandomCrop:
    def __init__(self, size, padding=0):
        self.size = size if isinstance(size, (list, tuple)) else (size, size)
        self.padding = padding

    def __call__(self, img):
        arr = np.asarray(img)
        chw = _looks_chw(arr)
        h_ax, w_ax = (1, 2) if chw else (0, 1)
        if self.padding:
            pads = [(0, 0)] * arr.ndim
            pads[h_ax] = (self.padding, self.padding)
            pads[w_ax] = (self.padding, self.padding)
            arr = np.pad(arr, pads)
        h, w = arr.shape[h_ax], arr.shape[w_ax]
        th, tw = self.size
        i = np.random.randint(0, h - th + 1)
        j = np.random.randint(0, w - tw + 1)
        sl = [slice(None)] * arr.ndim
        sl[h_ax] = slice(i, i + th)
        sl[w_ax] = slice(j, j + tw)
        return arr[tuple(sl)]


class RandomHorizontalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        arr = np.asarray(img)
        if np.random.rand() < self.prob:
            # horizontal = reverse the WIDTH axis: 1 for 2-D/HWC, 2
            # for CHW (`arr[..., ::-1]` on a 3-D HWC array reversed
            # CHANNELS — an RGB->BGR swap with zero flip)
            chw = _looks_chw(arr)
            return arr[:, :, ::-1] if chw else arr[:, ::-1]
        return arr


class RandomVerticalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        arr = np.asarray(img)
        if np.random.rand() < self.prob:
            # vertical = reverse the HEIGHT axis: 0 for 2-D/HWC, 1
            # for CHW (`arr[:, ::-1]` on a 3-D HWC array reversed
            # WIDTH — a horizontal flip masquerading as vertical)
            chw = _looks_chw(arr)
            return arr[:, ::-1, :] if chw else arr[::-1]
        return arr


def to_tensor(img, data_format="CHW"):
    return ToTensor(data_format)(img)


def normalize(img, mean, std, data_format="CHW"):
    return Normalize(mean, std, data_format)(img)


def resize(img, size, interpolation="bilinear"):
    return Resize(size, interpolation)(img)


from .transforms_extras import _is_chw as _looks_chw  # noqa: E402
from .transforms_extras import (  # noqa: F401,E402
    BaseTransform,
    BrightnessTransform,
    ColorJitter,
    ContrastTransform,
    Grayscale,
    HueTransform,
    Pad,
    RandomAffine,
    RandomErasing,
    RandomPerspective,
    RandomResizedCrop,
    RandomRotation,
    SaturationTransform,
    Transpose,
    adjust_brightness,
    adjust_contrast,
    adjust_hue,
    adjust_saturation,
    affine,
    center_crop,
    crop,
    erase,
    hflip,
    pad,
    perspective,
    rotate,
    to_grayscale,
    vflip,
)
