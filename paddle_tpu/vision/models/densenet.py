"""DenseNet (reference python/paddle/vision/models/densenet.py)."""
from __future__ import annotations

import paddle_tpu as paddle
from ... import nn

_CFG = {
    121: (64, 32, [6, 12, 24, 16]),
    161: (96, 48, [6, 12, 36, 24]),
    169: (64, 32, [6, 12, 32, 32]),
    201: (64, 32, [6, 12, 48, 32]),
    264: (64, 32, [6, 12, 64, 48]),
}


class _DenseLayer(nn.Layer):
    def __init__(self, num_input, growth_rate, bn_size, dropout):
        super().__init__()
        self.bn1 = nn.BatchNorm2D(num_input)
        self.conv1 = nn.Conv2D(num_input, bn_size * growth_rate, 1,
                               bias_attr=False)
        self.bn2 = nn.BatchNorm2D(bn_size * growth_rate)
        self.conv2 = nn.Conv2D(bn_size * growth_rate, growth_rate, 3,
                               padding=1, bias_attr=False)
        self.relu = nn.ReLU()
        self.dropout = nn.Dropout(dropout) if dropout else None

    def forward(self, x):
        out = self.conv1(self.relu(self.bn1(x)))
        out = self.conv2(self.relu(self.bn2(out)))
        if self.dropout is not None:
            out = self.dropout(out)
        return paddle.concat([x, out], axis=1)


class _Transition(nn.Layer):
    def __init__(self, num_input, num_output):
        super().__init__()
        self.bn = nn.BatchNorm2D(num_input)
        self.conv = nn.Conv2D(num_input, num_output, 1, bias_attr=False)
        self.relu = nn.ReLU()
        self.pool = nn.AvgPool2D(2, stride=2)

    def forward(self, x):
        return self.pool(self.conv(self.relu(self.bn(x))))


class DenseNet(nn.Layer):
    def __init__(self, layers=121, bn_size=4, dropout=0.0,
                 num_classes=1000, with_pool=True):
        super().__init__()
        assert layers in _CFG, "supported layers: %s" % list(_CFG)
        num_init, growth_rate, blocks = _CFG[layers]
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = nn.Sequential(
            nn.Conv2D(3, num_init, 7, stride=2, padding=3, bias_attr=False),
            nn.BatchNorm2D(num_init), nn.ReLU(),
            nn.MaxPool2D(3, stride=2, padding=1),
        )
        features = []
        ch = num_init
        for i, n in enumerate(blocks):
            for _ in range(n):
                features.append(_DenseLayer(ch, growth_rate, bn_size,
                                            dropout))
                ch += growth_rate
            if i != len(blocks) - 1:
                features.append(_Transition(ch, ch // 2))
                ch //= 2
        features += [nn.BatchNorm2D(ch), nn.ReLU()]
        self.features = nn.Sequential(*features)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.classifier = nn.Linear(ch, num_classes)

    def forward(self, x):
        x = self.features(self.stem(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.classifier(x.flatten(1))
        return x


def _densenet(layers, **kwargs):
    return DenseNet(layers=layers, **kwargs)


def densenet121(pretrained=False, **kwargs):
    return _densenet(121, **kwargs)


def densenet161(pretrained=False, **kwargs):
    return _densenet(161, **kwargs)


def densenet169(pretrained=False, **kwargs):
    return _densenet(169, **kwargs)


def densenet201(pretrained=False, **kwargs):
    return _densenet(201, **kwargs)


def densenet264(pretrained=False, **kwargs):
    return _densenet(264, **kwargs)
