"""MobileNetV1 (reference python/paddle/vision/models/mobilenetv1.py):
13 depthwise-separable blocks. Depthwise convs lower to XLA
feature-group convolutions (VPU-friendly on TPU)."""
from __future__ import annotations

from ... import nn


class _DWSep(nn.Layer):
    def __init__(self, cin, cout, stride):
        super().__init__()
        self.dw = nn.Conv2D(cin, cin, 3, stride=stride, padding=1,
                            groups=cin, bias_attr=False)
        self.bn1 = nn.BatchNorm2D(cin)
        self.pw = nn.Conv2D(cin, cout, 1, bias_attr=False)
        self.bn2 = nn.BatchNorm2D(cout)
        self.relu = nn.ReLU()

    def forward(self, x):
        x = self.relu(self.bn1(self.dw(x)))
        return self.relu(self.bn2(self.pw(x)))


class MobileNetV1(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        s = lambda c: max(int(c * scale), 8)  # noqa: E731
        cfg = [(64, 1), (128, 2), (128, 1), (256, 2), (256, 1), (512, 2),
               (512, 1), (512, 1), (512, 1), (512, 1), (512, 1),
               (1024, 2), (1024, 1)]
        self.stem = nn.Sequential(
            nn.Conv2D(3, s(32), 3, stride=2, padding=1, bias_attr=False),
            nn.BatchNorm2D(s(32)), nn.ReLU())
        blocks = []
        cin = s(32)
        for cout, stride in cfg:
            blocks.append(_DWSep(cin, s(cout), stride))
            cin = s(cout)
        self.blocks = nn.Sequential(*blocks)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = nn.Linear(cin, num_classes)

    def forward(self, x):
        x = self.blocks(self.stem(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(x.flatten(1))
        return x


def mobilenet_v1(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV1(scale=scale, **kwargs)
