"""ShuffleNetV2 (reference python/paddle/vision/models/shufflenetv2.py).
channel_shuffle is a reshape/transpose pair — free on TPU, XLA folds it
into the surrounding convolution layouts."""
from __future__ import annotations

import paddle_tpu as paddle
from ... import nn

_STAGE_OUT = {
    "0.25": [24, 24, 48, 96, 512],
    "0.33": [24, 32, 64, 128, 512],
    "0.5": [24, 48, 96, 192, 1024],
    "1.0": [24, 116, 232, 464, 1024],
    "1.5": [24, 176, 352, 704, 1024],
    "2.0": [24, 244, 488, 976, 2048],
}
_STAGE_REPEATS = [4, 8, 4]


def channel_shuffle(x, groups):
    b, c, h, w = x.shape
    x = paddle.reshape(x, [b, groups, c // groups, h, w])
    x = paddle.transpose(x, [0, 2, 1, 3, 4])
    return paddle.reshape(x, [b, c, h, w])


def _act(name):
    return nn.Swish() if name == "swish" else nn.ReLU()


class InvertedResidual(nn.Layer):
    def __init__(self, inp, oup, stride, act="relu"):
        super().__init__()
        self.stride = stride
        branch = oup // 2
        if stride == 1:
            self.branch2 = nn.Sequential(
                nn.Conv2D(inp // 2, branch, 1, bias_attr=False),
                nn.BatchNorm2D(branch), _act(act),
                nn.Conv2D(branch, branch, 3, stride=1, padding=1,
                          groups=branch, bias_attr=False),
                nn.BatchNorm2D(branch),
                nn.Conv2D(branch, branch, 1, bias_attr=False),
                nn.BatchNorm2D(branch), _act(act),
            )
            self.branch1 = None
        else:
            self.branch1 = nn.Sequential(
                nn.Conv2D(inp, inp, 3, stride=stride, padding=1, groups=inp,
                          bias_attr=False),
                nn.BatchNorm2D(inp),
                nn.Conv2D(inp, branch, 1, bias_attr=False),
                nn.BatchNorm2D(branch), _act(act),
            )
            self.branch2 = nn.Sequential(
                nn.Conv2D(inp, branch, 1, bias_attr=False),
                nn.BatchNorm2D(branch), _act(act),
                nn.Conv2D(branch, branch, 3, stride=stride, padding=1,
                          groups=branch, bias_attr=False),
                nn.BatchNorm2D(branch),
                nn.Conv2D(branch, branch, 1, bias_attr=False),
                nn.BatchNorm2D(branch), _act(act),
            )

    def forward(self, x):
        if self.branch1 is None:
            c = x.shape[1] // 2
            x1, x2 = x[:, :c], x[:, c:]
            out = paddle.concat([x1, self.branch2(x2)], axis=1)
        else:
            out = paddle.concat([self.branch1(x), self.branch2(x)], axis=1)
        return channel_shuffle(out, 2)


class ShuffleNetV2(nn.Layer):
    def __init__(self, scale=1.0, act="relu", num_classes=1000,
                 with_pool=True):
        super().__init__()
        key = {0.25: "0.25", 0.33: "0.33", 0.5: "0.5", 1.0: "1.0",
               1.5: "1.5", 2.0: "2.0"}.get(scale)
        if key is None:
            raise ValueError("unsupported scale %r" % scale)
        out_ch = _STAGE_OUT[key]
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.conv1 = nn.Sequential(
            nn.Conv2D(3, out_ch[0], 3, stride=2, padding=1,
                      bias_attr=False),
            nn.BatchNorm2D(out_ch[0]), _act(act),
        )
        self.maxpool = nn.MaxPool2D(3, stride=2, padding=1)
        stages = []
        inp = out_ch[0]
        for i, reps in enumerate(_STAGE_REPEATS):
            oup = out_ch[i + 1]
            seq = [InvertedResidual(inp, oup, 2, act)]
            for _ in range(reps - 1):
                seq.append(InvertedResidual(oup, oup, 1, act))
            stages.append(nn.Sequential(*seq))
            inp = oup
        self.stages = nn.LayerList(stages)
        self.conv_last = nn.Sequential(
            nn.Conv2D(inp, out_ch[-1], 1, bias_attr=False),
            nn.BatchNorm2D(out_ch[-1]), _act(act),
        )
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = nn.Linear(out_ch[-1], num_classes)

    def forward(self, x):
        x = self.maxpool(self.conv1(x))
        for stage in self.stages:
            x = stage(x)
        x = self.conv_last(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(x.flatten(1))
        return x


def _shufflenet(scale, act="relu", **kwargs):
    return ShuffleNetV2(scale=scale, act=act, **kwargs)


def shufflenet_v2_x0_25(pretrained=False, **kwargs):
    return _shufflenet(0.25, **kwargs)


def shufflenet_v2_x0_33(pretrained=False, **kwargs):
    return _shufflenet(0.33, **kwargs)


def shufflenet_v2_x0_5(pretrained=False, **kwargs):
    return _shufflenet(0.5, **kwargs)


def shufflenet_v2_x1_0(pretrained=False, **kwargs):
    return _shufflenet(1.0, **kwargs)


def shufflenet_v2_x1_5(pretrained=False, **kwargs):
    return _shufflenet(1.5, **kwargs)


def shufflenet_v2_x2_0(pretrained=False, **kwargs):
    return _shufflenet(2.0, **kwargs)


def shufflenet_v2_swish(pretrained=False, **kwargs):
    return _shufflenet(1.0, act="swish", **kwargs)
