"""Inception v3 (reference python/paddle/vision/models/inceptionv3.py).
Standard 299x299 topology: stem -> 3x InceptionA -> reduction ->
4x InceptionB(7x7 factorized) -> reduction -> 2x InceptionC."""
from __future__ import annotations

import paddle_tpu as paddle
from ... import nn


class ConvBN(nn.Layer):
    def __init__(self, cin, cout, k, stride=1, padding=0):
        super().__init__()
        self.conv = nn.Conv2D(cin, cout, k, stride=stride, padding=padding,
                              bias_attr=False)
        self.bn = nn.BatchNorm2D(cout)
        self.relu = nn.ReLU()

    def forward(self, x):
        return self.relu(self.bn(self.conv(x)))


class InceptionA(nn.Layer):
    def __init__(self, cin, pool_features):
        super().__init__()
        self.b1 = ConvBN(cin, 64, 1)
        self.b5 = nn.Sequential(ConvBN(cin, 48, 1),
                                ConvBN(48, 64, 5, padding=2))
        self.b3 = nn.Sequential(ConvBN(cin, 64, 1),
                                ConvBN(64, 96, 3, padding=1),
                                ConvBN(96, 96, 3, padding=1))
        self.bp = nn.Sequential(nn.AvgPool2D(3, stride=1, padding=1),
                                ConvBN(cin, pool_features, 1))

    def forward(self, x):
        return paddle.concat([self.b1(x), self.b5(x), self.b3(x),
                              self.bp(x)], axis=1)


class ReductionA(nn.Layer):
    def __init__(self, cin):
        super().__init__()
        self.b3 = ConvBN(cin, 384, 3, stride=2)
        self.b3d = nn.Sequential(ConvBN(cin, 64, 1),
                                 ConvBN(64, 96, 3, padding=1),
                                 ConvBN(96, 96, 3, stride=2))
        self.pool = nn.MaxPool2D(3, stride=2)

    def forward(self, x):
        return paddle.concat([self.b3(x), self.b3d(x), self.pool(x)],
                             axis=1)


class InceptionB(nn.Layer):
    """7x7-factorized block."""

    def __init__(self, cin, c7):
        super().__init__()
        self.b1 = ConvBN(cin, 192, 1)
        self.b7 = nn.Sequential(
            ConvBN(cin, c7, 1),
            ConvBN(c7, c7, (1, 7), padding=(0, 3)),
            ConvBN(c7, 192, (7, 1), padding=(3, 0)))
        self.b7d = nn.Sequential(
            ConvBN(cin, c7, 1),
            ConvBN(c7, c7, (7, 1), padding=(3, 0)),
            ConvBN(c7, c7, (1, 7), padding=(0, 3)),
            ConvBN(c7, c7, (7, 1), padding=(3, 0)),
            ConvBN(c7, 192, (1, 7), padding=(0, 3)))
        self.bp = nn.Sequential(nn.AvgPool2D(3, stride=1, padding=1),
                                ConvBN(cin, 192, 1))

    def forward(self, x):
        return paddle.concat([self.b1(x), self.b7(x), self.b7d(x),
                              self.bp(x)], axis=1)


class ReductionB(nn.Layer):
    def __init__(self, cin):
        super().__init__()
        self.b3 = nn.Sequential(ConvBN(cin, 192, 1),
                                ConvBN(192, 320, 3, stride=2))
        self.b7 = nn.Sequential(
            ConvBN(cin, 192, 1),
            ConvBN(192, 192, (1, 7), padding=(0, 3)),
            ConvBN(192, 192, (7, 1), padding=(3, 0)),
            ConvBN(192, 192, 3, stride=2))
        self.pool = nn.MaxPool2D(3, stride=2)

    def forward(self, x):
        return paddle.concat([self.b3(x), self.b7(x), self.pool(x)],
                             axis=1)


class InceptionC(nn.Layer):
    def __init__(self, cin):
        super().__init__()
        self.b1 = ConvBN(cin, 320, 1)
        self.b3_stem = ConvBN(cin, 384, 1)
        self.b3_a = ConvBN(384, 384, (1, 3), padding=(0, 1))
        self.b3_b = ConvBN(384, 384, (3, 1), padding=(1, 0))
        self.b3d_stem = nn.Sequential(ConvBN(cin, 448, 1),
                                      ConvBN(448, 384, 3, padding=1))
        self.b3d_a = ConvBN(384, 384, (1, 3), padding=(0, 1))
        self.b3d_b = ConvBN(384, 384, (3, 1), padding=(1, 0))
        self.bp = nn.Sequential(nn.AvgPool2D(3, stride=1, padding=1),
                                ConvBN(cin, 192, 1))

    def forward(self, x):
        s = self.b3_stem(x)
        d = self.b3d_stem(x)
        return paddle.concat(
            [self.b1(x), self.b3_a(s), self.b3_b(s),
             self.b3d_a(d), self.b3d_b(d), self.bp(x)], axis=1)


class InceptionV3(nn.Layer):
    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = nn.Sequential(
            ConvBN(3, 32, 3, stride=2), ConvBN(32, 32, 3),
            ConvBN(32, 64, 3, padding=1), nn.MaxPool2D(3, stride=2),
            ConvBN(64, 80, 1), ConvBN(80, 192, 3),
            nn.MaxPool2D(3, stride=2),
        )
        self.blocks = nn.Sequential(
            InceptionA(192, 32), InceptionA(256, 64), InceptionA(288, 64),
            ReductionA(288),
            InceptionB(768, 128), InceptionB(768, 160),
            InceptionB(768, 160), InceptionB(768, 192),
            ReductionB(768),
            InceptionC(1280), InceptionC(2048),
        )
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.drop = nn.Dropout(0.2)
            self.fc = nn.Linear(2048, num_classes)

    def forward(self, x):
        x = self.blocks(self.stem(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(self.drop(x.flatten(1)))
        return x


def inception_v3(pretrained=False, **kwargs):
    return InceptionV3(**kwargs)
