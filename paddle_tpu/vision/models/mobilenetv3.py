"""MobileNetV3 (reference python/paddle/vision/models/mobilenetv3.py):
inverted residuals with squeeze-excitation and hardswish."""
from __future__ import annotations

from ... import nn

# (kernel, exp, out, use_se, act, stride)
_LARGE = [
    (3, 16, 16, False, "relu", 1),
    (3, 64, 24, False, "relu", 2),
    (3, 72, 24, False, "relu", 1),
    (5, 72, 40, True, "relu", 2),
    (5, 120, 40, True, "relu", 1),
    (5, 120, 40, True, "relu", 1),
    (3, 240, 80, False, "hardswish", 2),
    (3, 200, 80, False, "hardswish", 1),
    (3, 184, 80, False, "hardswish", 1),
    (3, 184, 80, False, "hardswish", 1),
    (3, 480, 112, True, "hardswish", 1),
    (3, 672, 112, True, "hardswish", 1),
    (5, 672, 160, True, "hardswish", 2),
    (5, 960, 160, True, "hardswish", 1),
    (5, 960, 160, True, "hardswish", 1),
]
_SMALL = [
    (3, 16, 16, True, "relu", 2),
    (3, 72, 24, False, "relu", 2),
    (3, 88, 24, False, "relu", 1),
    (5, 96, 40, True, "hardswish", 2),
    (5, 240, 40, True, "hardswish", 1),
    (5, 240, 40, True, "hardswish", 1),
    (5, 120, 48, True, "hardswish", 1),
    (5, 144, 48, True, "hardswish", 1),
    (5, 288, 96, True, "hardswish", 2),
    (5, 576, 96, True, "hardswish", 1),
    (5, 576, 96, True, "hardswish", 1),
]


def _make_divisible(v, divisor=8):
    new_v = max(divisor, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


def _act(name):
    return nn.Hardswish() if name == "hardswish" else nn.ReLU()


class _SE(nn.Layer):
    def __init__(self, ch):
        super().__init__()
        mid = _make_divisible(ch // 4)
        self.pool = nn.AdaptiveAvgPool2D((1, 1))
        self.fc1 = nn.Conv2D(ch, mid, 1)
        self.relu = nn.ReLU()
        self.fc2 = nn.Conv2D(mid, ch, 1)
        self.hsig = nn.Hardsigmoid()

    def forward(self, x):
        s = self.hsig(self.fc2(self.relu(self.fc1(self.pool(x)))))
        return x * s


class _InvRes(nn.Layer):
    def __init__(self, cin, k, exp, cout, use_se, act, stride):
        super().__init__()
        self.use_res = stride == 1 and cin == cout
        seq = []
        if exp != cin:
            seq += [nn.Conv2D(cin, exp, 1, bias_attr=False),
                    nn.BatchNorm2D(exp), _act(act)]
        seq += [nn.Conv2D(exp, exp, k, stride=stride, padding=k // 2,
                          groups=exp, bias_attr=False),
                nn.BatchNorm2D(exp), _act(act)]
        if use_se:
            seq.append(_SE(exp))
        seq += [nn.Conv2D(exp, cout, 1, bias_attr=False),
                nn.BatchNorm2D(cout)]
        self.block = nn.Sequential(*seq)

    def forward(self, x):
        out = self.block(x)
        return x + out if self.use_res else out


class MobileNetV3(nn.Layer):
    def __init__(self, cfg, last_ch, scale=1.0, num_classes=1000,
                 with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        sc = lambda c: _make_divisible(c * scale)  # noqa: E731
        self.stem = nn.Sequential(
            nn.Conv2D(3, sc(16), 3, stride=2, padding=1, bias_attr=False),
            nn.BatchNorm2D(sc(16)), nn.Hardswish())
        blocks = []
        cin = sc(16)
        for k, exp, cout, use_se, act, stride in cfg:
            blocks.append(_InvRes(cin, k, sc(exp), sc(cout), use_se, act,
                                  stride))
            cin = sc(cout)
        last_conv = sc(cfg[-1][1])
        blocks += [nn.Conv2D(cin, last_conv, 1, bias_attr=False),
                   nn.BatchNorm2D(last_conv), nn.Hardswish()]
        self.blocks = nn.Sequential(*blocks)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            # head width scales too (reference mobilenetv3.py:319,394
            # last_channel = _make_divisible(scale * {1280,1024}))
            last_channel = sc(last_ch)
            self.classifier = nn.Sequential(
                nn.Linear(last_conv, last_channel), nn.Hardswish(),
                nn.Dropout(0.2), nn.Linear(last_channel, num_classes))

    def forward(self, x):
        x = self.blocks(self.stem(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.classifier(x.flatten(1))
        return x


class MobileNetV3Large(MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_LARGE, 1280, scale, num_classes, with_pool)


class MobileNetV3Small(MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_SMALL, 1024, scale, num_classes, with_pool)


def mobilenet_v3_large(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV3Large(scale=scale, **kwargs)


def mobilenet_v3_small(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV3Small(scale=scale, **kwargs)
