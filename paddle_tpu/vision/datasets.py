"""Vision datasets (reference python/paddle/vision/datasets/).

Zero-egress environment: loaders read local files when present
(MNIST idx / CIFAR pickle formats identical to the reference's), and every
dataset offers `synthetic=True` generating deterministic fake data with the
right shapes — the pattern the reference tests use for CI without data."""
from __future__ import annotations

import gzip
import os
import pickle
import struct

import numpy as np

from ..io import Dataset


class MNIST(Dataset):
    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=False, backend=None,
                 synthetic=None, size=1024):
        self.transform = transform
        self.mode = mode
        if synthetic is None:
            synthetic = image_path is None or not os.path.exists(image_path)
        if synthetic:
            rng = np.random.RandomState(0 if mode == "train" else 1)
            self.images = (rng.rand(size, 28, 28) * 255).astype(np.uint8)
            self.labels = rng.randint(0, 10, size).astype(np.int64)
        else:
            self.images = self._read_images(image_path)
            self.labels = self._read_labels(label_path)

    @staticmethod
    def _read_images(path):
        opener = gzip.open if path.endswith(".gz") else open
        with opener(path, "rb") as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            data = np.frombuffer(f.read(), dtype=np.uint8)
            return data.reshape(n, rows, cols)

    @staticmethod
    def _read_labels(path):
        opener = gzip.open if path.endswith(".gz") else open
        with opener(path, "rb") as f:
            magic, n = struct.unpack(">II", f.read(8))
            return np.frombuffer(f.read(), dtype=np.uint8).astype(np.int64)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        else:
            img = (img.astype(np.float32) / 255.0)[None]
        return img, self.labels[idx]

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    pass


class Cifar10(Dataset):
    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False, backend=None, synthetic=None, size=1024):
        self.transform = transform
        if synthetic is None:
            synthetic = data_file is None or not os.path.exists(data_file)
        if synthetic:
            rng = np.random.RandomState(0 if mode == "train" else 1)
            self.images = (rng.rand(size, 3, 32, 32) * 255).astype(np.uint8)
            self.labels = rng.randint(0, self._num_classes(), size).astype(
                np.int64)
        else:
            with open(data_file, "rb") as f:
                d = pickle.load(f, encoding="bytes")
            self.images = np.asarray(d[b"data"]).reshape(-1, 3, 32, 32)
            key = b"labels" if b"labels" in d else b"fine_labels"
            self.labels = np.asarray(d[key], np.int64)

    @staticmethod
    def _num_classes():
        return 10

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        else:
            img = img.astype(np.float32) / 255.0
        return img, self.labels[idx]

    def __len__(self):
        return len(self.images)


class Cifar100(Cifar10):
    @staticmethod
    def _num_classes():
        return 100


class ImageFolder(Dataset):
    def __init__(self, root, loader=None, transform=None):
        self.root = root
        self.transform = transform
        self.samples = []
        if os.path.isdir(root):
            classes = sorted(
                d for d in os.listdir(root)
                if os.path.isdir(os.path.join(root, d)))
            for ci, c in enumerate(classes):
                cdir = os.path.join(root, c)
                for fn in sorted(os.listdir(cdir)):
                    self.samples.append((os.path.join(cdir, fn), ci))

    def __getitem__(self, idx):
        path, label = self.samples[idx]
        arr = np.load(path) if path.endswith(".npy") else \
            self._load_image(path)
        if self.transform:
            arr = self.transform(arr)
        return arr, label

    @staticmethod
    def _load_image(path):
        raise RuntimeError(
            "image decoding requires PIL; store .npy arrays or pass a "
            "custom loader")

    def __len__(self):
        return len(self.samples)


class DatasetFolder(Dataset):
    """Directory-per-class dataset (reference datasets/folder.py
    DatasetFolder): root/<class_x>/xxx.ext. Default loader reads .npy
    arrays (no PIL in this environment); pass `loader` for other
    formats (e.g. vision.ops.read_file + decode_jpeg)."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        self.loader = loader or (lambda p: np.load(p))
        exts = tuple(extensions) if extensions is not None else (".npy",)
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        if not classes:
            raise RuntimeError("no class folders under %s" % root)
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            cdir = os.path.join(root, c)
            for fname in sorted(os.listdir(cdir)):
                path = os.path.join(cdir, fname)
                ok = (is_valid_file(path) if is_valid_file is not None
                      else fname.lower().endswith(exts))
                if ok:
                    self.samples.append((path, self.class_to_idx[c]))
        if not self.samples:
            raise RuntimeError(
                "no valid files under %s (extensions=%s)" % (root, exts))

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        img = self.loader(path)
        if self.transform is not None:
            img = self.transform(img)
        return img, target

    def __len__(self):
        return len(self.samples)


class Flowers(Dataset):
    """Oxford-102 flowers (reference datasets/flowers.py). Local files
    via data_file or deterministic synthetic fallback with the real
    schema (same convention as Cifar10 above)."""

    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None, download=False,
                 backend=None, synthetic=None, size=256):
        self.transform = transform
        if synthetic is None:
            synthetic = data_file is None or not os.path.exists(data_file)
        if not synthetic:
            # npz with 'images' [N,3,H,W] uint8 + 'labels' [N] int
            # (convert the original .mat offline; scipy isn't bundled)
            blob = np.load(data_file)
            self.images = np.asarray(blob["images"])
            self.labels = np.asarray(blob["labels"]).astype(np.int64)
        else:
            rng = np.random.RandomState(0 if mode == "train" else 1)
            self.images = (rng.rand(size, 3, 64, 64) * 255) \
                .astype(np.uint8)
            self.labels = rng.randint(0, 102, (size,)).astype(np.int64)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.images)


class VOC2012(Dataset):
    """Pascal VOC 2012 segmentation (reference datasets/voc2012.py):
    (image, seg-mask) pairs; synthetic fallback keeps the schema."""

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False, backend=None, synthetic=None, size=128):
        self.transform = transform
        if synthetic is None:
            synthetic = data_file is None or not os.path.exists(data_file)
        if not synthetic:
            # npz with 'images' [N,3,H,W] uint8 + 'masks' [N,H,W] int
            # (extract the original tar offline)
            blob = np.load(data_file)
            self.images = np.asarray(blob["images"])
            self.masks = np.asarray(blob["masks"]).astype(np.int64)
        else:
            rng = np.random.RandomState(0 if mode == "train" else 1)
            self.images = (rng.rand(size, 3, 64, 64) * 255) \
                .astype(np.uint8)
            self.masks = rng.randint(0, 21, (size, 64, 64)) \
                .astype(np.int64)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.masks[idx]

    def __len__(self):
        return len(self.images)
