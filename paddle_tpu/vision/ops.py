"""Vision ops (reference python/paddle/vision/ops.py: roi_align, nms,
deform_conv, box ops)."""
from __future__ import annotations

import math

import numpy as np

import jax
import jax.numpy as jnp

from ..core.dispatch import primitive
from ..core.tensor import Tensor

_A = jnp.asarray


@primitive
def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True):
    """RoIAlign via bilinear gather (reference phi/kernels/roi_align_kernel).
    x: [N,C,H,W]; boxes: [R,4] in (x1,y1,x2,y2)."""
    x = _A(x)
    boxes = _A(boxes)
    if isinstance(output_size, int):
        oh = ow = output_size
    else:
        oh, ow = output_size
    N, C, H, W = x.shape
    R = boxes.shape[0]
    offset = 0.5 if aligned else 0.0
    # assume single image (N==1) or boxes_num mapping handled upstream
    img_idx = jnp.zeros((R,), jnp.int32)

    x1 = boxes[:, 0] * spatial_scale - offset
    y1 = boxes[:, 1] * spatial_scale - offset
    x2 = boxes[:, 2] * spatial_scale - offset
    y2 = boxes[:, 3] * spatial_scale - offset
    rw = jnp.maximum(x2 - x1, 1e-3)
    rh = jnp.maximum(y2 - y1, 1e-3)
    bin_w = rw / ow
    bin_h = rh / oh

    iy = (jnp.arange(oh) + 0.5)
    ix = (jnp.arange(ow) + 0.5)
    cy = y1[:, None] + iy[None, :] * bin_h[:, None]  # [R, oh]
    cx = x1[:, None] + ix[None, :] * bin_w[:, None]  # [R, ow]

    def bilinear(img, yy, xx):
        y0 = jnp.clip(jnp.floor(yy).astype(jnp.int32), 0, H - 1)
        x0 = jnp.clip(jnp.floor(xx).astype(jnp.int32), 0, W - 1)
        y1_ = jnp.clip(y0 + 1, 0, H - 1)
        x1_ = jnp.clip(x0 + 1, 0, W - 1)
        wy = yy - y0
        wx = xx - x0
        v00 = img[:, y0, :][:, :, x0]
        v01 = img[:, y0, :][:, :, x1_]
        v10 = img[:, y1_, :][:, :, x0]
        v11 = img[:, y1_, :][:, :, x1_]
        return (v00 * (1 - wy)[None, :, None] * (1 - wx)[None, None, :]
                + v01 * (1 - wy)[None, :, None] * wx[None, None, :]
                + v10 * wy[None, :, None] * (1 - wx)[None, None, :]
                + v11 * wy[None, :, None] * wx[None, None, :])

    def per_roi(r):
        img = x[img_idx[r]]
        return bilinear(img, cy[r], cx[r])  # [C, oh, ow]

    out = jax.vmap(per_roi)(jnp.arange(R))
    return out


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """Host-side NMS (data-dependent output count — same reason the
    reference runs it as a CPU/custom op for dynamic shapes)."""
    b = np.asarray(boxes.numpy() if isinstance(boxes, Tensor) else boxes)
    s = (np.asarray(scores.numpy() if isinstance(scores, Tensor) else scores)
         if scores is not None else np.arange(len(b))[::-1].astype(np.float32))
    order = np.argsort(-s)
    keep = []
    while order.size > 0:
        i = order[0]
        keep.append(i)
        if order.size == 1:
            break
        xx1 = np.maximum(b[i, 0], b[order[1:], 0])
        yy1 = np.maximum(b[i, 1], b[order[1:], 1])
        xx2 = np.minimum(b[i, 2], b[order[1:], 2])
        yy2 = np.minimum(b[i, 3], b[order[1:], 3])
        w = np.maximum(0.0, xx2 - xx1)
        h = np.maximum(0.0, yy2 - yy1)
        inter = w * h
        area_i = (b[i, 2] - b[i, 0]) * (b[i, 3] - b[i, 1])
        area_o = ((b[order[1:], 2] - b[order[1:], 0])
                  * (b[order[1:], 3] - b[order[1:], 1]))
        iou = inter / (area_i + area_o - inter + 1e-10)
        order = order[1:][iou <= iou_threshold]
    keep = np.asarray(keep, np.int64)
    if top_k is not None:
        keep = keep[:top_k]
    return Tensor(jnp.asarray(keep))


@primitive
def box_coder(prior_box, prior_box_var, target_box, code_type="encode_center_size",
              box_normalized=True):
    pb = _A(prior_box)
    tb = _A(target_box)
    pbv = _A(prior_box_var) if prior_box_var is not None else None
    pw = pb[:, 2] - pb[:, 0] + (0.0 if box_normalized else 1.0)
    ph = pb[:, 3] - pb[:, 1] + (0.0 if box_normalized else 1.0)
    pcx = pb[:, 0] + pw * 0.5
    pcy = pb[:, 1] + ph * 0.5
    if code_type == "encode_center_size":
        tw = tb[:, 2] - tb[:, 0] + (0.0 if box_normalized else 1.0)
        th = tb[:, 3] - tb[:, 1] + (0.0 if box_normalized else 1.0)
        tcx = tb[:, 0] + tw * 0.5
        tcy = tb[:, 1] + th * 0.5
        ox = (tcx - pcx) / pw
        oy = (tcy - pcy) / ph
        ow = jnp.log(tw / pw)
        oh = jnp.log(th / ph)
        out = jnp.stack([ox, oy, ow, oh], axis=1)
        if pbv is not None:
            out = out / pbv
        return out
    raise NotImplementedError(code_type)


def generate_anchors(*a, **k):
    raise NotImplementedError("anchor generator lands with detection models")


@primitive
def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0):
    """Max RoI pooling (reference phi/kernels/roi_pool_kernel.h): for each
    box, divide the scaled region into output_size bins and take the max
    per bin. x: [N, C, H, W]; boxes: [R, 4] (x1, y1, x2, y2)."""
    x = _A(x)
    boxes = _A(boxes).astype(jnp.float32)
    if isinstance(output_size, int):
        ph = pw = output_size
    else:
        ph, pw = output_size
    N, C, H, W = x.shape
    bn = np.asarray(boxes_num.numpy() if isinstance(boxes_num, Tensor)
                    else boxes_num).astype(np.int64)
    batch_of = np.repeat(np.arange(bn.size), bn)  # static per trace

    ys = jnp.arange(H, dtype=jnp.float32)
    xs = jnp.arange(W, dtype=jnp.float32)

    def one(ri, box):
        img = x[batch_of[ri]].astype(jnp.float32)     # [C, H, W]
        x1 = jnp.round(box[0] * spatial_scale)
        y1 = jnp.round(box[1] * spatial_scale)
        x2 = jnp.round(box[2] * spatial_scale)
        y2 = jnp.round(box[3] * spatial_scale)
        rw = jnp.maximum(x2 - x1 + 1, 1.0)
        rh = jnp.maximum(y2 - y1 + 1, 1.0)
        bh = rh / ph
        bw = rw / pw
        out = []
        for i in range(ph):
            for j in range(pw):
                hs = jnp.floor(y1 + i * bh)
                he = jnp.ceil(y1 + (i + 1) * bh)
                ws = jnp.floor(x1 + j * bw)
                we = jnp.ceil(x1 + (j + 1) * bw)
                my = (ys >= hs) & (ys < jnp.maximum(he, hs + 1))
                mx = (xs >= ws) & (xs < jnp.maximum(we, ws + 1))
                m = my[:, None] & mx[None, :]
                v = jnp.where(m[None], img, -jnp.inf)
                mv = jnp.max(v, axis=(1, 2))
                out.append(jnp.where(jnp.isfinite(mv), mv, 0.0))
        return jnp.stack(out, 1).reshape(C, ph, pw)

    outs = [one(ri, boxes[ri]) for ri in range(boxes.shape[0])]
    return jnp.stack(outs, 0).astype(x.dtype)


@primitive
def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, min_max_aspect_ratios_order=False):
    """SSD prior (anchor) boxes (reference phi/kernels/prior_box_kernel.h).
    input: feature map [N, C, H, W]; image: [N, C, Him, Wim].
    Returns (boxes [H, W, P, 4], variances [H, W, P, 4])."""
    feat = _A(input)
    img = _A(image)
    H, W = feat.shape[2], feat.shape[3]
    IH, IW = img.shape[2], img.shape[3]
    step_h = steps[1] if steps[1] > 0 else IH / H
    step_w = steps[0] if steps[0] > 0 else IW / W
    ars = [1.0]
    for ar in aspect_ratios:
        if not any(abs(ar - a) < 1e-6 for a in ars):
            ars.append(float(ar))
            if flip:
                ars.append(1.0 / float(ar))
    whs = []
    for ms in min_sizes:
        if min_max_aspect_ratios_order:
            whs.append((ms, ms))
            if max_sizes:
                mx = max_sizes[min_sizes.index(ms)]
                whs.append((math.sqrt(ms * mx), math.sqrt(ms * mx)))
            for ar in ars:
                if abs(ar - 1.0) < 1e-6:
                    continue
                whs.append((ms * math.sqrt(ar), ms / math.sqrt(ar)))
        else:
            for ar in ars:
                whs.append((ms * math.sqrt(ar), ms / math.sqrt(ar)))
            if max_sizes:
                mx = max_sizes[min_sizes.index(ms)]
                whs.append((math.sqrt(ms * mx), math.sqrt(ms * mx)))
    P = len(whs)
    cy = (jnp.arange(H, dtype=jnp.float32) + offset) * step_h
    cx = (jnp.arange(W, dtype=jnp.float32) + offset) * step_w
    gy, gx = jnp.meshgrid(cy, cx, indexing="ij")      # [H, W]
    wh = jnp.asarray(whs, jnp.float32)                # [P, 2]
    x1 = (gx[..., None] - wh[None, None, :, 0] / 2) / IW
    y1 = (gy[..., None] - wh[None, None, :, 1] / 2) / IH
    x2 = (gx[..., None] + wh[None, None, :, 0] / 2) / IW
    y2 = (gy[..., None] + wh[None, None, :, 1] / 2) / IH
    boxes = jnp.stack([x1, y1, x2, y2], axis=-1)      # [H, W, P, 4]
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variance, jnp.float32),
                           boxes.shape)
    return boxes, var


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, rois_num=None):
    """reference distribute_fpn_proposals_kernel: route each RoI to an
    FPN level by scale. Host-side (data-dependent splits, like the
    reference CPU kernel). Returns (multi_rois list, restore_index,
    rois_num_per_level list)."""
    rois = np.asarray(fpn_rois.numpy() if isinstance(fpn_rois, Tensor)
                      else fpn_rois)
    w = np.maximum(rois[:, 2] - rois[:, 0], 0.0)
    h = np.maximum(rois[:, 3] - rois[:, 1], 0.0)
    scale = np.sqrt(w * h)
    lvl = np.floor(np.log2(scale / refer_scale + 1e-8)) + refer_level
    lvl = np.clip(lvl, min_level, max_level).astype(np.int64)
    multi, nums, order = [], [], []
    for l in range(min_level, max_level + 1):
        idx = np.flatnonzero(lvl == l)
        order.append(idx)
        multi.append(Tensor(jnp.asarray(rois[idx])))
        nums.append(Tensor(jnp.asarray(np.asarray([idx.size], np.int32))))
    order = np.concatenate(order) if order else np.empty((0,), np.int64)
    restore = np.empty_like(order)
    restore[order] = np.arange(order.size)
    return multi, Tensor(jnp.asarray(restore)), nums


def generate_proposals(scores, bbox_deltas, img_size, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       pixel_offset=False, return_rois_num=False):
    """RPN proposal generation (reference generate_proposals_v2 kernel):
    decode anchors with deltas, clip, filter small, NMS. Host-side
    composition of existing pieces (single image [A,1,H,W]-flattened or
    [N=1] batch)."""
    s = np.asarray(scores.numpy() if isinstance(scores, Tensor)
                   else scores).reshape(-1)
    d = np.asarray(bbox_deltas.numpy() if isinstance(bbox_deltas, Tensor)
                   else bbox_deltas).reshape(-1, 4)
    a = np.asarray(anchors.numpy() if isinstance(anchors, Tensor)
                   else anchors).reshape(-1, 4)
    v = np.asarray(variances.numpy() if isinstance(variances, Tensor)
                   else variances).reshape(-1, 4)
    im = np.asarray(img_size.numpy() if isinstance(img_size, Tensor)
                    else img_size).reshape(-1)
    order = np.argsort(-s)[:pre_nms_top_n]
    s, d, a, v = s[order], d[order], a[order], v[order]
    aw = a[:, 2] - a[:, 0] + (1.0 if pixel_offset else 0.0)
    ah = a[:, 3] - a[:, 1] + (1.0 if pixel_offset else 0.0)
    acx = a[:, 0] + aw / 2
    acy = a[:, 1] + ah / 2
    cx = v[:, 0] * d[:, 0] * aw + acx
    cy = v[:, 1] * d[:, 1] * ah + acy
    bw = aw * np.exp(np.minimum(v[:, 2] * d[:, 2], 10.0))
    bh = ah * np.exp(np.minimum(v[:, 3] * d[:, 3], 10.0))
    off = 1.0 if pixel_offset else 0.0
    boxes = np.stack([cx - bw / 2, cy - bh / 2,
                      cx + bw / 2 - off, cy + bh / 2 - off], 1)
    boxes[:, 0::2] = np.clip(boxes[:, 0::2], 0, im[1] - off)
    boxes[:, 1::2] = np.clip(boxes[:, 1::2], 0, im[0] - off)
    ok = ((boxes[:, 2] - boxes[:, 0] >= min_size)
          & (boxes[:, 3] - boxes[:, 1] >= min_size))
    boxes, s = boxes[ok], s[ok]
    keep = np.asarray(nms(Tensor(jnp.asarray(boxes)), nms_thresh,
                          scores=Tensor(jnp.asarray(s)),
                          top_k=post_nms_top_n).numpy())
    rois = Tensor(jnp.asarray(boxes[keep]))
    out_scores = Tensor(jnp.asarray(s[keep]))
    if return_rois_num:
        return rois, out_scores, Tensor(
            jnp.asarray(np.asarray([keep.size], np.int32)))
    return rois, out_scores


def decode_jpeg(x, mode="unchanged"):
    """Host-side JPEG decode (reference decode_jpeg_kernel is the GPU
    nvjpeg path; TPU input pipelines decode on host). x: 1-D uint8
    buffer; returns [C, H, W] uint8."""
    import io as _io

    from PIL import Image

    buf = np.asarray(x.numpy() if isinstance(x, Tensor) else x,
                     np.uint8).tobytes()
    img = Image.open(_io.BytesIO(buf))
    if mode == "gray":
        img = img.convert("L")
    elif mode == "rgb":
        img = img.convert("RGB")
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[None]
    else:
        arr = np.transpose(arr, (2, 0, 1))
    return Tensor(jnp.asarray(arr))
