"""Vision ops (reference python/paddle/vision/ops.py: roi_align, nms,
deform_conv, box ops)."""
from __future__ import annotations

import math

import numpy as np

import jax
import jax.numpy as jnp

from ..core.dispatch import primitive
from ..core.tensor import Tensor
from ..nn.layer import Layer

_A = jnp.asarray


@primitive
def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True):
    """RoIAlign via bilinear gather (reference phi/kernels/roi_align_kernel).
    x: [N,C,H,W]; boxes: [R,4] in (x1,y1,x2,y2)."""
    x = _A(x)
    boxes = _A(boxes)
    if isinstance(output_size, int):
        oh = ow = output_size
    else:
        oh, ow = output_size
    N, C, H, W = x.shape
    R = boxes.shape[0]
    offset = 0.5 if aligned else 0.0
    # map each ROI to its image via boxes_num, as the reference kernel's
    # roi_batch_id_list does. jnp.repeat with total_repeat_length stays
    # trace-safe (boxes_num may be a tracer inside jit/static replay).
    if boxes_num is not None:
        bn = _A(boxes_num).astype(jnp.int32)
        try:  # concrete path: validate the mapping covers every ROI
            if int(np.asarray(bn).sum()) != R:
                raise ValueError(
                    "roi_align: sum(boxes_num)=%d must equal the number "
                    "of boxes %d" % (int(np.asarray(bn).sum()), R))
        except jax.errors.TracerArrayConversionError:
            pass
        img_idx = jnp.repeat(jnp.arange(bn.shape[0], dtype=jnp.int32), bn,
                             total_repeat_length=R)
    else:
        if N > 1:
            raise ValueError(
                "roi_align: boxes_num is required when batch size > 1")
        img_idx = jnp.zeros((R,), jnp.int32)

    x1 = boxes[:, 0] * spatial_scale - offset
    y1 = boxes[:, 1] * spatial_scale - offset
    x2 = boxes[:, 2] * spatial_scale - offset
    y2 = boxes[:, 3] * spatial_scale - offset
    rw = jnp.maximum(x2 - x1, 1e-3)
    rh = jnp.maximum(y2 - y1, 1e-3)
    bin_w = rw / ow
    bin_h = rh / oh

    iy = (jnp.arange(oh) + 0.5)
    ix = (jnp.arange(ow) + 0.5)
    cy = y1[:, None] + iy[None, :] * bin_h[:, None]  # [R, oh]
    cx = x1[:, None] + ix[None, :] * bin_w[:, None]  # [R, ow]

    def bilinear(img, yy, xx):
        y0 = jnp.clip(jnp.floor(yy).astype(jnp.int32), 0, H - 1)
        x0 = jnp.clip(jnp.floor(xx).astype(jnp.int32), 0, W - 1)
        y1_ = jnp.clip(y0 + 1, 0, H - 1)
        x1_ = jnp.clip(x0 + 1, 0, W - 1)
        wy = yy - y0
        wx = xx - x0
        v00 = img[:, y0, :][:, :, x0]
        v01 = img[:, y0, :][:, :, x1_]
        v10 = img[:, y1_, :][:, :, x0]
        v11 = img[:, y1_, :][:, :, x1_]
        return (v00 * (1 - wy)[None, :, None] * (1 - wx)[None, None, :]
                + v01 * (1 - wy)[None, :, None] * wx[None, None, :]
                + v10 * wy[None, :, None] * (1 - wx)[None, None, :]
                + v11 * wy[None, :, None] * wx[None, None, :])

    def per_roi(r):
        img = x[img_idx[r]]
        return bilinear(img, cy[r], cx[r])  # [C, oh, ow]

    out = jax.vmap(per_roi)(jnp.arange(R))
    return out


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """Host-side NMS (data-dependent output count — same reason the
    reference runs it as a CPU/custom op for dynamic shapes)."""
    b = np.asarray(boxes.numpy() if isinstance(boxes, Tensor) else boxes)
    s = (np.asarray(scores.numpy() if isinstance(scores, Tensor) else scores)
         if scores is not None else np.arange(len(b))[::-1].astype(np.float32))
    order = np.argsort(-s)
    keep = []
    while order.size > 0:
        i = order[0]
        keep.append(i)
        if order.size == 1:
            break
        xx1 = np.maximum(b[i, 0], b[order[1:], 0])
        yy1 = np.maximum(b[i, 1], b[order[1:], 1])
        xx2 = np.minimum(b[i, 2], b[order[1:], 2])
        yy2 = np.minimum(b[i, 3], b[order[1:], 3])
        w = np.maximum(0.0, xx2 - xx1)
        h = np.maximum(0.0, yy2 - yy1)
        inter = w * h
        area_i = (b[i, 2] - b[i, 0]) * (b[i, 3] - b[i, 1])
        area_o = ((b[order[1:], 2] - b[order[1:], 0])
                  * (b[order[1:], 3] - b[order[1:], 1]))
        iou = inter / (area_i + area_o - inter + 1e-10)
        order = order[1:][iou <= iou_threshold]
    keep = np.asarray(keep, np.int64)
    if top_k is not None:
        keep = keep[:top_k]
    return Tensor(jnp.asarray(keep))


@primitive
def box_coder(prior_box, prior_box_var, target_box, code_type="encode_center_size",
              box_normalized=True):
    pb = _A(prior_box)
    tb = _A(target_box)
    pbv = _A(prior_box_var) if prior_box_var is not None else None
    pw = pb[:, 2] - pb[:, 0] + (0.0 if box_normalized else 1.0)
    ph = pb[:, 3] - pb[:, 1] + (0.0 if box_normalized else 1.0)
    pcx = pb[:, 0] + pw * 0.5
    pcy = pb[:, 1] + ph * 0.5
    if code_type == "encode_center_size":
        tw = tb[:, 2] - tb[:, 0] + (0.0 if box_normalized else 1.0)
        th = tb[:, 3] - tb[:, 1] + (0.0 if box_normalized else 1.0)
        tcx = tb[:, 0] + tw * 0.5
        tcy = tb[:, 1] + th * 0.5
        ox = (tcx - pcx) / pw
        oy = (tcy - pcy) / ph
        ow = jnp.log(tw / pw)
        oh = jnp.log(th / ph)
        out = jnp.stack([ox, oy, ow, oh], axis=1)
        if pbv is not None:
            out = out / pbv
        return out
    raise NotImplementedError(code_type)


def generate_anchors(*a, **k):
    raise NotImplementedError("anchor generator lands with detection models")


@primitive
def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0):
    """Max RoI pooling (reference phi/kernels/roi_pool_kernel.h): for each
    box, divide the scaled region into output_size bins and take the max
    per bin. x: [N, C, H, W]; boxes: [R, 4] (x1, y1, x2, y2)."""
    x = _A(x)
    boxes = _A(boxes).astype(jnp.float32)
    if isinstance(output_size, int):
        ph = pw = output_size
    else:
        ph, pw = output_size
    N, C, H, W = x.shape
    bn = np.asarray(boxes_num.numpy() if isinstance(boxes_num, Tensor)
                    else boxes_num).astype(np.int64)
    batch_of = np.repeat(np.arange(bn.size), bn)  # static per trace

    ys = jnp.arange(H, dtype=jnp.float32)
    xs = jnp.arange(W, dtype=jnp.float32)

    def one(ri, box):
        img = x[batch_of[ri]].astype(jnp.float32)     # [C, H, W]
        x1 = jnp.round(box[0] * spatial_scale)
        y1 = jnp.round(box[1] * spatial_scale)
        x2 = jnp.round(box[2] * spatial_scale)
        y2 = jnp.round(box[3] * spatial_scale)
        rw = jnp.maximum(x2 - x1 + 1, 1.0)
        rh = jnp.maximum(y2 - y1 + 1, 1.0)
        bh = rh / ph
        bw = rw / pw
        out = []
        for i in range(ph):
            for j in range(pw):
                hs = jnp.floor(y1 + i * bh)
                he = jnp.ceil(y1 + (i + 1) * bh)
                ws = jnp.floor(x1 + j * bw)
                we = jnp.ceil(x1 + (j + 1) * bw)
                my = (ys >= hs) & (ys < jnp.maximum(he, hs + 1))
                mx = (xs >= ws) & (xs < jnp.maximum(we, ws + 1))
                m = my[:, None] & mx[None, :]
                v = jnp.where(m[None], img, -jnp.inf)
                mv = jnp.max(v, axis=(1, 2))
                out.append(jnp.where(jnp.isfinite(mv), mv, 0.0))
        return jnp.stack(out, 1).reshape(C, ph, pw)

    outs = [one(ri, boxes[ri]) for ri in range(boxes.shape[0])]
    return jnp.stack(outs, 0).astype(x.dtype)


@primitive
def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, min_max_aspect_ratios_order=False):
    """SSD prior (anchor) boxes (reference phi/kernels/prior_box_kernel.h).
    input: feature map [N, C, H, W]; image: [N, C, Him, Wim].
    Returns (boxes [H, W, P, 4], variances [H, W, P, 4])."""
    feat = _A(input)
    img = _A(image)
    H, W = feat.shape[2], feat.shape[3]
    IH, IW = img.shape[2], img.shape[3]
    step_h = steps[1] if steps[1] > 0 else IH / H
    step_w = steps[0] if steps[0] > 0 else IW / W
    ars = [1.0]
    for ar in aspect_ratios:
        if not any(abs(ar - a) < 1e-6 for a in ars):
            ars.append(float(ar))
            if flip:
                ars.append(1.0 / float(ar))
    whs = []
    for ms in min_sizes:
        if min_max_aspect_ratios_order:
            whs.append((ms, ms))
            if max_sizes:
                mx = max_sizes[min_sizes.index(ms)]
                whs.append((math.sqrt(ms * mx), math.sqrt(ms * mx)))
            for ar in ars:
                if abs(ar - 1.0) < 1e-6:
                    continue
                whs.append((ms * math.sqrt(ar), ms / math.sqrt(ar)))
        else:
            for ar in ars:
                whs.append((ms * math.sqrt(ar), ms / math.sqrt(ar)))
            if max_sizes:
                mx = max_sizes[min_sizes.index(ms)]
                whs.append((math.sqrt(ms * mx), math.sqrt(ms * mx)))
    P = len(whs)
    cy = (jnp.arange(H, dtype=jnp.float32) + offset) * step_h
    cx = (jnp.arange(W, dtype=jnp.float32) + offset) * step_w
    gy, gx = jnp.meshgrid(cy, cx, indexing="ij")      # [H, W]
    wh = jnp.asarray(whs, jnp.float32)                # [P, 2]
    x1 = (gx[..., None] - wh[None, None, :, 0] / 2) / IW
    y1 = (gy[..., None] - wh[None, None, :, 1] / 2) / IH
    x2 = (gx[..., None] + wh[None, None, :, 0] / 2) / IW
    y2 = (gy[..., None] + wh[None, None, :, 1] / 2) / IH
    boxes = jnp.stack([x1, y1, x2, y2], axis=-1)      # [H, W, P, 4]
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variance, jnp.float32),
                           boxes.shape)
    return boxes, var


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, rois_num=None):
    """reference distribute_fpn_proposals_kernel: route each RoI to an
    FPN level by scale. Host-side (data-dependent splits, like the
    reference CPU kernel). Returns (multi_rois list, restore_index,
    rois_num_per_level list)."""
    rois = np.asarray(fpn_rois.numpy() if isinstance(fpn_rois, Tensor)
                      else fpn_rois)
    w = np.maximum(rois[:, 2] - rois[:, 0], 0.0)
    h = np.maximum(rois[:, 3] - rois[:, 1], 0.0)
    scale = np.sqrt(w * h)
    lvl = np.floor(np.log2(scale / refer_scale + 1e-8)) + refer_level
    lvl = np.clip(lvl, min_level, max_level).astype(np.int64)
    multi, nums, order = [], [], []
    for l in range(min_level, max_level + 1):
        idx = np.flatnonzero(lvl == l)
        order.append(idx)
        multi.append(Tensor(jnp.asarray(rois[idx])))
        nums.append(Tensor(jnp.asarray(np.asarray([idx.size], np.int32))))
    order = np.concatenate(order) if order else np.empty((0,), np.int64)
    restore = np.empty_like(order)
    restore[order] = np.arange(order.size)
    return multi, Tensor(jnp.asarray(restore)), nums


def generate_proposals(scores, bbox_deltas, img_size, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       pixel_offset=False, return_rois_num=False):
    """RPN proposal generation (reference generate_proposals_v2 kernel):
    decode anchors with deltas, clip, filter small, NMS. Host-side
    composition of existing pieces (single image [A,1,H,W]-flattened or
    [N=1] batch)."""
    s = np.asarray(scores.numpy() if isinstance(scores, Tensor)
                   else scores).reshape(-1)
    d = np.asarray(bbox_deltas.numpy() if isinstance(bbox_deltas, Tensor)
                   else bbox_deltas).reshape(-1, 4)
    a = np.asarray(anchors.numpy() if isinstance(anchors, Tensor)
                   else anchors).reshape(-1, 4)
    v = np.asarray(variances.numpy() if isinstance(variances, Tensor)
                   else variances).reshape(-1, 4)
    im = np.asarray(img_size.numpy() if isinstance(img_size, Tensor)
                    else img_size).reshape(-1)
    order = np.argsort(-s)[:pre_nms_top_n]
    s, d, a, v = s[order], d[order], a[order], v[order]
    aw = a[:, 2] - a[:, 0] + (1.0 if pixel_offset else 0.0)
    ah = a[:, 3] - a[:, 1] + (1.0 if pixel_offset else 0.0)
    acx = a[:, 0] + aw / 2
    acy = a[:, 1] + ah / 2
    cx = v[:, 0] * d[:, 0] * aw + acx
    cy = v[:, 1] * d[:, 1] * ah + acy
    bw = aw * np.exp(np.minimum(v[:, 2] * d[:, 2], 10.0))
    bh = ah * np.exp(np.minimum(v[:, 3] * d[:, 3], 10.0))
    off = 1.0 if pixel_offset else 0.0
    boxes = np.stack([cx - bw / 2, cy - bh / 2,
                      cx + bw / 2 - off, cy + bh / 2 - off], 1)
    boxes[:, 0::2] = np.clip(boxes[:, 0::2], 0, im[1] - off)
    boxes[:, 1::2] = np.clip(boxes[:, 1::2], 0, im[0] - off)
    ok = ((boxes[:, 2] - boxes[:, 0] >= min_size)
          & (boxes[:, 3] - boxes[:, 1] >= min_size))
    boxes, s = boxes[ok], s[ok]
    keep = np.asarray(nms(Tensor(jnp.asarray(boxes)), nms_thresh,
                          scores=Tensor(jnp.asarray(s)),
                          top_k=post_nms_top_n).numpy())
    rois = Tensor(jnp.asarray(boxes[keep]))
    out_scores = Tensor(jnp.asarray(s[keep]))
    if return_rois_num:
        return rois, out_scores, Tensor(
            jnp.asarray(np.asarray([keep.size], np.int32)))
    return rois, out_scores


def decode_jpeg(x, mode="unchanged"):
    """Host-side JPEG decode (reference decode_jpeg_kernel is the GPU
    nvjpeg path; TPU input pipelines decode on host). x: 1-D uint8
    buffer; returns [C, H, W] uint8."""
    import io as _io

    from PIL import Image

    buf = np.asarray(x.numpy() if isinstance(x, Tensor) else x,
                     np.uint8).tobytes()
    img = Image.open(_io.BytesIO(buf))
    if mode == "gray":
        img = img.convert("L")
    elif mode == "rgb":
        img = img.convert("RGB")
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[None]
    else:
        arr = np.transpose(arr, (2, 0, 1))
    return Tensor(jnp.asarray(arr))


# -- surface completions (reference vision/ops.py remaining names) -----------

def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """reference vision/ops.py deform_conv2d (delegates to the shared
    deformable_conv kernel body)."""
    import paddle_tpu.nn.functional as F

    return F.deformable_conv(x, offset, weight, mask=mask, bias=bias,
                             stride=stride, padding=padding,
                             dilation=dilation, groups=groups,
                             deformable_groups=deformable_groups)


class DeformConv2D(Layer):
    """reference vision/ops.py DeformConv2D layer."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        ks = kernel_size if isinstance(kernel_size, (list, tuple)) \
            else [kernel_size] * 2
        from ..nn import initializer as I

        self.weight = self.create_parameter(
            [out_channels, in_channels // groups] + list(ks),
            attr=weight_attr,
            default_initializer=None if weight_attr else I.XavierNormal())
        self.bias = (None if bias_attr is False else self.create_parameter(
            [out_channels], attr=bias_attr, is_bias=True))
        self._kw = dict(stride=stride, padding=padding, dilation=dilation,
                        deformable_groups=deformable_groups, groups=groups)

    def forward(self, x, offset, mask=None):
        return deform_conv2d(x, offset, self.weight, bias=self.bias,
                             mask=mask, **self._kw)


class RoIAlign(Layer):
    """reference vision/ops.py RoIAlign layer."""

    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._args = (output_size, spatial_scale)

    def forward(self, x, boxes, boxes_num):
        return roi_align(x, boxes, boxes_num, self._args[0],
                         spatial_scale=self._args[1])


class RoIPool(Layer):
    """reference vision/ops.py RoIPool layer."""

    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._args = (output_size, spatial_scale)

    def forward(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, self._args[0],
                        spatial_scale=self._args[1])


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
               name=None):
    """Position-sensitive RoI pooling (reference psroi_pool_kernel):
    input channels C = out_c * ph * pw; bin (i, j) of a box averages its
    OWN channel group — the R-FCN head op."""
    xv = _A(x)
    ph, pw = (output_size, output_size) if isinstance(output_size, int) \
        else output_size
    C = xv.shape[1]
    if C % (ph * pw):
        raise ValueError(
            "psroi_pool: input channels (%d) must equal out_c * %d"
            % (C, ph * pw))
    out_c = C // (ph * pw)
    bv = _A(boxes) * spatial_scale
    n_boxes = bv.shape[0]
    H, W = xv.shape[2], xv.shape[3]
    outs = []
    # batch index per box from boxes_num
    import numpy as _np

    counts = _np.asarray(_A(boxes_num)).astype(int)
    batch_of = _np.repeat(_np.arange(len(counts)), counts)
    for b in range(n_boxes):
        x1, y1, x2, y2 = [float(v) for v in _np.asarray(bv[b])]
        bh = max(y2 - y1, 0.1) / ph
        bw = max(x2 - x1, 0.1) / pw
        img = xv[int(batch_of[b])]
        bins = []
        for i in range(ph):
            row = []
            for j in range(pw):
                ys = int(_np.floor(y1 + i * bh))
                ye = max(int(_np.ceil(y1 + (i + 1) * bh)), ys + 1)
                xs = int(_np.floor(x1 + j * bw))
                xe = max(int(_np.ceil(x1 + (j + 1) * bw)), xs + 1)
                ys, ye = _np.clip([ys, ye], 0, H)
                xs, xe = _np.clip([xs, xe], 0, W)
                # channel group for bin (i, j)
                ch = slice((i * pw + j) * out_c, (i * pw + j + 1) * out_c)
                patch = img[ch, ys:ye, xs:xe]
                row.append(patch.mean(axis=(1, 2)) if patch.size
                           else jnp.zeros((out_c,), xv.dtype))
            bins.append(jnp.stack(row, axis=-1))
        outs.append(jnp.stack(bins, axis=-2))
    return Tensor(jnp.stack(outs))


class PSRoIPool(Layer):
    """reference vision/ops.py PSRoIPool layer."""

    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._args = (output_size, spatial_scale)

    def forward(self, x, boxes, boxes_num):
        return psroi_pool(x, boxes, boxes_num, self._args[0],
                          self._args[1])


def matrix_nms(bboxes, scores, score_threshold, post_threshold,
               nms_top_k, keep_top_k, use_gaussian=False, gaussian_sigma=2.0,
               background_label=0, normalized=True, return_index=False,
               return_rois_num=True, name=None):
    """Matrix NMS (reference matrix_nms_kernel / SOLOv2): decay each
    box's score by its max-IoU overlap with higher-scored boxes of the
    same class, in one matrix pass instead of sequential suppression."""
    import numpy as _np

    bv = _np.asarray(_A(bboxes))   # [N, M, 4]
    sv = _np.asarray(_A(scores))   # [N, C, M]
    all_out, all_idx, nums = [], [], []
    for n in range(bv.shape[0]):
        dets = []
        idxs = []
        for c in range(sv.shape[1]):
            if c == background_label:
                continue
            s = sv[n, c]
            keep = _np.nonzero(s > score_threshold)[0]
            if keep.size == 0:
                continue
            order = keep[_np.argsort(-s[keep])][:nms_top_k]
            boxes_c = bv[n, order]
            scores_c = s[order]
            # pairwise IoU
            x1 = _np.maximum(boxes_c[:, None, 0], boxes_c[None, :, 0])
            y1 = _np.maximum(boxes_c[:, None, 1], boxes_c[None, :, 1])
            x2 = _np.minimum(boxes_c[:, None, 2], boxes_c[None, :, 2])
            y2 = _np.minimum(boxes_c[:, None, 3], boxes_c[None, :, 3])
            inter = _np.clip(x2 - x1, 0, None) * _np.clip(y2 - y1, 0, None)
            area = (boxes_c[:, 2] - boxes_c[:, 0]) \
                * (boxes_c[:, 3] - boxes_c[:, 1])
            iou = inter / _np.maximum(area[:, None] + area[None, :] - inter,
                                      1e-9)
            iou = _np.triu(iou, k=1)
            # compensate_i = max overlap of box i with any HIGHER-scored
            # box (column max of the upper triangle) — SOLOv2 eq. (4)
            compensate = iou.max(axis=0)
            if use_gaussian:
                decay = _np.exp(-(iou ** 2 - compensate[:, None] ** 2)
                                / gaussian_sigma).min(axis=0)
            else:
                decay = ((1 - iou)
                         / _np.maximum(1 - compensate[:, None], 1e-9)) \
                    .min(axis=0)
            new_scores = scores_c * decay
            sel = new_scores > post_threshold
            for k in _np.nonzero(sel)[0]:
                dets.append([c, new_scores[k]] + boxes_c[k].tolist())
                idxs.append(order[k])
        dets = _np.asarray(dets, _np.float32).reshape(-1, 6)
        if keep_top_k > 0 and dets.shape[0] > keep_top_k:
            top = _np.argsort(-dets[:, 1])[:keep_top_k]
            dets = dets[top]
            idxs = [idxs[i] for i in top]
        all_out.append(dets)
        all_idx.extend(idxs)
        nums.append(dets.shape[0])
    out = Tensor(jnp.asarray(_np.concatenate(all_out, axis=0)
                             if all_out else _np.zeros((0, 6), _np.float32)))
    rois_num = Tensor(jnp.asarray(_np.asarray(nums, _np.int32)))
    if return_index:
        index = Tensor(jnp.asarray(_np.asarray(all_idx, _np.int32)))
        return (out, index, rois_num) if return_rois_num else (out, index)
    return (out, rois_num) if return_rois_num else out


def yolo_box(x, img_size, anchors, class_num, conf_thresh,
             downsample_ratio, clip_bbox=True, name=None, scale_x_y=1.0,
             iou_aware=False, iou_aware_factor=0.5):
    """Decode YOLOv3 head output to boxes+scores (reference
    yolo_box_kernel): x [N, len(anchors)/2*(5+C), H, W]."""
    xv = _A(x).astype(jnp.float32)
    N, _, H, W = xv.shape
    na = len(anchors) // 2
    anc = jnp.asarray(anchors, jnp.float32).reshape(na, 2)
    pred = xv.reshape(N, na, 5 + class_num, H, W)
    gx = (jnp.arange(W).reshape(1, 1, 1, W))
    gy = (jnp.arange(H).reshape(1, 1, H, 1))
    sig = jax.nn.sigmoid
    bx = (sig(pred[:, :, 0]) * scale_x_y
          - (scale_x_y - 1) / 2 + gx) / W
    by = (sig(pred[:, :, 1]) * scale_x_y
          - (scale_x_y - 1) / 2 + gy) / H
    bw = jnp.exp(pred[:, :, 2]) * anc[None, :, 0, None, None] \
        / (W * downsample_ratio)
    bh = jnp.exp(pred[:, :, 3]) * anc[None, :, 1, None, None] \
        / (H * downsample_ratio)
    conf = sig(pred[:, :, 4])
    probs = sig(pred[:, :, 5:]) * conf[:, :, None]
    imgs = _A(img_size).astype(jnp.float32)  # [N, 2] (h, w)
    ih = imgs[:, 0].reshape(N, 1, 1, 1)
    iw = imgs[:, 1].reshape(N, 1, 1, 1)
    x1 = (bx - bw / 2) * iw
    y1 = (by - bh / 2) * ih
    x2 = (bx + bw / 2) * iw
    y2 = (by + bh / 2) * ih
    if clip_bbox:
        x1 = jnp.clip(x1, 0, iw - 1)
        y1 = jnp.clip(y1, 0, ih - 1)
        x2 = jnp.clip(x2, 0, iw - 1)
        y2 = jnp.clip(y2, 0, ih - 1)
    boxes = jnp.stack([x1, y1, x2, y2], axis=-1).reshape(N, -1, 4)
    scores = probs.transpose(0, 1, 3, 4, 2).reshape(N, -1, class_num)
    mask = (conf > conf_thresh).reshape(N, -1, 1)
    return Tensor(boxes * mask), Tensor(scores * mask)


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=True, name=None, scale_x_y=1.0):
    """reference yolo_loss: full YOLOv3 target assignment is a training
    pipeline concern; the TPU stack trains detection heads with the
    composable losses (sigmoid bce + iou) — refuse with guidance."""
    raise NotImplementedError(
        "yolo_loss: compose F.binary_cross_entropy_with_logits over "
        "yolo_box-decoded outputs (the reference's monolithic kernel "
        "bundles target assignment; see vision/ops.py yolo_box)")


def generate_proposals_v2(scores, bbox_deltas, img_size, anchors,
                          variances, pre_nms_top_n=6000,
                          post_nms_top_n=1000, nms_thresh=0.5,
                          min_size=0.1, eta=1.0, pixel_offset=False,
                          return_rois_num=False, name=None):
    """v2 = v1 with pixel_offset semantics (reference
    generate_proposals_v2_op); delegates to the shared implementation."""
    return generate_proposals(scores, bbox_deltas, img_size, anchors,
                              variances, pre_nms_top_n=pre_nms_top_n,
                              post_nms_top_n=post_nms_top_n,
                              nms_thresh=nms_thresh, min_size=min_size,
                              eta=eta, pixel_offset=pixel_offset,
                              return_rois_num=return_rois_num)


def read_file(filename, name=None):
    """reference vision/ops.py read_file: raw file bytes as a uint8
    tensor (pair with decode_jpeg)."""
    with open(filename, "rb") as f:
        data = f.read()
    import numpy as _np

    return Tensor(jnp.asarray(_np.frombuffer(data, _np.uint8)))
