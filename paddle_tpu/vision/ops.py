"""Vision ops (reference python/paddle/vision/ops.py: roi_align, nms,
deform_conv, box ops)."""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..core.dispatch import primitive
from ..core.tensor import Tensor

_A = jnp.asarray


@primitive
def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True):
    """RoIAlign via bilinear gather (reference phi/kernels/roi_align_kernel).
    x: [N,C,H,W]; boxes: [R,4] in (x1,y1,x2,y2)."""
    x = _A(x)
    boxes = _A(boxes)
    if isinstance(output_size, int):
        oh = ow = output_size
    else:
        oh, ow = output_size
    N, C, H, W = x.shape
    R = boxes.shape[0]
    offset = 0.5 if aligned else 0.0
    # assume single image (N==1) or boxes_num mapping handled upstream
    img_idx = jnp.zeros((R,), jnp.int32)

    x1 = boxes[:, 0] * spatial_scale - offset
    y1 = boxes[:, 1] * spatial_scale - offset
    x2 = boxes[:, 2] * spatial_scale - offset
    y2 = boxes[:, 3] * spatial_scale - offset
    rw = jnp.maximum(x2 - x1, 1e-3)
    rh = jnp.maximum(y2 - y1, 1e-3)
    bin_w = rw / ow
    bin_h = rh / oh

    iy = (jnp.arange(oh) + 0.5)
    ix = (jnp.arange(ow) + 0.5)
    cy = y1[:, None] + iy[None, :] * bin_h[:, None]  # [R, oh]
    cx = x1[:, None] + ix[None, :] * bin_w[:, None]  # [R, ow]

    def bilinear(img, yy, xx):
        y0 = jnp.clip(jnp.floor(yy).astype(jnp.int32), 0, H - 1)
        x0 = jnp.clip(jnp.floor(xx).astype(jnp.int32), 0, W - 1)
        y1_ = jnp.clip(y0 + 1, 0, H - 1)
        x1_ = jnp.clip(x0 + 1, 0, W - 1)
        wy = yy - y0
        wx = xx - x0
        v00 = img[:, y0, :][:, :, x0]
        v01 = img[:, y0, :][:, :, x1_]
        v10 = img[:, y1_, :][:, :, x0]
        v11 = img[:, y1_, :][:, :, x1_]
        return (v00 * (1 - wy)[None, :, None] * (1 - wx)[None, None, :]
                + v01 * (1 - wy)[None, :, None] * wx[None, None, :]
                + v10 * wy[None, :, None] * (1 - wx)[None, None, :]
                + v11 * wy[None, :, None] * wx[None, None, :])

    def per_roi(r):
        img = x[img_idx[r]]
        return bilinear(img, cy[r], cx[r])  # [C, oh, ow]

    out = jax.vmap(per_roi)(jnp.arange(R))
    return out


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """Host-side NMS (data-dependent output count — same reason the
    reference runs it as a CPU/custom op for dynamic shapes)."""
    b = np.asarray(boxes.numpy() if isinstance(boxes, Tensor) else boxes)
    s = (np.asarray(scores.numpy() if isinstance(scores, Tensor) else scores)
         if scores is not None else np.arange(len(b))[::-1].astype(np.float32))
    order = np.argsort(-s)
    keep = []
    while order.size > 0:
        i = order[0]
        keep.append(i)
        if order.size == 1:
            break
        xx1 = np.maximum(b[i, 0], b[order[1:], 0])
        yy1 = np.maximum(b[i, 1], b[order[1:], 1])
        xx2 = np.minimum(b[i, 2], b[order[1:], 2])
        yy2 = np.minimum(b[i, 3], b[order[1:], 3])
        w = np.maximum(0.0, xx2 - xx1)
        h = np.maximum(0.0, yy2 - yy1)
        inter = w * h
        area_i = (b[i, 2] - b[i, 0]) * (b[i, 3] - b[i, 1])
        area_o = ((b[order[1:], 2] - b[order[1:], 0])
                  * (b[order[1:], 3] - b[order[1:], 1]))
        iou = inter / (area_i + area_o - inter + 1e-10)
        order = order[1:][iou <= iou_threshold]
    keep = np.asarray(keep, np.int64)
    if top_k is not None:
        keep = keep[:top_k]
    return Tensor(jnp.asarray(keep))


@primitive
def box_coder(prior_box, prior_box_var, target_box, code_type="encode_center_size",
              box_normalized=True):
    pb = _A(prior_box)
    tb = _A(target_box)
    pbv = _A(prior_box_var) if prior_box_var is not None else None
    pw = pb[:, 2] - pb[:, 0] + (0.0 if box_normalized else 1.0)
    ph = pb[:, 3] - pb[:, 1] + (0.0 if box_normalized else 1.0)
    pcx = pb[:, 0] + pw * 0.5
    pcy = pb[:, 1] + ph * 0.5
    if code_type == "encode_center_size":
        tw = tb[:, 2] - tb[:, 0] + (0.0 if box_normalized else 1.0)
        th = tb[:, 3] - tb[:, 1] + (0.0 if box_normalized else 1.0)
        tcx = tb[:, 0] + tw * 0.5
        tcy = tb[:, 1] + th * 0.5
        ox = (tcx - pcx) / pw
        oy = (tcy - pcy) / ph
        ow = jnp.log(tw / pw)
        oh = jnp.log(th / ph)
        out = jnp.stack([ox, oy, ow, oh], axis=1)
        if pbv is not None:
            out = out / pbv
        return out
    raise NotImplementedError(code_type)


def generate_anchors(*a, **k):
    raise NotImplementedError("anchor generator lands with detection models")
