"""paddle.vision (reference python/paddle/vision/)."""
from . import datasets, models, ops, transforms  # noqa: F401
