"""paddle_tpu.monitor — unified telemetry for the whole stack.

Three pillars (one registry, one postmortem path, one timeline):

1. **Metric registry** (monitor/registry.py): Counter/Gauge/Histogram
   with labels; near-zero overhead when disabled; JSON snapshot +
   Prometheus text exporters served over the fleet KV HTTP server
   (monitor/exporter.py); optional bridge mirroring samples onto the
   native chrome-trace counter timeline. serving/metrics.py and the
   compiled train step (parallel/engine.py) publish here.

2. **Collective flight recorder** (monitor/flight_recorder.py): a
   per-rank ring buffer of every eager collective, gathered through the
   TCPStore on timeout and diffed to name the first diverging
   rank/sequence — wired into distributed/process_group.py.

3. **Multi-rank trace merge** (monitor/trace_merge.py +
   tools/trace_merge.py): store-based clock-offset estimation and
   rank-prefixed chrome-trace aggregation into one aligned timeline.

4. **Perf attribution + sentinels** (monitor/perf.py +
   monitor/timeseries.py): MFU / model-FLOPs / HBM-peak accounting for
   compiled train steps (XLA cost/memory analysis over measured wall
   clock, phase-split compute|comm|host), per-token goodput + KV-page
   occupancy for the serving engine; a bounded (ts, value) ring behind
   every Counter/Gauge sample; pluggable regression sentinels (NaN
   loss, loss spike, throughput cliff, grad-norm explosion) that
   increment ``perf_anomalies_total{kind}``, drop events into the
   flight-recorder ring, and flip the /healthz degraded flag. All
   default-off (``FLAGS_perf_attribution`` / ``FLAGS_monitor_timeseries``
   / ``FLAGS_perf_sentinels``); served at /debugz/perf +
   /debugz/timeseries; rendered by tools/perf_report.py.

5. **Span journal** (monitor/trace.py, ``FLAGS_monitor_trace``):
   per-request serving timelines (contiguous queue/prefill/decode/
   preempted phase spans + token-milestone events carrying KV-page and
   slot occupancy), per-step train spans with flight-recorder-linked
   comm child spans, and OpenMetrics-style histogram bucket exemplars
   (bucket → trace id) through a registry hook slot. Served at
   /debugz/trace + /debugz/trace/{id}; merged into the chrome-trace
   timeline by tools/trace_merge.py --requests.

6. **Fleet telemetry plane** (monitor/fleet.py, ``FLAGS_monitor_fleet``):
   store-registered per-rank endpoints, a collector fusing every rank's
   /metrics.json + /debugz/perf + /healthz into rank-labeled fleet
   series (counter sums, gauge min/max/p50 spreads) served at
   /debugz/fleet, /debugz/fleet/ranks and federation-style
   /metrics/fleet; cross-rank straggler detection
   (``fleet_straggler_total{rank}``) that names the slow rank BEFORE a
   timeout; and anomaly-triggered fleet captures pulling bundles +
   journal tails from all ranks into one ``fleet_capture_<ts>/``
   artifact. Rendered live by tools/fleet_top.py.

7. **Memory plane** (monitor/memory.py, ``FLAGS_monitor_memory``):
   per-component device-memory ledger (``mem_device_bytes{component,
   job}``) reconciled against allocator stats, explicit
   static-vs-transient attribution (``mem_hbm_headroom_bytes{job}`` =
   capacity − static ledger − compiled peak), OOM forensics writing
   ``oom_postmortem_rank{r}.json`` before the failure re-raises (with
   a deterministic ``mem.oom`` injection site), and a leak sentinel
   firing ``perf_anomalies_total{kind="mem_leak"}`` on steady-state
   growth. Served at /debugz/memory; per-rank memory columns in the
   fleet table and tools/fleet_top.py.

8. **Continuous profiling plane** (monitor/profile.py,
   ``FLAGS_monitor_profile``): an always-on stdlib host sampling
   profiler (``sys._current_frames()`` at ``PT_PROFILE_HZ``, folded
   stacks with scheduler/store-io/device-wait/tokenize component
   attribution, /debugz/profile + /debugz/profile/folded), one-shot
   anomaly-triggered device capture windows (``capture_window`` /
   ``arm_capture`` around the next N hot steps, through the
   paddle_tpu/profiler Xprof session guard; armed by throughput-cliff
   and mem_leak sentinels, watchdog stalls, fleet stragglers —
   cooldown + cap, defer-not-drop), and measured phase timers
   (``profile_dispatch_seconds`` / ``profile_host_blocked_seconds`` /
   ``profile_host_gap_seconds``) that make PR-5's analytic phase split
   falsifiable via tools/perf_report.py. Division of labor: **profile
   = where the time measurably went**.

9. **SLO/error-budget plane + incident manager** (monitor/slo.py +
   monitor/incidents.py, ``FLAGS_monitor_slo``): declarative
   objectives (serving TTFT/TPOT/e2e latency attainment +
   availability; training step-time/goodput floors) judged over the
   timeseries ring by a plain ring listener — no new sampling path —
   publishing ``slo_attainment_ratio`` / ``slo_error_budget_
   remaining_ratio`` / ``slo_burn_rate`` with multi-window
   multi-burn-rate alerting (fast+slow pairs on the monotonic clock;
   page vs ticket severity from the pair); and ONE bounded incident
   table every detector reports into (``incidents.open/resolve`` with
   episode-keyed dedup, evidence links to the artifacts each detector
   already writes) that /healthz "degraded" derives from while the
   plane is on. Served at /debugz/slo + /debugz/incidents +
   /debugz/fleet/incidents; rendered by tools/slo_report.py.
   Division of labor: sentinels/watchdog/fleet **detect**, incidents
   **aggregate**, slo **judges**.

10. **Progress watchdog** (monitor/watchdog.py): heartbeat registry fed
   by the compiled train step, the serving engine loop, and store
   collectives; a daemon thread (``start_watchdog()`` / ``PT_WATCHDOG``)
   turns a stalled heartbeat into a cross-rank diagnostic bundle
   (all-thread stacks + flight ring + metric snapshot + heartbeat ages)
   naming the stalled or dead rank, and serves /healthz + /debugz/*
   live on the fleet KV HTTP server. Flight recorder = TIMEOUT-
   triggered; watchdog = PROGRESS-triggered.
"""
from __future__ import annotations

from .registry import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    Registry,
    counter,
    disable,
    enable,
    gauge,
    get_registry,
    histogram,
    is_enabled,
)
from .exporter import (  # noqa: F401
    MetricsServer,
    snapshot,
    start_metrics_server,
    stop_metrics_server,
    write_snapshot,
)
from .flight_recorder import (  # noqa: F401
    FlightRecorder,
    diagnose,
    get_flight_recorder,
)
from .watchdog import (  # noqa: F401
    Heartbeat,
    build_bundle,
    diagnose_bundles,
    heartbeat,
    is_watchdog_running,
    register_stall_action,
    start_watchdog,
    stop_watchdog,
    unregister_stall_action,
)
from . import fleet  # noqa: F401
from . import flight_recorder  # noqa: F401
from . import incidents  # noqa: F401
from . import memory  # noqa: F401
from . import perf  # noqa: F401
from . import profile  # noqa: F401
from . import slo  # noqa: F401
from . import timeseries  # noqa: F401
from . import trace  # noqa: F401
from . import trace_merge  # noqa: F401
from . import watchdog  # noqa: F401

__all__ = [
    "Counter", "Gauge", "Histogram", "Registry",
    "counter", "gauge", "histogram", "get_registry",
    "enable", "disable", "is_enabled",
    "MetricsServer", "snapshot", "write_snapshot",
    "start_metrics_server", "stop_metrics_server",
    "FlightRecorder", "get_flight_recorder", "diagnose",
    "Heartbeat", "heartbeat", "start_watchdog", "stop_watchdog",
    "is_watchdog_running", "build_bundle", "diagnose_bundles",
    "register_stall_action", "unregister_stall_action",
    "fleet", "flight_recorder", "incidents", "memory", "perf",
    "profile", "slo", "timeseries", "trace", "trace_merge",
    "watchdog",
]
