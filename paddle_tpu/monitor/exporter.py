"""Registry exporters: JSON snapshot artifacts + HTTP /metrics endpoint.

The HTTP side rides the existing fleet KV server
(distributed/fleet/utils/http_server.py) rather than growing a second
server stack: ``KVHTTPServer`` gained a ``get_routes`` hook, and
``MetricsServer`` registers the telemetry routes on it —

    GET /metrics        Prometheus text exposition (scrape target)
    GET /metrics.json   JSON snapshot (tools, dashboards, bench artifacts)
    GET /healthz        ok|stalled verdict + heartbeat ages (503 when
                        stalled — load-balancer/probe friendly)
    GET /debugz/stacks  live all-thread Python stack dump
    GET /debugz/flight  this rank's collective flight-recorder ring
    GET /debugz/bundle  full on-demand diagnostic bundle (stacks +
                        flight ring + metrics + heartbeat ages)
    GET /debugz/perf    MFU/goodput attribution + anomaly state
                        (monitor/perf.py payload)
    GET /debugz/timeseries  the metric time-series rings
                        (monitor/timeseries.py payload)
    GET /debugz/trace   span-journal summary + histogram exemplars
                        (monitor/trace.py payload)
    GET /debugz/trace/journal  the full journal artifact (the
                        write_journal format — what a fleet capture
                        pulls so tools/trace_merge.py can merge it)
    GET /debugz/trace/{id}  one trace's full span timeline (404 for an
                        unknown or evicted trace id) + a
                        ``federation`` block: on a serving-fleet
                        router process the replica-side fragments of
                        the fleet trace, fetched on demand
                        (enabled:false otherwise, zero fetches)
    GET /debugz/memory  memory-plane breakdown: per-component ledger,
                        allocator reconciliation, headroom, recent
                        admission/preempt decisions, OOM postmortems
                        (monitor/memory.py payload)
    GET /debugz/profile continuous-profiling summary: sampler stats,
                        component attribution, top-K folded stacks,
                        measured dispatch/blocked/gap per job, capture
                        windows (monitor/profile.py payload)
    GET /debugz/profile/folded  collapsed-stack text of the host
                        sampling profiler (flamegraph.pl input)
    GET /debugz/fleet   fleet summary: collector state, straggler
                        verdict, fused cross-rank aggregates
                        (monitor/fleet.py payload)
    GET /debugz/fleet/ranks  the per-rank fleet table (step, tokens/s,
                        MFU, heartbeat age, straggler flag — what
                        tools/fleet_top.py renders)
    GET /metrics/fleet  Prometheus federation-style exposition of the
                        fused fleet series (rank-labeled + aggregates)
    GET /debugz/resilience  fault-injection state + recovery/shed
                        counters + watchdog escalation mode
                        (paddle_tpu/resilience payload)
    GET /debugz/router  serving-fleet router summary: replica states
                        (live/draining/evicted), request-outcome
                        counts, affinity-index stats (served via the
                        monitor/fleet.py router hook; reports disabled
                        when FLAGS_serving_fleet is off)
    GET /debugz/router/replicas  the router's per-replica table (url,
                        generation, state, load, queue depth, per-
                        replica dispatch/affinity counts)
    GET /debugz/slo     SLO/error-budget verdicts: per-objective
                        attainment, budget remaining, burn rates per
                        alerting window, active burn alerts
                        (monitor/slo.py payload; enabled:false while
                        FLAGS_monitor_slo is off)
    GET /debugz/incidents  the unified incident table: open + recently
                        resolved incidents with severity, episode
                        counts and evidence links
                        (monitor/incidents.py payload)
    GET /debugz/fleet/incidents  fleet-wide incident timeline merged
                        from every scraped rank's table + the
                        collector's own, clock-offset-aligned and
                        deduped by incident id (monitor/fleet.py)
    GET /debugz/replay  record/replay journal summary + per-request
                        outcome digests (prompt/output token counts,
                        rolling token hash, flag snapshot, trace_id
                        cross-links) + the router's dispatch-decision
                        ring (serving/replay.py payload; reports
                        disabled — without importing the serving
                        package — while FLAGS_serving_replay is off)

The /healthz and /debugz routes are served live from monitor/watchdog.py
whether or not the watchdog thread is running (the verdict just reads
"watchdog: disabled" when it is not).

Snapshot artifacts (``write_snapshot``) carry metadata —
``written_at``/``pid``/caller-supplied context — so bench staleness is
detectable from the artifact itself (VERDICT r5: BENCH_r05 went stale
silently).
"""
from __future__ import annotations

import json
import os
import time

from . import fleet as _fleet
from . import incidents as _incidents
from . import memory as _memory
from . import perf as _perf
from . import profile as _profile
from . import slo as _slo
from . import timeseries as _timeseries
from . import trace as _trace
from . import watchdog as _watchdog
from .registry import get_registry


def snapshot(registry=None, meta=None):
    """Registry snapshot dict wrapped with provenance metadata."""
    reg = registry or get_registry()
    out = {
        "written_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "unix_time": time.time(),
        "pid": os.getpid(),
        "metrics": reg.snapshot(),
    }
    if meta:
        out["meta"] = dict(meta)
    return out


def write_snapshot(path, registry=None, meta=None):
    """Dump the snapshot JSON artifact; returns the snapshot dict."""
    snap = snapshot(registry, meta)
    d = os.path.dirname(os.path.abspath(path))
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        json.dump(snap, f, indent=1, default=str)
        f.write("\n")
    return snap


class MetricsServer:
    """Serve the registry over HTTP via the fleet KV server.

    >>> srv = MetricsServer(port=0).start()
    >>> urllib.request.urlopen(
    ...     "http://127.0.0.1:%d/metrics" % srv.port).read()
    """

    def __init__(self, port=0, registry=None):
        from ..distributed.fleet.utils.http_server import KVServer

        self._registry = registry or get_registry()
        self._kv = KVServer(port)
        routes = self._kv.http_server.get_routes
        routes["metrics"] = self._prometheus
        routes["metrics.json"] = self._json
        routes["healthz"] = _watchdog.http_healthz
        routes["debugz/stacks"] = _watchdog.http_stacks
        routes["debugz/flight"] = _watchdog.http_flight
        routes["debugz/bundle"] = _watchdog.http_bundle
        routes["debugz/perf"] = self._perf
        routes["debugz/timeseries"] = self._timeseries
        routes["debugz/trace"] = self._trace
        # exact routes win over the debugz/trace prefix dispatch, so
        # "journal" can never be misread as a trace id
        routes["debugz/trace/journal"] = self._trace_journal
        routes["debugz/memory"] = self._memory
        routes["debugz/profile"] = self._profile
        routes["debugz/profile/folded"] = self._profile_folded
        routes["debugz/resilience"] = self._resilience
        routes["debugz/fleet"] = self._fleet
        routes["debugz/fleet/ranks"] = self._fleet_ranks
        routes["metrics/fleet"] = self._fleet_prometheus
        routes["debugz/router"] = self._router
        routes["debugz/router/replicas"] = self._router_replicas
        routes["debugz/slo"] = self._slo
        routes["debugz/incidents"] = self._incidents
        routes["debugz/fleet/incidents"] = self._fleet_incidents
        routes["debugz/replay"] = self._replay
        self._kv.http_server.get_prefix_routes["debugz/trace"] = \
            self._trace_by_id

    @property
    def port(self):
        return self._kv.port

    def start(self):
        self._kv.start()
        return self

    def stop(self):
        self._kv.stop()

    # -- route registration (serving/fleet rides the same server) ------

    def add_route(self, path, fn):
        """Register a GET route: ``fn() -> (code, ctype, body)``."""
        self._kv.http_server.get_routes[path.strip("/")] = fn

    def add_prefix_route(self, prefix, fn):
        """Register a parametric GET route: ``fn(rest) -> ...``."""
        self._kv.http_server.get_prefix_routes[prefix.strip("/")] = fn

    def add_post_route(self, path, fn):
        """Register a POST route: ``fn(body) -> (code, ctype, body)``."""
        self._kv.http_server.post_routes[path.strip("/")] = fn

    def _prometheus(self):
        body = self._registry.prometheus_text().encode()
        return 200, "text/plain; version=0.0.4; charset=utf-8", body

    def _json(self):
        # json_safe: a NaN gauge (the sentinel's input) must not turn
        # the scrape into an unparseable bare-NaN body mid-incident
        body = json.dumps(_watchdog.json_safe(snapshot(self._registry)),
                          default=str).encode()
        return 200, "application/json", body

    def _perf(self):
        body = json.dumps(_watchdog.json_safe(_perf.perf_payload()),
                          default=str).encode()
        return 200, "application/json", body

    def _timeseries(self):
        body = json.dumps(_watchdog.json_safe(_timeseries.payload()),
                          default=str).encode()
        return 200, "application/json", body

    def _trace(self):
        body = json.dumps(_watchdog.json_safe(_trace.payload()),
                          default=str).encode()
        return 200, "application/json", body

    def _trace_journal(self):
        body = json.dumps(_watchdog.json_safe(_trace.dump()),
                          default=str).encode()
        return 200, "application/json", body

    def _memory(self):
        body = json.dumps(_watchdog.json_safe(_memory.memory_payload()),
                          default=str).encode()
        return 200, "application/json", body

    def _profile(self):
        body = json.dumps(
            _watchdog.json_safe(_profile.profile_payload()),
            default=str).encode()
        return 200, "application/json", body

    def _profile_folded(self):
        return (200, "text/plain; charset=utf-8",
                _profile.folded_route_text().encode())

    def _fleet(self):
        body = json.dumps(_watchdog.json_safe(_fleet.fleet_payload()),
                          default=str).encode()
        return 200, "application/json", body

    def _fleet_ranks(self):
        body = json.dumps(_watchdog.json_safe(_fleet.ranks_payload()),
                          default=str).encode()
        return 200, "application/json", body

    def _fleet_prometheus(self):
        body = _fleet.prometheus_fleet_text().encode()
        return 200, "text/plain; version=0.0.4; charset=utf-8", body

    def _router(self):
        # serving-fleet router summary: served via monitor/fleet.py's
        # duck-typed hook slot so the monitor plane never imports the
        # serving package (flag off / no router = pinned disabled body)
        body = json.dumps(_watchdog.json_safe(_fleet.router_payload()),
                          default=str).encode()
        return 200, "application/json", body

    def _router_replicas(self):
        body = json.dumps(
            _watchdog.json_safe(_fleet.router_replicas_payload()),
            default=str).encode()
        return 200, "application/json", body

    def _slo(self):
        body = json.dumps(_watchdog.json_safe(_slo.payload()),
                          default=str).encode()
        return 200, "application/json", body

    def _incidents(self):
        body = json.dumps(_watchdog.json_safe(_incidents.payload()),
                          default=str).encode()
        return 200, "application/json", body

    def _fleet_incidents(self):
        body = json.dumps(
            _watchdog.json_safe(_fleet.fleet_incidents_payload()),
            default=str).encode()
        return 200, "application/json", body

    def _replay(self):
        # lazier than the /debugz/resilience route: the serving
        # package pulls in the accelerator backend, so the monitor
        # plane must not import it just to say "disabled" — serve the
        # module only if an engine (or tool) already imported it. The
        # literal below is pinned bit-identical to
        # serving/replay.payload()'s disabled body by
        # tests/test_debugz_routes.py.
        import sys

        mod = sys.modules.get("paddle_tpu.serving.replay")
        if mod is None:
            p = {"enabled": False, "requests": [], "dispatches": 0}
        else:
            p = mod.payload()
        body = json.dumps(_watchdog.json_safe(p), default=str).encode()
        return 200, "application/json", body

    def _resilience(self):
        # lazy: paddle_tpu.resilience imports back into monitor — the
        # route resolves at request time, never at module import
        from ..resilience import payload as _resilience_payload

        body = json.dumps(_watchdog.json_safe(_resilience_payload()),
                          default=str).encode()
        return 200, "application/json", body

    def _trace_by_id(self, rest):
        trace_id, _, query = rest.partition("?")
        p = _trace.trace_payload(trace_id)
        if p is None:
            return (404, "application/json",
                    json.dumps({"error": "unknown trace",
                                "trace_id": trace_id}).encode())
        # on a router process the trace is fleet-wide: federate the
        # replica-side fragments on demand (enabled:false — and zero
        # cross-replica fetches — without FLAGS_serving_fleet + a
        # running router; the 404-for-unknown contract is unchanged).
        # ``?local=1`` pins the LOCAL view: the router's own federation
        # fetches ask for it, so a fragment request can never recurse
        # into another fan-out (loop-proofs a misconfigured topology
        # where a router's endpoint resolves back to a router process)
        if "local=1" not in query.split("&"):
            p["federation"] = _fleet.router_trace_federation(trace_id)
        body = json.dumps(_watchdog.json_safe(p), default=str).encode()
        return 200, "application/json", body


_server = None


def start_metrics_server(port=0, registry=None):
    """Start (or return the running) process-wide metrics endpoint."""
    global _server
    if _server is None:
        _server = MetricsServer(port, registry).start()
    return _server


def stop_metrics_server():
    global _server
    if _server is not None:
        _server.stop()
        _server = None
