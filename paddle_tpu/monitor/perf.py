"""MFU / goodput attribution + regression sentinels.

The monitor stack so far can say *that* a step ran (registry), *that* a
rank hung (watchdog), and *what* went over the wire (flight recorder) —
but not whether the step was any good. This module closes that gap with
the PaLM-style MFU recipe: analytic/measured FLOPs over measured wall
clock, phase-attributed, watched continuously.

1. **Attribution** (``TrainStepPerf``): the compiled train step's
   executable is asked what it actually is — ``cost_analysis()`` FLOPs
   and ``memory_analysis()`` peak bytes (the llama7b_plan fallback when
   the jaxlib build lacks the buffer-assignment peak) — and combined
   with the measured step wall time into:

     ``mfu{job}``               model-FLOPs utilization vs the machine
                                peak (cost_model.py MachineSpec; env
                                PT_PERF_PEAK_FLOPS overrides)
     ``model_flops{job}``       FLOPs of one optimizer step
     ``model_flops_per_s{job}`` achieved FLOP rate over the step window
     ``hbm_peak_bytes{job}``    executable HBM high-water mark
     ``perf_phase_seconds{job,phase}``  compute / comm / host split:
         host = inter-step gap on the driving thread, comm = measured
         eager-collective bracket time (flight-recorder entries by seq,
         wire bytes attached) or the analytic grad-sync estimate
         (bytes / ICI bw) when the collectives are compiled-implicit,
         compute = the step-call remainder.

   The serving engine publishes the serving analogs (per-token goodput
   — finished-request tokens only, preempted-and-recomputed work
   excluded — and KV-page occupancy) through serving/metrics.py, and
   mirrors them here via ``note_job("serving", ...)``.

2. **Sentinels**: pluggable detectors subscribed to the time-series
   ring (monitor/timeseries.py): NaN/inf loss, loss spike vs EWMA,
   throughput regression vs a rolling baseline, grad-norm explosion.
   A firing increments ``perf_anomalies_total{kind}``, drops a
   structured event into the flight-recorder ring, and flips the
   ``degraded`` flag that /healthz reports — the "loss went NaN two
   hours ago and nobody noticed" failure mode becomes a scrape-able,
   probe-able signal. Detectors are armed only after their warmup
   window; a clean warmup can never fire.

Gating (FLAGS precedent, all default-off): ``FLAGS_perf_attribution``
for (1) — it costs one AOT lower+compile of the step and one
loss-scalar host readback per step; ``FLAGS_perf_sentinels`` for (2) —
it implies the ``FLAGS_monitor_timeseries`` ring. Disabled = zero
native calls, zero extra threads, registry hot path unchanged
(test-pinned). Module import stays stdlib-only; jax objects only ever
arrive as arguments.
"""
from __future__ import annotations

import math
import os
import threading
import time

from . import registry as _registry
from . import timeseries as _timeseries
from .flight_recorder import get_flight_recorder
from .timeseries import _flag

# -- metrics (shared registry; every mutator no-ops when disabled) ----------

_MFU = _registry.gauge(
    "mfu", "model-FLOPs utilization of the last step window vs the "
    "machine peak (monitor/perf.py attribution)", labelnames=("job",))
_MODEL_FLOPS = _registry.gauge(
    "model_flops", "FLOPs of one optimizer step (XLA cost_analysis of "
    "the compiled executable)", labelnames=("job",))
_FLOPS_RATE = _registry.gauge(
    "model_flops_per_s", "achieved model FLOP/s over the last step "
    "window", labelnames=("job",))
_HBM_PEAK = _registry.gauge(
    "hbm_peak_bytes", "compiled-executable HBM high-water mark "
    "(memory_analysis; upper-bound estimate on jaxlib builds without "
    "the buffer-assignment peak)", labelnames=("job",))
_PHASE = _registry.gauge(
    "perf_phase_seconds", "last-window phase attribution: compute | "
    "comm | host", labelnames=("job", "phase"))
_TRAIN_LOSS = _registry.gauge(
    "train_loss", "last train-step loss (host readback under "
    "FLAGS_perf_attribution; the NaN/spike sentinels watch this "
    "series)", labelnames=("job",))
_ANOMALIES = _registry.counter(
    "perf_anomalies_total", "sentinel firings by kind",
    labelnames=("kind",))

_EVENTS_CAP = 64


class _PerfState:
    __slots__ = ("lock", "jobs", "events", "degraded_since",
                 "anomaly_counts", "sentinels", "listener_installed")

    def __init__(self):
        self.lock = threading.Lock()
        self.jobs = {}              # job -> last attribution report
        self.events = []            # recent anomaly events (bounded)
        self.degraded_since = None
        self.anomaly_counts = {}    # kind -> count (payload mirror)
        self.sentinels = []
        self.listener_installed = False


_state = _PerfState()


def attribution_enabled():
    return _flag("FLAGS_perf_attribution")


def sentinels_enabled():
    return _state.listener_installed


_machine_cache = None


def machine_spec():
    """Per-chip peak numbers: the auto-parallel cost model's
    MachineSpec (~v5e) with PT_PERF_{PEAK_FLOPS,HBM_BW,ICI_BW} env
    overrides — the denominator of every MFU in this module."""
    global _machine_cache
    if _machine_cache is None:
        try:
            from ..distributed.auto_parallel.cost_model import MachineSpec

            m = MachineSpec()
            spec = {"peak_flops": m.peak_flops, "hbm_bw": m.hbm_bw,
                    "ici_bw": m.ici_bw}
        except Exception:
            spec = {"peak_flops": 197e12, "hbm_bw": 819e9,
                    "ici_bw": 45e9}
        for key, env in (("peak_flops", "PT_PERF_PEAK_FLOPS"),
                         ("hbm_bw", "PT_PERF_HBM_BW"),
                         ("ici_bw", "PT_PERF_ICI_BW")):
            raw = os.environ.get(env)
            if raw:
                try:
                    spec[key] = float(raw)
                except ValueError:
                    pass
        _machine_cache = spec
    return dict(_machine_cache)


# -- executable analysis -----------------------------------------------------

def executable_analysis(compiled, steps=1, memory_only=False):
    """FLOPs + HBM accounting of one compiled executable (a jax AOT
    ``Compiled`` — passed in, never imported). ``steps`` divides the
    totals for multi-step modules. ``memory_only`` skips the
    cost_analysis FLOPs walk for callers (monitor/memory.py
    ``compiled_peak``) that only need the peak — the peak RULE still
    lives here and nowhere else. Never raises: perf attribution must
    not take down a training run."""
    out = {"source": "xla_cost_analysis", "steps_per_call": int(steps)}
    steps = max(int(steps), 1)
    if not memory_only:
        try:
            ca = compiled.cost_analysis()
            d = ca[0] if isinstance(ca, (list, tuple)) and ca else ca
            if d:
                flops = float(d.get("flops", 0.0))
                if flops > 0:
                    out["flops_per_step"] = flops / steps
                ba = float(d.get("bytes accessed", 0.0))
                if ba > 0:
                    out["bytes_accessed_per_step"] = ba / steps
        # ptlint: silent-except-ok — cost_analysis is a
        # backend-optional introspection API; absent fields are the
        # documented contract
        except Exception:
            pass
    try:
        ma = compiled.memory_analysis()
        arg = int(ma.argument_size_in_bytes)
        tmp = int(ma.temp_size_in_bytes)
        outb = int(ma.output_size_in_bytes)
        alias = int(ma.alias_size_in_bytes)
        out["argument_bytes"] = arg
        out["temp_bytes"] = tmp
        out["output_bytes"] = outb
        peak = getattr(ma, "peak_memory_in_bytes", None)
        if not peak:
            # llama7b_plan fallback: args + temps + outputs net of
            # donation aliasing — an over-estimate (liveness overlap is
            # ignored), flagged so readers don't mistake it for the
            # scheduler's real high-water mark
            peak = arg + tmp + outb - alias
            out["hbm_peak_is_estimate"] = True
        out["hbm_peak_bytes"] = int(peak)
    # ptlint: silent-except-ok — memory_analysis is a backend-optional
    # introspection API; absent fields are the documented contract
    except Exception:
        pass
    return out


def bench_fields(analysis, tokens_per_s=None, tokens_per_step=None,
                 peak_flops=None):
    """Bench-row JSON fields from an ``executable_analysis`` dict:
    ``mfu`` / ``model_flops_per_step`` / ``hbm_peak_bytes`` — the
    hardware-normalized form of a raw tokens/s number (bench.py and
    tools/model_benchmark.py emit these)."""
    out = {}
    if not analysis:
        return out
    flops = analysis.get("flops_per_step")
    if flops:
        out["model_flops_per_step"] = round(flops)
    if "hbm_peak_bytes" in analysis:
        out["hbm_peak_bytes"] = analysis["hbm_peak_bytes"]
        if analysis.get("hbm_peak_is_estimate"):
            out["hbm_peak_is_estimate"] = True
    peak = peak_flops or machine_spec()["peak_flops"]
    if flops and tokens_per_s and tokens_per_step:
        steps_per_s = tokens_per_s / float(tokens_per_step)
        out["model_flops_per_s"] = round(flops * steps_per_s)
        # 3 significant digits, never rounded to a flat 0: a CPU smoke
        # MFU of 3.6e-6 must stay a real number in the artifact
        out["mfu"] = float("%.3g" % (flops * steps_per_s / peak))
        out["mfu_peak_flops"] = peak
    return out


# -- train-step attribution --------------------------------------------------

class TrainStepPerf:
    """Per-train-step attribution for one engine instance. The engine
    calls ``on_step`` once per compiled call; the first call resolves
    ``analysis_fn`` (the engine's AOT lower+compile of its own step —
    one extra compile, under the opt-in flag)."""

    def __init__(self, job, analysis_fn=None, machine=None):
        self.job = job
        self._analysis_fn = analysis_fn
        self.analysis = None
        self._analysis_tried = False
        self.machine = machine or machine_spec()
        self._last_end = None       # perf_counter of the previous call end
        self._fr_seq = None         # flight-recorder seq watermark

    def _resolve_analysis(self):
        if self._analysis_tried:
            return
        self._analysis_tried = True
        fn, self._analysis_fn = self._analysis_fn, None
        if fn is None:
            return
        try:
            self.analysis = fn() or None
        except Exception:
            self.analysis = None
        # fn (and with it the closure-captured device batch) is
        # dropped either way: a one-shot analysis must not pin
        # batch-sized arrays in HBM for the run's lifetime

    def _comm_since_last(self):
        """(seconds, wire_bytes, source) of eager collectives since the
        previous step, by flight-recorder sequence watermark (timestamps
        live in a different clock domain than the engine's perf_counter
        — seq comparison is domain-free). Falls back to the analytic
        grad-sync estimate when the collectives are compiled-implicit
        (no eager entries): bytes published by distributed/compress.py
        over the ICI bandwidth."""
        fr = get_flight_recorder()
        mark = self._fr_seq
        self._fr_seq = fr._seq
        comm_s, wire = 0.0, 0
        if mark is not None and fr._seq > mark:
            for e in fr.entries():
                seq = e.get("seq")
                if seq is None or seq < mark:
                    continue
                t0, t1 = e.get("t_start"), e.get("t_end")
                if t0 is not None and t1 is not None:
                    comm_s += max(t1 - t0, 0.0)
                wire += int(e.get("wire_bytes", 0) or 0)
            if comm_s > 0 or wire > 0:
                return comm_s, wire, "flight_recorder"
        # analytic fallback: the compiled-path grad sync is invisible to
        # the eager recorder; use its published per-step wire bytes
        try:
            g = _registry.get_registry().get("grad_sync_bytes_per_step")
            if g is not None:
                vals = [v for _, v in g.collect()]
                nbytes = max(vals) if vals else 0
                if nbytes > 0:
                    return (nbytes / self.machine["ici_bw"], int(nbytes),
                            "analytic")
        # ptlint: silent-except-ok — absent/odd comm metric degrades
        # the overlap attribution to "none", which is the fallback row
        except Exception:
            pass
        return 0.0, 0, "none"

    def on_step(self, dt, steps=1, tokens=0, loss=None, t_start=None,
                t_end=None):
        """Publish attribution for one engine call covering ``steps``
        optimizer steps and ``tokens`` batch tokens, measured at ``dt``
        seconds of host wall (dispatch + blocking)."""
        if t_end is None:
            t_end = time.perf_counter()
        host_s = 0.0
        if self._last_end is not None and t_start is not None:
            host_s = max(t_start - self._last_end, 0.0)
        self._last_end = t_end
        self._resolve_analysis()
        comm_s, wire, comm_source = self._comm_since_last()
        comm_s = min(comm_s, dt + host_s)
        compute_s = max(dt - comm_s, 0.0)
        window = max(dt + host_s, 1e-12)
        # shares normalize over the SUM of attributed seconds, not the
        # window: comm measured in the inter-step gap (a background
        # sync thread) can exceed dt, and the split must still read as
        # fractions of a whole (== the window whenever comm fits
        # inside the step call)
        attributed = max(compute_s + comm_s + host_s, 1e-12)
        job = self.job
        report = {
            "steps": steps,
            "tokens": tokens,
            "step_seconds": dt,
            "window_seconds": window,
            "tokens_per_s": tokens / window if tokens else 0.0,
            "phase_seconds": {"compute": compute_s, "comm": comm_s,
                              "host": host_s},
            "phase_share": {
                "compute": compute_s / attributed,
                "comm": comm_s / attributed,
                "host": host_s / attributed,
            },
            "comm_source": comm_source,
            "comm_wire_bytes": wire,
            "peak_flops": self.machine["peak_flops"],
        }
        a = self.analysis
        if a:
            flops = a.get("flops_per_step")
            if flops:
                rate = flops * steps / window
                report["model_flops_per_step"] = flops
                report["model_flops_per_s"] = rate
                report["mfu"] = rate / self.machine["peak_flops"]
                _MODEL_FLOPS.labels(job=job).set(flops)
                _FLOPS_RATE.labels(job=job).set(rate)
                _MFU.labels(job=job).set(report["mfu"])
            if "hbm_peak_bytes" in a:
                report["hbm_peak_bytes"] = a["hbm_peak_bytes"]
                if a.get("hbm_peak_is_estimate"):
                    report["hbm_peak_is_estimate"] = True
                _HBM_PEAK.labels(job=job).set(a["hbm_peak_bytes"])
        for phase, v in report["phase_seconds"].items():
            _PHASE.labels(job=job, phase=phase).set(v)
        if loss is not None:
            try:
                lv = float(loss)
            except Exception:
                lv = None
            if lv is not None:
                report["loss"] = lv
                # nan/inf flow through on purpose: this gauge IS the
                # sentinel's input series
                _TRAIN_LOSS.labels(job=job).set(lv)
        note_job(job, **report)
        return report


def note_job(job, **fields):
    """Merge the latest attribution numbers for ``job`` into the
    /debugz/perf payload (serving/metrics.py mirrors goodput/occupancy
    here; train steps publish their whole report)."""
    fields["updated_at"] = time.time()
    with _state.lock:
        cur = _state.jobs.setdefault(job, {})
        cur.update(fields)


# -- sentinels ---------------------------------------------------------------

class Sentinel:
    """One detector over one ring series (matched by exact name or by
    ``name{...labels}`` prefix). Subclasses implement ``check(state,
    value)`` returning a detail dict to fire, None to stay quiet; the
    base class handles warmup (never fire before ``warmup`` samples)
    and a refire cooldown so a persistent condition counts episodes,
    not samples."""

    kind = "anomaly"

    def __init__(self, series, warmup=0, cooldown=None):
        self.series = series
        self.warmup = int(warmup)
        self.cooldown = int(cooldown if cooldown is not None
                            else max(warmup, 1))
        self._per_series = {}

    def matches(self, name):
        return name == self.series or name.startswith(self.series + "{")

    def _new_state(self):
        return {"n": 0, "cool": 0}

    def observe(self, name, ts, value):
        st = self._per_series.get(name)
        if st is None:
            st = self._per_series[name] = self._new_state()
        fired = None
        if st["n"] >= self.warmup and st["cool"] <= 0:
            fired = self.check(st, value)
            if fired is not None:
                st["cool"] = self.cooldown
        elif st["cool"] > 0:
            st["cool"] -= 1
        self.update(st, value)
        st["n"] += 1
        return fired

    def check(self, st, value):
        return None

    def update(self, st, value):
        pass

    def recovered(self, name):
        """Consume the recovery edge for ``name``: True exactly once
        after the sentinel's episode latch clears (subclasses set
        ``st["recovered"]`` when their condition ends). The incident
        table resolves on this edge — detection stays in the
        sentinel, aggregation in monitor/incidents.py."""
        st = self._per_series.get(name)
        return bool(st) and bool(st.pop("recovered", False))


class NaNLossSentinel(Sentinel):
    """Non-finite loss. Latched: one firing per contiguous non-finite
    run (a 10k-step NaN tail is one incident, not 10k)."""

    kind = "nan_loss"

    def __init__(self, series="train_loss", warmup=0):
        super().__init__(series, warmup=warmup, cooldown=0)

    def check(self, st, value):
        bad = not math.isfinite(value)
        if bad and not st.get("latched"):
            st["latched"] = True
            return {"value": repr(value)}
        if not bad and st.get("latched"):
            st["latched"] = False
            st["recovered"] = True
        return None


class LossSpikeSentinel(Sentinel):
    """Finite loss far above its EWMA. Non-finite samples are the NaN
    sentinel's domain — skipped entirely here (no fire, no stat
    update)."""

    kind = "loss_spike"

    def __init__(self, series="train_loss", warmup=8, alpha=0.3,
                 factor=3.0):
        super().__init__(series, warmup=warmup)
        self.alpha = alpha
        self.factor = factor

    def check(self, st, value):
        if not math.isfinite(value):
            return None
        mean, dev = st.get("mean"), st.get("dev", 0.0)
        if mean is None:
            return None
        thr = mean + self.factor * max(dev, 0.1 * abs(mean), 1e-9)
        if value > thr:
            st["spiking"] = True
            return {"value": value, "ewma": mean, "threshold": thr}
        if st.get("spiking"):
            st["spiking"] = False
            st["recovered"] = True
        return None

    def update(self, st, value):
        if not math.isfinite(value):
            return
        mean = st.get("mean")
        if mean is None:
            st["mean"], st["dev"] = value, 0.0
            return
        a = self.alpha
        st["dev"] = (1 - a) * st.get("dev", 0.0) + a * abs(value - mean)
        st["mean"] = (1 - a) * mean + a * value


class ThroughputRegressionSentinel(Sentinel):
    """Throughput below a fraction of its rolling-window baseline — the
    "the run quietly got 2x slower" detector over tokens/s."""

    kind = "throughput_regression"

    def __init__(self, series="train_tokens_per_s", warmup=8,
                 window=None, drop=0.5):
        super().__init__(series, warmup=warmup)
        self.window = int(window or max(warmup, 4))
        self.drop = drop

    def check(self, st, value):
        if not math.isfinite(value):
            return None
        win = st.get("win") or []
        if len(win) < self.window:
            return None
        baseline = sorted(win)[len(win) // 2]    # median
        thr = baseline * (1.0 - self.drop)
        if baseline > 0 and value < thr:
            st["cliff"] = True
            return {"value": value, "baseline": baseline,
                    "threshold": thr}
        if st.get("cliff"):
            st["cliff"] = False
            st["recovered"] = True
        return None

    def update(self, st, value):
        if not math.isfinite(value):
            return
        win = st.setdefault("win", [])
        win.append(value)
        if len(win) > self.window:
            del win[:len(win) - self.window]


class GradNormSentinel(Sentinel):
    """Gradient-norm explosion: norm a multiplicative factor above its
    EWMA. Watches ``train_grad_norm`` — published by whoever computes
    norms (a clipping optimizer, user code); inert when nobody does."""

    kind = "grad_norm_explosion"

    def __init__(self, series="train_grad_norm", warmup=8, alpha=0.3,
                 factor=10.0):
        super().__init__(series, warmup=warmup)
        self.alpha = alpha
        self.factor = factor

    def check(self, st, value):
        if not math.isfinite(value):
            return None
        mean = st.get("mean")
        if mean is None or mean <= 0:
            return None
        if value > self.factor * mean:
            st["exploding"] = True
            return {"value": value, "ewma": mean,
                    "threshold": self.factor * mean}
        if st.get("exploding"):
            st["exploding"] = False
            st["recovered"] = True
        return None

    def update(self, st, value):
        if not math.isfinite(value):
            return
        mean = st.get("mean")
        st["mean"] = value if mean is None \
            else (1 - self.alpha) * mean + self.alpha * value


def default_sentinels():
    return [NaNLossSentinel(), LossSpikeSentinel(),
            ThroughputRegressionSentinel(), GradNormSentinel()]


def _fire(sentinel, name, ts, value, detail):
    kind = sentinel.kind
    event = {
        "kind": kind,
        "series": name,
        "ts": ts,
        "detail": detail,
    }
    with _state.lock:
        _state.anomaly_counts[kind] = \
            _state.anomaly_counts.get(kind, 0) + 1
        if _state.degraded_since is None:
            _state.degraded_since = ts
        _state.events.append(event)
        if len(_state.events) > _EVENTS_CAP:
            del _state.events[:len(_state.events) - _EVENTS_CAP]
    try:
        _ANOMALIES.labels(kind=kind).inc()
    except Exception as e:
        _registry.warn_once(
            "perf.anomaly_counter",
            "paddle_tpu.monitor.perf: anomaly counter increment "
            "failed (event ring still recorded it): %r" % (e,))
    try:
        get_flight_recorder().note_event(
            "perf_anomaly", anomaly_kind=kind, series=name,
            value=repr(value), detail=detail)
    except Exception as e:
        _registry.warn_once(
            "perf.anomaly_flight_note",
            "paddle_tpu.monitor.perf: flight-recorder anomaly note "
            "failed: %r" % (e,))
    # ptprof (monitor/profile.py): profile-shaped anomalies
    # (throughput cliff, mem leak) arm a one-shot device-capture
    # window around the next hot steps, so the Xprof artifact is of
    # the ANOMALOUS steps. Lazy import, no-op while the plane is off.
    try:
        from . import profile as _profile

        _profile.on_anomaly(kind)
    except Exception as e:
        _registry.warn_once(
            "perf.profile_arm",
            "paddle_tpu.monitor.perf: profile capture arming failed "
            "(anomaly was still recorded above): %r" % (e,))
    # ptslo (monitor/incidents.py): every firing is also an incident —
    # episode-keyed on (kind, series) so a persistent condition is ONE
    # open incident that re-fires extend. Lazy import, one flag branch
    # while the plane is off.
    try:
        from . import incidents as _incidents

        _incidents.open(
            "perf/%s/%s" % (kind, name),
            severity=("page" if kind in ("nan_loss",
                                         "grad_norm_explosion")
                      else "ticket"),
            kind=kind, source="perf",
            summary="%s on %s" % (kind, name),
            evidence={"series": name, "detail": detail})
    except Exception as e:
        _registry.warn_once(
            "perf.incident_open",
            "paddle_tpu.monitor.perf: incident open failed (anomaly "
            "was still recorded above): %r" % (e,))


def _recover(sentinel, name):
    """The episode's recovery edge: resolve the matching incident.
    Detection (and the latch) stays in the sentinel — this only
    reports the edge to the table."""
    try:
        from . import incidents as _incidents

        _incidents.resolve("perf/%s/%s" % (sentinel.kind, name),
                           reason="sentinel recovered")
    except Exception as e:
        _registry.warn_once(
            "perf.incident_resolve",
            "paddle_tpu.monitor.perf: incident resolve failed "
            "(sentinel state already recovered): %r" % (e,))


def _dispatch(name, ts, value):
    """The timeseries listener: route each ring append through every
    matching sentinel. Must never raise (it runs inline on the metric
    hot path while sentinels are enabled)."""
    for s in list(_state.sentinels):
        try:
            if s.matches(name):
                detail = s.observe(name, ts, value)
                if detail is not None:
                    _fire(s, name, ts, value, detail)
                elif s.recovered(name):
                    _recover(s, name)
        except Exception as e:
            # must never raise (inline on the metric hot path), but a
            # sentinel dying forever deserves one line
            _registry.warn_once(
                "perf.sentinel.%s" % type(s).__name__,
                "paddle_tpu.monitor.perf: sentinel %s raised while "
                "observing %r (sentinel stays enabled): %r"
                % (type(s).__name__, name, e))


def enable_sentinels(sentinels=None):
    """Install the detector set (default: NaN loss, loss spike,
    throughput regression, grad-norm explosion) over the time-series
    ring — enabling the ring if it is off (detectors read it)."""
    _state.sentinels = list(sentinels if sentinels is not None
                            else default_sentinels())
    if not _timeseries.is_enabled():
        _timeseries.enable()
    _timeseries.add_listener(_dispatch)
    _state.listener_installed = True


def add_sentinel(sentinel):
    """Plug one more detector into the enabled set."""
    if not _state.listener_installed:
        enable_sentinels([])
    _state.sentinels.append(sentinel)
    return sentinel


def disable_sentinels():
    _timeseries.remove_listener(_dispatch)
    _state.listener_installed = False
    _state.sentinels = []


def is_degraded():
    return _state.degraded_since is not None


def clear_anomalies():
    """Acknowledge the incident: the degraded flag and recent-event
    list reset (the ``perf_anomalies_total`` counter is monotone and
    keeps its history)."""
    with _state.lock:
        _state.degraded_since = None
        _state.events = []
        _state.anomaly_counts = {}
    # the incident table is the healthz source of truth while the SLO
    # plane is on — acknowledging here must clear it there too, or the
    # flag would change what clear_anomalies means (pinned equivalent).
    try:
        from . import incidents as _incidents

        _incidents.resolve_source("perf", reason="anomalies cleared")
    except Exception as e:
        _registry.warn_once(
            "perf.incident_clear",
            "paddle_tpu.monitor.perf: incident clear failed (local "
            "anomaly state was still reset): %r" % (e,))


def anomaly_summary():
    with _state.lock:
        return {
            "degraded": _state.degraded_since is not None,
            "degraded_since": _state.degraded_since,
            "counts": dict(_state.anomaly_counts),
            "recent": list(_state.events[-8:]),
        }


# -- payload / routes --------------------------------------------------------

def perf_payload():
    """The /debugz/perf JSON body: per-job attribution + anomaly state
    + the machine model the MFUs were computed against."""
    with _state.lock:
        jobs = {j: dict(r) for j, r in _state.jobs.items()}
    return {
        "enabled": {
            "attribution": attribution_enabled(),
            "timeseries": _timeseries.is_enabled(),
            "sentinels": sentinels_enabled(),
        },
        "machine": machine_spec(),
        "jobs": jobs,
        "anomalies": anomaly_summary(),
        "time": time.time(),
    }


def reset():
    """Test hook: forget job reports and anomaly state."""
    clear_anomalies()
    with _state.lock:
        _state.jobs = {}


# env/FLAGS bootstrap, mirroring timeseries: sentinels armed from the
# first sample in a process started with FLAGS_perf_sentinels=1
if _flag("FLAGS_perf_sentinels"):
    enable_sentinels()
