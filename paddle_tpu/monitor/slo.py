"""SLO / error-budget plane: declarative objectives judged over the
existing timeseries ring, with multi-window multi-burn-rate alerting.

The sentinels (monitor/perf.py) answer "did something anomalous just
happen?"; this module answers the operator question behind ROADMAP
items 6/7: "are we meeting our latency/availability objectives, and
how fast are we spending the error budget?". Division of labor:
sentinels/watchdog/fleet **detect**, monitor/incidents.py
**aggregates**, this module **judges**.

Design, in the shape the Gemma-serving methodology (PAPERS.md) uses:

* **Objectives are declarative.** An :class:`Objective` names a ring
  series and a goodness rule: ``latency`` (sample good when value <=
  threshold — TTFT/TPOT/e2e histogram observations ride the ring raw,
  the PR-5 contract), ``floor`` (good when value >= threshold —
  training goodput/step-time floors over gauges), or ``availability``
  (cumulative counter deltas: good events vs shed/expired events,
  attainment = 1 - bad fraction). No new sampling path exists: the
  evaluator is a plain ``timeseries.add_listener`` consumer of the
  PR-5 fan-out, so anything the ring sees the judge sees.

* **Windows live on the monotonic clock.** Every event is stamped
  with ``clock()`` (``time.monotonic`` by default, injectable for
  deterministic tests — the ElasticManager/Router precedent); wall
  time never enters window math. ``PT_SLO_WINDOW_SCALE`` scales all
  four windows so tests exercise real multi-window behavior in
  milliseconds.

* **Multi-window multi-burn-rate alerting** (the SRE playbook): burn
  rate = (1 - attainment) / (1 - target); an alert opens only when a
  fast AND slow window pair BOTH exceed the pair's burn threshold
  (fast window = reactivity, slow window = evidence), and resolves
  when the fast window recovers. The page pair (60s/600s, burn 10x)
  and ticket pair (300s/3600s, burn 2x) give severity for free.
  Alerts are incidents: they open/extend/resolve through
  monitor/incidents.py like every other detector.

Discipline: default OFF behind ``FLAGS_monitor_slo``; the disabled
path is one enabled-load + branch, with zero threads (this module
never starts one — evaluation piggybacks on whatever thread recorded
the sample), zero native calls, zero registry series. Engines latch
at construction: enabling the flag mid-run affects only samples
recorded after ``enable()``. The gauges this module publishes
(``slo_*``) re-enter ``timeseries.record`` once; ``_observe`` ignores
them (no objective may target an ``slo_``/``incident_`` series) and a
reentrancy latch makes that a hard guarantee.
"""
from __future__ import annotations

import os
import threading
import time
from collections import deque

from . import registry as _registry
from . import timeseries as _timeseries
from .timeseries import _flag

# burn-rate grades: fast window reacts, slow window confirms, the
# pair's threshold is the burn multiple BOTH must exceed.  Env scale
# lets tests shrink hours to milliseconds without forking the math.
_GRADES = (
    {"grade": "page", "fast_s": 60.0, "slow_s": 600.0, "burn": 10.0},
    {"grade": "ticket", "fast_s": 300.0, "slow_s": 3600.0, "burn": 2.0},
)

_ATTAINMENT = _registry.gauge(
    "slo_attainment_ratio",
    "fraction of good events over the budget (ticket-slow) window",
    labelnames=("objective", "job"))
_BUDGET = _registry.gauge(
    "slo_error_budget_remaining_ratio",
    "error budget remaining over the budget window (1 = untouched, "
    "0 = exhausted)", labelnames=("objective", "job"))
_BURN = _registry.gauge(
    "slo_burn_rate",
    "error-budget burn multiple per alerting window "
    "(1.0 = spending exactly the budget)",
    labelnames=("objective", "window"))
_ALERTS = _registry.counter(
    "slo_alerts_total",
    "multi-window burn-rate alerts fired (transition edges only)",
    labelnames=("objective", "severity"))


def _env_float(name, default):
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return float(default)


def _env_int(name, default):
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return int(default)


class Objective(object):
    """One declarative objective over one ring series.

    kind="latency":      good sample  <=> value <= threshold
    kind="floor":        good sample  <=> value >= threshold
    kind="availability": ``series`` / ``bad_series`` are CUMULATIVE
        counters; each observation contributes its positive delta as
        good/bad events.  The first observation per series seeds the
        baseline (an evaluator enabled mid-run must not judge
        history it never watched).
    """

    __slots__ = ("name", "series", "kind", "threshold", "target",
                 "job", "bad_series", "events", "samples", "first_t",
                 "_last", "alerting")

    def __init__(self, name, series, kind="latency", threshold=None,
                 target=0.99, job="serving", bad_series=()):
        if kind not in ("latency", "floor", "availability"):
            raise ValueError("unknown objective kind: %r" % (kind,))
        if kind != "availability" and threshold is None:
            raise ValueError("objective %s: kind %s needs a threshold"
                             % (name, kind))
        self.name = name
        self.series = series
        self.kind = kind
        self.threshold = threshold
        self.target = float(target)
        self.job = job
        self.bad_series = tuple(bad_series)
        self.events = deque()       # (t_mono, good, total)
        self.samples = 0
        self.first_t = None
        self._last = {}             # series name -> last cumulative
        self.alerting = {}          # grade -> bool
        if self.target >= 1.0:
            # a zero-width budget makes burn infinite on the first
            # bad event; clamp just under 1 to keep the math finite
            self.target = 1.0 - 1e-9

    def _match(self, spec, name):
        return name == spec or name.startswith(spec + "{")

    def matches(self, name):
        if self._match(self.series, name):
            return True
        return any(self._match(b, name) for b in self.bad_series)

    def ingest(self, name, value, t):
        """Fold one ring sample into the event window."""
        if self.first_t is None:
            self.first_t = t
        if self.kind == "availability":
            last = self._last.get(name)
            self._last[name] = value
            if last is None:
                return          # baseline seed, judge deltas only
            delta = value - last
            if delta <= 0:
                return
            bad = any(self._match(b, name) for b in self.bad_series)
            good = 0 if bad else delta
            self.samples += delta
            self.events.append((t, good, delta))
        else:
            if value != value:      # NaN never judges good
                good = 0
            elif self.kind == "latency":
                good = 1 if value <= self.threshold else 0
            else:                   # floor
                good = 1 if value >= self.threshold else 0
            self.samples += 1
            self.events.append((t, good, 1))

    def prune(self, now, max_window_s):
        horizon = now - max_window_s
        ev = self.events
        while ev and ev[0][0] < horizon:
            ev.popleft()

    def attainment(self, now, window_s):
        good = total = 0
        horizon = now - window_s
        for t, g, n in self.events:
            if t >= horizon:
                good += g
                total += n
        if total <= 0:
            return None
        return good / float(total)

    def burn_rate(self, now, window_s):
        att = self.attainment(now, window_s)
        if att is None:
            return None
        return (1.0 - att) / (1.0 - self.target)


class _State(object):
    __slots__ = ("enabled", "lock", "clock", "objectives", "grades",
                 "min_samples", "in_eval")

    def __init__(self):
        self.enabled = False
        self.lock = threading.Lock()
        self.clock = time.monotonic
        self.objectives = []
        self.grades = ()
        self.min_samples = 20
        self.in_eval = threading.local()


_state = _State()


def _scaled_grades():
    scale = _env_float("PT_SLO_WINDOW_SCALE", 1.0)
    if scale <= 0:
        scale = 1.0
    return tuple(dict(g, fast_s=g["fast_s"] * scale,
                      slow_s=g["slow_s"] * scale) for g in _GRADES)


def default_objectives():
    """The stock objective set (each threshold/target env-tunable).

    Serving: TTFT/TPOT/e2e latency attainment + availability
    (1 - shed/expired fraction, over the request-event counters).
    Training: step-time ceiling and an optional goodput floor
    (``PT_SLO_GOODPUT_FLOOR`` <= 0 disables it — a floor of zero is
    vacuously met and would only pad the payload).
    """
    target = _env_float("PT_SLO_TARGET", 0.99)
    objs = [
        Objective("serving_ttft", "serving_ttft_seconds",
                  kind="latency",
                  threshold=_env_float("PT_SLO_TTFT_S", 2.0),
                  target=target, job="serving"),
        Objective("serving_tpot", "serving_tpot_seconds",
                  kind="latency",
                  threshold=_env_float("PT_SLO_TPOT_S", 0.25),
                  target=target, job="serving"),
        Objective("serving_e2e", "serving_e2e_seconds",
                  kind="latency",
                  threshold=_env_float("PT_SLO_E2E_S", 30.0),
                  target=target, job="serving"),
        Objective("serving_availability",
                  'serving_requests_total{event="finished"}',
                  kind="availability",
                  target=_env_float("PT_SLO_AVAIL_TARGET", 0.999),
                  job="serving",
                  bad_series=("serving_requests_shed_total",)),
        Objective("train_step_time", "train_step_seconds",
                  kind="latency",
                  threshold=_env_float("PT_SLO_STEP_S", 1.0),
                  target=target, job="train"),
    ]
    goodput_floor = _env_float("PT_SLO_GOODPUT_FLOOR", 0.0)
    if goodput_floor > 0:
        objs.append(Objective(
            "train_goodput", "train_tokens_per_s", kind="floor",
            threshold=goodput_floor, target=target, job="train"))
    return objs


def enable(objectives=None, clock=None):
    """Turn the judge on: ensure the ring is recording, install the
    listener, and (re)latch windows/objectives from the environment."""
    from . import incidents as _incidents
    _state.clock = clock or time.monotonic
    _state.grades = _scaled_grades()
    _state.min_samples = max(_env_int("PT_SLO_MIN_SAMPLES", 20), 1)
    with _state.lock:
        _state.objectives = list(
            objectives if objectives is not None
            else default_objectives())
    _timeseries.enable()
    _timeseries.add_listener(_observe)
    if not _incidents.is_enabled():
        _incidents.enable()
    _state.enabled = True
    return _state


def disable():
    _state.enabled = False
    _timeseries.remove_listener(_observe)


def is_enabled():
    return _state.enabled


def clear():
    """Test hook: drop windows and alert latches, keep objectives."""
    with _state.lock:
        for obj in _state.objectives:
            obj.events.clear()
            obj.samples = 0
            obj.first_t = None
            obj._last.clear()
            obj.alerting = {}


def add_objective(obj):
    with _state.lock:
        _state.objectives.append(obj)


def set_objectives(objs):
    with _state.lock:
        _state.objectives = list(objs)


def _max_window_s():
    return max((g["slow_s"] for g in _state.grades), default=3600.0)


def _observe(name, ts, value):
    """timeseries listener: fold matching samples, then re-judge the
    touched objectives.  Must never raise into the recording thread
    (the fan-out already warn_once-guards us, but cheap checks first)."""
    if not _state.enabled:
        return
    if getattr(_state.in_eval, "active", False):
        return      # our own slo_* gauge publications re-entering
    touched = []
    now = _state.clock()
    with _state.lock:
        for obj in _state.objectives:
            if obj.matches(name):
                obj.ingest(name, float(value), now)
                touched.append(obj)
    if touched:
        _evaluate(touched, now)


def _evaluate(objectives, now):
    from . import incidents as _incidents
    _state.in_eval.active = True
    try:
        max_w = _max_window_s()
        budget_w = max_w            # ticket-slow = the budget window
        for obj in objectives:
            with _state.lock:
                obj.prune(now, max_w * 1.25)
                att = obj.attainment(now, budget_w)
                burns = {}
                for g in _state.grades:
                    burns[g["grade"] + "_fast"] = \
                        obj.burn_rate(now, g["fast_s"])
                    burns[g["grade"] + "_slow"] = \
                        obj.burn_rate(now, g["slow_s"])
                warm = (obj.samples >= _state.min_samples
                        and obj.first_t is not None
                        and (now - obj.first_t)
                        >= min(g["fast_s"] for g in _state.grades))
            if att is not None:
                _ATTAINMENT.labels(objective=obj.name,
                                   job=obj.job).set(att)
                budget_used = (1.0 - att) / (1.0 - obj.target)
                _BUDGET.labels(objective=obj.name, job=obj.job).set(
                    max(0.0, 1.0 - budget_used))
            for wname, burn in burns.items():
                if burn is not None:
                    _BURN.labels(objective=obj.name,
                                 window=wname).set(burn)
            for g in _state.grades:
                _judge_grade(obj, g, burns, warm, _incidents)
    finally:
        _state.in_eval.active = False


def _judge_grade(obj, grade, burns, warm, _incidents):
    """One grade's alert edge: open when BOTH windows burn past the
    threshold (and warmup passed), extend while burning, resolve when
    the fast window recovers."""
    gname = grade["grade"]
    fast = burns.get(gname + "_fast")
    slow = burns.get(gname + "_slow")
    burning = (warm and fast is not None and slow is not None
               and fast > grade["burn"] and slow > grade["burn"])
    was = obj.alerting.get(gname, False)
    key = "slo/%s/%s" % (obj.name, gname)
    if burning:
        summary = ("SLO %s burning error budget at %.1fx/%.1fx "
                   "(threshold %.1fx, %s grade)"
                   % (obj.name, fast, slow, grade["burn"], gname))
        evidence = {
            "objective": obj.name, "job": obj.job,
            "target": obj.target,
            "burn_fast": fast, "burn_slow": slow,
            "windows_s": [grade["fast_s"], grade["slow_s"]],
            "burn_threshold": grade["burn"],
        }
        severity = "page" if gname == "page" else "ticket"
        _incidents.open(key, severity=severity, kind="slo_burn_rate",
                        source="slo", summary=summary,
                        evidence=evidence)
        if not was:
            obj.alerting[gname] = True
            try:
                _ALERTS.labels(objective=obj.name,
                               severity=severity).inc()
            except Exception as e:
                _registry.warn_once(
                    "slo.alerts_counter",
                    "paddle_tpu.monitor.slo: alert counter increment "
                    "failed (the incident is still open): %r" % (e,))
    elif was and (fast is None or fast <= grade["burn"]):
        obj.alerting[gname] = False
        _incidents.resolve(key, reason="fast-window burn recovered")


def payload():
    """The /debugz/slo JSON body."""
    if not _state.enabled:
        return {"enabled": False, "objectives": []}
    now = _state.clock()
    budget_w = _max_window_s()
    out = []
    with _state.lock:
        grades = _state.grades
        for obj in _state.objectives:
            att = obj.attainment(now, budget_w)
            burns = {}
            for g in grades:
                burns[g["grade"] + "_fast"] = \
                    obj.burn_rate(now, g["fast_s"])
                burns[g["grade"] + "_slow"] = \
                    obj.burn_rate(now, g["slow_s"])
            budget = None
            if att is not None:
                budget = max(0.0, 1.0 - (1.0 - att)
                             / (1.0 - obj.target))
            out.append({
                "objective": obj.name,
                "job": obj.job,
                "kind": obj.kind,
                "series": obj.series,
                "threshold": obj.threshold,
                "target": obj.target,
                "samples": obj.samples,
                "attainment": att,
                "budget_remaining_ratio": budget,
                "burn_rate": burns,
                "alerting": dict(obj.alerting),
            })
    return {
        "enabled": True,
        "window_scale": _env_float("PT_SLO_WINDOW_SCALE", 1.0),
        "grades": [dict(g) for g in _state.grades],
        "min_samples": _state.min_samples,
        "objectives": out,
        "time": time.time(),
    }


# env/FLAGS bootstrap (the timeseries/perf discipline): one flag turns
# on the ring + listener + incident table for the whole process.
if _flag("FLAGS_monitor_slo"):
    enable()
