"""ptprof — continuous profiling plane: always-on host sampler, anomaly
capture windows, measured phase reconciliation.

Every timing attribution the monitor stack owned before this module was
analytic or bracket-derived: ``perf_phase_seconds`` comes from XLA
cost_analysis plus flight-recorder watermarks, and the only MEASURED
profiles were manual ``paddle_tpu/profiler`` Xprof sessions someone had
to start by hand — so the profile you got was never the profile of the
*bad* steps. Three capabilities close that gap (the seventh pillar of
the division of labor: **profile = where the time measurably went**):

1. **Always-on host sampling profiler** — a stdlib-only daemon thread
   samples ``sys._current_frames()`` at ``PT_PROFILE_HZ`` (default 19,
   deliberately off the round numbers so the sampler never phase-locks
   to a 10/20 Hz periodic workload) on the MONOTONIC clock, folds each
   thread's stack into a bounded aggregation table (cap
   ``PT_PROFILE_MAX_STACKS``; past it samples collapse into
   per-component overflow buckets — attribution survives saturation,
   growth never goes unbounded), and attributes every sample to a
   component
   (``scheduler`` / ``store-io`` / ``device-wait`` / ``tokenize`` /
   ``other``) by leaf-most frame-to-module matching. Exported as
   collapsed-stack text (``/debugz/profile/folded`` — flamegraph.pl
   input) and a top-K summary (``/debugz/profile``). The sampler
   measures its OWN time per tick; the overhead bound (self-time < 1%
   of wall at the default hz) is test-pinned.

2. **Anomaly-triggered device capture windows** —
   ``capture_window(steps=N)`` / ``arm_capture()`` arms a ONE-SHOT
   ``jax.profiler.start_trace``/``stop_trace`` window around the next N
   hot-step invocations (``CompiledTrainStep.__call__``/``run_steps``,
   serving ``Engine.step``), through the ``paddle_tpu/profiler`` Xprof
   session guard so ptprof and a manual ``Profiler(with_xprof=True)``
   can never double-``start_trace``. Armed automatically by perf
   sentinels (throughput-cliff, mem_leak), watchdog stall escalation,
   and fresh fleet stragglers — so the Xprof artifact is of the
   ANOMALOUS steps, not whatever someone profiled by hand later.
   Cooldown + ``PT_PROFILE_MAX_CAPTURES`` cap, defer-not-drop (the
   PR-8 fleet-capture discipline): a trigger landing inside the
   cooldown queues and fires on the next eligible step. Each finished
   window writes ``profile_capture_<ts>/`` (manifest + per-window
   folded host stacks + the Xprof trace dir when the backend
   cooperates; host-only capture is still a capture).

3. **Measured phase reconciliation** — hot steps gain a dispatch/block
   timer: ``profile_dispatch_seconds{job}`` (call issue → handles
   returned), ``profile_host_blocked_seconds{job}`` (explicit
   ``block_until_ready`` on the step result), and
   ``profile_host_gap_seconds{job}`` (host time between consecutive
   steps). Mirrored into the /debugz/perf job rows (``perf.note_job``)
   so ``tools/perf_report.py`` can diff MEASURED against PR-5's
   analytic ``perf_phase_seconds`` — the analytic model becomes
   falsifiable, and the exposed-comm residual (measured step − analytic
   compute) is the scoreboard ROADMAP item 4 starts from. The serving
   engine additionally feeds per-phase host timers
   (``note_phase("prefill"|"decode", dt)``) for the
   ``serving_benchmark --profile`` rows.

Discipline (the PR-2/5/6/12 contract, test-pinned): default OFF via
``FLAGS_monitor_profile``. Engines latch ``step_hook(job)`` ONCE at
construction (the ptlint hot-path-latch convention) — while off the hot
paths pay one attribute load + branch: no daemon threads, no native
calls, no ``profile_*`` registry series, both debugz routes answer
``enabled: false``. Module import stays stdlib-only; jax is only ever
imported lazily behind the enabled paths (``block_until_ready``, the
Xprof window), so bare workers scraping the route never drag an
accelerator backend in.
"""
from __future__ import annotations

import json
import os
import sys
import threading
import time

from . import perf as _perf
from . import registry as _registry
from .timeseries import _flag

_THREAD_NAME = "pt-profiler"

# -- metrics (shared registry; series appear only while enabled) -------------

_DISPATCH = _registry.gauge(
    "profile_dispatch_seconds",
    "measured host wall of the last hot-step call (issue -> handles "
    "returned, incl. any implicit blocking inside the call)",
    labelnames=("job",))
_BLOCKED = _registry.gauge(
    "profile_host_blocked_seconds",
    "measured host wall spent in block_until_ready on the last step's "
    "result AFTER the call returned (device time exposed to the host)",
    labelnames=("job",))
_GAP = _registry.gauge(
    "profile_host_gap_seconds",
    "measured host wall between the previous step's completion and "
    "this step's dispatch (input pipeline / scheduler / host tax)",
    labelnames=("job",))
_SAMPLES = _registry.counter(
    "profile_samples_total",
    "host sampling-profiler samples taken (one per thread-sweep tick)")
_CAPTURES = _registry.counter(
    "profile_captures_total",
    "device capture windows completed, by arming reason",
    labelnames=("reason",))

# sentinel kinds that arm a capture window automatically (monitor/perf.py
# calls on_anomaly on every firing; only these kinds are profile-shaped
# — a NaN loss needs no timeline, a cliff or a leak does)
CAPTURE_KINDS = ("throughput_regression", "mem_leak")

# component attribution: leaf-most frame whose "filename:funcname" key
# contains one of the patterns wins; order = per-frame priority. The
# division: scheduler = batching/admission host logic, store-io = KV
# store + HTTP plumbing, device-wait = the jax dispatch/block surface,
# tokenize = text preprocessing, other = everything else.
COMPONENT_PATTERNS = (
    ("device-wait", ("/jax/", "jax/_src", "jaxlib",
                     "block_until_ready")),
    ("scheduler", ("serving/scheduler.py", "serving/engine.py",
                   "parallel/engine.py", "parallel/pipeline")),
    ("store-io", ("distributed/store.py", "fleet/utils/http_server",
                  "monitor/fleet.py", "monitor/exporter.py",
                  "socketserver", "http/server", "http/client",
                  "socket.py")),
    # anchored to tokenizer modules/functions — a bare "tokenize"
    # substring would claim CPython's stdlib tokenize.py (linecache/
    # inspect render paths) for text preprocessing it never did
    ("tokenize", ("text/tokenizer.py", "tokenizer", ":tokenize",
                  "_tokenizer_")),
)

_STACK_DEPTH = 48


class _ProfState:
    __slots__ = ("lock", "thread", "stop_event", "hz", "samples",
                 "self_time_s", "started_mono", "stacks", "overflow",
                 "max_stacks", "jobs", "captures", "pending", "window",
                 "last_capture_end", "cooldown_s", "max_captures")

    def __init__(self):
        self.lock = threading.Lock()
        self.thread = None
        self.stop_event = None
        self.hz = _env_float("PT_PROFILE_HZ", 19.0)
        self.samples = 0
        self.self_time_s = 0.0
        self.started_mono = None
        self.stacks = {}        # folded key -> {count, component}
        self.overflow = 0       # samples collapsed past max_stacks
        self.max_stacks = _env_int("PT_PROFILE_MAX_STACKS", 512)
        self.jobs = {}          # job -> cumulative measured totals
        self.captures = []      # finished capture records
        self.pending = []       # queued triggers (defer-not-drop)
        self.window = None      # the ONE in-flight capture window
        self.last_capture_end = None    # monotonic
        self.cooldown_s = _env_float("PT_PROFILE_CAPTURE_COOLDOWN_S",
                                     60.0)
        self.max_captures = _env_int("PT_PROFILE_MAX_CAPTURES", 4)


def _env_float(name, default):
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_int(name, default):
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


_state = _ProfState()


def is_enabled():
    return _flag("FLAGS_monitor_profile")


def _rank():
    try:
        from ..distributed import process_group as _pg

        pg = _pg.get_world_group()
        if pg is not None:
            return int(pg.rank)
    except Exception as e:
        _registry.warn_once(
            "profile.rank",
            "paddle_tpu.monitor.profile: world-group rank lookup "
            "failed (artifacts file as rank from env/0): %r" % (e,))
    try:
        return int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    except ValueError:
        return 0


# -- the host sampling profiler ----------------------------------------------

def _component_of(key):
    """Component of one frame key ("filename:funcname"), or None."""
    for comp, pats in COMPONENT_PATTERNS:
        for p in pats:
            if p in key:
                return comp
    return None


def _modname(filename):
    base = os.path.basename(filename)
    return base[:-3] if base.endswith(".py") else base


def _fold_thread(frame):
    """(folded_stack, component) of one thread's current frame chain.
    Manual f_back walk — no linecache/IO on the sampling tick."""
    parts = []
    comp = None
    f = frame
    depth = 0
    while f is not None and depth < _STACK_DEPTH:
        code = f.f_code
        if comp is None:
            c = _component_of("%s:%s" % (code.co_filename, code.co_name))
            if c is not None:
                comp = c
        parts.append("%s.%s" % (_modname(code.co_filename),
                                code.co_name))
        f = f.f_back
        depth += 1
    parts.reverse()     # collapsed-stack convention: root first
    return ";".join(parts), comp or "other"


def _sample_once():
    """One sweep over every thread but the sampler's own. Self-time is
    measured on the monotonic clock around the sweep — the overhead
    bound the tests pin reads these two counters."""
    t0 = time.monotonic()
    me = threading.get_ident()
    names = {t.ident: t.name for t in threading.enumerate()}
    folded = []
    for tid, frame in sys._current_frames().items():
        if tid == me:
            continue
        stack, comp = _fold_thread(frame)
        name = names.get(tid, "?")
        folded.append(("%s;%s" % (name, stack), comp))
    with _state.lock:
        for key, comp in folded:
            rec = _state.stacks.get(key)
            if rec is not None:
                rec["count"] += 1
            elif len(_state.stacks) < _state.max_stacks:
                _state.stacks[key] = {"count": 1, "component": comp}
            else:
                # saturated table: the sample still counts, collapsed
                # into ONE per-component overflow bucket (bounded by
                # the component set) — component attribution survives
                # saturation even when the exact stack is lost, so a
                # capture window opened after a long churny compile
                # still names where the time went
                _state.overflow += 1
                okey = "(overflow);%s" % comp
                orec = _state.stacks.get(okey)
                if orec is None:
                    orec = _state.stacks[okey] = {"count": 0,
                                                  "component": comp}
                orec["count"] += 1
        _state.samples += 1
        _state.self_time_s += time.monotonic() - t0
    _SAMPLES.inc()


def _sampler_run(stop_event, interval_s):
    while not stop_event.wait(interval_s):
        try:
            _sample_once()
        except Exception as e:
            # the profiler eating its own tick failures is the exact
            # blind spot this repo lints against: say it once, keep
            # sampling
            _registry.warn_once(
                "profile.sample_tick",
                "paddle_tpu.monitor.profile: sampler tick failed "
                "(sampler keeps running): %r" % (e,))


def start_sampler(hz=None):
    """Start (or return) the process-wide sampling daemon thread.
    Refuses while ``FLAGS_monitor_profile`` is off — the disabled path
    must stay thread-free even against an explicit call."""
    if not is_enabled():
        return None
    with _state.lock:
        if _state.thread is not None and _state.thread.is_alive():
            return _state.thread
        if hz is not None:
            _state.hz = float(hz)
        _state.hz = max(_state.hz, 0.1)
        # a (re)start opens a FRESH sampling window: counters, self-time
        # and the folded table reset together so overhead_share and the
        # "each count ≈ 1/hz s over window_s" time-weighting stay
        # internally consistent — snapshot before stopping if the old
        # window matters
        _state.samples = 0
        _state.self_time_s = 0.0
        _state.stacks = {}
        _state.overflow = 0
        _state.started_mono = time.monotonic()
        _state.stop_event = threading.Event()
        _state.thread = threading.Thread(
            target=_sampler_run,
            args=(_state.stop_event, 1.0 / _state.hz),
            name=_THREAD_NAME, daemon=True)
        _state.thread.start()
        return _state.thread


def stop_sampler():
    with _state.lock:
        ev, t = _state.stop_event, _state.thread
        _state.stop_event = None
        _state.thread = None
    if ev is not None:
        ev.set()
    if t is not None and t.is_alive():
        t.join(timeout=5)


def sampler_running():
    t = _state.thread
    return t is not None and t.is_alive()


def folded_snapshot():
    """{folded_stack: {count, component}} — cumulative since sampler
    start. Each count is one sample ≈ 1/hz seconds of that stack being
    live (the time-weighted view the watchdog bundle embeds)."""
    with _state.lock:
        return {k: dict(v) for k, v in _state.stacks.items()}


def component_totals(stacks=None):
    """Sample counts and shares by component."""
    if stacks is None:
        stacks = folded_snapshot()
    counts = {}
    for rec in stacks.values():
        counts[rec["component"]] = \
            counts.get(rec["component"], 0) + rec["count"]
    total = sum(counts.values())
    return {comp: {"samples": n,
                   "share": (n / total) if total else 0.0}
            for comp, n in sorted(counts.items())}


def folded_text(stacks=None, k=None):
    """Collapsed-stack text ("stack count" lines, count-descending) —
    flamegraph.pl / speedscope input."""
    if stacks is None:
        stacks = folded_snapshot()
    rows = sorted(stacks.items(), key=lambda kv: -kv[1]["count"])
    if k is not None:
        rows = rows[:int(k)]
    return "".join("%s %d\n" % (key, rec["count"]) for key, rec in rows)


# -- anomaly-triggered device capture windows --------------------------------

def arm_capture(steps=None, reason="manual", detail=None):
    """Queue a one-shot device-capture window around the next ``steps``
    hot-step invocations. Defer-not-drop: a trigger landing while a
    window is in flight or inside the cooldown stays queued and fires
    at the next eligible step (its watermark already advanced and will
    not re-fire on its own — the PR-8 discipline). Returns True when
    the trigger was queued (False while the plane is off)."""
    if not is_enabled():
        return False
    rec = {"reason": str(reason),
           "steps": max(int(steps if steps is not None
                            else _env_int("PT_PROFILE_CAPTURE_STEPS", 4)),
                        1),
           "detail": dict(detail) if detail else {},
           "armed_at": time.time()}
    with _state.lock:
        _state.pending.append(rec)
    return True


def capture_window(steps=4, reason="manual", detail=None):
    """The manual-arming spelling from the ISSUE: identical to
    ``arm_capture`` with an explicit step count."""
    return arm_capture(steps=steps, reason=reason, detail=detail)


def on_anomaly(kind):
    """perf-sentinel hook (monitor/perf.py calls this on every firing):
    profile-shaped kinds (CAPTURE_KINDS) arm a capture window so the
    Xprof trace covers the steps right after the anomaly."""
    if str(kind) in CAPTURE_KINDS:
        return arm_capture(reason="sentinel:%s" % kind)
    return False


def on_stall(stalls=None):
    """Watchdog escalation hook: a fresh stall episode arms a capture
    window — if the wedge clears (or recovery restarts the loop), the
    first steps after it get a measured profile."""
    detail = None
    if stalls:
        detail = {"stalls": [
            {"heartbeat": s.get("heartbeat"), "phase": s.get("phase"),
             "age_s": s.get("age_s")} for s in stalls]}
    return arm_capture(reason="watchdog_stall", detail=detail)


def on_straggler(ranks):
    """Fleet-collector hook: freshly flagged stragglers arm a local
    capture window (the collector rank's own steps — the cross-rank
    folded stacks ride the fleet capture's /debugz/profile pulls)."""
    return arm_capture(reason="straggler",
                       detail={"ranks": list(ranks)})


def _xprof_begin(trace_dir):
    """Start the device trace through the paddle_tpu/profiler session
    guard (ptprof and a manual Profiler can never double-start_trace).
    Returns (started, why_not). Lazy import: the profiler package pulls
    core.native, which a bare monitor worker must not pay for."""
    try:
        from .. import profiler as _profiler

        if not _profiler.xprof_session_begin("ptprof", trace_dir):
            return False, "xprof session held by %r" % (
                _profiler.xprof_session_owner(),)
        return True, None
    except Exception as e:
        return False, repr(e)


def _xprof_end():
    try:
        from .. import profiler as _profiler

        _profiler.xprof_session_end("ptprof")
    except Exception as e:
        _registry.warn_once(
            "profile.xprof_end",
            "paddle_tpu.monitor.profile: Xprof stop failed (host-side "
            "capture artifacts were still written): %r" % (e,))


def _capture_root():
    return os.environ.get("PT_MONITOR_DUMP_DIR") or "."


def _window_step_begin():
    """Hot-step entry (StepProfiler.step_begin): open a queued capture
    window when eligible. Cooldown math is monotonic — an NTP step must
    neither extend nor collapse it."""
    with _state.lock:
        if _state.window is not None or not _state.pending:
            return
        now = time.monotonic()
        if _state.last_capture_end is not None and \
                now - _state.last_capture_end < _state.cooldown_s:
            return
        if len(_state.captures) >= _state.max_captures:
            _state.pending = []
            return
        pending, _state.pending = _state.pending, []
        first = dict(pending[0])
        if len(pending) > 1:
            # later triggers fold into the window's manifest rather
            # than burning extra windows — distinct incidents keep
            # their reason attribution
            first["also"] = [{"reason": p["reason"],
                              "detail": p["detail"]}
                             for p in pending[1:]]
        ts = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
        d = os.path.join(_capture_root(), "profile_capture_%s" % ts)
        n = 1
        while os.path.exists(d):
            d = os.path.join(_capture_root(),
                             "profile_capture_%s_%d" % (ts, n))
            n += 1
        _state.window = {
            "reason": first["reason"],
            "detail": first.get("detail") or {},
            "also": first.get("also") or [],
            "steps": first["steps"],
            "steps_left": first["steps"],
            "dir": d,
            "jobs": [],
            "started_mono": now,
            "samples_mark": _state.samples,
            "folded_mark": {k: v["count"]
                            for k, v in _state.stacks.items()},
            "xprof": False,
            "xprof_error": None,
            # setup handshake: the device trace starts OUTSIDE the
            # lock below, so a concurrent step_end/abort from another
            # engine must not finalize until setup completed — it
            # requests the close and the setup path performs it
            "ready": False,
            "close_requested": False,
            "aborted": None,
        }
        w = _state.window
    # filesystem + device-trace work OUTSIDE the lock (the sampler and
    # other hot steps must not serialize behind an Xprof start)
    try:
        os.makedirs(d, exist_ok=True)
        started, why = _xprof_begin(os.path.join(d, "xprof"))
        if not started and why:
            _registry.warn_once(
                "profile.xprof_begin",
                "paddle_tpu.monitor.profile: device trace unavailable "
                "for capture %s (host-only capture proceeds): %s"
                % (d, why))
    except Exception as e:
        started, why = False, repr(e)
        _registry.warn_once(
            "profile.capture_begin",
            "paddle_tpu.monitor.profile: capture-window setup failed "
            "(window continues host-only): %r" % (e,))
    closed = None
    with _state.lock:
        w["xprof"] = started
        w["xprof_error"] = why
        w["ready"] = True
        if w["close_requested"] and _state.window is w:
            closed = _close_window_locked(w)
    if closed is not None:
        _xprof_end()
        _finalize_capture(w, *closed)


def _close_window_locked(w):
    """Under _state.lock: detach the window and compute its folded
    delta. Returns (delta, window_samples, window_s) for the caller to
    finalize OUTSIDE the lock."""
    _state.window = None
    _state.last_capture_end = time.monotonic()
    mark = w["folded_mark"]
    delta = {}
    for key, rec in _state.stacks.items():
        d = rec["count"] - mark.get(key, 0)
        if d > 0:
            delta[key] = {"count": d, "component": rec["component"]}
    return (delta, _state.samples - w["samples_mark"],
            time.monotonic() - w["started_mono"])


def _window_step_end(job):
    """Hot-step exit: count the step against the open window and
    finalize (stop trace, write manifest + folded delta) when the
    window is exhausted. A window still mid-setup (another engine's
    Xprof start in flight) is close-REQUESTED and finalized by the
    setup path — never finalized under its feet."""
    with _state.lock:
        w = _state.window
        if w is None:
            return
        if job not in w["jobs"]:
            w["jobs"].append(job)
        w["steps_left"] -= 1
        if w["steps_left"] > 0:
            return
        if not w["ready"]:
            w["close_requested"] = True
            return
        closed = _close_window_locked(w)
    # owner-checked stop: a no-op when ptprof never got the session
    _xprof_end()
    _finalize_capture(w, *closed)


def abort_window(reason="hot step raised mid-window"):
    """Finalize the open capture window EARLY — the hot-step exception
    path calls this so a step raising mid-window can never leak a live
    device trace or wedge the one-window-at-a-time state. The partial
    artifact still lands (a failing step is exactly the evidence the
    arming anomaly wanted), marked ``aborted`` in the manifest."""
    with _state.lock:
        w = _state.window
        if w is None:
            return
        w["aborted"] = str(reason)
        if not w["ready"]:
            w["close_requested"] = True
            return
        closed = _close_window_locked(w)
    _xprof_end()
    _finalize_capture(w, *closed)


def _finalize_capture(w, delta, window_samples, window_s):
    """Write the capture artifacts; never raises (a full disk must not
    take down the step that happened to close the window)."""
    rank = _rank()
    try:
        os.makedirs(w["dir"], exist_ok=True)
        fpath = os.path.join(w["dir"], "folded_rank%d.txt" % rank)
        tmp = fpath + ".tmp"
        with open(tmp, "w") as f:
            f.write(folded_text(delta))
        os.replace(tmp, fpath)
        manifest = {
            "kind": "profile_capture",
            "version": 1,
            "reason": w["reason"],
            "detail": w["detail"],
            "also": w["also"],
            "rank": rank,
            "pid": os.getpid(),
            "created_at": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                        time.gmtime()),
            "unix_time": time.time(),
            "steps": w["steps"],
            "jobs": w["jobs"],
            "window_s": window_s,
            "window_samples": window_samples,
            "sampler_hz": _state.hz,
            "components": component_totals(delta),
            "aborted": w.get("aborted"),
            "xprof": w["xprof"],
            "xprof_error": w["xprof_error"],
            "xprof_dir": (os.path.join(w["dir"], "xprof")
                          if w["xprof"] else None),
        }
        mpath = os.path.join(w["dir"], "manifest.json")
        tmp = mpath + ".tmp"
        with open(tmp, "w") as f:
            json.dump(manifest, f, indent=1, default=str)
            f.write("\n")
        os.replace(tmp, mpath)
    except Exception as e:
        _registry.warn_once(
            "profile.capture_write",
            "paddle_tpu.monitor.profile: capture artifact write "
            "failed (%s): %r" % (w["dir"], e))
        return
    rec = {"dir": w["dir"], "reason": w["reason"],
           "detail": w["detail"], "jobs": w["jobs"],
           "steps": w["steps"], "window_s": window_s,
           "aborted": w.get("aborted"), "xprof": w["xprof"],
           "unix_time": manifest["unix_time"]}
    with _state.lock:
        _state.captures.append(rec)
    _CAPTURES.labels(reason=w["reason"]).inc()


# -- measured phase reconciliation (the engine-facing latch) -----------------

class StepProfiler:
    """One engine's latched handle (the ``memory.tracker`` convention):
    the hot path only ever checks the handle, never the flag. Wraps
    each hot step with the dispatch/block/gap timers, mirrors the
    measured numbers into the /debugz/perf job row, and drives the
    capture-window lifecycle."""

    __slots__ = ("job", "_last_end")

    def __init__(self, job):
        self.job = job
        self._last_end = None

    def step_begin(self):
        """Before dispatch: open a queued capture window (if any)."""
        _window_step_begin()

    def step_end(self, t0, t1, block=None):
        """After the call returned at ``t1`` (perf_counter stamps from
        the caller): optionally block on the step's result to split
        dispatch from device-exposed time, publish the measured gauges,
        and count the step against any open capture window. Returns
        the measured dict."""
        t2 = t1
        if block is not None:
            try:
                import jax

                jax.block_until_ready(block)
                t2 = time.perf_counter()
            except Exception as e:
                _registry.warn_once(
                    "profile.block_until_ready",
                    "paddle_tpu.monitor.profile: block_until_ready "
                    "failed (blocked-time reads 0 this step): %r"
                    % (e,))
        dispatch = max(t1 - t0, 0.0)
        blocked = max(t2 - t1, 0.0)
        gap = (max(t0 - self._last_end, 0.0)
               if self._last_end is not None else 0.0)
        self._last_end = t2
        job = self.job
        _DISPATCH.labels(job=job).set(dispatch)
        _BLOCKED.labels(job=job).set(blocked)
        _GAP.labels(job=job).set(gap)
        with _state.lock:
            tot = _state.jobs.setdefault(job, {
                "steps": 0, "dispatch_s": 0.0, "blocked_s": 0.0,
                "gap_s": 0.0, "phases": {}})
            tot["steps"] += 1
            tot["dispatch_s"] += dispatch
            tot["blocked_s"] += blocked
            tot["gap_s"] += gap
        _perf.note_job(job,
                       profile_dispatch_seconds=dispatch,
                       profile_host_blocked_seconds=blocked,
                       profile_host_gap_seconds=gap)
        _window_step_end(job)
        return {"dispatch_s": dispatch, "blocked_s": blocked,
                "gap_s": gap}

    def step_abort(self):
        """Hot-step exception path: close any open capture window so a
        raising step can never leak a live device trace (the partial
        artifact still lands, marked aborted)."""
        abort_window("hot step raised (job=%s)" % self.job)

    def note_phase(self, phase, seconds):
        """Accumulate one sub-phase's measured host seconds (the
        serving engine feeds prefill/decode; serving_benchmark
        --profile reports the totals)."""
        with _state.lock:
            tot = _state.jobs.setdefault(self.job, {
                "steps": 0, "dispatch_s": 0.0, "blocked_s": 0.0,
                "gap_s": 0.0, "phases": {}})
            tot["phases"][str(phase)] = \
                tot["phases"].get(str(phase), 0.0) + float(seconds)


def step_hook(job):
    """THE construction-latch entry point: when ``FLAGS_monitor_profile``
    is on, make sure the sampler runs and return a ``StepProfiler``;
    when off, return None — one flag read at construction, and the hot
    path only ever checks the handle (the memory.tracker contract)."""
    if not is_enabled():
        return None
    start_sampler()
    return StepProfiler(job)


# -- payloads / routes -------------------------------------------------------

def job_totals():
    with _state.lock:
        return {j: {k: (dict(v) if isinstance(v, dict) else v)
                    for k, v in tot.items()}
                for j, tot in _state.jobs.items()}


def profile_payload(top_k=20):
    """The /debugz/profile JSON body. Off = pinned
    ``{"enabled": false}`` shape (the route answers 200 either way —
    "off" is a payload, not an error)."""
    enabled = is_enabled()
    out = {"enabled": enabled, "time": time.time(),
           "sampler": None, "components": {}, "top": [],
           "jobs": {}, "captures": [], "pending_captures": 0,
           "window": None}
    if not enabled:
        return out
    stacks = folded_snapshot()
    with _state.lock:
        samples = _state.samples
        self_time = _state.self_time_s
        started = _state.started_mono
        overflow = _state.overflow
        hz = _state.hz
        captures = list(_state.captures)
        pending = len(_state.pending)
        w = _state.window
        window = None if w is None else {
            "reason": w["reason"], "steps_left": w["steps_left"],
            "dir": w["dir"], "xprof": w["xprof"]}
    elapsed = (time.monotonic() - started) if started is not None \
        else None
    out["sampler"] = {
        "running": sampler_running(),
        "hz": hz,
        "samples": samples,
        "distinct_stacks": len(stacks),
        "overflow_samples": overflow,
        "self_time_s": self_time,
        "window_s": elapsed,
        "overhead_share": (self_time / elapsed
                           if elapsed and elapsed > 0 else None),
    }
    out["components"] = component_totals(stacks)
    rows = sorted(stacks.items(), key=lambda kv: -kv[1]["count"])
    out["top"] = [{"stack": key, "count": rec["count"],
                   "component": rec["component"]}
                  for key, rec in rows[:int(top_k)]]
    out["jobs"] = job_totals()
    out["captures"] = captures
    out["pending_captures"] = pending
    out["window"] = window
    return out


def folded_route_text():
    """The /debugz/profile/folded body (text/plain). Disabled = a
    comment header instead of an empty 200 body, so a probe can tell
    "off" from "on but idle"."""
    if not is_enabled():
        return "# ptprof disabled (FLAGS_monitor_profile off)\n"
    return folded_text()


def bundle_payload(top_k=64):
    """The watchdog-bundle embedding: the sampler's TIME-WEIGHTED view
    (each count ≈ 1/hz s) next to the bundle's point-in-time stacks —
    a stall postmortem shows where the time went, not just where
    threads sat at one instant. None while the plane is off (the
    bundle key stays null, never fabricated)."""
    if not is_enabled():
        return None
    stacks = folded_snapshot()
    rows = sorted(stacks.items(), key=lambda kv: -kv[1]["count"])
    with _state.lock:
        samples = _state.samples
        started = _state.started_mono
        hz = _state.hz
    return {
        "samples": samples,
        "hz": hz,
        "window_s": (time.monotonic() - started)
        if started is not None else None,
        "components": component_totals(stacks),
        "folded": {key: rec["count"] for key, rec in rows[:int(top_k)]},
    }


def reset():
    """Test hook: stop the sampler, forget stacks/jobs/captures/window
    state, restore the env-derived tunables (tests mutate hz /
    max_stacks / cooldown_s / max_captures and must not leak them into
    later suites), and drop the published ``profile_*`` series
    (flags-off after reset is pinned series-free)."""
    stop_sampler()
    with _state.lock:
        _state.samples = 0
        _state.self_time_s = 0.0
        _state.started_mono = None
        _state.stacks = {}
        _state.overflow = 0
        _state.jobs = {}
        _state.captures = []
        _state.pending = []
        w, _state.window = _state.window, None
        _state.last_capture_end = None
        _state.hz = _env_float("PT_PROFILE_HZ", 19.0)
        _state.max_stacks = _env_int("PT_PROFILE_MAX_STACKS", 512)
        _state.cooldown_s = _env_float("PT_PROFILE_CAPTURE_COOLDOWN_S",
                                       60.0)
        _state.max_captures = _env_int("PT_PROFILE_MAX_CAPTURES", 4)
    if w is not None:
        # an open window's device trace must not outlive the reset
        # (owner-checked: a no-op when ptprof never held the session)
        _xprof_end()
    for m in (_DISPATCH, _BLOCKED, _GAP, _CAPTURES):
        for key in list(m._children):
            m.remove(*key)
    for key in list(_SAMPLES._children):
        _SAMPLES.remove(*key)
    _SAMPLES._values.pop((), None)


# env/FLAGS bootstrap (the timeseries/perf/memory discipline): a process
# started with FLAGS_monitor_profile=1 samples from its first moments
# without any code change.
if _flag("FLAGS_monitor_profile"):
    start_sampler()
