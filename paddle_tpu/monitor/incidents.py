"""Unified incident manager: one bounded table every detector reports to.

The repo has eight independent anomaly sources — perf sentinels,
the mem-leak sentinel, watchdog stall episodes, fleet straggler
episodes, OOM postmortem writers, router lease evictions, poison
quarantine, sheds — each with its own counter, artifact and healthz
side-channel. This module is the aggregation layer over all of them:
a narrow ``open(key, ...)`` / ``resolve(key)`` API with episode-keyed
dedup (re-fire EXTENDS the open incident, recovery RESOLVES it —
each detector keeps its own episode latching and reports the edges
here), severity (``ticket`` < ``page``), an open → resolved lifecycle,
and causality links to the evidence artifacts the detectors already
produce (bundle path, postmortem path, capture dir, trace ids).

Division of labor (README "SLO & incidents"): sentinels/watchdog/fleet
**detect**, this table **aggregates**, monitor/slo.py **judges**
(objectives + error budgets). /healthz "degraded" derives from the
open set when the plane is on — one source of truth instead of N
side-channels (monitor/watchdog.py ``healthz_payload``).

Discipline (the PR-2/5/6/12/13 contract, test-pinned by
tests/test_slo.py): default OFF via ``FLAGS_monitor_slo``; while off,
``open()``/``resolve()``/``add_evidence()`` are one enabled-attribute
load + branch — no registry series, no threads (this module NEVER has
threads), no native calls, and ``/debugz/incidents`` reports
``enabled: false``. Incident ids embed ``(rank, pid)`` so a fleet
merge (monitor/fleet.py ``fleet_incidents_payload``) can dedup by id
across the collector's own table and every scraped rank table.

Wall-clock stamps (``opened_at``/``last_seen``/``resolved_at``) are
display/merge metadata only — nothing here subtracts or orders them
(the fleet merge shifts them by the NTP-style per-rank offsets, the
trace_merge discipline).
"""
from __future__ import annotations

import os
import threading
import time

from . import registry as _registry
from .timeseries import _flag

SEVERITIES = ("ticket", "page")     # ascending

# registry metrics (lazy series: nothing exists until the first
# open()/resolve() with the plane enabled — the series-free pin)
_OPENED = _registry.counter(
    "incident_opened_total",
    "incidents opened, by reporting detector and severity",
    labelnames=("source", "severity"))
_RESOLVED = _registry.counter(
    "incident_resolved_total",
    "incidents resolved (episode recovered or acknowledged), by "
    "reporting detector", labelnames=("source",))
_OPEN_COUNT = _registry.gauge(
    "incident_open_count", "currently-open incidents by severity",
    labelnames=("severity",))


def _env_int(name, default):
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


class _State:
    __slots__ = ("enabled", "lock", "open", "resolved", "seq", "rank")

    def __init__(self):
        self.enabled = False
        self.lock = threading.Lock()
        self.open = {}          # key -> incident dict
        self.resolved = []      # bounded, oldest first
        self.seq = 0
        self.rank = None


_state = _State()


def _resolved_cap():
    return max(_env_int("PT_INCIDENTS_CAP", 64), 1)


def enable(rank=None):
    """Turn the incident table on (process-wide). ``rank`` defaults to
    this process's trainer rank so incident ids name their origin."""
    if rank is None:
        rank = _env_int("PADDLE_TRAINER_ID", 0)
    _state.rank = int(rank)
    _state.enabled = True
    return _state


def disable():
    _state.enabled = False


def is_enabled():
    return _state.enabled


def clear():
    """Test hook: drop every incident (open and resolved)."""
    with _state.lock:
        self_open = list(_state.open.values())
        _state.open = {}
        _state.resolved = []
        _state.seq = 0
    for inc in self_open:
        _sync_open_gauge_severity(inc["severity"])


def _sync_open_gauge_severity(severity):
    n = sum(1 for i in _state.open.values()
            if i["severity"] == severity)
    try:
        _OPEN_COUNT.labels(severity=severity).set(n)
    except Exception as e:
        _registry.warn_once(
            "incidents.open_gauge",
            "paddle_tpu.monitor.incidents: open-count gauge update "
            "failed (table state is still authoritative): %r" % (e,))


def open(key, severity="ticket", kind=None, source=None, summary=None,
         evidence=None, rank=None):
    """Open (or extend) the incident for episode ``key``. Returns the
    incident id, or None while the plane is disabled.

    Dedup is episode-keyed: a second ``open`` on an already-open key
    bumps ``count``/``last_seen``, merges ``evidence``, and escalates
    severity (ticket -> page, never the reverse) instead of creating a
    duplicate — a detector may re-fire every sample while its episode
    lasts and the table shows ONE incident."""
    if not _state.enabled:
        return None
    if severity not in SEVERITIES:
        severity = "ticket"
    now = time.time()
    fresh = None
    with _state.lock:
        inc = _state.open.get(key)
        if inc is not None:
            inc["count"] += 1
            inc["last_seen"] = now
            if evidence:
                inc["evidence"].update(evidence)
            if summary:
                inc["summary"] = summary
            if SEVERITIES.index(severity) > \
                    SEVERITIES.index(inc["severity"]):
                inc["severity"] = severity
                fresh = ("escalated", inc)
            return inc["id"]
        _state.seq += 1
        inc = {
            "id": "inc-r%d-p%d-%d" % (
                _state.rank if rank is None else int(rank),
                os.getpid(), _state.seq),
            "key": key,
            "kind": kind or key.split("/", 1)[0],
            "source": source or "unknown",
            "severity": severity,
            "summary": summary or key,
            "rank": _state.rank if rank is None else int(rank),
            "state": "open",
            "opened_at": now,
            "last_seen": now,
            "count": 1,
            "evidence": dict(evidence or {}),
        }
        _state.open[key] = inc
        fresh = ("opened", inc)
    try:
        _OPENED.labels(source=inc["source"],
                       severity=inc["severity"]).inc()
    except Exception as e:
        _registry.warn_once(
            "incidents.opened_counter",
            "paddle_tpu.monitor.incidents: opened counter increment "
            "failed (incident %s is still in the table): %r"
            % (inc["id"], e))
    if fresh is not None:
        _sync_open_gauge_severity(inc["severity"])
    return inc["id"]


def resolve(key, reason=None):
    """Close the open incident for ``key`` (episode recovered). The
    record moves to the bounded resolved list. Returns True if an open
    incident was resolved."""
    if not _state.enabled:
        return False
    now = time.time()
    with _state.lock:
        inc = _state.open.pop(key, None)
        if inc is None:
            return False
        inc["state"] = "resolved"
        inc["resolved_at"] = now
        if reason:
            inc["resolve_reason"] = reason
        _state.resolved.append(inc)
        cap = _resolved_cap()
        if len(_state.resolved) > cap:
            del _state.resolved[:len(_state.resolved) - cap]
    try:
        _RESOLVED.labels(source=inc["source"]).inc()
    except Exception as e:
        _registry.warn_once(
            "incidents.resolved_counter",
            "paddle_tpu.monitor.incidents: resolved counter increment "
            "failed (incident %s is still resolved): %r"
            % (inc["id"], e))
    _sync_open_gauge_severity(inc["severity"])
    return True


def resolve_source(source, reason=None):
    """Resolve every open incident reported by ``source`` (the
    perf ``clear_anomalies`` acknowledgement path). Returns the count
    resolved."""
    if not _state.enabled:
        return 0
    with _state.lock:
        keys = [k for k, i in _state.open.items()
                if i["source"] == source]
    return sum(1 for k in keys if resolve(k, reason=reason))


def add_evidence(key, **links):
    """Attach causality links (artifact paths, trace ids) to the open
    incident for ``key``. Returns True if it was open."""
    if not _state.enabled:
        return False
    with _state.lock:
        inc = _state.open.get(key)
        if inc is None:
            return False
        inc["evidence"].update(links)
    return True


def get(key):
    with _state.lock:
        inc = _state.open.get(key)
        return dict(inc) if inc else None


def open_incidents():
    """Open incidents, oldest first (insertion order)."""
    with _state.lock:
        return [dict(i) for i in _state.open.values()]


def is_degraded():
    """One open incident anywhere = the process is degraded — the
    single healthz source of truth while the plane is on."""
    return _state.enabled and bool(_state.open)


def payload():
    """The /debugz/incidents JSON body."""
    if not _state.enabled:
        return {"enabled": False, "open": [], "resolved": []}
    with _state.lock:
        open_ = [dict(i) for i in _state.open.values()]
        resolved = [dict(i) for i in _state.resolved]
    by_sev = {}
    for i in open_:
        by_sev[i["severity"]] = by_sev.get(i["severity"], 0) + 1
    return {
        "enabled": True,
        "rank": _state.rank,
        "open": open_,
        "resolved": resolved,
        "counts": {"open": len(open_), "open_by_severity": by_sev,
                   "resolved": len(resolved)},
        "time": time.time(),
    }


# env/FLAGS bootstrap (the timeseries/perf discipline): a process
# started with FLAGS_monitor_slo=1 has the table live from the first
# detector firing, no code change anywhere.
if _flag("FLAGS_monitor_slo"):
    enable()
