"""Metric time-series ring: bounded (ts, value) history per series.

The registry (monitor/registry.py) keeps only the CURRENT value of each
Counter/Gauge series — enough for a scrape target, useless for "when
did throughput start sliding" questions asked mid-incident. This module
adds the missing time dimension: when enabled, every Counter/Gauge
sample (and Histogram observation) also appends ``(ts, value)`` to a
bounded per-series ring, giving three consumers a shared substrate:

1. **/debugz/timeseries** (monitor/exporter.py): the live rings as
   JSON, filterable by prefix — an incident responder's first stop.
2. **Watchdog bundle tails** (monitor/watchdog.py): diagnostic bundles
   embed the last-K points of the step-time/throughput/comm series, so
   a hang postmortem shows the deceleration leading INTO the stall,
   not just the frozen instant.
3. **Perf sentinels** (monitor/perf.py): regression detectors subscribe
   to ring appends (``add_listener``) and watch for NaN losses, loss
   spikes, throughput cliffs, grad-norm explosions.

Discipline (the registry's own): default OFF via
``FLAGS_monitor_timeseries`` (bootstrapped from the environment like
every FLAGS_*), and while off the registry hot path is UNCHANGED — the
hook slot in the registry state stays ``None``, so mutators pay the one
pre-existing attribute-load + branch and nothing else; no threads, no
native calls, nothing allocated (test-pinned by tests/test_perf.py).
Everything here is stdlib-only so worker processes can run it without
touching an accelerator backend.
"""
from __future__ import annotations

import os
import threading
import time

from . import registry as _registry


def _flag(name, default=False):
    """FLAGS_* lookup without a hard core-package import at module load
    (monitor stays stdlib-importable for bare worker processes)."""
    try:
        from ..core.flags import flag

        return bool(flag(name, default))
    except Exception:
        raw = os.environ.get(name)
        if raw is None:
            return default
        return raw.lower() in ("1", "true", "yes", "on")


DEFAULT_CAPACITY = 256


class Ring:
    """Fixed-capacity list of (ts, value) points for one series."""

    __slots__ = ("capacity", "_points")

    def __init__(self, capacity):
        self.capacity = max(int(capacity), 1)
        self._points = []

    def append(self, ts, value):
        self._points.append((ts, value))
        if len(self._points) > self.capacity:
            del self._points[:len(self._points) - self.capacity]

    def tail(self, k=None):
        if k is None:
            return list(self._points)
        return list(self._points[-int(k):])

    def values(self, k=None):
        return [v for _, v in self.tail(k)]

    def __len__(self):
        return len(self._points)


class _TSState:
    __slots__ = ("enabled", "capacity", "rings", "lock", "listeners")

    def __init__(self):
        self.enabled = False
        self.capacity = int(os.environ.get("PT_TIMESERIES_CAPACITY",
                                           str(DEFAULT_CAPACITY)))
        self.rings = {}         # series name -> Ring
        self.lock = threading.Lock()
        self.listeners = []     # fn(name, ts, value) — perf sentinels


_state = _TSState()


def _hook(metric, key, value):
    """The registry-side mutator hook (installed only while enabled):
    resolve the prometheus-style series name and record the sample.
    Runs inline on the metric hot path — keep it allocation-light."""
    record(metric._series_name(key), value)


def record(name, value, ts=None):
    """Append one point to ``name``'s ring (creating it on first use)
    and fan out to listeners. Safe to call directly for series that
    don't ride the registry (tests feed synthetic traces this way)."""
    if not _state.enabled:
        return
    if ts is None:
        ts = time.time()
    try:
        value = float(value)
    except (TypeError, ValueError):
        return
    with _state.lock:
        ring = _state.rings.get(name)
        if ring is None:
            ring = _state.rings[name] = Ring(_state.capacity)
        ring.append(ts, value)
    # listeners run OUTSIDE the lock: a sentinel that reads other rings
    # (throughput vs step time) must not deadlock against a concurrent
    # recorder; the rings' point lists are only ever appended to
    for fn in list(_state.listeners):
        try:
            fn(name, ts, value)
        except Exception as e:
            _registry.warn_once(
                "timeseries.listener.%s" % getattr(
                    fn, "__name__", repr(fn)),
                "paddle_tpu.monitor.timeseries: listener %r raised "
                "on %r (listener stays attached): %r"
                % (getattr(fn, "__name__", fn), name, e))


def enable(capacity=None):
    """Turn ring recording on (process-wide) and install the registry
    hook. Idempotent; ``capacity`` only affects rings created later."""
    if capacity is not None:
        _state.capacity = max(int(capacity), 1)
    _state.enabled = True
    _registry._state.ts_hook = _hook
    return _state


def disable():
    """Stop recording: the registry hook slot returns to ``None`` so
    the mutator fast path is exactly the disabled-from-boot one.
    Recorded rings are kept (snapshot-able post-incident); ``clear()``
    drops them."""
    _state.enabled = False
    _registry._state.ts_hook = None


def is_enabled():
    return _state.enabled


def clear():
    with _state.lock:
        _state.rings = {}


def add_listener(fn):
    """Subscribe ``fn(name, ts, value)`` to every ring append."""
    if fn not in _state.listeners:
        _state.listeners.append(fn)


def remove_listener(fn):
    try:
        _state.listeners.remove(fn)
    except ValueError:
        pass


def get_ring(name):
    with _state.lock:
        return _state.rings.get(name)


def snapshot(match=None, k=None):
    """{series: {capacity, points: [[ts, value], ...]}} — ``match``
    filters by substring/prefix; ``k`` bounds each series' tail."""
    with _state.lock:
        items = list(_state.rings.items())
    out = {}
    for name, ring in items:
        if match and match not in name:
            continue
        out[name] = {"capacity": ring.capacity,
                     "points": [[ts, v] for ts, v in ring.tail(k)]}
    return out


def tail(prefixes=(), k=32):
    """Last-K points of every series matching one of ``prefixes`` —
    the watchdog-bundle embedding (a hang postmortem wants the step
    time / throughput / comm deceleration, not every ring)."""
    if not _state.enabled and not _state.rings:
        return {}
    with _state.lock:
        items = list(_state.rings.items())
    out = {}
    for name, ring in items:
        if prefixes and not any(name.startswith(p) for p in prefixes):
            continue
        out[name] = [[ts, v] for ts, v in ring.tail(k)]
    return out


def payload():
    """The /debugz/timeseries JSON body."""
    return {
        "enabled": _state.enabled,
        "capacity": _state.capacity,
        "series_count": len(_state.rings),
        "series": snapshot(),
    }


# env/FLAGS bootstrap (the registry's PT_MONITOR discipline): a process
# started with FLAGS_monitor_timeseries=1 (or sentinels, which read the
# ring) records from the first sample without any code change.
if _flag("FLAGS_monitor_timeseries") or _flag("FLAGS_perf_sentinels"):
    enable()
