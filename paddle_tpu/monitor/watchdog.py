"""Progress watchdog: heartbeat registry, stall detection, diagnostic bundles.

The flight recorder (monitor/flight_recorder.py) is TIMEOUT-triggered:
it only speaks when a store-backed collective gives up. The dominant
production failure modes never get that far — a compiled step hung in
the tunnel, a serving-scheduler deadlock, a rank that silently died —
so this module adds the PROGRESS-triggered half of the postmortem
surface:

1. **Heartbeats** — long-running loops report progress through a named
   ``Heartbeat``: the compiled train step (parallel/engine.py), the
   serving engine loop (serving/engine.py), and store-backed collectives
   (distributed/process_group.py, bracketing the flight-recorder entry
   so "in collective gseq=N for 40s" is distinguishable from "stuck
   between steps"). ``beat()`` marks progress; ``busy(phase)`` marks an
   in-flight region. Stalls are only armed INSIDE a busy bracket — a
   loop that exited cleanly and went idle is not a stall, which is what
   keeps a clean tier-1 run under an enabled watchdog free of false
   positives.

2. **Watchdog daemon thread** — started by ``start_watchdog()`` or the
   ``PT_WATCHDOG=1`` env flag; polls the heartbeats and, when an active
   phase stops advancing past ``PT_WATCHDOG_STALL_S`` (default 60),
   emits a **diagnostic bundle**: every Python thread's stack, the
   flight-recorder ring, a metric-registry snapshot, and per-heartbeat
   ages — written to ``PT_MONITOR_DUMP_DIR`` as
   ``watchdog_bundle_rank{r}.json``.

3. **Cross-rank gather** — in multi-rank runs (a world
   StoreProcessGroup exists) the firing rank publishes a bundle REQUEST
   through the TCPStore; every rank's watchdog answers with its own
   bundle (the stalled rank's daemon thread is alive even while its
   main thread sleeps — that is how the postmortem gets the guilty
   stack). Each watchdog also refreshes a liveness lease every tick, so
   a rank that died outright is named by lease expiry. The gathered
   bundles are diagnosed (``diagnose_bundles``) and persisted as
   ``watchdog_postmortem_rank{r}.json`` naming the stalled (or dead)
   rank — the same barrier-free gather discipline the flight recorder
   uses.

4. **Live endpoints** — monitor/exporter.py registers ``/healthz``
   (ok|stalled verdict + heartbeat ages; HTTP 503 when stalled),
   ``/debugz/stacks``, ``/debugz/flight`` and ``/debugz/bundle`` on the
   fleet KV HTTP server; tools/debug_bundle.py fetches and merges them
   across ranks.

Disabled by default with the registry's discipline: ``beat``/``busy``
early-return (no locks, no native calls), and no daemon thread exists —
both asserted by tests/test_watchdog.py.
"""
from __future__ import annotations

import json
import os
import sys
import threading
import time
import traceback

from . import registry as _registry
from .flight_recorder import get_flight_recorder

_WD_PREFIX = "__wd"
_THREAD_NAME = "pt-watchdog"


def _env_truthy(name, default="0"):
    return os.environ.get(name, default).lower() in ("1", "true", "on")


class _WDState:
    __slots__ = ("enabled", "autostart", "thread", "stop_event",
                 "stall_threshold_s", "poll_interval_s", "grace_s",
                 "lease_s", "fired", "last_request_answered",
                 "healthz_out", "dump_dir", "action")

    def __init__(self):
        self.enabled = False
        self.autostart = _env_truthy("PT_WATCHDOG")
        self.thread = None
        self.stop_event = None
        self.stall_threshold_s = float(
            os.environ.get("PT_WATCHDOG_STALL_S", "60"))
        self.poll_interval_s = None
        self.grace_s = 5.0      # re-derived from the poll interval at start
        self.lease_s = None
        self.fired = {}
        self.last_request_answered = None   # nonce of the last answered req
        self.healthz_out = None
        self.dump_dir = None
        # escalation mode (PT_WATCHDOG_ACTION): "bundle" (default) =
        # diagnose only; "recover" = additionally invoke the registered
        # stall actions (resilience layer hooks) so a stalled bracket
        # can TRIGGER recovery instead of only writing a postmortem
        self.action = os.environ.get("PT_WATCHDOG_ACTION", "bundle")


_state = _WDState()
# stall-action hooks (escalation targets): called from the daemon
# thread on a FRESH stall episode when PT_WATCHDOG_ACTION=recover.
# The resilience layer registers here (ResilientTrainLoop requests a
# snapshot-resume, a serving wrapper can request drain); hooks must be
# quick + non-blocking (set a flag the owning loop consumes) and must
# never raise — a recovery hook that wedges the watchdog would be the
# failure it exists to fix.
_stall_actions = []
_hb_lock = threading.Lock()
# RLock: the restart path (explicit config while running) stops the old
# thread from inside start_watchdog. Guards against two threads racing
# the PT_WATCHDOG autostart and leaking an unstoppable duplicate daemon.
_lifecycle_lock = threading.RLock()
_heartbeats = {}


# -- heartbeats --------------------------------------------------------------

class _NoopBusy:
    """Shared disabled-path context manager: zero allocations per use."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NOOP_BUSY = _NoopBusy()


class _Busy:
    __slots__ = ("_hb", "_phase", "_info", "_token")

    def __init__(self, hb, phase, info):
        self._hb = hb
        self._phase = phase
        self._info = info

    def __enter__(self):
        self._token = self._hb._enter_phase(self._phase, self._info)
        return self

    def __exit__(self, *exc):
        self._hb._exit_phase(self._token)
        return False


class Heartbeat:
    """One named progress source. ``beat()`` marks progress; ``busy()``
    marks an in-flight region — a stall is an active busy phase whose
    most recent progress (phase entry or any beat since) is older than
    the watchdog threshold."""

    def __init__(self, name):
        self.name = name
        self._lock = threading.Lock()
        self.beats = 0
        self.last_beat = None
        self._phases = {}       # token -> {"phase", "info", "since"}
        self._next_token = 0

    def beat(self, n=1):
        if not _state.enabled:
            return
        now = time.monotonic()
        tid = threading.get_ident()
        with self._lock:
            self.beats += n
            self.last_beat = now
            # progress is tracked PER PHASE, attributed by thread: a
            # beat from thread T only refreshes T's own in-flight
            # phases — another thread's completed work must not mask a
            # wedged one on the same (process-wide) heartbeat
            for p in self._phases.values():
                if p["tid"] == tid:
                    p["progress"] = now

    def busy(self, phase, **info):
        """Context manager marking an in-flight region (arms stall
        detection for its duration). ``info`` rides into healthz and
        bundles — the collective bracket passes op/seq/gseq/group."""
        if not _state.enabled:
            return _NOOP_BUSY
        return _Busy(self, phase, info)

    def _enter_phase(self, phase, info):
        now = time.monotonic()
        with self._lock:
            token = self._next_token
            self._next_token += 1
            self._phases[token] = {"phase": phase, "info": info,
                                   "since": now, "progress": now,
                                   "tid": threading.get_ident()}
            return token

    def _exit_phase(self, token):
        now = time.monotonic()
        tid = threading.get_ident()
        with self._lock:
            self._phases.pop(token, None)
            self.beats += 1
            self.last_beat = now
            # a nested phase completing IS progress for its enclosing
            # phases on the same thread (the serving run loop's steps)
            for p in self._phases.values():
                if p["tid"] == tid:
                    p["progress"] = now

    def snapshot(self, now=None):
        """Ages are computed on the MONOTONIC clock (``since`` /
        ``last_beat`` are monotonic stamps, compared only within this
        process): a wall-clock NTP step larger than the stall threshold
        must not fire a false stall storm or mask a real hang."""
        now = now or time.monotonic()
        with self._lock:
            phases = [{
                "phase": p["phase"],
                "info": dict(p["info"]),
                "since": p["since"],
                "age_s": round(now - p["progress"], 3),
            } for p in self._phases.values()]
        return {
            "name": self.name,
            "beats": self.beats,
            "last_beat": self.last_beat,
            "last_beat_age_s": (round(now - self.last_beat, 3)
                                if self.last_beat is not None else None),
            "active_phases": sorted(phases, key=lambda p: p["since"]),
        }


def heartbeat(name):
    """Get-or-create the process-wide heartbeat ``name``. First call
    auto-starts the watchdog when the ``PT_WATCHDOG`` env flag is set
    (the one-env-flag enable path)."""
    hb = _heartbeats.get(name)
    if hb is None:
        with _hb_lock:
            hb = _heartbeats.setdefault(name, Heartbeat(name))
    if _state.autostart and not _state.enabled:
        start_watchdog()
    return hb


def heartbeats_snapshot(now=None):
    now = now or time.monotonic()
    with _hb_lock:
        hbs = list(_heartbeats.values())
    return {hb.name: hb.snapshot(now) for hb in hbs}


def _find_stalls(now=None, threshold_s=None):
    """Active busy phases older than the stall threshold (monotonic)."""
    now = now or time.monotonic()
    if threshold_s is None:
        threshold_s = _state.stall_threshold_s
    stalls = []
    for name, snap in heartbeats_snapshot(now).items():
        for p in snap["active_phases"]:
            if p["age_s"] > threshold_s:
                stalls.append({
                    "heartbeat": name,
                    "phase": p["phase"],
                    "info": p["info"],
                    "age_s": p["age_s"],
                    "since": p["since"],
                    "threshold_s": threshold_s,
                })
    return stalls


# -- bundle assembly ---------------------------------------------------------

def thread_stacks():
    """Every Python thread's current stack (the py-spy-at-home core of
    the bundle — works from the daemon thread while the main thread is
    wedged)."""
    names = {t.ident: (t.name, t.daemon) for t in threading.enumerate()}
    stacks = []
    for ident, frame in sys._current_frames().items():
        name, daemon = names.get(ident, ("?", None))
        frames = [{"file": f.filename, "line": f.lineno, "func": f.name,
                   "code": (f.line or "").strip()}
                  for f in traceback.extract_stack(frame)]
        stacks.append({"thread_id": ident, "name": name,
                       "daemon": daemon, "frames": frames})
    return sorted(stacks, key=lambda s: str(s["name"]))


def _world():
    """(pg, rank, world_size) of the world group, or (None, 0, 1)."""
    from ..distributed import process_group as _pg

    pg = _pg.get_world_group()
    if pg is None:
        return None, 0, 1
    return pg, pg.rank, pg.world_size


def build_bundle(reason="debugz", stalls=None):
    """One rank's full diagnostic bundle (stdlib-only, JSON-ready)."""
    now = time.time()       # provenance stamps only; ages are monotonic
    pg, rank, world = _world()
    if stalls is None:
        stalls = _find_stalls() if _state.enabled else []
    try:
        metrics = _registry.get_registry().snapshot()
    except Exception:
        metrics = {}
    try:
        flight = get_flight_recorder().dump(rank, world)
    except Exception:
        flight = {}
    # time-series tail (monitor/timeseries.py, ring enabled): the
    # deceleration leading INTO the stall — step time, throughput, and
    # comm series — not just the frozen instant
    try:
        from . import timeseries as _timeseries

        ts_tail = _timeseries.tail(
            prefixes=("train_step_seconds", "train_tokens_per_s",
                      "train_loss", "comm_", "grad_sync_", "mem_",
                      "serving_throughput", "serving_goodput"),
            k=int(os.environ.get("PT_WATCHDOG_TS_TAIL", "32")))
    except Exception:
        ts_tail = {}
    try:
        from . import perf as _perf

        anomalies = _perf.anomaly_summary()
    except Exception:
        anomalies = {}
    # active (unfinished) spans (monitor/trace.py, journal enabled):
    # "rank 3 stalled while request r17 was mid-preemption-recompute
    # at gseq=N" — the journey context next to the frozen stacks
    try:
        from . import trace as _trace

        spans = _trace.active_spans()
    except Exception:
        spans = []
    # ptprof time-weighted profile (monitor/profile.py, sampler on):
    # WHERE the time went across the window leading into the stall —
    # the de-dup against the point-in-time "stacks" section above, so
    # a postmortem shows the time distribution, not just where threads
    # sat at one instant. None while FLAGS_monitor_profile is off.
    try:
        from . import profile as _profile

        prof = _profile.bundle_payload()
    except Exception:
        prof = None
    return {
        "kind": "watchdog_bundle",
        "version": 1,
        "reason": reason,
        "created_at": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                    time.gmtime(now)),
        "unix_time": now,
        "pid": os.getpid(),
        "rank": rank,
        "world_size": world,
        "watchdog": {
            "enabled": _state.enabled,
            "stall_threshold_s": _state.stall_threshold_s,
        },
        "verdict": "stalled" if stalls else "ok",
        "stalls": stalls,
        "heartbeats": heartbeats_snapshot(),
        "stacks": thread_stacks(),
        "flight_recorder": flight,
        "metrics": metrics,
        "timeseries_tail": ts_tail,
        "perf_anomalies": anomalies,
        "active_spans": spans,
        "profile_folded": prof,
    }


def _dump_dir():
    return (_state.dump_dir or os.environ.get("PT_MONITOR_DUMP_DIR")
            or ".")


def _atomic_write_json(path, obj):
    """tmp + rename: a kill mid-write (the very crash these artifacts
    diagnose) must never leave truncated JSON."""
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(obj, f, indent=1, default=str)
        f.write("\n")
    os.replace(tmp, path)
    return path


def write_bundle(bundle, dump_dir=None, name=None):
    d = dump_dir or _dump_dir()
    try:
        os.makedirs(d, exist_ok=True)
        return _atomic_write_json(
            os.path.join(d, name or ("watchdog_bundle_rank%d.json"
                                     % bundle["rank"])),
            bundle)
    except OSError:
        return None


# -- cross-rank exchange -----------------------------------------------------
#
# Clock discipline: rank clocks are never compared against each other
# (multi-host skew can exceed any lease window — the repo ships NTP-style
# offset estimation in trace_merge.py precisely because such offsets
# occur). Request nonces are matched by EQUALITY, and deadness is "the
# lease value stopped ADVANCING across the local gather window", both of
# which are skew-immune.

def _publish_bundle(store, rank, bundle, answering=None):
    bundle = dict(bundle)
    bundle["published_at"] = time.time()
    if answering is not None:
        bundle["answering"] = answering
    store.set("%s/bundle/rank%d" % (_WD_PREFIX, rank),
              json.dumps(bundle, default=str).encode())


def _publish_lease(store, rank):
    store.set("%s/alive/rank%d" % (_WD_PREFIX, rank),
              json.dumps({"t": time.time(), "pid": os.getpid()}).encode())


def _publish_request(store, rank, nonce):
    store.set("%s/req" % _WD_PREFIX,
              json.dumps({"t": nonce, "by_rank": rank}).encode())


def _read_request(store):
    data = store.get("%s/req" % _WD_PREFIX, timeout_s=0.05)
    if data is None:
        return None
    try:
        return json.loads(data.decode())
    except Exception:
        return None


def read_lease_stamps(store, world_size):
    """{rank: raw lease timestamp (None if never published)}. Stamps
    are only ever compared for EQUALITY against a later read of the
    same rank's key — never against another clock."""
    stamps = {}
    for r in range(world_size):
        data = store.get("%s/alive/rank%d" % (_WD_PREFIX, r),
                         timeout_s=0.05)
        t = None
        if data is not None:
            try:
                t = float(json.loads(data.decode())["t"])
            except (ValueError, KeyError, TypeError,
                    UnicodeDecodeError):
                pass    # malformed stamp reads as "no heartbeat"
        stamps[r] = t
    return stamps


def gather_bundles(store, world_size, grace_s=None, expect_nonce=None,
                   on_poll=None):
    """Collect per-rank bundles within the grace window. Barrier-free
    (the flight recorder's gather discipline): a dead rank never
    answers, and its absence IS the signal.

    A rank is locked in early only when its bundle carries
    ``answering == expect_nonce`` (it answered THIS incident's
    request); otherwise polling continues and the LATEST version seen
    before the deadline wins — a leftover bundle from a previous
    incident on the same store can be superseded but never blocks the
    fresh answer (wall-clock freshness checks are deliberately avoided:
    cross-host skew would break them).

    ``on_poll`` runs once per poll round — the caller's own liveness
    refresh: a FIRING rank spends its whole gather window inside this
    function instead of ticking, and without refreshing its lease here
    every concurrently-firing peer would read it as dead."""
    if grace_s is None:
        grace_s = _state.grace_s
    deadline = time.monotonic() + grace_s
    bundles = {}
    pending = set(range(world_size))
    while pending and time.monotonic() < deadline:
        if on_poll is not None:
            try:
                on_poll()
            except Exception as e:
                _registry.warn_once(
                    "watchdog.on_poll",
                    "paddle_tpu.monitor.watchdog: on_poll callback "
                    "raised during bundle gather: %r" % (e,))
        locked_in = False
        for r in sorted(pending):
            left = deadline - time.monotonic()
            data = store.get("%s/bundle/rank%d" % (_WD_PREFIX, r),
                             timeout_s=max(min(left, 0.25), 0.05))
            if data is None:
                continue
            try:
                b = json.loads(data.decode())
            except Exception:
                continue
            bundles[r] = b          # latest version wins
            if expect_nonce is None \
                    or b.get("answering") == expect_nonce:
                pending.discard(r)
                locked_in = True
        # pacing: an ABSENT bundle key blocks its get for the poll
        # window, but a stale leftover bundle (exists, wrong nonce)
        # returns instantly — without this sleep a round of only-stale
        # pending ranks busy-spins on the store for the entire grace
        # window (a ptcheck bundle-fixture finding: the gather loop's
        # schedule was unbounded whenever a previous incident left its
        # bundles behind)
        if pending and not locked_in:
            time.sleep(0.05)
    return bundles


# -- cross-rank diagnosis ----------------------------------------------------

def _collective_phase(bundle):
    """The innermost in-flight collective of a bundle's heartbeats, or
    None ('between steps'). Innermost = latest since: allreduce lowers
    to allgather, and the inner op is where the rank actually waits."""
    best = None
    for snap in (bundle.get("heartbeats") or {}).values():
        for p in snap.get("active_phases", ()):
            if "gseq" not in (p.get("info") or {}):
                continue
            if best is None or p["since"] > best["since"]:
                best = p
    return best


def diagnose_bundles(bundles, world_size=None, liveness=None,
                     lease_s=None):
    """Name the stalled (or dead) rank from gathered bundles.

    ``bundles``: {rank: bundle}; ``liveness``: {rank: lease age or
    None}. Mirrors the flight recorder's majority logic on the live
    in-collective positions: ranks blocked in a collective are the
    WAITERS — the suspect is a rank that is not in any collective
    ("between steps"), behind the furthest per-group sequence, dead
    (lease expired), or silent (no bundle, no lease)."""
    if lease_s is None:
        lease_s = _state.lease_s or _default_lease_s()
    bundles = {int(r): b for r, b in bundles.items()}
    liveness = {int(r): a for r, a in (liveness or {}).items()}
    ranks = range(world_size) if world_size else sorted(bundles)
    per_rank, dead, missing, in_coll = {}, [], [], {}
    for r in ranks:
        b = bundles.get(r)
        age = liveness.get(r)
        if b is None:
            # dead = a LEASE that expired (the rank was provably alive
            # and stopped renewing). No lease info at all (offline
            # merge, or a rank that never ran the watchdog) is merely
            # "no bundle" — still a suspect when peers wait on it, but
            # never reported as a confirmed death.
            if age is not None and age > lease_s:
                dead.append(r)
                per_rank[r] = {"state": "dead",
                               "lease_age_s": age}
            else:
                missing.append(r)
                per_rank[r] = {"state": "no-bundle",
                               "lease_age_s": age}
            continue
        coll = _collective_phase(b)
        stalls = b.get("stalls") or []
        if coll is not None:
            in_coll[r] = coll
            per_rank[r] = {"state": "in-collective",
                           "phase": coll["phase"],
                           "info": coll["info"],
                           "age_s": coll["age_s"]}
        elif stalls:
            per_rank[r] = {"state": "stalled",
                           "stalls": stalls}
        else:
            hb_ages = {n: s.get("last_beat_age_s")
                       for n, s in (b.get("heartbeats") or {}).items()}
            per_rank[r] = {"state": "between-steps",
                           "last_beat_ages_s": hb_ages}
    report = {
        "kind": "watchdog_postmortem",
        "world_size": world_size,
        "ranks_reporting": sorted(bundles),
        "dead_ranks": dead,
        "missing_ranks": missing,
        "per_rank": per_rank,
        "stalled_ranks": [],
        "collective": None,
        "status": "inconclusive",
    }
    if in_coll:
        # majority group, furthest gseq = where the pack is waiting
        groups = {}
        for r, p in in_coll.items():
            groups.setdefault(p["info"].get("group"), []).append(r)
        group = max(groups, key=lambda g: len(groups[g]))
        members = groups[group]
        front = max(int(in_coll[r]["info"].get("gseq", -1))
                    for r in members)
        behind = sorted(
            r for r in members
            if int(in_coll[r]["info"].get("gseq", -1)) < front)
        absent = sorted(r for r in ranks
                        if r not in in_coll and r not in dead)
        report["collective"] = {
            "group": group,
            "gseq": front,
            "waiting_ranks": sorted(r for r in members
                                    if r not in behind),
            "op": next((in_coll[r]["info"].get("op") for r in members
                        if int(in_coll[r]["info"].get("gseq", -1))
                        == front), None),
        }
        suspects = sorted(set(behind) | set(absent) | set(dead))
        if suspects:
            report["status"] = "stalled"
            report["stalled_ranks"] = suspects
        else:
            report["status"] = "external-stall"
    else:
        # no rank is inside a collective: suspects are the ranks that
        # reported a local stall, plus any dead ones
        suspects = sorted(set(dead)
                          | {r for r, p in per_rank.items()
                             if p["state"] == "stalled"})
        if suspects:
            report["status"] = "stalled"
            report["stalled_ranks"] = suspects
        elif bundles:
            report["status"] = "ok"
    report["summary"] = summarize_postmortem(report)
    return report


def summarize_postmortem(report):
    if report.get("status") == "stalled":
        bits = []
        for r in report["stalled_ranks"]:
            p = report["per_rank"].get(r, {})
            state = p.get("state", "?")
            if state == "in-collective":
                bits.append("rank %d behind in collective (%s)"
                            % (r, p.get("phase")))
            elif state == "dead":
                bits.append("rank %d DEAD (lease age %s)"
                            % (r, p.get("lease_age_s")))
            else:
                bits.append("rank %d %s" % (r, state))
        coll = report.get("collective")
        where = (" while peers wait in %s gseq=%s"
                 % (coll["op"], coll["gseq"])) if coll else ""
        return "watchdog stall: %s%s" % ("; ".join(bits), where)
    if report.get("status") == "external-stall":
        coll = report.get("collective") or {}
        return ("all ranks blocked in collective %s gseq=%s — "
                "store/network suspect, no rank diverges"
                % (coll.get("op"), coll.get("gseq")))
    return "watchdog: status %s" % report.get("status")


# -- the daemon thread -------------------------------------------------------

def _default_lease_s():
    return max(4 * (_state.poll_interval_s or 1.0), 10.0)


def _on_stall(stalls):
    """Local bundle + (multi-rank) request/gather/diagnose. Runs on the
    daemon thread; must never raise."""
    bundle = build_bundle("stall", stalls)
    path = write_bundle(bundle)
    lines = ["paddle_tpu.monitor.watchdog: STALL detected (bundle: %s)"
             % path]
    for s in stalls:
        lines.append("  %s/%s age %.1fs %s"
                     % (s["heartbeat"], s["phase"], s["age_s"],
                        s["info"] or ""))
    report = None
    pg, rank, world = _world()
    if pg is not None and world > 1:
        try:
            # the nonce identifies THIS incident's request; it is only
            # ever compared for equality, so peer clock skew is moot
            nonce = "%d.%f" % (rank, time.time())
            _state.last_request_answered = nonce   # don't answer self
            _publish_request(pg.store, rank, nonce)
            # a concurrently-firing peer may have a request up already;
            # tag our bundle as answering it so ITS gather locks us in
            peer_req = _read_request(pg.store)
            _publish_bundle(pg.store, rank, bundle,
                            answering=(peer_req or {}).get("t"))
            stamps0 = read_lease_stamps(pg.store, world)
            peers = gather_bundles(
                pg.store, world, expect_nonce=nonce,
                on_poll=lambda: _publish_lease(pg.store, rank))
            peers[rank] = bundle
            # deadness = the lease stopped ADVANCING across the gather
            # window (grace >= 2x the peers' poll interval, so a live
            # watchdog always ticks at least once inside it)
            stamps1 = read_lease_stamps(pg.store, world)
            liveness = {}
            dead_age = _state.grace_s + \
                (_state.lease_s or _default_lease_s()) + 1.0
            for r in range(world):
                if stamps1.get(r) is None:
                    liveness[r] = None          # never leased: unknown
                elif stamps1[r] == stamps0.get(r) and r != rank:
                    liveness[r] = dead_age
                else:
                    liveness[r] = 0.0           # advanced: alive
            # a DEAD rank's bundle that did not answer THIS incident is
            # a leftover from a previous one — drop it so the diagnosis
            # reaches the lease-expiry branch instead of reading stale
            # state as current
            for r in list(peers):
                if r != rank and liveness.get(r) == dead_age \
                        and peers[r].get("answering") != nonce:
                    del peers[r]
            report = diagnose_bundles(peers, world, liveness)
            report["detected_by_rank"] = rank
            report["bundles"] = peers
            d = _dump_dir()
            os.makedirs(d, exist_ok=True)
            ppath = _atomic_write_json(
                os.path.join(d, "watchdog_postmortem_rank%d.json"
                             % rank), report)
            report["report_path"] = ppath
            lines.append("  " + report["summary"])
            lines.append("  postmortem: %s" % ppath)
        except Exception as e:
            lines.append("  cross-rank gather failed: %r" % e)
    sys.stderr.write("\n".join(lines) + "\n")
    # ptslo (monitor/incidents.py): each stall episode is ONE open
    # page-severity incident keyed on (heartbeat, phase) — re-fires of
    # a persistent stall extend it, the _tick prune loop resolves it —
    # with the bundle (and multi-rank postmortem) as evidence. Lazy
    # import, one flag branch while the plane is off.
    try:
        from . import incidents as _incidents

        for s in stalls:
            evidence = {"bundle": path}
            if report is not None and report.get("report_path"):
                evidence["postmortem"] = report["report_path"]
                if report.get("stalled_ranks"):
                    evidence["stalled_ranks"] = \
                        report["stalled_ranks"]
            _incidents.open(
                "watchdog/stall/%s/%s" % (s["heartbeat"], s["phase"]),
                severity="page", kind="stall", source="watchdog",
                summary="stall: %s/%s blocked %.1fs"
                % (s["heartbeat"], s["phase"], s["age_s"]),
                evidence=evidence)
    except Exception as e:
        _registry.warn_once(
            "watchdog.incident_open",
            "paddle_tpu.monitor.watchdog: stall incident open failed "
            "(stall was still reported above): %r" % (e,))
    # ptprof escalation (monitor/profile.py): a fresh stall arms a
    # one-shot device-capture window, so the first steps after the
    # wedge clears (or recovery restarts the loop) get an Xprof trace
    # + folded host stacks. No-op while FLAGS_monitor_profile is off.
    try:
        from . import profile as _profile

        _profile.on_stall(stalls)
    except Exception as e:
        _registry.warn_once(
            "watchdog.profile_arm",
            "paddle_tpu.monitor.watchdog: profile capture arming "
            "failed (stall was still reported above): %r" % (e,))
    try:
        _STALLS_TOTAL.inc()
    except Exception as e:
        _registry.warn_once(
            "watchdog.stalls_counter",
            "paddle_tpu.monitor.watchdog: stall counter increment "
            "failed (stall was still reported above): %r" % (e,))
    if _state.action == "recover" and _stall_actions:
        for fn in list(_stall_actions):
            try:
                fn(stalls, report)
            except Exception as e:
                sys.stderr.write(
                    "paddle_tpu.monitor.watchdog: stall action %r "
                    "failed: %r\n" % (fn, e))
    return report


def register_stall_action(fn):
    """Register an escalation hook ``fn(stalls, report)`` invoked on a
    fresh stall episode when ``PT_WATCHDOG_ACTION=recover``. Returns
    ``fn`` (decorator-friendly)."""
    if fn not in _stall_actions:
        _stall_actions.append(fn)
    return fn


def unregister_stall_action(fn):
    try:
        _stall_actions.remove(fn)
    except ValueError:
        pass


def stall_action():
    """Current escalation mode ("bundle" | "recover") and hook count —
    surfaced at /debugz/resilience."""
    return {"mode": _state.action, "hooks": len(_stall_actions)}


def _write_healthz_artifact():
    path = _state.healthz_out
    if not path:
        return
    try:
        _atomic_write_json(path, healthz_payload())
    except OSError:
        pass


def _tick():
    now = time.monotonic()
    pg, rank, world = _world()
    if pg is not None and world > 1:
        try:
            _publish_lease(pg.store, rank)
            req = _read_request(pg.store)
            if req is not None \
                    and req.get("t") != _state.last_request_answered \
                    and req.get("by_rank") != rank:
                # a peer is gathering: answer with our bundle even if
                # we are healthy or idle — this is how the postmortem
                # gets the guilty rank's stack. Nonce equality (never
                # wall-clock age) decides whether we already answered.
                _state.last_request_answered = req.get("t")
                _publish_bundle(pg.store, rank,
                                build_bundle("request"),
                                answering=req.get("t"))
        except Exception as e:
            _registry.warn_once(
                "watchdog.respond",
                "paddle_tpu.monitor.watchdog: cross-rank bundle "
                "response failed (postmortem will miss this rank's "
                "stacks): %r" % (e,))
    _write_healthz_artifact()
    stalls = _find_stalls(now)
    live_keys = set()
    fresh = []
    for s in stalls:
        key = (s["heartbeat"], s["phase"], s["since"])
        live_keys.add(key)
        if key not in _state.fired:
            _state.fired[key] = now
            fresh.append(s)
    # prune episodes whose phase ended so a future stall re-fires —
    # the same edge resolves the episode's incident (monitor/
    # incidents.py; no-op branch while the SLO plane is off)
    for key in list(_state.fired):
        if key not in live_keys:
            del _state.fired[key]
            try:
                from . import incidents as _incidents

                _incidents.resolve(
                    "watchdog/stall/%s/%s" % (key[0], key[1]),
                    reason="stalled phase ended")
            except Exception as e:
                _registry.warn_once(
                    "watchdog.incident_resolve",
                    "paddle_tpu.monitor.watchdog: stall incident "
                    "resolve failed (episode latch still pruned): %r"
                    % (e,))
    if fresh:
        _on_stall(stalls)


def _run(stop_event, poll_s):
    while not stop_event.wait(poll_s):
        try:
            _tick()
        except Exception as e:
            # the watchdog eating its own tick failures is the exact
            # blind spot it exists to diagnose: say it once, keep
            # ticking
            _registry.warn_once(
                "watchdog.tick",
                "paddle_tpu.monitor.watchdog: tick failed (watchdog "
                "still polling): %r" % (e,))


def start_watchdog(stall_threshold_s=None, poll_interval_s=None,
                   grace_s=None, dump_dir=None):
    """Start (or return) the process-wide watchdog daemon thread and
    enable heartbeat recording. Idempotent without arguments; an
    explicit config on an already-running watchdog (e.g. started by the
    PT_WATCHDOG autostart) restarts the thread with the new settings
    rather than silently keeping the old ones."""
    with _lifecycle_lock:
        return _start_watchdog_locked(stall_threshold_s,
                                      poll_interval_s, grace_s,
                                      dump_dir)


def _start_watchdog_locked(stall_threshold_s, poll_interval_s, grace_s,
                           dump_dir):
    if _state.thread is not None and _state.thread.is_alive():
        if stall_threshold_s is None and poll_interval_s is None \
                and grace_s is None and dump_dir is None:
            return _state.thread
        autostart = _state.autostart
        stop_watchdog()
        _state.autostart = autostart
    if stall_threshold_s is not None:
        _state.stall_threshold_s = float(stall_threshold_s)
    if dump_dir is not None:
        _state.dump_dir = dump_dir
    if poll_interval_s is None:
        poll_interval_s = float(os.environ.get(
            "PT_WATCHDOG_POLL_S",
            str(max(min(_state.stall_threshold_s / 4.0, 5.0), 0.2))))
    _state.poll_interval_s = float(poll_interval_s)
    env_grace = os.environ.get("PT_WATCHDOG_GRACE_S")
    if grace_s is not None:
        _state.grace_s = float(grace_s)
    elif env_grace is not None:
        _state.grace_s = float(env_grace)
    else:
        # the gather window must outlast the PEERS' poll interval: a
        # healthy rank only answers a bundle request on its next tick,
        # so grace <= poll would falsely name slow-but-healthy ranks
        _state.grace_s = max(5.0, 2.0 * _state.poll_interval_s + 1.0)
    _state.lease_s = float(os.environ.get(
        "PT_WATCHDOG_LEASE_S", str(_default_lease_s())))
    _state.healthz_out = os.environ.get("PT_WATCHDOG_HEALTHZ_OUT")
    # like every PT_WATCHDOG_* sibling, the escalation mode re-reads
    # the env at start: setting PT_WATCHDOG_ACTION after import (the
    # common "configure then start" order) must take effect — and an
    # unset env resets to the default rather than keeping a stale mode.
    # Unknown values are called out loudly and degrade to diagnose-only:
    # a typo ('recovery') silently disabling the escalation the operator
    # armed would be discovered only after the outage.
    action = os.environ.get("PT_WATCHDOG_ACTION", "bundle")
    if action not in ("bundle", "recover"):
        sys.stderr.write(
            "paddle_tpu.monitor.watchdog: unknown PT_WATCHDOG_ACTION=%r "
            "(expected 'bundle' or 'recover'); using 'bundle'\n"
            % action)
        action = "bundle"
    _state.action = action
    _state.fired = {}
    _state.enabled = True
    _state.stop_event = threading.Event()
    _state.thread = threading.Thread(
        target=_run, args=(_state.stop_event, _state.poll_interval_s),
        name=_THREAD_NAME, daemon=True)
    _state.thread.start()
    return _state.thread


def stop_watchdog():
    """Stop the daemon thread and disable heartbeat recording."""
    with _lifecycle_lock:
        _state.enabled = False
        _state.autostart = False
        if _state.stop_event is not None:
            _state.stop_event.set()
        t = _state.thread
        if t is not None and t.is_alive():
            t.join(timeout=5)
        _state.thread = None
        _state.stop_event = None


def is_watchdog_running():
    return _state.thread is not None and _state.thread.is_alive()


# watchdog's own telemetry rides the shared registry (inc is a no-op
# while the monitor is disabled, like every other mutator)
_STALLS_TOTAL = _registry.counter(
    "watchdog_stalls_total", "stall episodes detected by the watchdog")


# -- live endpoints (registered on the fleet KV server by exporter.py) -------

def healthz_payload():
    now = time.time()       # reported wall stamp; ages are monotonic
    stalls = _find_stalls() if _state.enabled else []
    _, rank, world = _world()
    # perf-sentinel degradation (monitor/perf.py): a NaN loss or
    # throughput cliff marks the endpoint degraded — orthogonal to the
    # stalled verdict (a degraded run is alive and probe-200, but a
    # deploy gate can read the flag). With the SLO plane on, the
    # incident table is the single source of truth instead: degraded
    # = any open incident (the sentinels still report through it, so
    # the verdict is equivalent until something else opens one). Flag
    # off, the payload is bit-identical to the pre-incident build
    # (test-pinned).
    incidents_open = None
    try:
        from . import perf as _perf

        try:
            from . import incidents as _incidents
        except Exception:
            _incidents = None
        if _incidents is not None and _incidents.is_enabled():
            degraded = _incidents.is_degraded()
            incidents_open = len(_incidents.open_incidents())
        else:
            degraded = _perf.is_degraded()
        anomalies = _perf.anomaly_summary() if degraded else None
    except Exception:
        degraded, anomalies = False, None
    body = {
        "status": "stalled" if stalls
        else ("degraded" if degraded else "ok"),
        "degraded": degraded,
        "perf_anomalies": anomalies,
        "watchdog": "enabled" if _state.enabled else "disabled",
        "stall_threshold_s": _state.stall_threshold_s,
        "rank": rank,
        "world_size": world,
        "pid": os.getpid(),
        "time": now,
        "stalls": stalls,
        "heartbeats": {
            name: {
                "beats": s["beats"],
                "last_beat_age_s": s["last_beat_age_s"],
                "active_phases": s["active_phases"],
            } for name, s in heartbeats_snapshot().items()},
    }
    # key exists only while the incident plane is on — the flag-off
    # payload stays byte-for-byte what PR-17 served (test-pinned)
    if incidents_open is not None:
        body["incidents_open"] = incidents_open
    return body


def json_safe(obj):
    """Recursively replace non-finite floats with their string
    spellings. HTTP debug payloads carry NaN on purpose (a NaN loss IS
    the incident), but Python's json emits bare ``NaN`` tokens that
    strict parsers (jq, JSON.parse) reject — and an incident-response
    endpoint must stay parseable exactly mid-incident."""
    if isinstance(obj, float):
        if obj != obj:
            return "NaN"
        if obj == float("inf"):
            return "Infinity"
        if obj == float("-inf"):
            return "-Infinity"
        return obj
    if isinstance(obj, dict):
        return {k: json_safe(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [json_safe(v) for v in obj]
    return obj


def _json_route(payload, code=200):
    return code, "application/json", \
        json.dumps(json_safe(payload), default=str).encode()


def http_healthz():
    p = healthz_payload()
    return _json_route(p, 503 if p["status"] == "stalled" else 200)


def http_stacks():
    return _json_route({"pid": os.getpid(), "time": time.time(),
                        "stacks": thread_stacks()})


def http_flight():
    _, rank, world = _world()
    return _json_route(get_flight_recorder().dump(rank, world))


def http_bundle():
    return _json_route(build_bundle("debugz"))
